"""
Neural network subpackage.

Parity with the reference's ``heat/nn/__init__.py``: exposes ``DataParallel``/
``DataParallelMultiGPU`` plus a fallthrough module surface. The reference falls
through to ``torch.nn`` ("torch with Heat interposed", nn/functional.py:9-33); the
TPU-native fallthrough is ``flax.linen`` — ``ht.nn.Dense``, ``ht.nn.Conv`` etc. are
flax modules, and ``ht.nn.functional`` maps to ``jax.nn``.
"""

from .data_parallel import DataParallel, DataParallelMultiGPU
from .attention import ring_attention, scaled_dot_product_attention, ulysses_attention
from . import functional

try:
    import flax.linen as _linen
except ImportError:  # pragma: no cover
    _linen = None


def __getattr__(name: str):
    """Fall through to flax.linen for module classes (reference heat/nn/__init__
    falls through to torch.nn)."""
    if _linen is not None and hasattr(_linen, name):
        return getattr(_linen, name)
    raise AttributeError(f"module 'heat_tpu.nn' has no attribute {name!r}")
