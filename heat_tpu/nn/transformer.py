"""
End-to-end distributed transformer: ONE fused executable per train step
(ISSUE 20, ROADMAP item 1).

Every subsystem this module composes existed in isolation — flash attention,
fused-GEMM epilogues, reduction-sink losses, the DP/DASO trainers, elastic
checkpointing — but nothing ever demonstrated the repo's headline claim: a
whole train step amortized into one fused program (the XLA-fusion thesis at
workload scale). Three mechanisms make the claim structural, not incidental:

**Packed parameters.** All transformer parameters live in ONE flat 1-D
``theta`` DNDarray and the momentum in a same-shaped ``mu`` (layout is a
static function of the config, unpacked inside the jitted program by
constant-offset slicing). Donation aliasing is then exact — ``theta`` and
``mu`` each shape/dtype-match exactly one output (``theta'``, ``mu'``) —
and the kernel's output arity stays at three whatever the depth.

**One fused chain per step.** A train step records exactly FOUR nodes via
:func:`~heat_tpu.core.fusion.defer_app` (kind ``"transformer"``):
``tf-grad`` (forward + cross-entropy + backward, returning ``[loss, grad]``
packed f32), ``tf-momentum`` (``mu' = m·mu + g``), ``tf-update``
(``theta' = theta - lr·mu'``), and a root ``tf-loss`` SINK that extracts
the scalar loss while structurally consuming ``theta'`` — the structural
operand is what pulls the whole optimizer update inside the sink's
subgraph, so ``materialize_for`` widens the flush and loss, ``mu'`` and
``theta'`` all return from the SAME jitted kernel: one dispatch, one
trace-cache entry, ``executables_per_step == 1``.

**Steady-state donation.** The train loop rebinds its :class:`TrainState`
before reading the loss, so the previous step's ``theta``/``mu`` buffers
enter the chain as dead-owner leaves and the PR 3 machinery aliases them to
``theta'``/``mu'`` in place — ``theta`` feeds TWO recorded nodes (grad and
update), which is exactly the multi-consumer case the widened
``_donatable`` wrapper-count bound (ISSUE 20) admits. After the one warmup
compile (plus the donation-mask re-key on step 2) the L1 key is IDENTICAL
every step: ``fusion.kernels_compiled == 0`` and
``flush_reason{collective} == 0`` per steady-state step, with
``fusion.donated{steady_state}`` growing by 2 buffers/step.

Attention inside the recorded program is dense causal (f32 softmax) under
``jax.value_and_grad`` — the pallas flash kernel defines no VJP — while the
no-grad :func:`infer_step` forward routes to
:func:`~heat_tpu.core.pallas.flash.attention_local` (``train=True``: the
``pallas.flash.train_tile`` knob) when the pallas tier admits it. The MLP
is a row-chunked fused GEMM pair whose chunk height is the
``transformer.mlp.tile`` knob. Sequence-split batches (``split=1``) and
batch-split batches (``split=0``) ride as sharded leaves: GSPMD emits the
collectives inside the SAME fused program — no recorded collective nodes,
so the chain never breaks on one.

Everything is gated behind ``HEAT_TPU_TRANSFORMER=1``; off (the default)
:func:`train_step` runs the eager per-op reference — the SAME memoized
callables dispatched standalone, bit-for-bit the ``HEAT_TPU_FUSION=0``
differential oracle.

For the DP/DASO trainers the same math is exposed over an UNPACKED param
pytree (:func:`init_tree` / :func:`apply_tree` / :func:`tree_loss` /
:class:`TransformerModule`) — the packed fused loop and the trainer loop
share one forward implementation, so their losses agree to dtype tolerance.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories as _factories
from ..core import fusion as _fusion
from ..core import types as _types
from ..core.dndarray import DNDarray
from ..monitoring import instrument as _instr
from ..monitoring.registry import STATE as _MON

__all__ = [
    "enabled",
    "TransformerConfig",
    "TrainState",
    "init_state",
    "train_step",
    "infer_step",
    "read_loss",
    "read_logits",
    "param_count",
    "init_tree",
    "apply_tree",
    "tree_loss",
    "TransformerModule",
]


def enabled() -> bool:
    """Whether the fused one-executable-per-step train path is armed
    (``HEAT_TPU_TRANSFORMER=1``; one env read — the off-path cost). Off, a
    :func:`train_step` runs the eager per-op reference — bit-for-bit the
    pre-ISSUE-20 engine."""
    return os.environ.get("HEAT_TPU_TRANSFORMER", "").strip().lower() in (
        "1", "true", "on",
    )


# ------------------------------------------------------------------ config
@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """The static identity of one transformer workload: geometry, dtype,
    and the (baked-in) SGD-momentum hyperparameters. Every field is part of
    the recorded nodes' cross-process-stable ``static`` tuple — two configs
    never alias in any cache."""

    vocab: int = 64
    dim: int = 32
    heads: int = 2
    depth: int = 2
    mlp_ratio: int = 2
    max_seq: int = 16
    dtype: str = "float32"
    seed: int = 0
    lr: float = 0.1
    momentum: float = 0.9

    def __post_init__(self):
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unsupported transformer dtype {self.dtype!r}")
        if self.dim % self.heads != 0:
            raise ValueError("dim must be divisible by heads")

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    @property
    def jnp_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32

    @property
    def heat_dtype(self):
        return _types.bfloat16 if self.dtype == "bfloat16" else _types.float32

    @classmethod
    def from_env(cls) -> "TransformerConfig":
        """The smoke/bench-side config: seeded by
        ``HEAT_TPU_TRANSFORMER_SEED`` (default 0) at the fixed toy
        geometry, so independent processes build bit-identical models."""
        return cls(seed=int(os.environ.get("HEAT_TPU_TRANSFORMER_SEED", "0") or 0))


@functools.lru_cache(maxsize=64)
def _layout(vocab: int, dim: int, heads: int, depth: int, mlp_ratio: int,
            max_seq: int):
    """``((name, shape, offset, size), ...), total`` — the packed-theta map.
    A pure function of the geometry: both processes of a warm-cache pair
    compute identical offsets, so the L2 digest is honest."""
    hidden = mlp_ratio * dim
    names = [("embed", (vocab, dim)), ("pos", (max_seq, dim))]
    for i in range(depth):
        names += [
            (f"b{i}.ln1", (dim,)),
            (f"b{i}.wqkv", (dim, 3 * dim)),
            (f"b{i}.wo", (dim, dim)),
            (f"b{i}.ln2", (dim,)),
            (f"b{i}.w1", (dim, hidden)),
            (f"b{i}.w2", (hidden, dim)),
        ]
    names.append(("lnf", (dim,)))
    out, off = [], 0
    for name, shape in names:
        size = int(np.prod(shape))
        out.append((name, tuple(shape), off, size))
        off += size
    return tuple(out), off


def param_count(cfg: TransformerConfig) -> int:
    """Total packed parameter count of ``cfg`` (the length of ``theta``)."""
    return _layout(cfg.vocab, cfg.dim, cfg.heads, cfg.depth, cfg.mlp_ratio,
                   cfg.max_seq)[1]


def _unpack(theta, lay):
    return {name: theta[off:off + size].reshape(shape)
            for name, shape, off, size in lay}


def _init_flat(cfg: TransformerConfig) -> np.ndarray:
    """Deterministic host-seeded packed initialization (norm scales at 1,
    weights scaled standard normal) — the cross-process weight oracle."""
    lay, total = _layout(cfg.vocab, cfg.dim, cfg.heads, cfg.depth,
                         cfg.mlp_ratio, cfg.max_seq)
    rng = np.random.default_rng(cfg.seed)
    theta = np.empty(total, np.float32)
    for name, shape, off, size in lay:
        if name.endswith(("ln1", "ln2", "lnf")):
            theta[off:off + size] = 1.0
        else:
            fan = shape[0] if len(shape) > 1 else 1
            theta[off:off + size] = (
                rng.standard_normal(size) * (0.4 / np.sqrt(fan))
            ).astype(np.float32)
    return theta


# ------------------------------------------------------------------ math
def _rms(h, g):
    h32 = h.astype(jnp.float32)
    r = h32 * jax.lax.rsqrt(jnp.mean(h32 * h32, axis=-1, keepdims=True) + 1e-6)
    return (r * g.astype(jnp.float32)).astype(h.dtype)


def _mlp_chunked(x, w1, w2, tile: int):
    """The fused-GEMM MLP pair over row blocks of ``tile`` height: each
    chunk's up-projection, gelu and down-projection stay resident between
    the two GEMMs (XLA fuses the epilogue into the first), and the chunk
    height — the ``transformer.mlp.tile`` knob — bounds the live f32
    hidden activation. ``x`` is 2-D ``(rows, dim)``; shapes are static
    inside jit, so the python chunk loop unrolls at trace time."""
    n = int(x.shape[0])
    t = max(8, int(tile))
    outs = []
    for i in range(0, n, t):
        blk = x[i:i + t]
        hid = jax.nn.gelu(
            jnp.dot(blk, w1, preferred_element_type=jnp.float32)
        ).astype(x.dtype)
        outs.append(jnp.dot(hid, w2))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


def _forward_p(p, x, *, dim, heads, depth, mlp_tile, flash, interpret):
    """The shared forward over an unpacked param dict ``p``: embedding +
    ``depth`` pre-norm blocks of causal attention → chunked-GEMM MLP →
    residual, final norm, tied-embedding f32 logits."""
    B, S = x.shape
    hd = dim // heads
    scale = float(hd) ** -0.5
    h = jnp.take(p["embed"], x, axis=0) + p["pos"][:S][None].astype(
        p["embed"].dtype
    )
    for i in range(depth):
        a = _rms(h, p[f"b{i}.ln1"])
        qkv = jnp.dot(a, p[f"b{i}.wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, heads, hd)
        k = k.reshape(B, S, heads, hd)
        v = v.reshape(B, S, heads, hd)
        if flash:
            from ..core.pallas import flash as _fl

            o = _fl.attention_local(
                q, k, v, causal=True, scale=scale, interpret=interpret,
                train=True,
            )
        else:
            qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
            mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
            prob = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", prob, vf).astype(h.dtype)
        h = h + jnp.dot(o.reshape(B, S, dim), p[f"b{i}.wo"])
        m = _rms(h, p[f"b{i}.ln2"])
        y2 = _mlp_chunked(
            m.reshape(B * S, dim), p[f"b{i}.w1"], p[f"b{i}.w2"], mlp_tile
        )
        h = h + y2.reshape(B, S, dim).astype(h.dtype)
    h = _rms(h, p["lnf"])
    return jnp.dot(h.astype(jnp.float32), p["embed"].T.astype(jnp.float32))


def _xent(logits, y):
    """Mean next-token cross-entropy — the reduction the root sink carries."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, y[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - ll)


# ---------------------------------------------------------------- kernels
#
# One memoized callable per static configuration: ``defer_app`` keys the
# trace cache on the fn's object identity and the L2 digest on
# (opname, static) — both shear unless the SAME object serves every step.
# Every factory takes the FULL static tuple, so it doubles as the warmup
# app-rebuilder (registered at module import, resolved cross-process by
# ``heat_tpu.serving.warmup`` through ``fusion.app_rebuilder``).
_FNS: dict = {}

#: static tuple layout (train):
#: (vocab, dim, heads, depth, mlp_ratio, max_seq, dtype, lr, momentum, tile)
#: infer appends (flash, interpret).


def _train_static(cfg: TransformerConfig, mlp_tile: int) -> tuple:
    return (cfg.vocab, cfg.dim, cfg.heads, cfg.depth, cfg.mlp_ratio,
            cfg.max_seq, cfg.dtype, float(cfg.lr), float(cfg.momentum),
            int(mlp_tile))


def _vg_fn_for(static):
    """Forward + cross-entropy + backward: returns ``[loss, grad]`` packed
    ``(1 + n_params,)`` in the MODEL dtype so the loss rides to the sink
    without a second forward. Attention is dense causal — the recorded
    program must be differentiable end to end."""
    static = tuple(static)
    key = ("tf-grad", static)
    fn = _FNS.get(key)
    if fn is None:
        _v, dim, heads, depth, mlp_r, max_seq, _dt, _lr, _m, tile = static

        def loss_of(theta, x, y, _dim=dim, _h=heads, _d=depth, _mr=mlp_r,
                    _ms=max_seq, _vv=_v, _t=tile):
            lay, _tot = _layout(_vv, _dim, _h, _d, _mr, _ms)
            p = _unpack(theta, lay)
            logits = _forward_p(
                p, x, dim=_dim, heads=_h, depth=_d, mlp_tile=_t,
                flash=False, interpret=False,
            )
            return _xent(logits, y)

        def fn(theta, x, y, _loss_of=loss_of):
            loss, g = jax.value_and_grad(_loss_of)(theta, x, y)
            # the pack carries theta's dtype: every output of the fused
            # chain then shares the compute precision, so the shadow-replay
            # audit sizes its carve-out tolerance to it (a bf16 chain
            # audited at the f32 bound trips on legitimate cross-node
            # excess-precision elision)
            return jnp.concatenate(
                [loss.reshape(1).astype(theta.dtype), g.astype(theta.dtype)]
            )

        _FNS[key] = fn
    return fn


def _mom_fn_for(static):
    """``mu' = momentum · mu + g`` (f32 accumulate, stored in ``mu``'s
    dtype — the donation alias must match exactly)."""
    static = tuple(static)
    key = ("tf-momentum", static)
    fn = _FNS.get(key)
    if fn is None:
        from ..optim import fused_sgd as _sgd

        momentum = float(static[8])

        def fn(mu, gpack, _m=momentum, _sgd=_sgd):
            return _sgd.momentum_update(mu, gpack[1:], _m)

        _FNS[key] = fn
    return fn


def _upd_fn_for(static):
    """``theta' = theta - lr · mu'`` (f32 math, ``theta``'s dtype out)."""
    static = tuple(static)
    key = ("tf-update", static)
    fn = _FNS.get(key)
    if fn is None:
        from ..optim import fused_sgd as _sgd

        lr = float(static[7])

        def fn(theta, mu2, _lr=lr, _sgd=_sgd):
            return _sgd.apply_update(theta, mu2, _lr)

        _FNS[key] = fn
    return fn


def _loss_pick_fn_for(static):
    """The root SINK: extract the scalar loss from the grad pack while
    structurally consuming ``theta'`` — the no-op operand is what places
    the optimizer update inside the sink's subgraph, so the widened flush
    returns loss, ``mu'`` and ``theta'`` from ONE kernel."""
    static = tuple(static)
    key = ("tf-loss", static)
    fn = _FNS.get(key)
    if fn is None:
        def fn(gpack, theta2):
            del theta2  # structural dependency only: rides the same kernel
            return gpack[0]

        _FNS[key] = fn
    return fn


def _infer_fn_for(static):
    """The no-grad forward (logits); ``flash``/``interpret`` baked into the
    node identity — the pallas route and the dense reference must never
    alias in any cache."""
    static = tuple(static)
    key = ("tf-infer", static)
    fn = _FNS.get(key)
    if fn is None:
        (_v, dim, heads, depth, mlp_r, max_seq, _dt, _lr, _m, tile,
         flash, interpret) = static

        def fn(theta, x, _dim=dim, _h=heads, _d=depth, _mr=mlp_r,
               _ms=max_seq, _vv=_v, _t=tile, _fl=bool(flash),
               _ip=bool(interpret)):
            lay, _tot = _layout(_vv, _dim, _h, _d, _mr, _ms)
            p = _unpack(theta, lay)
            return _forward_p(
                p, x, dim=_dim, heads=_h, depth=_d, mlp_tile=_t,
                flash=_fl, interpret=_ip,
            )

        _FNS[key] = fn
    return fn


def _mlp_tile_pref() -> int:
    """The fused-MLP chunk height: the static 128, or the measured winner
    under ``HEAT_TPU_TUNING=1`` (knob ``transformer.mlp.tile``; one env
    read when off — the PR 18 inertness contract)."""
    from .. import tuning as _tuning

    if not _tuning.enabled():
        return 128
    try:
        return int(_tuning.lookup("transformer.mlp.tile", context={}))
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return 128


def _interpret() -> bool:
    from ..core import pallas as _PL

    return bool(_PL.use_interpret())


def _infer_flash_route(cfg: TransformerConfig, seq: int, split) -> bool:
    """Whether the no-grad forward takes the pallas flash kernel: registry
    predicates, square-shape rails, and single-device (or interpreted)
    placement — a compiled ``pallas_call`` has no GSPMD partitioning rule."""
    from ..core import pallas as _PL
    from ..core.pallas import flash as _plflash

    if split is not None:
        return False
    ok = _plflash.shape_ok(int(seq), int(seq), cfg.head_dim)
    if not _PL.available(
        "flash_ring", dtype=np.dtype(cfg.jnp_dtype), shape_ok=ok
    ):
        return False
    return bool(_PL.use_interpret()) or jax.device_count() == 1


# ---------------------------------------------------------------- state
class TrainState:
    """The persistent training state: packed ``theta``/``mu`` DNDarrays
    plus the host step counter. Holding the returned state alive is the
    state contract (it keeps the update nodes' owners live so they ride
    the fused kernel as extra outputs); REBINDING it before
    :func:`read_loss` is the donation contract (the old buffers become
    dead-owner leaves the donation pass may alias) — exactly the ISSUE 19
    KVCache discipline applied to parameters."""

    __slots__ = ("theta", "mu", "step", "cfg")

    def __init__(self, theta: DNDarray, mu: DNDarray, step: int,
                 cfg: TransformerConfig):
        self.theta = theta
        self.mu = mu
        self.step = int(step)
        self.cfg = cfg

    def checkpoint_state(self) -> dict:
        """The pytree a preemption/elastic checkpoint persists (host
        arrays — split-agnostic on restore)."""
        return {
            "theta": np.asarray(self.theta.larray, np.float32),
            "mu": np.asarray(self.mu.larray, np.float32),
            "step": self.step,
        }

    @classmethod
    def from_checkpoint(cls, state: dict, cfg: TransformerConfig,
                        split: Optional[int] = None) -> "TrainState":
        theta = _factories.array(
            np.asarray(state["theta"], np.float32), dtype=cfg.heat_dtype,
            split=split,
        )
        mu = _factories.array(
            np.asarray(state["mu"], np.float32), dtype=cfg.heat_dtype,
            split=split,
        )
        return cls(theta, mu, int(state["step"]), cfg)


def init_state(cfg: TransformerConfig) -> TrainState:
    """Seeded packed state: ``theta`` from the host RNG, ``mu`` zeros.
    Parameters are replicated (``split=None``) — the batch carries the
    sharding; GSPMD emits whatever collectives the mesh needs inside the
    fused program."""
    theta = _factories.array(_init_flat(cfg), dtype=cfg.heat_dtype)
    mu = _factories.zeros((param_count(cfg),), dtype=cfg.heat_dtype)
    return TrainState(theta, mu, 0, cfg)


# ---------------------------------------------------------------- steps
def _as_tokens(a, cfg: TransformerConfig):
    """Normalize a batch operand: DNDarrays pass through (their split IS
    the distribution policy); host arrays become i32 jax arrays."""
    if isinstance(a, DNDarray):
        return a
    return jnp.asarray(np.asarray(a, np.int32))


def _train_eager(state: TrainState, xj, yj):
    """The eager per-op reference: the SAME memoized callables the fused
    chain records, dispatched standalone on concrete arrays — the
    differential oracle, and the path when the knob is off."""
    cfg = state.cfg
    stat = _train_static(cfg, _mlp_tile_pref())
    vg = _vg_fn_for(stat)
    mom = _mom_fn_for(stat)
    upd = _upd_fn_for(stat)
    pick = _loss_pick_fn_for(stat)
    xc = xj.parray if isinstance(xj, DNDarray) else xj
    yc = yj.parray if isinstance(yj, DNDarray) else yj
    gpack = vg(state.theta.parray, xc, yc)
    mu2 = mom(state.mu.parray, gpack)
    theta2 = upd(state.theta.parray, mu2)
    loss = pick(gpack, theta2)
    t2 = _factories.array(theta2, dtype=cfg.heat_dtype, copy=False)
    m2 = _factories.array(mu2, dtype=cfg.heat_dtype, copy=False)
    lg = _factories.array(loss, dtype=cfg.heat_dtype, copy=False)
    return lg, t2, m2


def train_step(state: TrainState, x, y) -> Tuple[DNDarray, TrainState]:
    """One SGD-momentum step over the packed state: returns
    ``(loss, new_state)`` with ``loss`` a scalar DNDarray in the model
    dtype (deferred when the fused path records) and ``new_state`` the
    advanced state.

    ``x``/``y`` are ``(B, S)`` int32 token/label batches — host arrays, or
    DNDarrays split along batch (0) or sequence (1). The caller must drop
    its reference to the OLD state before reading the loss: that is what
    makes ``theta``/``mu`` dead-owner leaves the donation pass aliases to
    ``theta'``/``mu'`` (the steady-state zero-allocation contract)."""
    cfg = state.cfg
    xj = _as_tokens(x, cfg)
    yj = _as_tokens(y, cfg)

    if enabled() and _fusion.enabled():
        stat = _train_static(cfg, _mlp_tile_pref())
        vg = _vg_fn_for(stat)
        mom = _mom_fn_for(stat)
        upd = _upd_fn_for(stat)
        pick = _loss_pick_fn_for(stat)
        gpack = _fusion.defer_app(
            vg, "tf-grad", (state.theta, xj, yj),
            static=stat, out_split=None, kind="transformer",
        )
        mu2 = (
            None if gpack is None else _fusion.defer_app(
                mom, "tf-momentum", (state.mu, gpack),
                static=stat, out_split=None, kind="transformer",
            )
        )
        theta2 = (
            None if mu2 is None else _fusion.defer_app(
                upd, "tf-update", (state.theta, mu2),
                static=stat, out_split=None, kind="transformer",
            )
        )
        loss = (
            None if theta2 is None else _fusion.defer_app(
                pick, "tf-loss", (gpack, theta2),
                static=stat, sink=True, out_split=None, kind="transformer",
            )
        )
        if loss is not None:
            if _MON.enabled:
                _instr.transformer_event("step-fused")
            return loss, TrainState(theta2, mu2, state.step + 1, cfg)

    lg, t2, m2 = _train_eager(state, xj, yj)
    if _MON.enabled:
        _instr.transformer_event("step-eager")
    return lg, TrainState(t2, m2, state.step + 1, cfg)


def infer_step(state: TrainState, x) -> DNDarray:
    """The no-grad forward: ``(B, S, vocab)`` f32 logits as one fused sink
    (flash-routed when the pallas tier admits the training shape), or the
    eager reference when the knob is off / the chain refuses."""
    cfg = state.cfg
    xj = _as_tokens(x, cfg)
    seq = int(xj.shape[1])
    split = xj.split if isinstance(xj, DNDarray) else None
    stat = _train_static(cfg, _mlp_tile_pref()) + (
        bool(_infer_flash_route(cfg, seq, split)), _interpret(),
    )
    fwd = _infer_fn_for(stat)

    if enabled() and _fusion.enabled():
        lg = _fusion.defer_app(
            fwd, "tf-infer", (state.theta, xj),
            static=stat, sink=True, out_split=None, kind="transformer",
        )
        if lg is not None:
            if _MON.enabled:
                _instr.transformer_event("infer-fused")
            return lg

    xc = xj.parray if isinstance(xj, DNDarray) else xj
    logits = fwd(state.theta.parray, xc)
    if _MON.enabled:
        _instr.transformer_event("infer-eager")
    return _factories.array(logits, dtype=_types.float32, copy=False)


def read_loss(loss: DNDarray) -> float:
    """The per-step materialization barrier: flush the train chain
    (attributed ``fusion.flush_reason{transformer}``) and return the host
    scalar loss."""
    with _fusion.flush_reason("transformer"):
        return float(np.asarray(loss.larray))


def read_logits(logits: DNDarray) -> np.ndarray:
    """Materialization barrier for :func:`infer_step` logits."""
    with _fusion.flush_reason("transformer"):
        return np.asarray(logits.larray)


# --------------------------------------------------- DP/DASO tree surface
def init_tree(cfg: TransformerConfig) -> dict:
    """The UNPACKED param pytree for the DP/DASO trainers — numerically
    identical views of the same seeded packed initialization."""
    lay, _total = _layout(cfg.vocab, cfg.dim, cfg.heads, cfg.depth,
                          cfg.mlp_ratio, cfg.max_seq)
    flat = _init_flat(cfg)
    return {
        name: jnp.asarray(flat[off:off + size].reshape(shape), cfg.jnp_dtype)
        for name, shape, off, size in lay
    }


def apply_tree(params: dict, x, cfg: TransformerConfig):
    """The shared forward over the unpacked pytree (dense attention — the
    trainer step differentiates it)."""
    return _forward_p(
        params, jnp.asarray(x, jnp.int32), dim=cfg.dim, heads=cfg.heads,
        depth=cfg.depth, mlp_tile=_mlp_tile_pref(), flash=False,
        interpret=False,
    )


def tree_loss(params, apply_fn, x, y):
    """``loss_fn(params, apply_fn, x, y)`` in the DP/DASO trainer signature:
    mean next-token cross-entropy of the shared forward."""
    return _xent(apply_fn(params, x), jnp.asarray(y, jnp.int32))


class TransformerModule:
    """The flax-free ``.init/.apply`` adapter :class:`DataParallel` and
    DASO's local module expect — deterministic seeded init (the rng is
    accepted and ignored: replicated identical init is the DP contract)."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg

    def init(self, rng, x):
        del rng, x
        return init_tree(self.cfg)

    def apply(self, params, x):
        return apply_tree(params, x, self.cfg)


# ------------------------------------------------- warmup app-rebuilders
#
# The cross-process rebuild hooks (ISSUE 20 satellite): the serving warmup
# imports this module lazily (kind == module name) and asks for the SAME
# memoized callable a live recorder would use — the corpus-recorded
# train-step signature then AOT-compiles in a fresh process at zero live
# traffic.
for _opname, _builder in (
    ("tf-grad", _vg_fn_for),
    ("tf-momentum", _mom_fn_for),
    ("tf-update", _upd_fn_for),
    ("tf-loss", _loss_pick_fn_for),
    ("tf-infer", _infer_fn_for),
):
    _fusion.register_app_rebuilder("transformer", _opname, _builder)
del _opname, _builder
