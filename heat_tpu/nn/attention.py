"""
Long-context attention: ring (sequence-parallel) and Ulysses (all-to-all head-parallel)
attention over the device mesh.

The reference has no transformer code; its mechanism for scaling one huge axis is the
ring-systolic sweep of ``heat/spatial/distance.py:209-494`` (stationary row slabs,
rotating column slabs). Ring attention is the same communication pattern with an
online-softmax accumulator instead of a distance tile write-back, so this module
generalizes the machinery of :mod:`heat_tpu.spatial.distance` to attention:

- :func:`ring_attention` — queries stay put, (K, V) blocks rotate around the ring via
  ``lax.ppermute`` (one ICI hop per step), each step rescales the running
  (max, denominator, numerator) triple exactly as flash attention does. Memory per
  device is O(seq/p · seq/p) for the score tile, so sequence length scales linearly
  with the ring size.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from sequence-split to
  head-split, runs dense attention locally, and re-shards back (DeepSpeed-Ulysses
  pattern); cheaper than the ring when heads ≥ devices and the full sequence fits.

Both accept either raw ``jax.Array`` inputs of shape ``(batch, seq, heads, head_dim)``
plus a :class:`~heat_tpu.core.communication.MeshCommunication`, or sequence-split
(``split=1``) :class:`~heat_tpu.core.dndarray.DNDarray` operands.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core._compat import shard_map as _shard_map
from ..core.communication import MeshCommunication, sanitize_comm
from ..core.dndarray import DNDarray
from ..core import pallas as _PL
from ..core import types

__all__ = ["scaled_dot_product_attention", "ring_attention", "ulysses_attention"]


def _heat_flash_ok(q: jax.Array, k: jax.Array, v: jax.Array) -> bool:
    """Whether the repo's own pallas flash kernel
    (:mod:`heat_tpu.core.pallas.flash`) may take this dispatch: the tier's
    registry predicates (platform/hatch/dtype), the kernel's tiling bounds,
    and — because a compiled ``pallas_call`` has no GSPMD partitioning rule —
    either the interpreter or a provably single-device placement. This is the
    fused path for the multi-device GSPMD case the jax TPU kernel refuses
    (and for single-tile sequence lengths its 128-block tiling cannot
    divide). The ``sq == 1`` autoregressive decode case (ISSUE 19) rides
    the relaxed :func:`~heat_tpu.core.pallas.flash.shape_ok` K-side rule,
    so a bucketed KV-cache capacity (320, 1536, a mined edge) no longer
    silently falls back to the dense jnp path."""
    from ..core.pallas import flash as _plflash

    if q.ndim != 4 or k.shape != v.shape or q.shape[-1] != k.shape[-1]:
        return False
    if not (q.dtype == k.dtype == v.dtype):
        return False
    shape_ok = _plflash.shape_ok(q.shape[1], k.shape[1], q.shape[-1])
    if not _PL.available("flash_ring", dtype=q.dtype, shape_ok=shape_ok):
        return False
    if _PL.use_interpret():
        return True  # interpret mode discharges to partitionable jax ops
    try:
        return len(q.devices()) == 1
    except Exception:
        return jax.device_count() == 1


def _flash_available(q: jax.Array, k: jax.Array) -> bool:
    """Whether the pallas TPU flash-attention kernel applies: single-device TPU
    operands (the distributed paths handle their own blocking), floating dtypes,
    and sequence lengths the kernel's fixed 128-block tiling divides."""
    if jax.default_backend() != "tpu":
        return False
    if q.shape[1] % 128 != 0 or k.shape[1] % 128 != 0:
        return False
    head_dim = q.shape[-1]
    if head_dim > 128 and head_dim % 128 != 0:  # kernel rejects such head dims
        return False
    try:
        if len(q.devices()) != 1:
            return False
    except Exception:
        # Traced values carry no placement; inside jit the kernel is only safe
        # when the whole program runs on one device (no sharding possible —
        # with more devices a batch-sharded operand could reach the unpartitioned
        # pallas call, so fall back to dense XLA which shards under GSPMD).
        if jax.device_count() != 1:
            return False
    return q.dtype in (jnp.float32, jnp.bfloat16)


def scaled_dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """
    Attention on ``(batch, seq, heads, head_dim)`` operands.

    ``impl``: ``"auto"`` uses the fused pallas flash-attention kernel on a single
    TPU device (one HBM pass, no materialised score matrix) and the dense XLA
    formulation elsewhere; ``"dense"``/``"flash"`` force a path.
    """
    if impl not in ("auto", "dense", "flash"):
        raise ValueError(f"impl must be 'auto', 'dense' or 'flash', got {impl!r}")
    if impl == "flash" and jax.default_backend() != "tpu":
        # the forced kernel used to die deep inside the
        # jax.experimental.pallas TPU lowering on other backends — name the
        # requirement instead (ISSUE 10 satellite)
        raise ValueError(
            "impl='flash' requires the TPU backend (the fused "
            "jax.experimental.pallas flash-attention kernel only lowers for "
            f"TPU), but jax.default_backend() is {jax.default_backend()!r}; "
            "use impl='auto' or impl='dense' here"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "auto" and not _flash_available(q, k) and _heat_flash_ok(q, k, v):
        # the repo's own flash kernel (heat_tpu/core/pallas/flash.py): the
        # multi-device GSPMD path (and single-tile sequence lengths) that the
        # jax TPU kernel's availability test refuses and that previously fell
        # back to dense; a failed dispatch degrades to dense, counted
        from ..core.pallas import flash as _plflash

        try:
            _PL.execute_guard()
            o = _plflash.attention_local(
                q, k, v, causal=causal, scale=scale, interpret=_PL.use_interpret()
            )
            _PL.dispatch("flash_ring")
            return o
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:
            _PL.fallback("execute")
    if impl == "flash" or (impl == "auto" and _flash_available(q, k)):
        from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

        o = flash_attention(
            # kernel layout is (batch, heads, seq, head_dim)
            jnp.transpose(q, (0, 2, 1, 3)),
            jnp.transpose(k, (0, 2, 1, 3)),
            jnp.transpose(v, (0, 2, 1, 3)),
            causal=causal,
            sm_scale=scale,
        )
        return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])
        k_pos = jnp.arange(k.shape[1])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, -jnp.inf)
    o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    return o.astype(q.dtype)


def _ring_attention_sharded(
    axis: str, p: int, causal: bool, scale: float,
    use_pallas: bool = False, interpret: bool = False,
):
    """Build the per-device ring body (runs under shard_map).

    With ``use_pallas`` the per-hop online-softmax update runs as the
    hand-tiled flash kernel (:mod:`heat_tpu.core.pallas.flash`): the running
    (max, denominator, numerator) triple stays VMEM-resident across the
    hop's K/V tiles instead of materializing the score/probability matrices
    as separate jnp passes. Same recurrence, same ppermute schedule; the
    caller owns availability and degradation."""
    perm = [(i, (i - 1) % p) for i in range(p)]  # rotate K/V blocks towards lower ranks

    if use_pallas:
        from ..core.pallas import flash as _plflash

        def ring(q_blk: jax.Array, k_blk: jax.Array, v_blk: jax.Array) -> jax.Array:
            i0 = lax.axis_index(axis)
            b, s_blk, h, d = q_blk.shape
            bh = b * h

            def merge(x):
                return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, s_blk, d)

            qm = merge(q_blk).astype(jnp.float32)
            q_pos = i0 * s_blk + jnp.arange(s_blk, dtype=jnp.int32)
            m0 = jnp.full((bh, s_blk), -jnp.inf, jnp.float32)
            l0 = jnp.zeros((bh, s_blk), jnp.float32)
            o0 = jnp.zeros((bh, s_blk, d), jnp.float32)

            def accumulate(k_cur, v_cur, m, l, o, t):
                j = (i0 + t) % p
                k_pos = j * s_blk + jnp.arange(s_blk, dtype=jnp.int32)
                return _plflash.tile_update(
                    qm, merge(k_cur), merge(v_cur), m, l, o,
                    scale=scale, causal=causal, q_pos=q_pos, k_pos=k_pos,
                    interpret=interpret,
                )

            def step(carry, t):
                k_cur, v_cur, m, l, o = carry
                m, l, o = accumulate(k_cur, v_cur, m, l, o, t)
                k_next = lax.ppermute(k_cur, axis, perm)
                v_next = lax.ppermute(v_cur, axis, perm)
                return (k_next, v_next, m, l, o), None

            (k_last, v_last, m, l, o), _ = lax.scan(
                step, (k_blk, v_blk, m0, l0, o0), jnp.arange(p - 1)
            )
            _, l, o = accumulate(k_last, v_last, m, l, o, p - 1)
            out = (o / l[..., None]).reshape(b, h, s_blk, d)
            return jnp.transpose(out, (0, 2, 1, 3)).astype(q_blk.dtype)

        return ring

    def ring(q_blk: jax.Array, k_blk: jax.Array, v_blk: jax.Array) -> jax.Array:
        # q_blk/k_blk/v_blk: (b, s/p, h, d) — this device's sequence block.
        i0 = lax.axis_index(axis)
        b, s_blk, h, d = q_blk.shape
        q32 = q_blk.astype(jnp.float32)
        q_pos = i0 * s_blk + jnp.arange(s_blk)
        m0 = jnp.full((b, h, s_blk), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, s_blk), jnp.float32)
        o0 = jnp.zeros((b, h, s_blk, d), jnp.float32)

        def accumulate(k_cur, v_cur, m, l, o, t):
            # block index currently held: step 0 is our own block, so causal rows
            # see their diagonal first and the running max is finite from the start.
            j = (i0 + t) % p
            s = jnp.einsum("bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32)) * scale
            if causal:
                k_pos = j * s_blk + jnp.arange(s_blk)
                s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)  # 0 at the first step (m = -inf, m_new finite)
            prob = jnp.exp(s - m_new[..., None])
            l = l * alpha + jnp.sum(prob, axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", prob, v_cur.astype(jnp.float32)
            )
            return m_new, l, o

        def step(carry, t):
            k_cur, v_cur, m, l, o = carry
            m, l, o = accumulate(k_cur, v_cur, m, l, o, t)
            k_next = lax.ppermute(k_cur, axis, perm)
            v_next = lax.ppermute(v_cur, axis, perm)
            return (k_next, v_next, m, l, o), None

        # p-1 permuted rounds, then the last held block without the (discarded)
        # final rotation — p-1 ICI hops total, not p.
        (k_last, v_last, m, l, o), _ = lax.scan(
            step, (k_blk, v_blk, m0, l0, o0), jnp.arange(p - 1)
        )
        _, l, o = accumulate(k_last, v_last, m, l, o, p - 1)
        out = o / l[..., None]
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q_blk.dtype)

    return ring


def ring_attention(
    q: Union[jax.Array, DNDarray],
    k: Union[jax.Array, DNDarray],
    v: Union[jax.Array, DNDarray],
    comm: Optional[MeshCommunication] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> Union[jax.Array, DNDarray]:
    """
    Sequence-parallel attention: Q blocks stationary, (K, V) blocks rotate around the
    ``ppermute`` ring with a flash-style online softmax (the comm pattern of the
    reference's ring ``_dist``, distance.py:279-346, with attention accumulators).

    Operands are ``(batch, seq, heads, head_dim)``; the sequence axis is sharded over
    the mesh. Falls back to dense attention when not distributed or the sequence axis
    doesn't shard evenly.
    """
    if isinstance(q, DNDarray):
        return _dnd_attention(ring_attention, q, k, v, causal=causal, scale=scale)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    comm = sanitize_comm(comm)
    if (
        not isinstance(comm, MeshCommunication)
        or not comm.is_distributed()
        or q.shape[1] % comm.size != 0
        or k.shape[1] != q.shape[1]
    ):
        return scaled_dot_product_attention(q, k, v, causal=causal, scale=scale)
    axis = comm.axis_name

    def build(use_pallas: bool, interpret: bool = False):
        return _shard_map(
            _ring_attention_sharded(
                axis, comm.size, causal, scale, use_pallas, interpret
            ),
            mesh=comm.mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
            check_vma=False,
        )

    # pallas flash inner tile (ISSUE 10): the per-device K/V block extents
    # are static here, so availability is decided once per call; a failed
    # kernel dispatch degrades to the plain-jnp ring body, counted
    from ..core.pallas import flash as _plflash

    s_blk = q.shape[1] // comm.size
    if (
        q.dtype == k.dtype == v.dtype
        and k.shape == v.shape
        and _PL.available(
            "flash_ring",
            dtype=q.dtype,
            shape_ok=_plflash.shape_ok(s_blk, s_blk, q.shape[-1]),
        )
    ):
        try:
            _PL.execute_guard()
            out = build(True, _PL.use_interpret())(q, k, v)
            _PL.dispatch("flash_ring")
            return out
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:
            _PL.fallback("execute")
    return build(False)(q, k, v)


def ulysses_attention(
    q: Union[jax.Array, DNDarray],
    k: Union[jax.Array, DNDarray],
    v: Union[jax.Array, DNDarray],
    comm: Optional[MeshCommunication] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> Union[jax.Array, DNDarray]:
    """
    All-to-all sequence parallelism (DeepSpeed-Ulysses): re-shard sequence-split
    operands to head-split with one ``lax.all_to_all``, run dense attention on the
    full sequence locally, and re-shard back. Requires ``heads % p == 0``; falls back
    to dense attention (or the ring) otherwise.
    """
    if isinstance(q, DNDarray):
        return _dnd_attention(ulysses_attention, q, k, v, causal=causal, scale=scale)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    comm = sanitize_comm(comm)
    if (
        not isinstance(comm, MeshCommunication)
        or not comm.is_distributed()
        or q.shape[1] % comm.size != 0
        or q.shape[2] % comm.size != 0
        or k.shape[1] % comm.size != 0
        or v.shape[1] != k.shape[1]
    ):
        return scaled_dot_product_attention(q, k, v, causal=causal, scale=scale)
    axis = comm.axis_name

    def body(q_blk, k_blk, v_blk):
        # (b, s/p, h, d) -> all_to_all -> (b, s, h/p, d): full sequence, head shard
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        o = scaled_dot_product_attention(
            to_heads(q_blk), to_heads(k_blk), to_heads(v_blk), causal=causal, scale=scale
        )
        return to_seq(o)

    fn = _shard_map(
        body,
        mesh=comm.mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return fn(q, k, v)


def _dnd_attention(impl, q: DNDarray, k: DNDarray, v: DNDarray, **kw) -> DNDarray:
    """DNDarray front-end: operands must share split (sequence axis 1 when split)."""
    for t in (q, k, v):
        if not isinstance(t, DNDarray):
            raise TypeError("q, k, v must all be DNDarrays (or all jax arrays)")
        if t.ndim != 4:
            raise ValueError("attention operands must be (batch, seq, heads, head_dim)")
        if t.split not in (None, 1):
            raise ValueError("attention operands must be split on the sequence axis (1)")
        if t.comm is not q.comm:
            raise ValueError("q, k, v must share one communicator/mesh")
    out = impl(q.larray, k.larray, v.larray, comm=q.comm, **kw)
    return DNDarray(
        out, q.shape, types.canonical_heat_type(out.dtype), q.split, q.device, q.comm, True
    )
