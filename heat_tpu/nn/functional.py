"""
Functional NN interface.

Parity with the reference's ``heat/nn/functional.py`` (:9-33), which is a
module-level ``__getattr__`` falling through to ``torch.nn.functional``. The
TPU-native fallthrough targets ``jax.nn`` (activations, softmax, one_hot, …) and then
``flax.linen`` for anything jax.nn lacks.
"""

from __future__ import annotations

import jax.nn as _jnn

try:
    import flax.linen as _fnn
except ImportError:  # pragma: no cover - flax is baked into the target image
    _fnn = None


def __getattr__(name: str):
    """Fall through to jax.nn, then flax.linen (reference functional.py:9-33)."""
    if hasattr(_jnn, name):
        return getattr(_jnn, name)
    if _fnn is not None and hasattr(_fnn, name):
        return getattr(_fnn, name)
    raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute {name!r}")
