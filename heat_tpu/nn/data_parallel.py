"""
Data-parallel neural network training.

Parity with the reference's ``heat/nn/data_parallel.py``: there ``DataParallel``
(:21) wraps a ``torch.nn.Module``, seeds all ranks identically, and registers
per-parameter backward hooks that ``Allreduce``/``Iallreduce`` gradients (:223-278),
with forward pre-hooks draining handles just-in-time (:140-222).
``DataParallelMultiGPU`` (:314) adds intra-node NCCL replication for DASO.

The TPU-native redesign: parameters are replicated over the mesh, the batch is
sharded over the ``data`` axis, and the whole train step is one jitted SPMD program —
XLA inserts exactly the gradient psum the reference's hooks perform, overlapped with
backward compute by the latency-hiding scheduler. The wrapper owns (module, params,
mesh) and hands out jitted train/eval steps; there is nothing to hook because the
collective is part of the compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.communication import MeshCommunication, sanitize_comm
from ..core.dndarray import DNDarray
from ..monitoring import instrument as _instr
from ..monitoring.registry import STATE as _MON
from ..robustness import preemption as _preempt

__all__ = ["DataParallel", "DataParallelMultiGPU"]


def pad_or_trim_batch(a: jax.Array, world: int, ragged: str, warn_holder) -> jax.Array:
    """
    Resolve a batch whose leading axis is not divisible by ``world`` devices.
    ``ragged='cycle'`` pads by wrapping rows from the batch start (every row still
    trains; the duplicates carry slightly more weight in that one batch — like the
    reference's unequal per-rank chunks averaged by the gradient allreduce);
    ``'trim'`` drops the remainder (torch DataLoader ``drop_last``). Warns once per
    ``warn_holder`` (the owning wrapper/optimizer).
    """
    if ragged not in ("cycle", "trim"):
        raise ValueError(f"ragged must be 'cycle' or 'trim', got {ragged!r}")
    n = a.shape[0]
    if n % world == 0:
        return a
    if n < world and ragged == "trim":
        raise ValueError(f"batch of {n} rows cannot be sharded over {world} devices")
    if not getattr(warn_holder, "_ragged_warned", False):
        import warnings

        warnings.warn(
            f"batch of {n} rows is not divisible by the {world}-device mesh; "
            f"policy {ragged!r} applies to every such batch ('cycle' wraps rows "
            "from the batch start, 'trim' drops the remainder). Size batches as "
            "a multiple of the device count for exact weighting.",
            RuntimeWarning,
            stacklevel=4,
        )
        warn_holder._ragged_warned = True
    if ragged == "cycle":
        target = -(-n // world) * world
        reps = jnp.take(a, jnp.arange(target - n) % n, axis=0)
        return jnp.concatenate([a, reps], axis=0)
    return a[: (n // world) * world]


class DataParallel:
    """
    Distributed data-parallel wrapper around a flax module (or a pure
    ``apply(params, x)`` function).

    Parameters
    ----------
    module :
        A ``flax.linen.Module`` or any object with ``.init(rng, x)`` and
        ``.apply(params, x)``.
    comm : MeshCommunication, optional
        Communicator whose mesh carries the ``data`` axis; defaults to the world
        communicator (all devices, 1-D).
    optimizer :
        An optax gradient transformation (optional; can also be supplied to
        :meth:`make_train_step`).
    blocking : bool
        Parity flag with the reference's blocking/non-blocking hook modes
        (data_parallel.py:223-278); under jit both compile to the same overlapped
        psum, so this only gates an explicit ``block_until_ready`` after each step.

    Reference parity: heat/nn/data_parallel.py:21-313.
    """

    def __init__(self, module, comm: Optional[MeshCommunication] = None, optimizer=None, blocking: bool = False):
        self.module = module
        self.comm = sanitize_comm(comm)
        self.optimizer = optimizer
        self.blocking = blocking
        self.params = None
        self.opt_state = None
        self.step_count = 0
        self._train_step = None
        self._loss_fn = None
        self._elastic = None

    # ------------------------------------------------------------------ mesh helpers
    @property
    def mesh(self) -> Mesh:
        """The device mesh used for data parallelism."""
        return self.comm.mesh

    @property
    def data_axis(self) -> str:
        """Mesh axis name the batch is sharded over."""
        return self.comm.axis_name

    def batch_sharding(self, ndim: int) -> NamedSharding:
        """Sharding that splits axis 0 (the batch) over the data axis."""
        return NamedSharding(self.mesh, P(self.data_axis, *([None] * (ndim - 1))))

    def replicated(self) -> NamedSharding:
        """Fully replicated sharding (for parameters)."""
        return NamedSharding(self.mesh, P())

    def shard_batch(self, *arrays, ragged: str = "cycle"):
        """
        Place arrays with the batch axis sharded over the mesh. A batch whose
        length is not divisible by the device count is handled per ``ragged``:

        - ``'cycle'`` (default): pad by wrapping rows from the batch start — every
          row still trains (the duplicated rows carry slightly more weight in that
          one batch, like the reference's unequal per-rank chunks averaged by the
          gradient allreduce).
        - ``'trim'``: drop the remainder rows (torch DataLoader ``drop_last``).
        """
        world = self.comm.size
        out = []
        for a in arrays:
            if isinstance(a, DNDarray):
                a = a.larray
            a = jnp.asarray(a)
            if a.ndim > 0:
                a = pad_or_trim_batch(a, world, ragged, self)
                a = jax.device_put(a, self.batch_sharding(a.ndim))
            out.append(a)
        return out[0] if len(out) == 1 else tuple(out)

    # ------------------------------------------------------------------ param setup
    def init(self, rng: int | jax.Array, *sample) -> Any:
        """
        Initialize parameters identically on every device (the reference seeds all
        ranks the same and broadcasts, data_parallel.py:108-109 — replication gives
        this for free).
        """
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        sample = [s.larray if isinstance(s, DNDarray) else jnp.asarray(s) for s in sample]
        params = self.module.init(rng, *sample)
        self.params = jax.device_put(params, self.replicated())
        if self.optimizer is not None:
            self.opt_state = self.optimizer.init(self.params)
        return self.params

    def __call__(self, *args, params=None):
        """Forward pass with the current (replicated) parameters."""
        params = self.params if params is None else params
        args = [a.larray if isinstance(a, DNDarray) else jnp.asarray(a) for a in args]
        return self.module.apply(params, *args)

    # ------------------------------------------------------------------ training
    def make_train_step(self, loss_fn: Callable, optimizer=None) -> Callable:
        """
        Builds the jitted SPMD train step:
        ``step(params, opt_state, *batch) -> (params, opt_state, loss)``.

        ``loss_fn(apply_out..., *batch_tail)``? No — signature:
        ``loss_fn(params, apply_fn, *batch) -> scalar loss``. The mean over the
        sharded batch makes XLA emit the gradient psum over the ``data`` axis — the
        entire reference hook machinery (data_parallel.py:223-298).
        """
        optimizer = optimizer or self.optimizer
        if optimizer is None:
            raise ValueError("an optax optimizer is required to build a train step")
        apply_fn = self.module.apply
        rep = self.replicated()

        @jax.jit
        def step(params, opt_state, *batch):
            def lossf(p):
                return loss_fn(p, apply_fn, *batch)

            loss, grads = jax.value_and_grad(lossf)(params)
            updates, opt_state2 = optimizer.update(grads, opt_state, params)
            params2 = optax.apply_updates(params, updates)
            return params2, opt_state2, loss

        self._train_step = step
        return step

    def attach_elastic(self, supervisor) -> None:
        """Attach an :class:`~heat_tpu.robustness.elastic.ElasticSupervisor`:
        every :meth:`train_step` then heartbeats + probes peers BEFORE
        dispatching (a collective against a dead peer would hang — the poll
        must precede the doomed dispatch), and a detected peer loss drains,
        checkpoints the last step-boundary state, and raises
        :class:`~heat_tpu.robustness.elastic.PeerLostError` for the worker's
        main to exit ``ELASTIC_RESTART_EXIT``."""
        self._elastic = supervisor

    def train_step(self, *batch) -> jax.Array:
        """Run one jitted update on the stored (params, opt_state); returns the
        loss."""
        if self._train_step is None:
            raise RuntimeError("call make_train_step(loss_fn, optimizer) first")
        # elastic contract: poll at the step boundary, before any dispatch —
        # the state saved on peer loss is the previous boundary's consistent
        # snapshot, and the collective that would hang never launches
        if self._elastic is not None:
            self._elastic.check(self.checkpoint_state, self.step_count)
        batch = self.shard_batch(*batch)
        if not isinstance(batch, tuple):
            batch = (batch,)
        if _MON.enabled:
            # per-step throughput span: the device-time mark (block on the
            # loss) makes rows/s honest under async dispatch
            import time as _time

            rows = int(batch[0].shape[0]) if getattr(batch[0], "ndim", 0) else 0
            t0 = _time.perf_counter()
            self.params, self.opt_state, loss = self._train_step(
                self.params, self.opt_state, *batch
            )
            jax.block_until_ready(loss)
            _instr.step_event("dp.train_step", _time.perf_counter() - t0, rows=rows)
        else:
            self.params, self.opt_state, loss = self._train_step(
                self.params, self.opt_state, *batch
            )
        if self.blocking:
            jax.block_until_ready(loss)
        self.step_count += 1
        # preemption contract: the step boundary is the only place (params,
        # opt_state) is a consistent snapshot — a SIGTERM seen by an active
        # PreemptionGuard lands a checkpoint HERE, not in signal context
        if _preempt.should_checkpoint():
            _preempt.checkpoint_now(self.checkpoint_state(), step=self.step_count)
        return loss

    def checkpoint_state(self) -> dict:
        """The pytree a preemption (or user-initiated) checkpoint persists:
        replicated params, optimizer state, and the step counter — with the
        global RNG state riding along inside ``save_checkpoint``. Restore with
        ``CheckpointManager.restore_latest_valid(dp.checkpoint_state())`` and
        :meth:`load_state`."""
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "step": self.step_count,
        }

    def load_state(self, state: dict) -> None:
        """Adopt a restored :meth:`checkpoint_state` pytree (the resume half
        of the preemption contract)."""
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.step_count = int(state["step"])


class DataParallelMultiGPU(DataParallel):
    """
    Hierarchical data parallelism partner of DASO (reference
    data_parallel.py:314-376, where it wraps the model in torch DDP over intra-node
    NCCL). Here the hierarchy is a 2-D ``(node, local)`` mesh owned by the DASO
    optimizer; this wrapper simply binds that mesh's flattened data axis.
    """

    def __init__(self, module, optimizer=None, comm: Optional[MeshCommunication] = None):
        super().__init__(module, comm=comm, optimizer=getattr(optimizer, "local_optimizer", optimizer))
        self.daso = optimizer
