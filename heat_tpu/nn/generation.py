"""
Autoregressive decode with persistent KV-cache state (ISSUE 19, ROADMAP
item 2): one decode step = ONE fused chain over a persistent cache.

The generative-serving thesis is the fusion engine's amortization argument
applied across *time*: a decode loop re-executes one small program thousands
of times, so everything per-step must be cache-hits — no compile, no
allocation, no per-op dispatch. Three mechanisms compose here:

**One fused chain per step.** A decode step records exactly three nodes via
:func:`~heat_tpu.core.fusion.defer_app`: ``append_k`` and ``append_v``
(embed the step's tokens, project, and write each request's row at its own
cache position via a vmapped ``dynamic_update_slice``) and a root
``attend`` SINK (project q, attend over the just-appended caches at ragged
per-request lengths, project out, tied-embedding logits). Because the root
is a sink and the new cache DNDarrays stay alive in the returned
:class:`KVCache`, ``materialize_for`` widens the flush: the logits AND both
updated caches return from the SAME jitted kernel — three outputs, one
dispatch, one trace-cache entry.

**Steady-state donation.** The *previous* step's cache buffers enter the
chain as dead-owner leaves (the scheduler rebinds its ``KVCache`` before
reading logits), shape/dtype-matching the append outputs — the PR 3
donation machinery aliases them to the new caches, so a steady-state decode
step allocates nothing and the L1 key (program, leaves, donation mask,
outputs) is IDENTICAL every step: ``fusion.kernels_compiled == 0`` after
the first step, proven re-donation via ``fusion.donated{steady_state}``.

**Bucketed capacities.** Cache capacity is chosen at *allocation* time from
:func:`heat_tpu.serving.buckets.effective` edges (pow2 default, PR 18
corpus-mined edges when tuning is armed), so the compiled-kernel count is
bounded by the bucket count as sequences grow — and the flush itself stays
un-bucketed (flush-time bucketing would void donation).

Ragged lengths ride as a traced ``(B,)`` i32 leaf: per-request masking
changes VALUES, never the program, so requests of distinct lengths share
one kernel. Attention routes to flash's M=1 decode kernel
(:func:`heat_tpu.core.pallas.flash.attention_decode`) when the pallas tier
admits it, else the dense jnp reference — the choice is baked into the
node's stable identity so the two never alias in any cache.

Everything is gated behind ``HEAT_TPU_GENERATION=1``; off (the default)
:func:`decode_step` runs the eager per-op reference path — bit-for-bit the
pre-ISSUE-19 engine, and the differential oracle for the fused chain.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories as _factories
from ..core import fusion as _fusion
from ..core import types as _types
from ..core.dndarray import DNDarray

__all__ = [
    "enabled",
    "capacity_for",
    "ToyModel",
    "KVCache",
    "decode_step",
    "read_logits",
    "greedy",
    "generate_reference",
    "digest_of_tokens",
]


def enabled() -> bool:
    """Whether the fused generation decode path is armed
    (``HEAT_TPU_GENERATION=1``; one env read — the off-path cost). Off, a
    :func:`decode_step` runs the eager per-op reference — bit-for-bit the
    pre-ISSUE-19 engine."""
    return os.environ.get("HEAT_TPU_GENERATION", "").strip().lower() in (
        "1", "true", "on",
    )


#: Fallback bucket spec for cache capacities when no policy is configured:
#: pow2 edges up to 1024, linear 1024-multiples above (serving/buckets.py).
_DEFAULT_BUCKETS = "pow2"

#: Floor capacity — below this the bucket ladder would churn kernels for
#: trivial sequence-length differences.
MIN_CAPACITY = 16


def capacity_for(n: int, spec: Optional[str] = None) -> int:
    """The bucketed KV-cache capacity for ``n`` tokens: the smallest edge
    >= n of the effective bucket policy (``HEAT_TPU_GENERATION_BUCKETS``,
    default pow2; the PR 18 corpus-mined edges replace the parsed policy
    when ``HEAT_TPU_TUNING=1`` is armed), floored at :data:`MIN_CAPACITY`.
    Capacity bucketing happens at *allocation* time, so the per-step fused
    flush keys on exact shapes and donation stays live."""
    from ..serving import buckets as _buckets

    if spec is None:
        spec = os.environ.get("HEAT_TPU_GENERATION_BUCKETS", "").strip() or (
            _DEFAULT_BUCKETS
        )
    parsed = _buckets.effective(spec)
    if parsed is None:
        parsed = _buckets.policy(_DEFAULT_BUCKETS)
    edges, tail = parsed
    return max(MIN_CAPACITY, _buckets.bucket_dim(max(1, int(n)), edges, tail))


# ------------------------------------------------------------------ toy model
class ToyModel:
    """A deterministic single-layer attention LM — the smallest model that
    exercises the full cache-state machinery (ISSUE 19 scopes the tentpole
    to the scheduler/cache work, not the ROADMAP item 1 transformer).

    Parameters are seeded host-side (``np.random.default_rng``) and held as
    jax arrays ON the model object — the live references keep the donation
    pass from ever aliasing a weight buffer (strict refcount bound in
    ``fusion._donatable``). Logits tie the embedding (``h @ E.T``) in f32.
    """

    def __init__(self, vocab: int = 64, dim: int = 32, heads: int = 2,
                 head_dim: int = 8, seed: int = 0, dtype: str = "float32"):
        if dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unsupported generation model dtype {dtype!r}")
        self.vocab, self.dim = int(vocab), int(dim)
        self.heads, self.head_dim = int(heads), int(head_dim)
        self.seed, self.dtype = int(seed), dtype
        jdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        rng = np.random.default_rng(self.seed)

        def w(shape, scale):
            return jnp.asarray(rng.standard_normal(shape) * scale, jdt)

        self.E = w((self.vocab, self.dim), 0.4)
        # positional rows (indexed by each slot's ragged length, mod table
        # size): without them a greedy toy LM hits an argmax fixed point in a
        # few steps and every differential/digest test would compare constant
        # sequences
        self.P = w((64, self.dim), 0.5)
        self.Wq = w((self.dim, self.heads * self.head_dim), 0.3)
        self.Wk = w((self.dim, self.heads * self.head_dim), 0.3)
        self.Wv = w((self.dim, self.heads * self.head_dim), 0.3)
        self.Wo = w((self.heads * self.head_dim, self.dim), 0.3)
        self.scale = float(self.head_dim) ** -0.5

    @property
    def jnp_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32

    @property
    def heat_dtype(self):
        return _types.bfloat16 if self.dtype == "bfloat16" else _types.float32

    @classmethod
    def from_env(cls) -> "ToyModel":
        """The serving-side model: seeded by ``HEAT_TPU_GENERATION_SEED``
        (default 0) at the fixed toy geometry, so a loadgen client computes
        bit-identical expected digests without any weight exchange."""
        return cls(seed=int(os.environ.get("HEAT_TPU_GENERATION_SEED", "0") or 0))


# ---------------------------------------------------------------- kernels
#
# One memoized callable per static configuration: ``defer_app`` keys the
# trace cache on the fn's object identity and the L2 digest on
# (opname, static) — both shear unless the SAME object serves every step.
_FNS: dict = {}


def _append_fn_for(heads: int, head_dim: int):
    """Embed + project the step's tokens and write each request's (1, H, D)
    row at its own cache position — the in-place KV append (positions are
    traced, so ragged lengths share one kernel; XLA CSEs the embedding
    gather with the attend node's inside the fused program)."""
    key = ("append", heads, head_dim)
    fn = _FNS.get(key)
    if fn is None:
        def fn(cache, emb, pemb, w, tokens, lengths, _h=heads, _d=head_dim):
            x = jnp.take(emb, tokens, axis=0)
            x = x + jnp.take(pemb, lengths % pemb.shape[0], axis=0)
            proj = jnp.dot(x, w).reshape(x.shape[0], _h, _d).astype(cache.dtype)
            pos = jnp.clip(lengths, 0, cache.shape[1] - 1)

            def put(c, p, u):
                return jax.lax.dynamic_update_slice(c, u[None], (p, 0, 0))

            return jax.vmap(put)(cache, pos, proj)

        _FNS[key] = fn
    return fn


def _attend_fn_for(heads: int, head_dim: int, scale: float, flash: bool,
                   interpret: bool):
    """Project q, attend over the appended caches at ragged per-request
    lengths, project out with a residual, and emit tied-embedding f32
    logits. ``flash`` bakes the M=1 pallas decode route vs the dense jnp
    reference into the node identity (the two differ by the kernel's
    documented reassociation carve-out and must never alias in a cache)."""
    key = ("attend", heads, head_dim, float(scale), bool(flash), bool(interpret))
    fn = _FNS.get(key)
    if fn is None:
        def fn(kc, vc, emb, pemb, wq, wo, tokens, lengths, _h=heads,
               _d=head_dim, _scale=float(scale), _flash=bool(flash),
               _interp=bool(interpret)):
            x = jnp.take(emb, tokens, axis=0)
            x = x + jnp.take(pemb, lengths % pemb.shape[0], axis=0)
            q = jnp.dot(x, wq).reshape(x.shape[0], 1, _h, _d).astype(kc.dtype)
            att = jnp.clip(lengths, 0, kc.shape[1] - 1) + 1  # incl. this step
            if _flash:
                from ..core.pallas import flash as _fl

                o = _fl.attention_decode(
                    q, kc, vc, att, scale=_scale, interpret=_interp
                )
            else:
                qf, kf, vf = (a.astype(jnp.float32) for a in (q, kc, vc))
                s = jnp.einsum("bqhd,bchd->bhqc", qf, kf) * _scale
                mask = jnp.arange(kc.shape[1])[None, :] < att[:, None]
                s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhqc,bchd->bqhd", p, vf).astype(kc.dtype)
            h = x + jnp.dot(o.reshape(o.shape[0], _h * _d).astype(x.dtype), wo)
            return jnp.dot(h.astype(jnp.float32), emb.T.astype(jnp.float32))

        _FNS[key] = fn
    return fn


def _flash_route(model: ToyModel, capacity: int, split) -> bool:
    """Whether this decode step's attention takes the pallas M=1 kernel:
    the registry predicates (platform/hatch/dtype), the relaxed decode
    ``shape_ok``, and a single-device (or interpreted) placement — a
    compiled ``pallas_call`` has no GSPMD partitioning rule."""
    from ..core import pallas as _PL
    from ..core.pallas import flash as _plflash

    if split is not None:
        return False
    ok = _plflash.shape_ok(1, int(capacity), model.head_dim)
    if not _PL.available(
        "flash_ring", dtype=np.dtype(model.jnp_dtype), shape_ok=ok
    ):
        return False
    return bool(_PL.use_interpret()) or jax.device_count() == 1


# ---------------------------------------------------------------- cache state
class KVCache:
    """The persistent decode state: ``k``/``v`` DNDarrays of shape
    ``(B, capacity, heads, head_dim)`` plus HOST-side per-slot valid lengths
    (``np.int32`` — scheduler bookkeeping; the traced copy enters each
    step's chain as a leaf). Holding the returned cache alive is the state
    contract: it is exactly what keeps the append nodes' owners live (so
    they ride the fused kernel as extra outputs) and what the NEXT step's
    leaves donate from once rebound."""

    __slots__ = ("k", "v", "lengths", "capacity")

    def __init__(self, k: DNDarray, v: DNDarray, lengths: np.ndarray,
                 capacity: int):
        self.k = k
        self.v = v
        self.lengths = np.asarray(lengths, np.int32)
        self.capacity = int(capacity)

    @property
    def batch(self) -> int:
        return int(self.k.shape[0])

    @classmethod
    def alloc(cls, model: ToyModel, batch: int, capacity: Optional[int] = None,
              split: Optional[int] = None) -> "KVCache":
        cap = int(capacity) if capacity else capacity_for(MIN_CAPACITY)
        shape = (int(batch), cap, model.heads, model.head_dim)
        k = _factories.zeros(shape, dtype=model.heat_dtype, split=split)
        v = _factories.zeros(shape, dtype=model.heat_dtype, split=split)
        return cls(k, v, np.zeros(int(batch), np.int32), cap)

    def grow(self, model: ToyModel, need: int) -> "KVCache":
        """Re-bucket to the smallest capacity edge >= ``need`` (a rare
        boundary event: one eager pad + one new kernel per bucket edge —
        the bounded-kernel-count contract). Returns self when no growth is
        needed."""
        if need <= self.capacity:
            return self
        cap = capacity_for(need)
        split = self.k.split
        pad = [(0, 0)] * 4
        pad[1] = (0, cap - self.capacity)

        def widen(d: DNDarray) -> DNDarray:
            arr = np.asarray(jnp.pad(d.larray, pad))
            return _factories.array(
                arr, dtype=model.heat_dtype, split=split, copy=False
            )

        return KVCache(widen(self.k), widen(self.v), self.lengths, cap)


# ---------------------------------------------------------------- decode step
def _decode_eager(model: ToyModel, cache: KVCache, tok, lens):
    """The eager per-op reference: the SAME memoized callables the fused
    chain records, dispatched standalone on concrete arrays — the
    differential oracle, and the serving path when the knob is off."""
    append = _append_fn_for(model.heads, model.head_dim)
    attend = _attend_fn_for(
        model.heads, model.head_dim, model.scale,
        _flash_route(model, cache.capacity, cache.k.split), _interpret(),
    )
    kc = append(cache.k.parray, model.E, model.P, model.Wk, tok, lens)
    vc = append(cache.v.parray, model.E, model.P, model.Wv, tok, lens)
    logits = attend(kc, vc, model.E, model.P, model.Wq, model.Wo, tok, lens)
    split = cache.k.split
    k2 = _factories.array(kc, dtype=model.heat_dtype, split=split, copy=False)
    v2 = _factories.array(vc, dtype=model.heat_dtype, split=split, copy=False)
    lg = _factories.array(logits, dtype=_types.float32, copy=False)
    return lg, k2, v2


def _interpret() -> bool:
    from ..core import pallas as _PL

    return bool(_PL.use_interpret())


def decode_step(model: ToyModel, cache: KVCache, tokens,
                advance=None):
    """One decode step over the persistent cache: append ``tokens`` (host
    ``(B,)`` int32, one per slot) at each slot's current length, attend over
    the appended caches, and return ``(logits, new_cache)`` — logits a
    ``(B, vocab)`` f32 DNDarray (deferred when the fused path records),
    ``new_cache`` the advanced state.

    ``advance`` (host bool ``(B,)``, default all) selects which slots'
    lengths move forward: an inactive slot still gets the (ignored) append
    at its frozen position — values change, the program never does, so
    sequences join and leave the batch at zero recompiles. The caller must
    drop its reference to the OLD cache before reading the logits: that is
    what makes the old buffers dead-owner leaves the donation pass may
    alias (the steady-state zero-allocation contract)."""
    B = cache.batch
    tok = jnp.asarray(np.asarray(tokens, np.int32).reshape(B))
    lens = jnp.asarray(cache.lengths)
    if advance is None:
        new_lengths = cache.lengths + 1
    else:
        new_lengths = cache.lengths + np.asarray(advance, np.int32).reshape(B)

    if enabled() and _fusion.enabled():
        append = _append_fn_for(model.heads, model.head_dim)
        attend = _attend_fn_for(
            model.heads, model.head_dim, model.scale,
            _flash_route(model, cache.capacity, cache.k.split), _interpret(),
        )
        stat = (model.heads, model.head_dim)
        split = cache.k.split
        kc = _fusion.defer_app(
            append, "gen-append",
            (cache.k, model.E, model.P, model.Wk, tok, lens),
            static=stat, out_split=split, kind="generation",
        )
        vc = (
            None if kc is None else _fusion.defer_app(
                append, "gen-append",
                (cache.v, model.E, model.P, model.Wv, tok, lens),
                static=stat, out_split=split, kind="generation",
            )
        )
        lg = (
            None if vc is None else _fusion.defer_app(
                attend, "gen-attend",
                (kc, vc, model.E, model.P, model.Wq, model.Wo, tok, lens),
                static=stat + (
                    float(model.scale),
                    bool(_flash_route(model, cache.capacity, split)),
                    _interpret(),
                ),
                sink=True, out_split=None, kind="generation",
            )
        )
        if lg is not None:
            return lg, KVCache(kc, vc, new_lengths, cache.capacity)

    lg, k2, v2 = _decode_eager(model, cache, tok, lens)
    return lg, KVCache(k2, v2, new_lengths, cache.capacity)


def read_logits(logits: DNDarray) -> np.ndarray:
    """The per-step materialization barrier: flush the decode chain
    (attributed ``fusion.flush_reason{generation}``) and return host f32
    logits."""
    with _fusion.flush_reason("generation"):
        return np.asarray(logits.larray)


def greedy(logits: np.ndarray) -> np.ndarray:
    """Greedy next-token choice, host-side (``(B,)`` int32)."""
    return np.argmax(np.asarray(logits), axis=-1).astype(np.int32)


# ---------------------------------------------------------------- reference
def generate_reference(model: ToyModel, prompt: Sequence[int], max_new: int,
                       eos: Optional[int] = None) -> List[int]:
    """Single-sequence greedy generation through the EAGER reference path —
    the loadgen client's expected-digest oracle (deterministic: seeded
    weights, argmax sampling, batch-independent per-slot math)."""
    prompt = [int(t) for t in prompt]
    if not prompt:
        raise ValueError("generation prompt must be non-empty")
    cache = KVCache.alloc(
        model, 1, capacity=capacity_for(len(prompt) + int(max_new))
    )
    out: List[int] = []
    nxt: Optional[int] = None
    feed = list(prompt)
    while len(out) < int(max_new):
        tok = np.asarray([feed.pop(0) if feed else nxt], np.int32)
        lg, k2, v2 = _decode_eager(
            model, cache, jnp.asarray(tok), jnp.asarray(cache.lengths)
        )
        cache = KVCache(k2, v2, cache.lengths + 1, cache.capacity)
        if feed:
            continue  # still consuming the prompt: logits ignored
        nxt = int(greedy(read_logits(lg))[0])
        if eos is not None and nxt == int(eos):
            break
        out.append(nxt)
    return out


def digest_of_tokens(tokens: Sequence[int]) -> str:
    """Canonical sha256 of a generated token sequence — the streaming wire
    format's integrity check (server final line, loadgen comparison)."""
    return hashlib.sha256(
        json.dumps([int(t) for t in tokens]).encode()
    ).hexdigest()
