"""
Generic operation templates all public ops funnel through.

Parity with the reference's ``heat/core/_operations.py`` (``__binary_op`` :24,
``__cum_op`` :185, ``__local_op`` :282, ``__reduce_op`` :356). The reference's
distribution matching — redistributing the non-dominant operand onto the dominant
operand's chunk map (:113-165) — is unnecessary here: operands are global arrays whose
shardings XLA reconciles; only the *logical* split of the result is computed, following
the reference's dominance rules (:57-71): the leftmost non-``None`` split wins.

Ragged split axes ride the padded physical layout (see ``dndarray.py``): the hot
templates compute directly on the sharded physical arrays — elementwise/cumulative ops
let the pad carry garbage (it sits at the global END of the axis, so it never
contaminates the valid region), and reductions across the split axis first fill the
pad with the operation's neutral element (the reference's neutral-element fill for
empty ranks, _operations.py:414-425, repurposed for pad rows).
"""

from __future__ import annotations

import builtins
from typing import Callable, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from . import devices as _devices
from . import fusion as _fusion
from . import sanitation
from . import stride_tricks
from .communication import sanitize_comm
from .dndarray import DNDarray

# observability: the disabled path costs exactly one truthiness check per
# dispatch (an attribute load on a slotted state object — no dict/string work)
from ..monitoring.registry import STATE as _MON
from ..monitoring import instrument as _instr

__all__ = []


def resolve_keepdims(keepdim=None, keepdims=None) -> bool:
    """
    Normalize the two keep-dimensions spellings every reducer accepts: the
    reference's torch-style ``keepdim`` (arithmetics.py:860+) and numpy's
    ``keepdims``. Explicitly conflicting values raise instead of silently
    preferring one.
    """
    if keepdim is not None and keepdims is not None and bool(keepdim) != bool(keepdims):
        raise ValueError(
            f"conflicting keepdim={keepdim!r} and keepdims={keepdims!r}; pass one"
        )
    return bool(keepdim if keepdim is not None else (keepdims or False))


def __neutral_for(partial_op: Callable, dtype) -> Optional[object]:
    """Neutral element with which pad rows are filled before ``partial_op`` reduces
    across the split axis (None = no fill known; caller falls back to the logical
    view)."""
    if partial_op in (jnp.sum, jnp.nansum, jnp.count_nonzero):
        return 0
    if partial_op in (jnp.prod, jnp.nanprod):
        return 1
    if partial_op in (jnp.max, jnp.argmax, jnp.nanmax):
        dt = np.dtype(dtype)
        if dt.kind == "b":
            return False
        return np.iinfo(dt).min if dt.kind in "iu" else -np.inf
    if partial_op in (jnp.min, jnp.argmin, jnp.nanmin):
        dt = np.dtype(dtype)
        if dt.kind == "b":
            return True
        return np.iinfo(dt).max if dt.kind in "iu" else np.inf
    if partial_op is jnp.all:
        return True
    if partial_op is jnp.any:
        return False
    return None


def __binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """
    Generic binary operation: promotes dtypes (reference _operations.py:24-111),
    broadcasts shapes, determines the output split via operand dominance (:57-71), and
    applies the jnp callable on the global arrays.
    """
    from . import factories
    from . import types
    from .types import canonical_heat_type, result_type

    if _MON.enabled:
        _instr.op_dispatch("binary")
    fn_kwargs = fn_kwargs or {}

    scalars = (builtins.int, builtins.float, builtins.bool, builtins.complex, np.number, np.bool_)
    if not isinstance(t1, (DNDarray, *scalars)) and not isinstance(t1, (np.ndarray, list, tuple)):
        raise TypeError(f"unsupported operand type(s): {type(t1)}")
    if not isinstance(t2, (DNDarray, *scalars)) and not isinstance(t2, (np.ndarray, list, tuple)):
        raise TypeError(f"unsupported operand type(s): {type(t2)}")

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        t1 = factories.array(t1)

    promoted = result_type(t1, t2)
    if operation is jnp.true_divide and not types.heat_type_is_inexact(promoted):
        # true division of exact (int/bool) operands is float (reference
        # arithmetics.py div == torch.true_divide promotion)
        promoted = types.promote_types(promoted, types.float32)
        if _MON.enabled:
            _instr.dtype_fallback("true_divide")

    # normalize operands WITHOUT touching array data (a pending fused
    # expression must not materialize just to be used as an operand)
    ops_in = []  # ('d', DNDarray) | ('s', scalar) | ('a', jnp array)
    shapes = []
    dnd_ops = []
    for t in (t1, t2):
        if isinstance(t, DNDarray):
            ops_in.append(("d", t))
            shapes.append(tuple(t.shape))
            dnd_ops.append(t)
        elif isinstance(t, scalars):
            ops_in.append(("s", t))  # keep weak typing for scalars
            shapes.append(())
        else:
            a = jnp.asarray(t)
            ops_in.append(("a", a))
            shapes.append(tuple(a.shape))

    out_shape = stride_tricks.broadcast_shapes(*shapes)

    # output split: leftmost non-None split among DNDarray operands, remapped through
    # broadcasting (reference dominance rules _operations.py:57-71)
    out_split = None
    for t in dnd_ops:
        if t.split is not None:
            out_split = len(out_shape) - (t.ndim - t.split)
            break
    if out_split is not None and out_split < 0:
        out_split = None

    device = dnd_ops[0].device if dnd_ops else _devices.get_device()
    comm = dnd_ops[0].comm if dnd_ops else sanitize_comm(None)

    # --- deferred-execution fast path (core/fusion.py): record the op as an
    # expression node instead of dispatching one standalone XLA executable;
    # HEAT_TPU_FUSION=0 or any non-recordable shape falls through to the
    # unchanged eager path below
    if out is None and _fusion.enabled():
        deferred = _fusion.defer_binary(
            operation, ops_in, promoted, out_shape, out_split, device, comm, where, fn_kwargs
        )
        if deferred is not None:
            return deferred

    if out is not None:
        # an out= buffer forces eager execution: pending operands flush here
        # (and a pending out is later overwritten — its dead graph is dropped)
        with _fusion.flush_reason("out-alias"):
            arrays = [t.larray if k == "d" else t for k, t in ops_in]
    else:
        arrays = [t.larray if k == "d" else t for k, t in ops_in]

    # Ragged fast path: when an operand carries a padded split axis, compute on the
    # sharded physical arrays instead of gathering the logical views — garbage in the
    # pad region stays in the pad region (same physical extent on every operand).
    phys = (
        out_split is not None
        and where is None
        and dnd_ops
        and any(t.is_padded for t in dnd_ops)
        and (out is None or out.split == out_split)
    )
    if phys:
        from .communication import MeshCommunication

        comm_pad = next((t.comm for t in dnd_ops if isinstance(t.comm, MeshCommunication)), None)
        phys_arrays = []
        for t, a in zip((t1, t2), arrays):
            and_shape = tuple(t.shape) if isinstance(t, DNDarray) else tuple(np.shape(a))
            ndim_a = len(and_shape)
            ax_t = ndim_a - (len(out_shape) - out_split)
            if ax_t < 0 or ndim_a == 0 or and_shape[ax_t] == 1:
                # scalars / broadcast-1 axes broadcast over the padded extent too
                phys_arrays.append(t.larray if isinstance(t, DNDarray) else a)
            elif isinstance(t, DNDarray) and t.split == ax_t and and_shape[ax_t] == out_shape[out_split]:
                phys_arrays.append(t.parray)
            elif and_shape[ax_t] == out_shape[out_split] and comm_pad is not None and (
                not isinstance(t, DNDarray) or t.split is None
            ):
                # replicated operand (raw array or unsplit DNDarray) at full logical
                # extent: pad it to the shared physical extent
                phys_arrays.append(
                    comm_pad.pad_physical(t.larray if isinstance(t, DNDarray) else jnp.asarray(a), ax_t)
                )
            else:
                phys = False
                break
        if phys:
            arrays = phys_arrays

    result = operation(*arrays, **fn_kwargs)
    if result.dtype != promoted.jnp_type() and np.dtype(result.dtype).kind != "b":
        # comparison ops legitimately return bool; numeric ops are cast to the
        # heat-promoted type
        if operation not in (jnp.equal, jnp.not_equal):
            if _MON.enabled:
                _instr.dtype_fallback("binary_cast")
            result = result.astype(promoted.jnp_type())
    res_dtype = canonical_heat_type(result.dtype)

    if where is not None:
        if isinstance(where, DNDarray):
            where = where.larray
        base = out.larray if out is not None else jnp.zeros(out_shape, dtype=result.dtype)
        result = jnp.where(where, result, base)

    if out is not None:
        sanitation.sanitize_out(out, out_shape, out_split, device)
        if tuple(result.shape) == out_shape or tuple(result.shape) == tuple(out.pshape):
            out.larray = result.astype(out.dtype.jnp_type())
        else:
            out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out

    # result.shape is the physical shape on the ragged fast path; out_shape is the
    # logical one — DNDarray.__init__ reconciles either form
    return DNDarray(result, out_shape, res_dtype, out_split, device, comm, True)


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    force_logical: bool = False,
    **kwargs,
) -> DNDarray:
    """
    Generic elementwise local operation (reference _operations.py:282-355): no
    communication, split/layout of the input is retained.

    ``force_logical``: compute on the logical view even when the result shape
    would match the physical one — for ops that are shape-preserving but NOT
    elementwise along the split axis (e.g. ``diff`` with prepend/append, whose
    shrink+extend cancels out), where the pad rows would otherwise leak into
    logical positions.
    """
    from .types import canonical_heat_type

    if _MON.enabled:
        _instr.op_dispatch("local")
    sanitation.sanitize_in(x)
    # deferred-execution fast path: elementwise shape-preserving unary ops are
    # recorded in the pending expression DAG (core/fusion.py); anything else —
    # out= buffers, force_logical over pads, shape-changing calls, non-jnp
    # callables — takes the unchanged eager path
    if out is None and _fusion.enabled():
        deferred = _fusion.defer_local(operation, x, kwargs, force_logical)
        if deferred is not None:
            return deferred
    if force_logical and x.is_padded:
        result = operation(x.larray, **kwargs)
        gshape = tuple(result.shape)
        res_dtype = canonical_heat_type(result.dtype)
        if out is not None:
            sanitation.sanitize_out(out, gshape, x.split, x.device)
            out.larray = result.astype(out.dtype.jnp_type())
            return out
        return DNDarray(result, gshape, res_dtype, x.split, x.device, x.comm, True)
    # compute on the physical array: elementwise ops keep the pad in the pad region
    if out is not None:
        with _fusion.flush_reason("out-alias"):
            operand = x.parray
    else:
        operand = x.parray
    result = operation(operand, **kwargs)
    if tuple(result.shape) == tuple(x.parray.shape):
        gshape = x.shape
    elif x.is_padded:
        # shape-changing op (e.g. diff): the physical result is not the canonical
        # padded layout of any logical shape — recompute on the logical view
        result = operation(x.larray, **kwargs)
        gshape = tuple(result.shape)
    else:
        gshape = tuple(result.shape)
    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, gshape, x.split, x.device)
        if tuple(result.shape) == tuple(out.pshape) or tuple(result.shape) == tuple(out.shape):
            out.larray = result.astype(out.dtype.jnp_type())
        else:
            out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, gshape, res_dtype, x.split, x.device, x.comm, True)


def __reduce_op(
    x: DNDarray,
    partial_op: Callable,
    reduction_op=None,
    axis=None,
    out: Optional[DNDarray] = None,
    neutral=None,
    keepdims: bool = False,
    **kwargs,
) -> DNDarray:
    """
    Generic reduction (reference _operations.py:356-482). The reference computes a
    local partial reduce and crosses ranks with an MPI ``Allreduce`` when the split
    axis is reduced (:441-444); here the global jnp reduction compiles to the same
    psum/pmax collective when the operand is sharded on the reduced axis. The
    ``reduction_op``/``neutral`` arguments are kept for signature parity.
    """
    from .types import canonical_heat_type

    if _MON.enabled:
        _instr.op_dispatch("reduce")
    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)

    # split bookkeeping: reduced split axis -> None; earlier axes removed shift it left
    split = x.split
    xsplit = None if x.split is None else int(x.split) % max(x.ndim, 1)
    axes = range(x.ndim) if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    split_reduced = xsplit is not None and (axis is None or xsplit in axes)
    if split is not None:
        if split_reduced:
            split = None
        elif not keepdims:
            split = xsplit - sum(1 for a in axes if a < xsplit)
        else:
            split = xsplit

    # the logical result shape (the physical one may carry the pad through)
    if axis is None:
        out_gshape = tuple(1 for _ in x.shape) if keepdims else ()
    elif keepdims:
        out_gshape = tuple(1 if d in axes else s for d, s in enumerate(x.shape))
    else:
        out_gshape = tuple(s for d, s in enumerate(x.shape) if d not in axes)

    # normalize a where= mask once for both paths: DNDarray masks become the
    # logical jnp array, and the whole reduction computes on the logical view
    # (the mask's extent is logical — a physical-pad position has no mask bit)
    where_arr = None
    w = kwargs.get("where")
    if w is not None and not isinstance(w, (builtins.bool, np.bool_)):
        kwargs = dict(kwargs)
        with _fusion.flush_reason("reduction"):
            where_arr = w.larray if isinstance(w, DNDarray) else jnp.asarray(w)
        kwargs["where"] = where_arr

    # --- reduction-sink fast path (core/fusion.py): a pending fused chain on
    # the operand is consumed in-register — the elementwise subgraph, the pad
    # handling, the reduction, and the sharded cross-device combine trace as
    # ONE jitted kernel instead of flushing the intermediate to HBM and
    # streaming it back in. HEAT_TPU_FUSION_SINKS=0 (or any non-sinkable
    # combination) falls through to the unchanged flushing path below.
    if out is None and _fusion.sink_ready(x):
        pre = ()
        sinkable = True
        expected_pshape = out_gshape
        dt_np = np.dtype(x.dtype.jnp_type())
        # ml_dtypes floats (bfloat16) report numpy kind 'V': test via issubdtype
        if dt_np.itemsize < 4 and jnp.issubdtype(dt_np, jnp.floating) and partial_op not in (
            jnp.max, jnp.min, jnp.nanmax, jnp.nanmin, jnp.any, jnp.all, jnp.count_nonzero,
        ):
            # sub-32-bit floats: eager rounds to bf16/f16 after every op, but a
            # fused producer feeding the reduce's f32-upcast accumulator legally
            # skips the final narrow rounding (XLA excess precision — verified on
            # this backend). Order-preserving reduces (rounding is monotone, so
            # the selected extremum's rounded value is identical) and boolean
            # tests stay sinkable; arithmetic accumulations flush for parity.
            if _MON.enabled:
                _instr.fusion_sink_fallback("low-float")
            sinkable = False
        if sinkable and x.is_padded:
            n_log = int(x.shape[xsplit])
            if where_arr is not None:
                # the eager path computes on the sliced logical view; an
                # in-trace slice would reassociate the ragged shards' partial
                # sums (see fusion.defer_moment) — the pallas ragged-reduce
                # kernel (ISSUE 10) masks the pad AND the where= mask with
                # the op's neutral in-register instead; combinations it does
                # not express keep the counted eager flush
                deferred = _fusion.defer_ragged_reduce(
                    x, partial_op, axis, keepdims, kwargs, out_gshape
                )
                if deferred is not None:
                    return deferred
                if _MON.enabled:
                    _instr.fusion_sink_fallback("padded-operand")
                sinkable = False
            elif split_reduced:
                neutral_fill = (
                    None
                    if partial_op in (jnp.argmax, jnp.argmin) and axis is None
                    else __neutral_for(partial_op, x.dtype.jnp_type())
                )
                if neutral_fill is not None:
                    # in-trace x.filled(neutral): bit-exact vs the eager fill
                    # (the canonical pad content never reaches the combine)
                    pre = (("fill", xsplit, n_log, neutral_fill),)
                else:
                    # flattened arg-reduction: flat indices must be logical —
                    # the pallas kernel masks the pad out of the running
                    # (value, index) pair and remaps the physical flat index
                    # exactly; otherwise the eager logical view flushes
                    deferred = _fusion.defer_ragged_reduce(
                        x, partial_op, axis, keepdims, kwargs, out_gshape
                    )
                    if deferred is not None:
                        return deferred
                    if _MON.enabled:
                        _instr.fusion_sink_fallback("padded-operand")
                    sinkable = False
            else:
                # physical pass-through: the surviving split axis keeps its pad
                expected_pshape = x.comm.padded_shape(out_gshape, split)
        if sinkable:
            nanfix = (
                partial_op in (jnp.max, jnp.min)
                and np.dtype(x.dtype.jnp_type()).kind in "fc"
                and split_reduced
            )
            deferred = _fusion.defer_reduce(
                x, partial_op, axis, keepdims, kwargs, pre, nanfix,
                out_gshape, split, expected_pshape,
            )
            if deferred is not None:
                return deferred

    # pad handling: a reduction across the split axis must not see the pad — fill it
    # with the op's neutral element (reference neutral-element fill for empty chunks,
    # _operations.py:414-425); reductions over other axes keep the pad in the pad
    # region of the (still padded, still sharded) result
    with _fusion.flush_reason("reduction"):
        if x.is_padded and where_arr is not None:
            operand = x.larray  # logical mask extent -> logical operand
        elif x.is_padded and split_reduced:
            if partial_op in (jnp.argmax, jnp.argmin) and axis is None:
                # flattened arg-reductions return flat indices: those must be logical
                operand = x.larray
            else:
                neutral = __neutral_for(partial_op, x.dtype.jnp_type())
                operand = x.filled(neutral) if neutral is not None else x.larray
        else:
            operand = x.parray
    result = partial_op(operand, axis=axis, keepdims=keepdims, **kwargs)
    result = jnp.asarray(result)
    if (
        partial_op in (jnp.max, jnp.min)
        and np.dtype(operand.dtype).kind in "fc"
        and split_reduced
    ):
        # numpy/torch max/min propagate NaN; a single-device jnp reduce does
        # too, but the SPMD partitioner's cross-shard pmax/pmin combine drops
        # it — re-assert propagation with an explicit any-NaN pass (floats
        # only; the pad fill is +-inf, never NaN, so the pad cannot poison it)
        hasnan = jnp.any(jnp.isnan(operand), axis=axis, keepdims=keepdims)
        result = jnp.where(hasnan, jnp.asarray(jnp.nan, result.dtype), result)

    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, out_gshape, split, x.device)
        if tuple(result.shape) == tuple(out.pshape) or tuple(result.shape) == tuple(out.shape):
            out.larray = result.astype(out.dtype.jnp_type())
        else:
            out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, out_gshape, res_dtype, split, x.device, x.comm, True)


def __cum_op(
    x: DNDarray,
    partial_op: Callable,
    exscan_op=None,
    final_op=None,
    neutral=None,
    axis: int = 0,
    dtype=None,
    out: Optional[DNDarray] = None,
) -> DNDarray:
    """
    Generic cumulative operation (reference _operations.py:185-281: local cumop +
    ``Exscan`` + local combine). Along a distributed split axis the same pipeline
    runs as one shard_map program (``comm.Cum``): local cumulative, exclusive
    prefix of the per-block totals, combine — only the block totals cross the
    mesh, where XLA's native scan-over-a-sharded-axis would all-gather the full
    operand (HLO-proven in tests/test_hlo_contract.py).
    """
    from .communication import MeshCommunication
    from .types import canonical_heat_type

    if _MON.enabled:
        _instr.op_dispatch("cum")
    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operations over flattened arrays: pass axis")
    comm = x.comm
    opname = {jnp.cumsum: "sum", jnp.cumprod: "prod"}.get(partial_op)
    use_comm_cum = (
        opname is not None
        and x.split is not None
        and axis == int(x.split) % max(x.ndim, 1)
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    )
    cast_dtype = None if dtype is None else canonical_heat_type(dtype)

    # --- reduction-sink fast path (core/fusion.py): the cumulative becomes a
    # sink of the pending chain; along a distributed split axis the comm.Cum
    # shard_map pipeline (local cum + block-total exchange + combine) is
    # traced INTO the same XLA program as the fused elementwise subgraph
    if out is None and _fusion.sink_ready(x):
        deferred = _fusion.defer_cum(
            x, partial_op, axis, cast_dtype,
            comm if use_comm_cum else None, opname,
        )
        if deferred is not None:
            return deferred

    if use_comm_cum:
        # pad-safe: pad rows sit at the global END of the axis, so every valid
        # block's offset is built from valid predecessors only; garbage totals
        # flow exclusively into pad-only blocks. The operand flush inside the
        # collective prep is reason-labelled so fusion.flushes/flush_reason
        # stay honest on this path (ISSUE 4 bugfix).
        with _fusion.flush_reason("collective"):
            operand = x.parray
        result = comm.Cum(operand, op=opname, split=axis)
    else:
        # physical compute is safe even along a padded split axis: the pad sits at
        # the global END, so the cumulative prefix over the valid region never sees it
        with _fusion.flush_reason("cumulative"):
            operand = x.parray
        result = partial_op(operand, axis=axis)
    if dtype is not None:
        result = result.astype(cast_dtype.jnp_type())
    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, x.shape, x.split, x.device)
        out.larray = result.astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, x.shape, res_dtype, x.split, x.device, x.comm, True)
