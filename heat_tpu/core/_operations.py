"""
Generic operation templates all public ops funnel through.

Parity with the reference's ``heat/core/_operations.py`` (``__binary_op`` :24,
``__cum_op`` :185, ``__local_op`` :282, ``__reduce_op`` :356). The reference's
distribution matching — redistributing the non-dominant operand onto the dominant
operand's chunk map (:113-165) — is unnecessary here: operands are global arrays whose
shardings XLA reconciles; only the *logical* split of the result is computed, following
the reference's dominance rules (:57-71): the leftmost non-``None`` split wins.
"""

from __future__ import annotations

import builtins
from typing import Callable, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from . import devices as _devices
from . import sanitation
from . import stride_tricks
from .communication import sanitize_comm
from .dndarray import DNDarray

__all__ = []


def __binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """
    Generic binary operation: promotes dtypes (reference _operations.py:24-111),
    broadcasts shapes, determines the output split via operand dominance (:57-71), and
    applies the jnp callable on the global arrays.
    """
    from . import factories
    from .types import canonical_heat_type, result_type

    fn_kwargs = fn_kwargs or {}

    scalars = (builtins.int, builtins.float, builtins.bool, builtins.complex, np.number, np.bool_)
    if not isinstance(t1, (DNDarray, *scalars)) and not isinstance(t1, (np.ndarray, list, tuple)):
        raise TypeError(f"unsupported operand type(s): {type(t1)}")
    if not isinstance(t2, (DNDarray, *scalars)) and not isinstance(t2, (np.ndarray, list, tuple)):
        raise TypeError(f"unsupported operand type(s): {type(t2)}")

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        t1 = factories.array(t1)

    promoted = result_type(t1, t2)

    arrays = []
    dnd_ops = []
    for t in (t1, t2):
        if isinstance(t, DNDarray):
            arrays.append(t.larray)
            dnd_ops.append(t)
        elif isinstance(t, scalars):
            arrays.append(t)  # keep weak typing for scalars
        else:
            arrays.append(jnp.asarray(t))

    out_shape = stride_tricks.broadcast_shapes(
        *[tuple(np.shape(a)) if not hasattr(a, "shape") else tuple(a.shape) for a in arrays]
    )

    # output split: leftmost non-None split among DNDarray operands, remapped through
    # broadcasting (reference dominance rules _operations.py:57-71)
    out_split = None
    for t in dnd_ops:
        if t.split is not None:
            out_split = len(out_shape) - (t.ndim - t.split)
            break
    if out_split is not None and out_split < 0:
        out_split = None

    device = dnd_ops[0].device if dnd_ops else _devices.get_device()
    comm = dnd_ops[0].comm if dnd_ops else sanitize_comm(None)

    result = operation(*arrays, **fn_kwargs)
    if result.dtype != promoted.jnp_type() and np.dtype(result.dtype).kind != "b":
        # comparison ops legitimately return bool; numeric ops are cast to the
        # heat-promoted type
        if operation not in (jnp.equal, jnp.not_equal):
            result = result.astype(promoted.jnp_type())
    res_dtype = canonical_heat_type(result.dtype)

    if where is not None:
        if isinstance(where, DNDarray):
            where = where.larray
        base = out.larray if out is not None else jnp.zeros(out_shape, dtype=result.dtype)
        result = jnp.where(where, result, base)

    if out is not None:
        sanitation.sanitize_out(out, out_shape, out_split, device)
        out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out

    return DNDarray(result, tuple(result.shape), res_dtype, out_split, device, comm, True)


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """
    Generic elementwise local operation (reference _operations.py:282-355): no
    communication, split/layout of the input is retained.
    """
    from .types import canonical_heat_type

    sanitation.sanitize_in(x)
    result = operation(x.larray, **kwargs)
    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, x.shape, x.split, x.device)
        out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, tuple(result.shape), res_dtype, x.split, x.device, x.comm, True)


def __reduce_op(
    x: DNDarray,
    partial_op: Callable,
    reduction_op=None,
    axis=None,
    out: Optional[DNDarray] = None,
    neutral=None,
    keepdims: bool = False,
    **kwargs,
) -> DNDarray:
    """
    Generic reduction (reference _operations.py:356-482). The reference computes a
    local partial reduce and crosses ranks with an MPI ``Allreduce`` when the split
    axis is reduced (:441-444); here the global jnp reduction compiles to the same
    psum/pmax collective when the operand is sharded on the reduced axis. The
    ``reduction_op``/``neutral`` arguments are kept for signature parity.
    """
    from .types import canonical_heat_type

    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    result = partial_op(x.larray, axis=axis, keepdims=keepdims, **kwargs)
    result = jnp.asarray(result)

    # split bookkeeping: reduced split axis -> None; earlier axes removed shift it left
    split = x.split
    if split is not None:
        axes = range(x.ndim) if axis is None else ((axis,) if isinstance(axis, int) else axis)
        if axis is None or split in axes:
            split = None
        elif not keepdims:
            split -= sum(1 for a in axes if a < split)

    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), split, x.device)
        out.larray = jnp.broadcast_to(result, out.shape).astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, tuple(result.shape), res_dtype, split, x.device, x.comm, True)


def __cum_op(
    x: DNDarray,
    partial_op: Callable,
    exscan_op=None,
    final_op=None,
    neutral=None,
    axis: int = 0,
    dtype=None,
    out: Optional[DNDarray] = None,
) -> DNDarray:
    """
    Generic cumulative operation (reference _operations.py:185-281: local cumop +
    ``Exscan`` + local combine; here the global jnp scan lowers to the same pattern).
    """
    from .types import canonical_heat_type

    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operations over flattened arrays: pass axis")
    result = partial_op(x.larray, axis=axis)
    if dtype is not None:
        result = result.astype(canonical_heat_type(dtype).jnp_type())
    res_dtype = canonical_heat_type(result.dtype)
    if out is not None:
        sanitation.sanitize_out(out, x.shape, x.split, x.device)
        out.larray = result.astype(out.dtype.jnp_type())
        return out
    return DNDarray(result, tuple(result.shape), res_dtype, x.split, x.device, x.comm, True)
