"""Core namespace: flat re-export of every op module (parity: reference
heat/core/__init__.py:1-31)."""

from .communication import *
from .arithmetics import *
from .base import *
from .complex_math import *
from .constants import *
from .devices import *
from .dndarray import *
from .exponential import *
from .factories import *
from .indexing import *
from .io import *
from .logical import *
from .manipulations import *
from .memory import *
from .printing import *
from .relational import *
from .rounding import *
from .sanitation import *
from .statistics import *
from .stride_tricks import *
from .tiling import *
from .trigonometrics import *
from .types import *
from .types import finfo, iinfo
from .version import __version__
from . import linalg
from . import random
from . import version
