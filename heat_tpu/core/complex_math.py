"""
Complex number operations (all element-local).

Parity with the reference's ``heat/core/complex_math.py`` (``__all__`` at
complex_math.py:15).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x, deg: bool = False, out=None) -> DNDarray:
    """Element-wise argument (phase) of a complex array; in degrees if ``deg``
    (reference complex_math.py angle)."""
    res = _operations.__local_op(jnp.angle, x, None)
    if deg:
        from . import trigonometrics

        res = trigonometrics.rad2deg(res)
    if out is not None:
        from . import sanitation

        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def conjugate(x, out=None) -> DNDarray:
    """Element-wise complex conjugate (reference complex_math.py conjugate)."""
    return _operations.__local_op(jnp.conj, x, out)


conj = conjugate


def imag(x) -> DNDarray:
    """Imaginary part; zeros for real input (reference complex_math.py imag)."""
    return _operations.__local_op(jnp.imag, x)


def real(x) -> DNDarray:
    """Real part (reference complex_math.py real)."""
    from . import types

    if not issubclass(x.dtype, types.complexfloating):
        return x
    return _operations.__local_op(jnp.real, x)
