"""
MXU-blocked local dense factorizations: compact-WY QR, right-looking blocked
LU, and a polar-based SVD.

Why this module exists: BENCH_r05 put the matmul anchor at 98% MFU while every
local dense factorization sat at 0.3-2.2% MXU — the ``jnp.linalg.*`` kernels
XLA lowers on TPU are column-at-a-time and leave the systolic array idle, and
they sit on the hot path of the distributed layer (TSQR local blocks and BCGS2
panel QRs in ``qr.py``, the diagonal-block LU in ``_elimination.py``, the
local solves behind ``basics.solve/det/inv``). The restructuring here is the
standard communication-avoiding recipe:

* **QR** — blocked Householder with compact-WY accumulation (Demmel, Grigori,
  Hoemmen & Langou, "Communication-optimal parallel and sequential QR and LU
  factorizations", SISC 2012): factor a narrow panel with the slow-but-small
  Householder sweep, accumulate the panel's reflectors into the
  ``I - V T Vᵀ`` representation (LAPACK ``larft``), and apply the block
  reflector to the trailing matrix as two large GEMMs at
  ``Precision.HIGHEST``. O(n³) work becomes O(n²·b) slow panel work plus
  GEMM-shaped everything-else.
* **LU** — right-looking blocked LU with partial pivoting *within* panels
  (ibid.): ``lax.linalg.lu`` on the (m-k, b) panel, one triangular solve for
  the block row, one rank-b GEMM update of the trailing submatrix. The
  returned ``(lu, piv)`` pair is bit-compatible with
  ``jax.scipy.linalg.lu_factor``'s, so ``lu_solve`` consumes it directly —
  this backs ``solve``/``det``/``slogdet``/``inv`` and the diagonal-block
  factor of the distributed elimination.
* **SVD** — QR tall inputs down to square, then QDWH polar iteration
  (Nakatsukasa & Higham, "Stable and efficient spectral divide and conquer",
  SISC 2013): at most 6 dynamically-weighted Halley steps, each a tall QR or
  a Cholesky solve plus GEMMs, followed by ``eigh`` of the small symmetric
  polar factor. Every flop that can be a GEMM is a GEMM.

Dispatch policy (``doc/blocked_linalg_notes.md`` has the measured table):

* ``HEAT_TPU_BLOCKED_LINALG=0`` disables the module everywhere — every entry
  point then calls the exact ``jnp.linalg`` expression the pre-blocked code
  used, bit for bit. The flag is read per call (eager paths) or captured into
  the compiled-builder cache key (``qr.py``/``_elimination.py`` shard_map
  programs), so flipping it mid-process never serves a stale kernel.
* Below a per-op crossover size (``CROSSOVER``) the ``jnp.linalg`` kernel wins
  on latency and the dispatcher falls back automatically; panel width defaults
  to a static size-thresholded heuristic (``default_panel_width``). Under
  ``HEAT_TPU_TUNING=1`` both become per-device measurements: the tuning layer
  (ISSUE 18, ``heat_tpu/tuning/``) probes panel widths per shape class and
  races blocked-vs-``jnp.linalg`` at bracketing sizes to cache the measured
  crossover (``panel_width`` / ``_crossover`` below).

Observability: each eager entry point runs under a PR-1 ``monitoring`` span
with the panel geometry attached, and per-phase flop counters
(``linalg.blocked.<op>.panel_flops`` / ``.update_flops`` / ``.qform_flops``,
``linalg.blocked.svd.polar_iters``) make the MXU story visible in
``monitoring.report``/``bench.py`` telemetry.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ...monitoring.registry import STATE as _MON, REGISTRY as _REG
from ...monitoring import events as _ev

__all__ = [
    "CROSSOVER",
    "kernels_enabled",
    "default_panel_width",
    "panel_width",
    "qr",
    "local_qr",
    "lu_factor",
    "solve",
    "det",
    "slogdet",
    "inv",
    "polar",
    "svd",
]

#: All trailing-update / accumulation GEMMs run at full input precision — the
#: factorizations feed residual-certified solvers and orthogonality tests; a
#: one-pass bf16 GEMM here would cost ~1e-2 relative error (see
#: basics.GEMM_PRECISION, same policy).
GEMM_PRECISION = jax.lax.Precision.HIGHEST

#: Minimum ``min(m, n)`` at which the blocked kernel beats the corresponding
#: ``jnp.linalg`` lowering (measured on v5e, doc/blocked_linalg_notes.md);
#: below it the panel machinery is pure overhead and the dispatcher falls
#: back automatically.
CROSSOVER = {"qr": 128, "lu": 256, "svd": 128}


def kernels_enabled() -> bool:
    """Whether the blocked kernels are globally enabled (default on).

    ``HEAT_TPU_BLOCKED_LINALG=0`` (or ``false``/``off``) restores the
    pre-blocked ``jnp.linalg`` paths bit for bit. Read per call — eager entry
    points honor a mid-process flip; compiled shard_map builders capture the
    value into their cache key instead (see ``qr.py``/``_elimination.py``).
    """
    val = os.environ.get("HEAT_TPU_BLOCKED_LINALG", "")
    return val.strip().lower() not in ("0", "false", "off")


def default_panel_width(m: int, n: int) -> int:
    """Static size-thresholded panel-width heuristic
    (doc/blocked_linalg_notes.md table): ``k = min(m, n)`` maps to 32
    (k < 256), 64 (k < 512), 128 (k < 8192), else 256 — fixed thresholds,
    not a measurement. The trailing-update GEMM contracts over the panel
    width, so MXU-aligned widths (128/256) win once the factorization is
    large enough to amortize the O(2mnb) slow-panel work; small problems
    take narrow panels to keep the sequential Householder sweep short.

    A *measured* per-device panel width exists only under
    ``HEAT_TPU_TUNING=1``: :func:`panel_width` probes the
    ``linalg.blocked.panel`` knob (ISSUE 18) and falls back to this
    heuristic whenever tuning is off or the probe fails.
    """
    k = min(m, n)
    if k < 256:
        return 32
    if k < 512:
        return 64
    if k < 8192:
        return 128
    return 256


def panel_width(m: int, n: int) -> int:
    """The panel width the eager entry points actually use: the static
    :func:`default_panel_width` heuristic, or — under ``HEAT_TPU_TUNING=1``
    (one env read when off) — the measured winner for this factorization's
    pow2 shape class (``linalg.blocked.panel``)."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return default_panel_width(m, n)
    k = max(1, min(m, n))
    k_bucket = min(1 << (k - 1).bit_length(), 8192)
    try:
        return _tuning.lookup(
            "linalg.blocked.panel",
            shape_class=k_bucket,
            context={"m": m, "n": n, "k_bucket": k_bucket},
        )
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return default_panel_width(m, n)


def _crossover(op: str) -> int:
    """The min(m, n) at which the blocked ``op`` takes over from
    ``jnp.linalg``: the static ``CROSSOVER`` table, or — under
    ``HEAT_TPU_TUNING=1`` — the measured blocked-vs-reference race result
    (``linalg.blocked.crossover.<op>``)."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return CROSSOVER[op]
    try:
        return _tuning.lookup(f"linalg.blocked.crossover.{op}")
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return CROSSOVER[op]


def _size_ok(op: str, m: int, n: int, dtype) -> bool:
    """Crossover + dtype eligibility, independent of the env flag (compiled
    builders capture the flag separately, into their cache key)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        return False  # complex Householder/QDWH not implemented; jnp handles
    return min(m, n) >= _crossover(op)


def _use_blocked(op: str, m: int, n: int, dtype) -> bool:
    return kernels_enabled() and _size_ok(op, m, n, dtype)


def _f32_compute_dtype(dtype):
    """Working dtype: half precisions are factored in f32 (a bf16 Householder
    pivot is numerically meaningless) and the factors cast back on exit."""
    dt = jnp.dtype(dtype)
    if dt in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.dtype(jnp.float32)
    return dt


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _count(name: str, value) -> None:
    if _MON.enabled:
        _REG.counter(name).inc(int(value))


# --------------------------------------------------------------------- flop models
def _qr_flops(m: int, n: int, want_q: bool) -> Tuple[int, int, int]:
    """(panel, update, qform) modeled flops of the blocked Householder QR."""
    k = min(m, n)
    b = default_panel_width(m, n)
    panel = update = 0
    for off in range(0, k, b):
        w = min(b, k - off)
        rows = m - off
        panel += 2 * rows * w * w
        trail = n - off - w
        update += 4 * rows * w * trail  # two (rows,w)x(rows,trail) GEMMs
    qform = (4 * m * n * k - 2 * k * k * (m + n)) if want_q else 0
    return panel, update, max(qform, 0)


def _lu_flops(m: int, n: int) -> Tuple[int, int, int]:
    """(panel, trsm, update) modeled flops of the right-looking blocked LU."""
    k = min(m, n)
    b = default_panel_width(m, n)
    panel = trsm = update = 0
    for off in range(0, k, b):
        w = min(b, k - off)
        rows = m - off
        trail = n - off - w
        panel += rows * w * w
        trsm += w * w * trail
        update += 2 * (rows - w) * w * trail
    return panel, trsm, update


# ------------------------------------------------------------------ panel QR (WY)
def _householder_panel(a):
    """Householder QR of one (rows, w) panel — the slow-but-small path.

    Returns ``(V, T, R)``: ``V`` (rows, w) unit-lower-trapezoidal Householder
    vectors, ``T`` (w, w) upper-triangular compact-WY factor with
    ``Q_panel = I - V T Vᵀ`` (LAPACK ``geqr2`` + ``larft``), and ``R`` (w, w)
    the panel's triangular factor. A ``fori_loop`` over the w columns keeps
    the trace size O(1) per panel; all row masking is against a static iota.
    """
    rows, w = a.shape
    dt = a.dtype
    ridx = jnp.arange(rows)
    cidx = jnp.arange(w)

    def step(j, carry):
        a, v_mat, t_mat = carry
        col = jax.lax.dynamic_slice_in_dim(a, j, 1, axis=1)[:, 0]
        below = ridx > j
        at_j = (ridx == j).astype(dt)
        alpha = jnp.sum(jnp.where(ridx == j, col, 0))
        tail = jnp.where(below, col, 0)
        sigma = jnp.sum(tail * tail)
        norm_x = jnp.sqrt(alpha * alpha + sigma)
        beta = jnp.where(alpha >= 0, -norm_x, norm_x)
        denom = alpha - beta
        degenerate = (sigma == 0) | (denom == 0)
        safe_denom = jnp.where(degenerate, jnp.ones((), dt), denom)
        v = jnp.where(below, col / safe_denom, jnp.zeros((), dt)) + at_j
        safe_beta = jnp.where(degenerate, jnp.ones((), dt), beta)
        tau = jnp.where(degenerate, jnp.zeros((), dt), (beta - alpha) / safe_beta)
        # apply H_j = I - tau v vᵀ to the whole panel (one skinny GEMV pair)
        w_row = jnp.matmul(v[None, :], a, precision=GEMM_PRECISION)[0]
        a = a - tau * v[:, None] * w_row[None, :]
        # larft forward accumulation: T[:j, j] = -tau T[:j, :j] (V[:, :j]ᵀ v)
        vtv = jnp.matmul(v_mat.T, v[:, None], precision=GEMM_PRECISION)
        tcol = -tau * jnp.matmul(t_mat, vtv, precision=GEMM_PRECISION)
        tcol = jnp.where(cidx[:, None] < j, tcol, 0) + tau * (cidx[:, None] == j)
        t_mat = jax.lax.dynamic_update_slice(t_mat, tcol.astype(dt), (0, j))
        v_mat = jax.lax.dynamic_update_slice(v_mat, v[:, None], (0, j))
        return a, v_mat, t_mat

    a, v_mat, t_mat = jax.lax.fori_loop(
        0, w, step, (a, jnp.zeros((rows, w), dt), jnp.zeros((w, w), dt))
    )
    return v_mat, t_mat, jnp.triu(a[:w, :])


def _qr_impl(a, panel: int, want_q: bool):
    """Blocked compact-WY QR of a 2-D array (trace-level; callers jit).

    Returns ``(q, r)`` with thin ``q`` (m, k) and ``r`` (k, n), k = min(m, n)
    — the ``jnp.linalg.qr`` "reduced" convention — or just ``r`` when
    ``want_q`` is False.
    """
    m, n = a.shape
    dt = a.dtype
    k_total = min(m, n)
    offs = list(range(0, k_total, panel))
    factors = []
    r = a
    for off in offs:
        w = min(panel, k_total - off)
        sub = r[off:, off:]
        v_mat, t_mat, r_p = _householder_panel(sub[:, :w])
        # trailing update as two big GEMMs: C -= V (Tᵀ (Vᵀ C))
        c = sub[:, w:]
        if c.shape[1]:
            wk = jnp.matmul(v_mat.T, c, precision=GEMM_PRECISION)
            wk = jnp.matmul(t_mat.T, wk, precision=GEMM_PRECISION)
            c = c - jnp.matmul(v_mat, wk, precision=GEMM_PRECISION)
        top = jnp.concatenate(
            [jnp.pad(r_p, ((0, m - off - w), (0, 0))), c], axis=1
        )
        r = r.at[off:, off:].set(top)
        factors.append((off, v_mat, t_mat))
    r_final = jnp.triu(r[:k_total, :])
    if not want_q:
        return r_final
    # form thin Q by applying the block reflectors to I in reverse order
    q = jnp.eye(m, k_total, dtype=dt)
    for off, v_mat, t_mat in reversed(factors):
        qs = q[off:, :]
        wk = jnp.matmul(v_mat.T, qs, precision=GEMM_PRECISION)
        wk = jnp.matmul(t_mat, wk, precision=GEMM_PRECISION)
        q = q.at[off:, :].set(qs - jnp.matmul(v_mat, wk, precision=GEMM_PRECISION))
    return q, r_final


@functools.lru_cache(maxsize=256)
def _qr_jit(m: int, n: int, dtype_name: str, panel: int, want_q: bool):
    return jax.jit(lambda a: _qr_impl(a, panel, want_q))


def local_qr(a, calc_q: bool = True, use_blocked: Optional[bool] = None, panel: Optional[int] = None):
    """Trace-safe local QR used inside compiled programs (TSQR/BCGS2 blocks,
    QDWH iterations): blocked compact-WY when allowed, ``jnp.linalg.qr``
    otherwise.

    ``use_blocked`` must be passed explicitly by lru-cached shard_map builders
    (the env flag is part of their cache key); ``None`` reads the env flag at
    trace time — only correct for non-cached callers.
    """
    m, n = a.shape
    if use_blocked is None:
        use_blocked = kernels_enabled()
    if not use_blocked or not _size_ok("qr", m, n, a.dtype):
        if calc_q:
            q, r = jnp.linalg.qr(a)
            return q, r
        return jnp.linalg.qr(a, mode="r")
    cdt = _f32_compute_dtype(a.dtype)
    x = a.astype(cdt)
    out = _qr_impl(x, panel or panel_width(m, n), calc_q)
    if calc_q:
        q, r = out
        return q.astype(a.dtype), r.astype(a.dtype)
    return out.astype(a.dtype)


def qr(a, calc_q: bool = True, panel: Optional[int] = None):
    """Blocked compact-WY QR (eager entry point): ``(q, r)`` thin factors, or
    ``r`` alone when ``calc_q`` is False. Falls back to the exact pre-blocked
    ``jnp.linalg.qr`` expression when disabled, below crossover, or complex.
    """
    a = jnp.asarray(a)
    m, n = a.shape
    if not _use_blocked("qr", m, n, a.dtype):
        if calc_q:
            q, r = jnp.linalg.qr(a)
            return q, r
        return jnp.linalg.qr(a, mode="r")
    b = panel or panel_width(m, n)
    pf, uf, qf = _qr_flops(m, n, calc_q)
    if _MON.enabled and not _is_tracer(a):
        _REG.counter("linalg.blocked.dispatch").inc(label="qr")
        _count("linalg.blocked.qr.panel_flops", pf)
        _count("linalg.blocked.qr.update_flops", uf)
        _count("linalg.blocked.qr.qform_flops", qf)
        with _ev.span("linalg.blocked.qr", m=m, n=n, panel=b, flops=pf + uf + qf):
            return _qr_dispatch(a, m, n, b, calc_q)
    return _qr_dispatch(a, m, n, b, calc_q)


def _qr_dispatch(a, m, n, b, calc_q):
    cdt = _f32_compute_dtype(a.dtype)
    out = _qr_jit(m, n, np.dtype(cdt).name, b, calc_q)(a.astype(cdt))
    if calc_q:
        return out[0].astype(a.dtype), out[1].astype(a.dtype)
    return out.astype(a.dtype)


# ------------------------------------------------------------------- blocked LU
def _lu_impl(a, panel: int):
    """Right-looking blocked LU with partial pivoting within panels.

    Returns ``(lu, piv)`` in ``jax.scipy.linalg.lu_factor`` format: ``lu``
    holds L (unit lower, implicit diagonal) and U packed together, ``piv`` is
    the 0-based LAPACK ipiv sequence of length min(m, n) —
    ``jax.scipy.linalg.lu_solve`` consumes the pair directly. Pivot search is
    confined to the current panel's rows (standard getrf blocking: the panel
    spans ALL remaining rows, so this is full partial pivoting, not
    block-local pivoting).
    """
    m, n = a.shape
    k_total = min(m, n)
    lu = a
    pivs = []
    for off in range(0, k_total, panel):
        w = min(panel, k_total - off)
        pan = lu[off:, off : off + w]  # (m-off, w): all remaining rows
        p_lu, p_piv, p_perm = jax.lax.linalg.lu(pan)
        pivs.append(p_piv[:w].astype(jnp.int32) + off)
        # permute the OTHER columns of the remaining rows by the panel's perm
        left = lu[off:, :off][p_perm, :]
        right = lu[off:, off + w :][p_perm, :]
        if off:
            lu = lu.at[off:, :off].set(left)
        lu = lu.at[off:, off : off + w].set(p_lu)
        if right.shape[1]:
            # block row: U12 = L11⁻¹ A12 (small triangular solve) ...
            l11 = p_lu[:w, :w]
            u12 = jax.scipy.linalg.solve_triangular(
                l11, right[:w], lower=True, unit_diagonal=True
            )
            lu = lu.at[off : off + w, off + w :].set(u12)
            # ... then ONE rank-w MXU GEMM over the whole trailing submatrix
            if right.shape[0] > w:
                l21 = p_lu[w:, :w]
                a22 = right[w:] - jnp.matmul(l21, u12, precision=GEMM_PRECISION)
                lu = lu.at[off + w :, off + w :].set(a22)
    piv = (
        jnp.concatenate(pivs)
        if pivs
        else jnp.zeros((0,), jnp.int32)
    )
    return lu, piv


@functools.lru_cache(maxsize=256)
def _lu_jit(m: int, n: int, dtype_name: str, panel: int):
    return jax.jit(lambda a: _lu_impl(a, panel))


def lu_factor_local(a, use_blocked: Optional[bool] = None, panel: Optional[int] = None):
    """Trace-safe LU used inside compiled programs (the diagonal-block factor
    of ``_elimination.py``): blocked right-looking when allowed,
    ``jax.scipy.linalg.lu_factor`` otherwise. Same ``(lu, piv)`` contract
    either way."""
    m, n = a.shape
    if use_blocked is None:
        use_blocked = kernels_enabled()
    if not use_blocked or not _size_ok("lu", m, n, a.dtype):
        return jax.scipy.linalg.lu_factor(a)
    return _lu_impl(a, panel or panel_width(m, n))


def lu_factor(a, panel: Optional[int] = None):
    """Blocked LU factorization (eager entry point), LAPACK ``(lu, piv)``
    contract; falls back to ``jax.scipy.linalg.lu_factor`` when disabled or
    below crossover."""
    a = jnp.asarray(a)
    m, n = a.shape
    if not _use_blocked("lu", m, n, a.dtype):
        return jax.scipy.linalg.lu_factor(a)
    b = panel or panel_width(m, n)
    pf, tf, uf = _lu_flops(m, n)
    if _MON.enabled and not _is_tracer(a):
        _REG.counter("linalg.blocked.dispatch").inc(label="lu")
        _count("linalg.blocked.lu.panel_flops", pf)
        _count("linalg.blocked.lu.trsm_flops", tf)
        _count("linalg.blocked.lu.update_flops", uf)
        with _ev.span("linalg.blocked.lu", m=m, n=n, panel=b, flops=pf + tf + uf):
            return _lu_jit(m, n, np.dtype(_f32_compute_dtype(a.dtype)).name, b)(
                a.astype(_f32_compute_dtype(a.dtype))
            )
    cdt = _f32_compute_dtype(a.dtype)
    return _lu_jit(m, n, np.dtype(cdt).name, b)(a.astype(cdt))


def solve(a, b):
    """``x = a⁻¹ b`` through the blocked LU; bit-for-bit
    ``jnp.linalg.solve(a, b)`` when disabled or below crossover."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.ndim != 2 or not _use_blocked("lu", a.shape[0], a.shape[1], a.dtype):
        return jnp.linalg.solve(a, b)
    if _MON.enabled and not _is_tracer(a):
        with _ev.span("linalg.blocked.solve", n=a.shape[0], nrhs=int(b.shape[1]) if b.ndim > 1 else 1):
            lu, piv = lu_factor(a)
            return jax.scipy.linalg.lu_solve((lu, piv), b.astype(lu.dtype)).astype(b.dtype)
    lu, piv = lu_factor(a)
    return jax.scipy.linalg.lu_solve((lu, piv), b.astype(lu.dtype)).astype(b.dtype)


def _slogdet_from_lu(lu, piv):
    diag = jnp.diagonal(lu)
    swaps = jnp.sum(piv != jnp.arange(piv.shape[0], dtype=piv.dtype))
    parity = jnp.where(swaps % 2 == 0, 1.0, -1.0).astype(lu.dtype)
    sign = parity * jnp.prod(jnp.sign(diag))
    logabs = jnp.sum(jnp.log(jnp.abs(diag)))
    return sign, logabs


def slogdet(a):
    """``(sign, logabsdet)`` via the blocked LU (2-D square only); falls back
    to ``jnp.linalg.slogdet`` when disabled or below crossover."""
    a = jnp.asarray(a)
    if a.ndim != 2 or not _use_blocked("lu", a.shape[0], a.shape[1], a.dtype):
        return jnp.linalg.slogdet(a)
    lu, piv = lu_factor(a)
    sign, logabs = _slogdet_from_lu(lu, piv)
    return sign.astype(a.dtype), logabs.astype(_f32_compute_dtype(a.dtype))


def det(a):
    """Determinant via the blocked LU (2-D square only); bit-for-bit
    ``jnp.linalg.det`` when disabled or below crossover."""
    a = jnp.asarray(a)
    if a.ndim != 2 or not _use_blocked("lu", a.shape[0], a.shape[1], a.dtype):
        return jnp.linalg.det(a)
    sign, logabs = slogdet(a)
    return sign * jnp.exp(logabs).astype(sign.dtype)


def inv(a):
    """Inverse via the blocked LU + n-RHS ``lu_solve``; bit-for-bit
    ``jnp.linalg.inv`` when disabled or below crossover."""
    a = jnp.asarray(a)
    if a.ndim != 2 or not _use_blocked("lu", a.shape[0], a.shape[1], a.dtype):
        return jnp.linalg.inv(a)
    lu, piv = lu_factor(a)
    eye = jnp.eye(a.shape[0], dtype=lu.dtype)
    return jax.scipy.linalg.lu_solve((lu, piv), eye).astype(a.dtype)


# --------------------------------------------------------------- QDWH polar / SVD
def _qdwh_schedule(l0: float, eps: float):
    """Static QDWH weight schedule (Nakatsukasa & Higham 2013, eq. 3.5).

    The lower-bound recurrence ``l ← l (a + b l²)/(1 + c l²)`` is pure scalar
    math, so the per-iteration weights (a, b, c) — and the QR-vs-Cholesky
    variant choice — are computed in Python at trace time. Converges in at
    most 6 iterations from l0 = 1e-16.
    """
    l = l0
    sched = []
    for _ in range(12):
        l2 = max(l * l, 1e-300)
        d = (4.0 * (1.0 - l2) / (l2 * l2)) ** (1.0 / 3.0)
        sq = math.sqrt(1.0 + d)
        a_w = sq + 0.5 * math.sqrt(max(8.0 - 4.0 * d + 8.0 * (2.0 - l2) / (l2 * sq), 0.0))
        b_w = (a_w - 1.0) ** 2 / 4.0
        c_w = a_w + b_w - 1.0
        sched.append((a_w, b_w, c_w))
        l = l * (a_w + b_w * l2) / (1.0 + c_w * l2)
        if abs(1.0 - l) < 10.0 * eps:
            break
    return sched


def _polar_impl(a, panel: int, l0: float):
    """QDWH polar factor of a square matrix: ``a = u_p @ h`` with ``u_p``
    orthogonal and ``h`` symmetric PSD. Every iteration is a tall blocked QR
    (c large) or a Cholesky solve (c small) plus GEMMs — pure MXU work."""
    n = a.shape[0]
    dt = a.dtype
    eps = float(jnp.finfo(dt).eps)
    alpha = jnp.maximum(jnp.linalg.norm(a), jnp.asarray(1e-30, dt))
    x = (a / alpha).astype(dt)
    eye = jnp.eye(n, dtype=dt)
    for a_w, b_w, c_w in _qdwh_schedule(l0, eps):
        bc = b_w / c_w
        if c_w > 100.0:
            # QR variant: [sqrt(c) X; I] = [Q1; Q2] R;  X' = (b/c) X + k Q1 Q2ᵀ
            y = jnp.concatenate([jnp.sqrt(jnp.asarray(c_w, dt)) * x, eye], axis=0)
            q, _ = _qr_impl(y, panel, True)
            q1, q2 = q[:n], q[n:]
            k_w = (a_w - bc) / math.sqrt(c_w)
            x = bc * x + k_w * jnp.matmul(q1, q2.T, precision=GEMM_PRECISION)
        else:
            # Cholesky variant: Z = I + c XᵀX;  X' = (b/c) X + (a - b/c) X Z⁻¹
            z = eye + c_w * jnp.matmul(x.T, x, precision=GEMM_PRECISION)
            w = jnp.linalg.cholesky(z)
            v = jax.scipy.linalg.solve_triangular(w, x.T, lower=True)
            v = jax.scipy.linalg.solve_triangular(w.T, v, lower=False)
            x = bc * x + (a_w - bc) * v.T
    u_p = x
    h = jnp.matmul(u_p.T, a, precision=GEMM_PRECISION)
    h = 0.5 * (h + h.T)
    return u_p, h


def _default_l0(dtype) -> float:
    # a crude lower bound on sigma_min/sigma_max costs only iterations, and
    # the schedule converges from 1e-16 in <= 6 of them; one value per dtype
    # keeps the compiled-program cache small
    return 1e-16 if jnp.dtype(dtype) == jnp.dtype(jnp.float64) else 1e-6


@functools.lru_cache(maxsize=128)
def _polar_jit(n: int, dtype_name: str, panel: int, l0: float):
    return jax.jit(lambda a: _polar_impl(a, panel, l0))


def polar(a, panel: Optional[int] = None):
    """QDWH polar decomposition ``a = u @ h`` of a square matrix (eager)."""
    a = jnp.asarray(a)
    n = a.shape[0]
    cdt = _f32_compute_dtype(a.dtype)
    b = panel or panel_width(2 * n, n)
    u, h = _polar_jit(n, np.dtype(cdt).name, b, _default_l0(cdt))(a.astype(cdt))
    return u.astype(a.dtype), h.astype(a.dtype)


def _svd_square_impl(a, panel: int, l0: float):
    """SVD of a square matrix via QDWH polar + eigh of the symmetric factor."""
    u_p, h = _polar_impl(a, panel, l0)
    lam, v = jnp.linalg.eigh(h)  # ascending
    lam, v = lam[::-1], v[:, ::-1]
    s = jnp.abs(lam)
    # a (numerically tiny) negative eigenvalue flips into the left vectors so
    # the product U diag(S) Vᵀ stays exactly u_p @ h
    signs = jnp.where(lam < 0, -1.0, 1.0).astype(a.dtype)
    u = jnp.matmul(u_p, v, precision=GEMM_PRECISION) * signs[None, :]
    return u, s, v.T


def _svd_impl(a, panel: int, l0: float, compute_uv: bool):
    """Tall/square SVD: blocked-QR reduction to square, then QDWH + eigh."""
    m, n = a.shape
    if m > n:
        q, r = _qr_impl(a, panel, True)
        u_r, s, vh = _svd_square_impl(r, panel, l0)
        if not compute_uv:
            return s
        return jnp.matmul(q, u_r, precision=GEMM_PRECISION), s, vh
    out = _svd_square_impl(a, panel, l0)
    if not compute_uv:
        return out[1]
    return out


@functools.lru_cache(maxsize=128)
def _svd_jit(m: int, n: int, dtype_name: str, panel: int, l0: float, compute_uv: bool):
    return jax.jit(lambda a: _svd_impl(a, panel, l0, compute_uv))


def svd(a, full_matrices: bool = False, compute_uv: bool = True, panel: Optional[int] = None):
    """Polar-based SVD (eager entry point): tall inputs are blocked-QR'd down
    to square, the square factor takes the QDWH polar route, and ``eigh`` of
    the small symmetric polar factor yields the singular triplets. Wide
    inputs go through the transpose. Falls back to the exact pre-blocked
    ``jnp.linalg.svd`` expression when disabled, below crossover,
    ``full_matrices=True``, or complex.
    """
    a = jnp.asarray(a)
    m, n = a.shape
    if full_matrices or not _use_blocked("svd", m, n, a.dtype):
        if not compute_uv:
            return jnp.linalg.svd(a, compute_uv=False)
        return jnp.linalg.svd(a, full_matrices=full_matrices)
    if n > m:
        # wide: svd(aᵀ) = (V, S, Uᵀ) — swap and transpose the factors
        out = svd(a.T, full_matrices=False, compute_uv=compute_uv, panel=panel)
        if not compute_uv:
            return out
        ut, s, vht = out
        return vht.T, s, ut.T
    cdt = _f32_compute_dtype(a.dtype)
    b = panel or panel_width(m, n)
    l0 = _default_l0(cdt)
    n_iters = len(_qdwh_schedule(l0, float(jnp.finfo(cdt).eps)))
    if _MON.enabled and not _is_tracer(a):
        _REG.counter("linalg.blocked.dispatch").inc(label="svd")
        _count("linalg.blocked.svd.polar_iters", n_iters)
        pf, uf, qf = _qr_flops(m, n, True)
        _count("linalg.blocked.svd.qr_flops", (pf + uf + qf) if m > n else 0)
        # per polar iteration: QR variant ~ (10/3 + 2) n³, Cholesky ~ 4 n³
        _count("linalg.blocked.svd.polar_flops", int(n_iters * 5 * n**3))
        with _ev.span("linalg.blocked.svd", m=m, n=n, panel=b, polar_iters=n_iters):
            return _svd_dispatch(a, m, n, cdt, b, l0, compute_uv)
    return _svd_dispatch(a, m, n, cdt, b, l0, compute_uv)


def _svd_dispatch(a, m, n, cdt, b, l0, compute_uv):
    out = _svd_jit(m, n, np.dtype(cdt).name, b, l0, compute_uv)(a.astype(cdt))
    if not compute_uv:
        return out.astype(_f32_compute_dtype(a.dtype))
    u, s, vh = out
    return u.astype(a.dtype), s.astype(_f32_compute_dtype(a.dtype)), vh.astype(a.dtype)
