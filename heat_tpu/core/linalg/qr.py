"""
Distributed QR decomposition.

Parity with the reference's ``heat/core/linalg/qr.py``: the reference implements a
tiled CAQR/TSQR tree over ``SquareDiagTiles`` with hand-written tile sends
(``__split0_r_calc`` :319, ``__split0_merge_tile_rows`` :490, ``__split0_q_loop``
:675; CAQR citations at qr.py:49-58) and a block-column Householder sweep for split=1
(:866). The TPU redesign:

* ``split=None`` → local ``jnp.linalg.qr`` (reference qr.py:98-106 does the same).
* ``split=0`` tall-skinny → a **single-level TSQR** in ``shard_map``: each device QRs
  its row block, the small R factors are all-gathered and QR'd redundantly, and the
  local Q is corrected with its slice of the merge Q. This is the same communication
  volume as the reference's tile tree with one tile per device, expressed as one
  all-gather over ICI.
* ``split=1`` (column-sharded, m >= n) → a **block-column sweep** in ``shard_map``
  (the reference's split=1 Householder sweep, qr.py:866-1042, as twice-
  reorthogonalized block classical Gram-Schmidt, "BCGS2"): at step k the current
  panel is broadcast (one-hot psum), every earlier column block projects it out
  (local GEMM + psum — two passes, which restores Householder-grade
  orthogonality), the owner keeps the panel's local QR as its Q block, and the
  projection coefficients assemble R column-by-column. A is never gathered; per
  step the traffic is O(m·b + n·b), b = n/p.
* other splits → gather and factorise locally (correct, not comm-optimal).
"""

from __future__ import annotations

import collections
import functools
import warnings
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import blocked
from .._compat import shard_map as _shard_map
from .. import sanitation
from .. import types
from ..communication import MeshCommunication
from ..dndarray import DNDarray

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def __build_bcgs(mesh, axis: str, p: int, m: int, n: int, jdtype: str, use_blocked=None):
    """Compile the split=1 block Gram-Schmidt sweep for one problem shape.

    ``use_blocked`` selects the MXU-blocked compact-WY kernel for the local
    panel QRs (None reads ``HEAT_TPU_BLOCKED_LINALG`` now); it is part of the
    compile cache key so flipping the env var mid-process never reuses a
    program built for the other kernel."""
    if use_blocked is None:
        use_blocked = blocked.kernels_enabled()
    return __build_bcgs_cached(mesh, axis, p, m, n, jdtype, bool(use_blocked))


@functools.lru_cache(maxsize=64)
def __build_bcgs_cached(mesh, axis: str, p: int, m: int, n: int, jdtype: str, use_blocked: bool):
    b = n // p
    dt = np.dtype(jdtype)
    hi = jax.lax.Precision.HIGHEST

    def local(a_block):  # (m, b) — my column panel
        me = jax.lax.axis_index(axis)

        def step(k, carry):
            q_me, r_me = carry  # (m,b), (n,b) my Q block + my R block-column
            # broadcast column panel k (the owner's CURRENT data)
            panel = jax.lax.psum(jnp.where(me == k, q_me, jnp.zeros_like(q_me)), axis)
            active = me < k

            def project(pnl):
                c = jnp.where(
                    active, jnp.matmul(q_me.T, pnl, precision=hi), jnp.zeros((b, b), dt)
                )
                proj = jax.lax.psum(jnp.matmul(q_me, c, precision=hi), axis)
                return pnl - proj, c

            p1, c1 = project(panel)
            p2, c2 = project(p1)  # second pass: BCGS2 reorthogonalization
            # redundant (m,b) panel QR on every shard — compact-WY blocked
            # above the crossover (blocked.py), jnp.linalg.qr below it
            qk, rkk = blocked.local_qr(p2, use_blocked=use_blocked)
            q_me = jnp.where(me == k, qk, q_me)
            # R column-block k, assembled once: earlier shards contribute their
            # projection coefficients at their row block, the owner contributes
            # the panel R at row block k
            contrib = jnp.zeros((n, b), dt)
            contrib = jax.lax.dynamic_update_slice(
                contrib, jnp.where(active, c1 + c2, jnp.zeros((b, b), dt)), (me * b, 0)
            )
            contrib = jnp.where(
                me == k,
                jax.lax.dynamic_update_slice(jnp.zeros((n, b), dt), rkk, (k * b, 0)),
                contrib,
            )
            rcol = jax.lax.psum(contrib, axis)
            r_me = jnp.where(me == k, rcol, r_me)
            return q_me, r_me

        q0 = a_block
        r0 = jnp.zeros((n, b), dt)
        q_f, r_f = jax.lax.fori_loop(0, p, step, (q0, r0))
        return q_f, r_f

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )
    )


def _build_tsqr(mesh, axis: str, p: int, use_blocked=None):
    """Compile the single-level TSQR sweep: per-device panel QR, an all-gather
    of the (n, n) R factors ONLY (never the operand), a redundant (p*n, n) QR,
    and the local correction GEMM. Builder-shaped so the AOT multi-chip suite
    (tests/test_tpu_aot.py) can compile it against a v5e topology.

    ``use_blocked`` (None = read ``HEAT_TPU_BLOCKED_LINALG`` now) routes the
    local panel and merge QRs through the MXU-blocked compact-WY kernel; it is
    part of the compile cache key."""
    if use_blocked is None:
        use_blocked = blocked.kernels_enabled()
    return _build_tsqr_cached(mesh, axis, p, bool(use_blocked))


@functools.lru_cache(maxsize=64)
def _build_tsqr_cached(mesh, axis: str, p: int, use_blocked: bool):
    def local(block):
        # local row-block QR: the TSQR building block BENCH_r05 measured at
        # 1.1% MXU on the jnp lowering — blocked compact-WY above the crossover
        q1, r1 = blocked.local_qr(block, use_blocked=use_blocked)  # (m/p, n), (n, n)
        r_stack = jax.lax.all_gather(r1, axis)  # (p, n, n)
        n = r1.shape[0]
        q2, r = blocked.local_qr(
            r_stack.reshape(p * n, n), use_blocked=use_blocked
        )  # (p*n, n), (n, n)
        i = jax.lax.axis_index(axis)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, i * n, n, axis=0)  # (n, n)
        # full-precision correction GEMM: a bf16 pass here degrades Q's orthogonality
        q = jnp.matmul(q1, q2_block, precision=jax.lax.Precision.HIGHEST)
        return q, r

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=(P(axis, None), P(None, None)),
            check_vma=False,
        )
    )


def __tsqr(a: DNDarray) -> Tuple[jax.Array, jax.Array]:
    """Tall-skinny QR over the row-sharded global array via shard_map.

    A PENDING fused chain on the operand traces INTO the TSQR program
    (``fusion.flush_through``, ISSUE 7): the producer chain, the per-device
    panel QRs, the R all-gather, and the merge factorization compile as ONE
    executable — the chain's own value rides the same kernel, so the TSQR
    merge costs one program per iteration instead of flush + dispatch.
    ``HEAT_TPU_FUSION_COLLECTIVES=0`` restores the flush-first path."""
    from .. import fusion as _fusion

    comm: MeshCommunication = a.comm
    use_blocked = blocked.kernels_enabled()
    fn = _build_tsqr(comm.mesh, comm.axis_name, comm.size, use_blocked=use_blocked)
    if _fusion.collective_ready(a):
        out = _fusion.flush_through(
            a,
            fn,
            ("tsqr", comm.mesh, comm.axis_name, comm.size, use_blocked),
            reason="linalg",
        )
        if out is not None:
            return out
    a._flush("linalg")
    return fn(a.larray)


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """
    QR decomposition: ``a = Q @ R`` with orthonormal ``Q`` and upper-triangular ``R``.
    Returns a namedtuple ``QR(Q, R)`` (``Q`` is None when ``calc_q=False``).

    Parameters
    ----------
    a : DNDarray
        2-D array to decompose.
    tiles_per_proc : int
        Tile granularity knob of the reference's tile tree (qr.py:17-48); accepted
        for parity — XLA owns physical tiling here.
    calc_q : bool
        Whether to compute Q.
    overwrite_a : bool
        Parity flag (jax arrays are immutable; a copy semantics no-op).

    Reference parity: heat/core/linalg/qr.py:17-1042.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D DNDarray, got {a.ndim}-d")
    if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
        raise ValueError("tiles_per_proc must be a positive int")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    m, n = a.shape
    comm = a.comm

    use_tsqr = (
        a.split == 0
        and calc_q
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
        and comm.is_shardable(a.shape, 0)
        and (m // comm.size) >= n
    )
    if use_tsqr:
        # flush handling lives in __tsqr: a pending operand chain traces INTO
        # the TSQR program instead of flushing first (ISSUE 7)
        q_data, r_data = __tsqr(a)
        q = DNDarray(q_data, (m, n), a.dtype, 0, a.device, a.comm, True)
        r = DNDarray(r_data, (n, n), a.dtype, None, a.device, a.comm, True)
        return QR(q, r)
    a._flush("linalg")

    use_bcgs = (
        a.split == 1
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
        and comm.is_shardable(a.shape, 1)
        and m >= n
        and n // comm.size >= 1
    )
    if use_bcgs:
        fn = __build_bcgs(
            comm.mesh, comm.axis_name, comm.size, m, n, np.dtype(a.dtype.jnp_type()).name
        )
        q_data, r_data = fn(a.parray)
        r = DNDarray(r_data, (n, n), a.dtype, 1, a.device, a.comm, True)
        if not calc_q:
            return QR(None, r)
        q = DNDarray(q_data, (m, n), a.dtype, 1, a.device, a.comm, True)
        return QR(q, r)

    # local / gathered path (reference qr.py:98-106 for split=None)
    distributed = isinstance(comm, MeshCommunication) and comm.is_distributed()
    if distributed and a.split is not None:
        # VERDICT r2 weak #5: the fall-off from the TSQR/BCGS2 paths was silent.
        # Ragged split-0, short panels (m/p < n), calc_q=False on split=0, and
        # n/p < 1 on split=1 all factorize on the GATHERED operand — correct,
        # but a comm cliff the caller should know about.
        reasons = []
        if a.split == 0:
            if not comm.is_shardable(a.shape, 0):
                reasons.append(f"ragged split axis ({m} rows over {comm.size} devices)")
            if (m // comm.size) < n:
                reasons.append(f"short panels (m/p = {m // comm.size} < n = {n})")
            if not calc_q:
                reasons.append("calc_q=False on split=0 (TSQR builds Q)")
        else:
            if not comm.is_shardable(a.shape, 1):
                reasons.append(f"ragged split axis ({n} cols over {comm.size} devices)")
            if m < n or n // comm.size < 1:
                reasons.append("panel geometry outside the BCGS2 sweep (m < n or n/p < 1)")
        warnings.warn(
            "qr: falling back to the gathered factorization — the operand is "
            f"replicated for one jnp.linalg.qr call ({'; '.join(reasons)}). "
            "The distributed TSQR (split=0, m/p >= n, divisible, calc_q=True) and "
            "BCGS2 (split=1, m >= n >= p, divisible) paths avoid this.",
            stacklevel=2,
        )
    if calc_q:
        q_data, r_data = blocked.qr(a.larray)
        q_split = a.split if a.split == 0 else None
        gq = tuple(q_data.shape)
        if distributed:
            # place like the metadata promises; R is replicated like the TSQR
            # path's out_specs guarantee (DNDarray.__init__ re-pads ragged axes)
            r_data = jax.device_put(r_data, comm.sharding(r_data.ndim, None))
        q = DNDarray(q_data, gq, a.dtype, q_split, a.device, a.comm, True)
        r = DNDarray(r_data, tuple(r_data.shape), a.dtype, None, a.device, a.comm, True)
        return QR(q, r)
    r_data = blocked.qr(a.larray, calc_q=False)
    if distributed:
        r_data = comm.shard(r_data, None)
    r = DNDarray(r_data, tuple(r_data.shape), a.dtype, None, a.device, a.comm, True)
    return QR(None, r)


DNDarray.qr = qr
