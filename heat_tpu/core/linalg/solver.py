"""
Iterative solvers built entirely from framework ops.

Parity with the reference's ``heat/core/linalg/solver.py`` (``cg`` :13-66,
``lanczos`` :68-184) — algorithmic layer with no direct communication; all collectives
come from the distributed matmul/dot underneath.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .. import factories
from .. import sanitation
from ..dndarray import DNDarray
from .basics import matmul, dot, transpose, norm

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """
    Conjugate gradients for ``A @ x = b`` with symmetric positive-definite ``A``
    (reference linalg/solver.py:13-66).
    """
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError("A, b and x0 need to be of type ht.DNDarray")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")
    A._flush("linalg")
    b._flush("linalg")
    x0._flush("linalg")

    r = b - matmul(A, x0)
    p = r
    rsold = matmul(r, r)
    x = x0

    for i in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / matmul(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = matmul(r, r)
        if float(jnp.sqrt(rsnew.larray)) < 1e-10:
            if out is not None:
                out.larray = x.larray
                return out
            return x
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out.larray = x.larray
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """
    Lanczos tridiagonalization of a symmetric matrix: returns ``(V, T)`` with
    ``A ≈ V @ T @ V.T``, ``V`` the (n, m) Krylov basis and ``T`` tridiagonal
    (reference linalg/solver.py:68-184).
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type ht.DNDarray, but was {type(A)}")
    if not isinstance(m, int):
        raise TypeError(f"m must be int, got {type(m)}")
    n, column = A.shape
    if n != column:
        raise TypeError("A needs to be a square matrix")
    A._flush("linalg")

    T = factories.zeros((m, m), device=A.device, comm=A.comm)
    if v0 is None:
        from .. import random

        vr = random.rand(n, split=A.split, device=A.device, comm=A.comm)
        v0 = vr / norm(vr)
    else:
        if v0.split != A.split:
            v0 = v0.resplit(A.split)

    # first iteration
    w = matmul(A, v0)
    alpha = dot(w, v0)
    w = w - alpha * v0
    T[0, 0] = alpha
    V = [v0]

    for i in range(1, m):
        beta = norm(w)
        if abs(float(beta.larray)) < 1e-10:
            # pick a new random orthogonal vector (breakdown restart)
            from .. import random

            vr = random.rand(n, split=A.split, device=A.device, comm=A.comm)
            vi = vr / norm(vr)
        else:
            vi = w / beta
        # full re-orthogonalization against previous basis vectors
        for vj in V:
            vi = vi - dot(vi, vj) * vj
        vi = vi / norm(vi)
        w = matmul(A, vi)
        alpha = dot(w, vi)
        w = w - alpha * vi - beta * V[-1]
        T[i - 1, i] = beta
        T[i, i - 1] = beta
        T[i, i] = alpha
        V.append(vi)

    from ..manipulations import stack

    V_dnd = transpose(stack(V, axis=0), None)  # (n, m)
    if V_out is not None:
        V_out.larray = V_dnd.larray
        T_out.larray = T.larray
        return V_out, T_out
    return V_dnd, T
