"""
Distributed determinant / inverse via blocked panel elimination over the mesh.

The reference runs an *unblocked* Gauss-Jordan elimination over the split
matrix — a Python loop over all n columns with per-element ``.item()`` host
round-trips and row ``Bcast``s (reference heat/core/linalg/basics.py:160-423).
The TPU-native redesign blocks the elimination at device-panel granularity so
every step is MXU work:

* the (n, n) split-0 matrix lives as p row panels of (m, n), m = n/p (the
  padded physical layout; ragged n is embedded into the padded square
  ``blockdiag(A, I_pad)`` whose det/inv trivially recover A's);
* step k of p: the owner's diagonal block ``D_k`` is psum-broadcast, factored
  locally with partially-pivoted LU (``jax.scipy.linalg.lu_factor`` — *better*
  pivoting than the reference, which only swaps rows when a diagonal entry is
  near zero), the scaled pivot panel ``D_k^{-1} A_k`` is psum-broadcast, and
  every other panel applies one rank-m GEMM update;
* ``det`` right-looks (trailing columns only) and accumulates
  ``prod_k det(D_k)`` from the LU diagonals and pivot parities; ``inv`` runs
  the full Gauss-Jordan on the augmented identity panels.

Per-device memory stays O(n^2/p) — the full matrix is never gathered (asserted
on compiled HLO in tests/test_hlo_contract.py). Communication per step is two
(m, n) psums riding ICI; total volume 2·n^2 per device, the same order as one
all-gather, but the peak live footprint is panel-sized.

Pivoting is *block-local*: a singular diagonal block of a nonsingular matrix
(the one case needing cross-panel row swaps) yields non-finite/zero results;
the callers in ``basics.det``/``basics.inv`` detect that on the host and fall
back to the replicated path with a warning, mirroring the QR fallback policy.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

GEMM_PRECISION = jax.lax.Precision.HIGHEST


def can_distribute_elimination(a) -> bool:
    """Whether det/inv take the distributed panel path: a 2-D square matrix,
    split on rows or columns, on a real multi-device mesh, with at least one
    logical row per device (smaller matrices gather trivially)."""
    return (
        a.ndim == 2
        and a.split in (0, 1)
        and a.comm.is_distributed()
        and a.shape[0] >= a.comm.size
    )


def _block_det_sign(piv: jax.Array, m: int) -> jax.Array:
    """Parity of a LAPACK-style ipiv vector: each ``piv[i] != i`` is one swap."""
    swaps = jnp.sum(piv != jnp.arange(m, dtype=piv.dtype))
    return jnp.where(swaps % 2 == 0, 1.0, -1.0)


@functools.lru_cache(maxsize=None)
def _build_panel_det(mesh, axis_name: str, p: int, m: int, dtype_name: str):
    """shard_map program: blocked right-looking LU determinant of a (p*m, p*m)
    row-split matrix. Returns a replicated scalar."""
    n = p * m
    dt = jnp.dtype(dtype_name)

    rdt = jnp.finfo(dt).dtype if jnp.issubdtype(dt, jnp.complexfloating) else dt

    def local(a):  # (m, n) local row panel
        idx = jax.lax.axis_index(axis_name)
        # determinant as (unit, log|det|, bad): the raw product of n diagonal
        # entries overflows f32 for modest n (exactly as numpy's does — the
        # caller re-materializes unit * exp(logabs), inf and all), while the
        # ``bad`` flag separates *block-singular pivoting failures* (zero or
        # non-finite LU diagonals) from honest overflow/underflow
        unit = jnp.ones((), dtype=dt)
        logabs = jnp.zeros((), dtype=rdt)
        bad = jnp.zeros((), dtype=bool)
        for k in range(p):
            c0, c1 = k * m, (k + 1) * m
            # owner's diagonal block, broadcast to all (psum of a one-hot sum)
            own = (idx == k).astype(dt)
            d_blk = jax.lax.psum(own * a[:, c0:c1], axis_name)  # (m, m)
            lu, piv = jax.scipy.linalg.lu_factor(d_blk)
            diag = jnp.diagonal(lu)
            absd = jnp.abs(diag)
            bad = bad | ~jnp.all(jnp.isfinite(diag)) | jnp.any(absd == 0)
            safe = jnp.where(absd == 0, jnp.ones((), rdt), absd)
            unit = unit * _block_det_sign(piv, m).astype(dt) * jnp.prod(diag / safe)
            logabs = logabs + jnp.sum(jnp.log(safe))
            if k + 1 < p:
                # scaled pivot panel D^{-1} A_k over the trailing columns
                pa = jax.lax.psum(
                    own * jax.scipy.linalg.lu_solve((lu, piv), a[:, c1:]), axis_name
                )  # (m, n - c1)
                f = a[:, c0:c1]  # my block column k
                upd = a[:, c1:] - jnp.matmul(f, pa, precision=GEMM_PRECISION)
                # panels <= k are already reduced; leave them untouched
                a = a.at[:, c1:].set(jnp.where(idx > k, upd, a[:, c1:]))
        return unit, logabs, bad

    spec = P(axis_name, None)
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=(P(), P(), P()), check_vma=False
        )
    )


@functools.lru_cache(maxsize=None)
def _build_panel_inv(mesh, axis_name: str, p: int, m: int, dtype_name: str):
    """shard_map program: blocked Gauss-Jordan inverse of a (p*m, p*m)
    row-split matrix. Returns the row-split inverse."""
    n = p * m
    dt = jnp.dtype(dtype_name)

    def panel_mm(x, y, idx):
        """Row panel of X @ Y for row-split X, Y: SUMMA over the mesh — step k
        psum-broadcasts Y's panel k and accumulates one (m, m) x (m, n) GEMM."""
        acc = jnp.zeros_like(x)
        for k in range(p):
            own = (idx == k).astype(dt)
            yk = jax.lax.psum(own * y, axis_name)  # (m, n)
            acc = acc + jnp.matmul(x[:, k * m : (k + 1) * m], yk, precision=GEMM_PRECISION)
        return acc

    def local(a):  # (m, n) local row panel
        idx = jax.lax.axis_index(axis_name)
        a0 = a
        # my rows of the identity: row r of panel idx is global row idx*m + r
        rows = idx * m + jnp.arange(m)
        eye = (rows[:, None] == jnp.arange(n)[None, :]).astype(dt)
        b = eye
        for k in range(p):
            c0, c1 = k * m, (k + 1) * m
            own = (idx == k).astype(dt)
            d_blk = jax.lax.psum(own * a[:, c0:c1], axis_name)
            lu_piv = jax.scipy.linalg.lu_factor(d_blk)
            # scaled pivot panels D^{-1} [A_k | B_k], broadcast to all
            pa = jax.lax.psum(own * jax.scipy.linalg.lu_solve(lu_piv, a), axis_name)
            pb = jax.lax.psum(own * jax.scipy.linalg.lu_solve(lu_piv, b), axis_name)
            f = a[:, c0:c1]
            is_owner = idx == k
            a = jnp.where(is_owner, pa, a - jnp.matmul(f, pa, precision=GEMM_PRECISION))
            b = jnp.where(is_owner, pb, b - jnp.matmul(f, pb, precision=GEMM_PRECISION))
        # one Newton (Schulz) refinement step, X <- X + X (I - A X): sequential
        # block elimination amplifies f32 rounding ~1000x over a pivoted LU;
        # squaring the residual wins that accuracy back for 2 extra SUMMA
        # passes (4 n^3 / p flops per device), still gather-free
        r = eye - panel_mm(a0, b, idx)
        b = b + panel_mm(b, r, idx)
        return b

    spec = P(axis_name, None)
    return jax.jit(
        jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False)
    )


def _embed_padded_square(a) -> Tuple[jax.Array, int, int]:
    """
    Physical (n', n) row panels -> padded square blockdiag(A, I) of shape
    (n', n') with n' = p * ceil(n/p). Pure elementwise/pad ops — the SPMD
    partitioner keeps everything panel-local. det(X) == det(A); inv(X)'s top
    left (n, n) block is inv(A).
    """
    phys = a.parray  # (n', n), pad-row content unspecified
    n = a.shape[0]
    n_phys = phys.shape[0]
    rows = jnp.arange(n_phys)[:, None]
    x = jnp.where(rows < n, phys, jnp.zeros((), dtype=phys.dtype))
    if n_phys > n:
        x = jnp.pad(x, ((0, 0), (0, n_phys - n)))
        cols = jnp.arange(n_phys)[None, :]
        pad_eye = (rows == cols) & (rows >= n)
        x = jnp.where(pad_eye, jnp.ones((), dtype=x.dtype), x)
    return x, n, n_phys


def distributed_det(a) -> Tuple[jax.Array, bool]:
    """
    Determinant of a 2-D split matrix via blocked panel LU; never gathers the
    full operand. Returns ``(det, bad)``: ``bad`` is True when a diagonal
    block's LU hit a zero/non-finite pivot — block-local pivoting cannot reach
    across panels, so the caller must fall back to tell a genuinely singular
    matrix from a pivoting failure. ``det`` overflows/underflows exactly like
    numpy's raw-product determinant.
    """
    if a.split == 1:
        from . import basics

        a = basics.transpose(a)  # det(A) == det(A^T); transpose is local + remap
    comm = a.comm
    x, _, n_phys = _embed_padded_square(a)
    fn = _build_panel_det(
        comm.mesh, comm.axis_name, comm.size, n_phys // comm.size, np.dtype(x.dtype).name
    )
    unit, logabs, bad = fn(x)
    return unit * jnp.exp(logabs).astype(unit.dtype), bool(bad)


def distributed_inv(a) -> jax.Array:
    """Inverse of a 2-D split matrix via blocked Gauss-Jordan; never gathers
    the full operand. Returns the *logical* (n, n) inverse of ``a`` (or of
    ``a^T`` when split=1 — the caller re-transposes). May contain non-finite
    entries when a diagonal block is singular — callers fall back."""
    comm = a.comm
    x, n, n_phys = _embed_padded_square(a)
    fn = _build_panel_inv(
        comm.mesh, comm.axis_name, comm.size, n_phys // comm.size, np.dtype(x.dtype).name
    )
    out = fn(x)
    return out[:n, :n]
