"""
Distributed determinant / inverse via blocked panel elimination over the mesh.

The reference runs an *unblocked* Gauss-Jordan elimination over the split
matrix — a Python loop over all n columns with per-element ``.item()`` host
round-trips and row ``Bcast``s (reference heat/core/linalg/basics.py:160-423).
The TPU-native redesign blocks the elimination at device-panel granularity so
every step is MXU work:

* the (n, n) split-0 matrix lives as p row panels of (m, n), m = n/p (the
  padded physical layout; ragged n is embedded into the padded square
  ``blockdiag(A, I_pad)`` whose det/inv trivially recover A's);
* step k of p: the owner's diagonal block ``D_k`` is psum-broadcast, factored
  locally with partially-pivoted LU (``jax.scipy.linalg.lu_factor`` — *better*
  pivoting than the reference, which only swaps rows when a diagonal entry is
  near zero), the scaled pivot panel ``D_k^{-1} A_k`` is psum-broadcast, and
  every other panel applies one rank-m GEMM update;
* ``det`` right-looks (trailing columns only) and accumulates
  ``prod_k det(D_k)`` from the LU diagonals and pivot parities; ``inv`` runs
  the full Gauss-Jordan on the augmented identity panels.

Per-device memory stays O(n^2/p) — the full matrix is never gathered (asserted
on compiled HLO in tests/test_hlo_contract.py). Communication per step is two
(m, n) psums riding ICI; total volume 2·n^2 per device, the same order as one
all-gather, but the peak live footprint is panel-sized.

Pivoting is *block-local*: a singular diagonal block of a nonsingular matrix
(the one case needing cross-panel row swaps) yields non-finite/zero results;
the callers in ``basics.det``/``basics.inv`` detect that on the host and fall
back to the replicated path with a warning, mirroring the QR fallback policy.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import blocked
from .._compat import shard_map as _shard_map

GEMM_PRECISION = jax.lax.Precision.HIGHEST


def can_distribute_elimination(a) -> bool:
    """Whether det/inv take the distributed panel path: a 2-D square matrix,
    split on rows or columns, on a real multi-device mesh, with at least one
    logical row per device (smaller matrices gather trivially)."""
    return (
        a.ndim == 2
        and a.split in (0, 1)
        and a.comm.is_distributed()
        and a.shape[0] >= a.comm.size
    )


def acceptance_tol(dtype) -> float:
    """Residual acceptance threshold for the distributed inv/solve paths,
    scaled with the working precision (~3*sqrt(eps) of the real counterpart
    dtype; ~1e-3 for f32, ~4.5e-8 for f64). A dtype-independent constant would
    let an f64 solve ship f32-class accuracy instead of falling back to the
    replicated LAPACK path."""
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.complexfloating):
        dt = jnp.finfo(dt).dtype
    return float(3.0 * np.sqrt(np.finfo(dt).eps))


def _block_det_sign(piv: jax.Array, m: int) -> jax.Array:
    """Parity of a LAPACK-style ipiv vector: each ``piv[i] != i`` is one swap."""
    swaps = jnp.sum(piv != jnp.arange(m, dtype=piv.dtype))
    return jnp.where(swaps % 2 == 0, 1.0, -1.0)


def _build_panel_det(mesh, axis_name: str, p: int, m: int, dtype_name: str, use_blocked=None):
    """shard_map program: blocked right-looking LU determinant of a (p*m, p*m)
    row-split matrix. Returns a replicated scalar.

    ``use_blocked`` routes the diagonal-block factor through the MXU-blocked
    right-looking LU (blocked.py) when the block is above its crossover
    (None = read ``HEAT_TPU_BLOCKED_LINALG`` now); part of the compile cache
    key so an env flip never reuses the other kernel's program."""
    if use_blocked is None:
        use_blocked = blocked.kernels_enabled()
    return _build_panel_det_cached(mesh, axis_name, p, m, dtype_name, bool(use_blocked))


@functools.lru_cache(maxsize=None)
def _build_panel_det_cached(mesh, axis_name: str, p: int, m: int, dtype_name: str, use_blocked: bool):
    n = p * m
    dt = jnp.dtype(dtype_name)

    rdt = jnp.finfo(dt).dtype if jnp.issubdtype(dt, jnp.complexfloating) else dt

    def local(a):  # (m, n) local row panel
        idx = jax.lax.axis_index(axis_name)
        # determinant as (unit, log|det|, bad): the raw product of n diagonal
        # entries overflows f32 for modest n (exactly as numpy's does — the
        # caller re-materializes unit * exp(logabs), inf and all), while the
        # ``bad`` flag separates *block-singular pivoting failures* (zero or
        # non-finite LU diagonals) from honest overflow/underflow
        unit = jnp.ones((), dtype=dt)
        logabs = jnp.zeros((), dtype=rdt)
        bad = jnp.zeros((), dtype=bool)
        for k in range(p):
            c0, c1 = k * m, (k + 1) * m
            # owner's diagonal block, broadcast to all (psum of a one-hot sum)
            own = (idx == k).astype(dt)
            d_blk = jax.lax.psum(own * a[:, c0:c1], axis_name)  # (m, m)
            lu, piv = blocked.lu_factor_local(d_blk, use_blocked=use_blocked)
            diag = jnp.diagonal(lu)
            absd = jnp.abs(diag)
            bad = bad | ~jnp.all(jnp.isfinite(diag)) | jnp.any(absd == 0)
            safe = jnp.where(absd == 0, jnp.ones((), rdt), absd)
            unit = unit * _block_det_sign(piv, m).astype(dt) * jnp.prod(diag / safe)
            logabs = logabs + jnp.sum(jnp.log(safe))
            if k + 1 < p:
                # scaled pivot panel D^{-1} A_k over the trailing columns
                pa = jax.lax.psum(
                    own * jax.scipy.linalg.lu_solve((lu, piv), a[:, c1:]), axis_name
                )  # (m, n - c1)
                f = a[:, c0:c1]  # my block column k
                upd = a[:, c1:] - jnp.matmul(f, pa, precision=GEMM_PRECISION)
                # panels <= k are already reduced; leave them untouched
                a = a.at[:, c1:].set(jnp.where(idx > k, upd, a[:, c1:]))
        return unit, logabs, bad

    spec = P(axis_name, None)
    return jax.jit(
        _shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=(P(), P(), P()), check_vma=False
        )
    )


def _make_panel_ops(axis_name: str, p: int, m: int, dt, use_blocked: bool = False):
    """The two building blocks every panel program shares: the blocked
    Gauss-Jordan elimination sweep (applied to A and a companion panel B) and
    the SUMMA row-panel matmul. ``use_blocked`` routes the per-step diagonal
    block factor through the MXU-blocked LU (blocked.py)."""

    def panel_mm(x, y, idx):
        """Row panel of X @ Y for row-split X (width p*m) and row-split Y (any
        width): SUMMA over the mesh — step k psum-broadcasts Y's panel k and
        accumulates one (m, m) x (m, width) GEMM."""
        acc = jnp.zeros_like(y)
        for k in range(p):
            own = (idx == k).astype(dt)
            yk = jax.lax.psum(own * y, axis_name)
            acc = acc + jnp.matmul(x[:, k * m : (k + 1) * m], yk, precision=GEMM_PRECISION)
        return acc

    def eliminate(a, b, idx):
        """
        Two-phase blocked LU solve of ``A X = B`` (forward elimination of the
        below-diagonal blocks with pivot-row scaling, then backward
        substitution of the above-diagonal ones) — the numerically stabler
        split of the work: single-sweep Gauss-Jordan contaminates every row
        each step and pays an extra cond(A) power in forward error, which
        measured ~0.5 relative by n=4096 f32 on cond~1e4 inputs.
        Returns B's reduced panels (= A^{-1} B up to LU-class rounding).
        """
        # forward: row-block k is scaled to a unit diagonal block; only rows
        # BELOW it eliminate their block column
        for k in range(p):
            c0, c1 = k * m, (k + 1) * m
            own = (idx == k).astype(dt)
            d_blk = jax.lax.psum(own * a[:, c0:c1], axis_name)
            lu_piv = blocked.lu_factor_local(d_blk, use_blocked=use_blocked)
            pa = jax.lax.psum(own * jax.scipy.linalg.lu_solve(lu_piv, a), axis_name)
            pb = jax.lax.psum(own * jax.scipy.linalg.lu_solve(lu_piv, b), axis_name)
            f = a[:, c0:c1]
            below = idx > k
            a = jnp.where(
                idx == k, pa, jnp.where(below, a - jnp.matmul(f, pa, precision=GEMM_PRECISION), a)
            )
            b = jnp.where(
                idx == k, pb, jnp.where(below, b - jnp.matmul(f, pb, precision=GEMM_PRECISION), b)
            )
        # backward: A is now unit-block-upper-triangular; substitute upward
        for k in range(p - 1, 0, -1):
            own = (idx == k).astype(dt)
            pb = jax.lax.psum(own * b, axis_name)
            f = a[:, k * m : (k + 1) * m]
            b = jnp.where(
                idx < k, b - jnp.matmul(f, pb, precision=GEMM_PRECISION), b
            )
        return b

    return panel_mm, eliminate


def _refine(x, b, a, binv, panel_mm, idx, axis_name):
    """Two residual-GUARDED iterative-refinement steps: x' = x + M (b - A x)
    with M ~ A^{-1}; each kept only if it shrinks the residual (refinement
    diverges when ||I - A M|| >= 1, and an unguarded step was measured
    turning a 0.5-relative solution into 293). Returns ``(x, rel_residual)``
    — the caller decides whether the certified residual is good enough."""
    # all norms are computed max-abs-scaled: raw sum(b*b) overflows f32 for
    # |b| ~ 1e19+, which would zero the certified residual and silently
    # disable the ill-conditioning fallback for large-magnitude systems
    wdt = b.dtype if b.dtype != jnp.bool_ else jnp.float32
    # norms live in the REAL counterpart dtype: sum(t*t) of a complex residual
    # is complex, which breaks the better/< guards and the caller's float(rel)
    rdt = jnp.finfo(wdt).dtype if jnp.issubdtype(wdt, jnp.complexfloating) else wdt
    tiny = jnp.asarray(1e-30, rdt)
    scale = jnp.maximum(jax.lax.pmax(jnp.max(jnp.abs(b)), axis_name), tiny)

    def fro2(t):
        t = jnp.abs(t / scale)
        return jax.lax.psum(jnp.sum(t * t), axis_name)

    r = b - panel_mm(a, x, idx)
    nr = fro2(r)
    for _ in range(2):
        x1 = x + panel_mm(binv, r, idx)
        r1 = b - panel_mm(a, x1, idx)
        n1 = fro2(r1)
        better = n1 < nr
        x = jnp.where(better, x1, x)
        r = jnp.where(better, r1, r)
        nr = jnp.where(better, n1, nr)
    nb = fro2(b)
    return x, jnp.sqrt(nr / jnp.maximum(nb, tiny))


def _inv_panels(a, idx, axis_name: str, p: int, m: int, dt, use_blocked: bool = False):
    """Inverse panels of a row-split (p*m, p*m) matrix with a certified
    relative residual ||I - A X||_F / ||I||_F: two-phase block elimination
    plus residual-guarded refinement (SUMMA passes, gather-free). Block-local
    pivoting bounds accuracy at ~cond(A)*eps*growth — the residual tells the
    caller when that was not enough."""
    n = p * m
    panel_mm, eliminate = _make_panel_ops(axis_name, p, m, dt, use_blocked)
    rows = idx * m + jnp.arange(m)
    eye = (rows[:, None] == jnp.arange(n)[None, :]).astype(dt)
    binv = eliminate(a, eye, idx)
    return _refine(binv, eye, a, binv, panel_mm, idx, axis_name)


def _build_panel_solve(mesh, axis_name: str, p: int, m: int, k: int, dtype_name: str, use_blocked=None):
    """shard_map program: solve A X = B for a (p*m, p*m) row-split A and a
    (p*m, k) row-split B via two-phase block elimination of the augmented
    [B | I] plus residual-guarded iterative refinement. Returns
    ``(x_panels, rel_residual)`` — the certified residual lets the caller
    fall back when block-local pivoting was not enough for this matrix.
    Gather-free throughout. ``use_blocked`` (cache-keyed) selects the
    MXU-blocked diagonal-block LU."""
    if use_blocked is None:
        use_blocked = blocked.kernels_enabled()
    return _build_panel_solve_cached(mesh, axis_name, p, m, k, dtype_name, bool(use_blocked))


@functools.lru_cache(maxsize=None)
def _build_panel_solve_cached(mesh, axis_name: str, p: int, m: int, k: int, dtype_name: str, use_blocked: bool):
    dt = jnp.dtype(dtype_name)

    def local(a, b):  # (m, n) and (m, k) local row panels
        idx = jax.lax.axis_index(axis_name)
        panel_mm, eliminate = _make_panel_ops(axis_name, p, m, dt, use_blocked)
        # one elimination over the augmented [B | I]: the identity columns
        # yield the approximate inverse the refinement step uses as its
        # correction operator, sharing A's reduction work with the solve
        n_ = p * m
        rows = idx * m + jnp.arange(m)
        eye = (rows[:, None] == jnp.arange(n_)[None, :]).astype(dt)
        out = eliminate(a, jnp.concatenate([b, eye], axis=1), idx)
        x, binv = out[:, :k], out[:, k:]
        return _refine(x, b, a, binv, panel_mm, idx, axis_name)

    spec = P(axis_name, None)
    return jax.jit(
        _shard_map(
            local, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, P()), check_vma=False
        )
    )


def _build_panel_inv(mesh, axis_name: str, p: int, m: int, dtype_name: str, use_blocked=None):
    """shard_map program: two-phase block-elimination inverse of a (p*m, p*m)
    row-split matrix with guarded refinement. Returns ``(inverse_panels,
    rel_residual)``. ``use_blocked`` (cache-keyed) selects the MXU-blocked
    diagonal-block LU."""
    if use_blocked is None:
        use_blocked = blocked.kernels_enabled()
    return _build_panel_inv_cached(mesh, axis_name, p, m, dtype_name, bool(use_blocked))


@functools.lru_cache(maxsize=None)
def _build_panel_inv_cached(mesh, axis_name: str, p: int, m: int, dtype_name: str, use_blocked: bool):
    dt = jnp.dtype(dtype_name)

    def local(a):  # (m, n) local row panel
        idx = jax.lax.axis_index(axis_name)
        return _inv_panels(a, idx, axis_name, p, m, dt, use_blocked)

    spec = P(axis_name, None)
    return jax.jit(
        _shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=(spec, P()), check_vma=False
        )
    )


def _embed_padded_square(a) -> Tuple[jax.Array, int, int]:
    """
    Physical (n', n) row panels -> padded square blockdiag(A, I) of shape
    (n', n') with n' = p * ceil(n/p). Pure elementwise/pad ops — the SPMD
    partitioner keeps everything panel-local. det(X) == det(A); inv(X)'s top
    left (n, n) block is inv(A).
    """
    phys = a.parray  # (n', n), pad-row content unspecified
    n = a.shape[0]
    n_phys = phys.shape[0]
    rows = jnp.arange(n_phys)[:, None]
    x = jnp.where(rows < n, phys, jnp.zeros((), dtype=phys.dtype))
    if n_phys > n:
        x = jnp.pad(x, ((0, 0), (0, n_phys - n)))
        cols = jnp.arange(n_phys)[None, :]
        pad_eye = (rows == cols) & (rows >= n)
        x = jnp.where(pad_eye, jnp.ones((), dtype=x.dtype), x)
    return x, n, n_phys


def distributed_det(a) -> Tuple[jax.Array, bool]:
    """
    Determinant of a 2-D split matrix via blocked panel LU; never gathers the
    full operand. Returns ``(det, bad)``: ``bad`` is True when a diagonal
    block's LU hit a zero/non-finite pivot — block-local pivoting cannot reach
    across panels, so the caller must fall back to tell a genuinely singular
    matrix from a pivoting failure. ``det`` overflows/underflows exactly like
    numpy's raw-product determinant (materialized from the slogdet pair).
    """
    unit, logabs, bad = distributed_slogdet(a)
    return unit * jnp.exp(logabs).astype(unit.dtype), bad


def distributed_slogdet(a) -> Tuple[jax.Array, jax.Array, bool]:
    """(sign, log|det|, bad) of a 2-D split matrix via the same blocked panel
    LU as :func:`distributed_det` — the pair is what the kernel natively
    accumulates, so no overflow is possible (numpy.linalg.slogdet parity)."""
    if a.split == 1:
        from . import basics

        a = basics.transpose(a)
    comm = a.comm
    x, _, n_phys = _embed_padded_square(a)
    fn = _build_panel_det(
        comm.mesh, comm.axis_name, comm.size, n_phys // comm.size, np.dtype(x.dtype).name
    )
    unit, logabs, bad = fn(x)
    return unit, logabs, bool(bad)


def distributed_solve(a, b_phys: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """
    Solve ``A X = B`` for a 2-D split-0 matrix ``a`` and right-hand side
    panels ``b_phys`` ((n', k), row-split, pad rows zero); returns
    ``(x, rel_residual)`` — the logical (n, k) solution and the certified
    relative residual ``||B - A X||_F / ||B||_F``. Gather-free: the same
    per-step psum-broadcast panels as the inverse, with the (m, k) RHS panel
    riding the augmented elimination. Block-local pivoting bounds accuracy
    at ~cond(A)*eps*growth; callers fall back on a poor residual (or on
    non-finite entries from a singular diagonal block).
    """
    comm = a.comm
    x, n, n_phys = _embed_padded_square(a)
    # bucket the RHS width to the next power of two: k is user-controlled, so
    # caching compiled programs per exact k would trace/retain one executable
    # per distinct width (zero-padded columns solve to zero and are sliced off)
    k = int(k)
    k_pad = 1 << max(k - 1, 0).bit_length() if k > 1 else 1
    b_run = b_phys.astype(x.dtype)
    if k_pad != k:
        b_run = jnp.pad(b_run, ((0, 0), (0, k_pad - k)))
    fn = _build_panel_solve(
        comm.mesh, comm.axis_name, comm.size, n_phys // comm.size, k_pad,
        np.dtype(x.dtype).name,
    )
    out, rel = fn(x, b_run)
    return out[:n, :k], rel


def distributed_inv(a) -> Tuple[jax.Array, jax.Array]:
    """Inverse of a 2-D split matrix via two-phase block elimination; never
    gathers the full operand. Returns ``(inverse, rel_residual)`` — the
    *logical* (n, n) inverse and the certified ``||I - A X||_F / ||I||_F``.
    Callers fall back on a poor residual or non-finite entries (singular
    diagonal block / ill-conditioning beyond block-local pivoting)."""
    comm = a.comm
    x, n, n_phys = _embed_padded_square(a)
    fn = _build_panel_inv(
        comm.mesh, comm.axis_name, comm.size, n_phys // comm.size, np.dtype(x.dtype).name
    )
    out, rel = fn(x)
    return out[:n, :n], rel
