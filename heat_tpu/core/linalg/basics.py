"""
Basic linear algebra.

Parity with the reference's ``heat/core/linalg/basics.py`` (``__all__``: cross, det,
dot, inv, matmul, matrix_norm, norm, outer, projection, trace, transpose, tril, triu,
vdot, vecdot, vector_norm). The reference hand-schedules block-panel matmul with
double-buffered ``Ibcast`` rounds (basics.py:799-1094) and a ring for ``outer``
(:1565-1575); on TPU the sharded ``jnp.matmul`` *is* that algorithm — XLA SPMD emits
the panel broadcasts/collectives and overlaps them with MXU compute via its
latency-hiding scheduler.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import _elimination
from . import blocked
from .. import factories
from .. import fusion as _fusion
from .. import sanitation
from .. import stride_tricks
from .. import types
from ..communication import MeshCommunication
from ..dndarray import DNDarray

# Linalg runs its MXU contractions at full input precision by default — including
# the iterative solvers (cg/lanczos) and final SVD projections, which accumulate
# GEMM error: the TPU default lowers f32 operands to one bf16 pass (~1e-2 relative
# error), but reference users expect the accuracy of torch's f32 GEMM. Callers that
# prefer throughput pass matmul(..., precision=jax.lax.Precision.DEFAULT) — the
# rsvd power-iteration sketch does, and ML fit loops (e.g. the KMeans step) use raw
# jnp contractions at the fast default deliberately.
GEMM_PRECISION = jax.lax.Precision.HIGHEST

__all__ = [
    "cross",
    "det",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "slogdet",
    "solve",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


def __wrap(proto: DNDarray, data: jax.Array, split) -> DNDarray:
    # data is the logical result; DNDarray.__init__ establishes the canonical
    # (padded, sharded) physical placement for ragged split axes
    return DNDarray(
        data, tuple(data.shape), types.canonical_heat_type(data.dtype), split, proto.device, proto.comm, True
    )


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    """Cross product of 3-element vectors along an axis (reference
    linalg/basics.py:47-159)."""
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    a._flush("linalg")
    b._flush("linalg")
    data = jnp.cross(a.larray, b.larray, axisa=axisa, axisb=axisb, axisc=axisc, axis=axis)
    return __wrap(a, data, a.split if a.split is not None and a.split < data.ndim else None)


def det(a: DNDarray) -> DNDarray:
    """
    Determinant of a square matrix (reference linalg/basics.py:160-245 runs an
    unblocked distributed Gauss-Jordan with row Bcasts).

    A 2-D matrix split on rows or columns takes the **distributed blocked-LU
    path** (``_elimination.distributed_det``): device-panel elimination via
    ``shard_map`` — per step one psum-broadcast diagonal block, one local
    partially-pivoted LU, one MXU GEMM trailing update — so the full operand is
    never gathered to one device (HLO-asserted in tests/test_hlo_contract.py).
    Pivoting is block-local; the rare singular-diagonal-block case is detected
    (zero/non-finite result) and falls back to the replicated ``jnp.linalg.det``
    with a warning, like the QR fallback. Batch-split stacks partition
    trivially along the batch axis and use the local path directly.
    """
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("a must be a square matrix (or batch thereof)")
    a._flush("linalg")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)

    def __wrap_det(data):
        data = jnp.asarray(data)
        return DNDarray(
            data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, a.device, a.comm, True
        )

    if _elimination.can_distribute_elimination(a):
        data, bad = _elimination.distributed_det(a)
        if not bad:
            return __wrap_det(data)
        # a zero/non-finite LU pivot inside a diagonal block: either the matrix
        # is genuinely singular or only that block is (block-local pivoting
        # can't reach across panels) — only the replicated LU can tell the two
        # apart
        warnings.warn(
            "distributed det hit a singular diagonal block (singular matrix or "
            "block-pivoting failure); falling back to the replicated "
            "determinant, which gathers the full matrix to every device",
            UserWarning,
        )
    # local/replicated path: MXU-blocked LU (blocked.py) above the crossover,
    # the old jnp.linalg.det bit-for-bit below it or with the gate off
    if a.larray.ndim == 2:
        return __wrap_det(blocked.det(a.larray))
    return __wrap_det(jnp.linalg.det(a.larray))


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> Union[DNDarray, float]:
    """
    Dot product: scalar for 1-D inputs, matmul for 2-D (reference
    linalg/basics.py:246-330).
    """
    if isinstance(a, DNDarray) and isinstance(b, DNDarray) and a.ndim == 1 and b.ndim == 1:
        if out is None and _fusion.enabled():
            # GEMM producer node over the (possibly pending) operands: the dot
            # and any scalar epilogue chain compile as one XLA program
            deferred = _fusion.defer_matmul(
                a, b, None, GEMM_PRECISION, (), None, op="dot"
            )
            if deferred is not None:
                return deferred
        a._flush("linalg")
        b._flush("linalg")
        res = jnp.dot(a.larray, b.larray, precision=GEMM_PRECISION)
        result = DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)
        if out is not None:
            out.larray = res.astype(out.dtype.jnp_type())
            return out
        return result
    if a.ndim <= 2 and b.ndim <= 2:
        res = matmul(a, b)
        if out is not None:
            out.larray = res.larray.astype(out.dtype.jnp_type())
            return out
        return res
    raise NotImplementedError("ht.dot supports 1-D and 2-D operands")


def inv(a: DNDarray) -> DNDarray:
    """
    Multiplicative inverse of a square matrix (reference linalg/basics.py:331-423
    runs an unblocked distributed Gauss-Jordan).

    A 2-D matrix split on rows or columns takes the **distributed blocked
    Gauss-Jordan path** (``_elimination.distributed_inv``): shard_map
    device-panel elimination on the augmented identity — per step two (m, n)
    psum-broadcasts and two MXU GEMM updates — so the full operand is never
    gathered (HLO-asserted in tests/test_hlo_contract.py). A split=1 input is
    inverted as ``inv(A) = inv(A^T)^T`` (transpose is a local permute + split
    remap). Block-local pivoting: singular diagonal blocks yield non-finite
    entries, detected on the host with a warned fallback to the replicated
    ``jnp.linalg.inv`` — a genuinely singular matrix raises like the reference.
    """
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("a must be a square matrix (or batch thereof)")
    a._flush("linalg")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    if _elimination.can_distribute_elimination(a):
        if a.split == 1:
            # inv(A) = inv(A^T)^T; transpose is a local permute + split remap,
            # so the recursion lands on the split=0 panel path (or its fallback)
            return transpose(inv(transpose(a)))
        data, rel = _elimination.distributed_inv(a)
        if bool(jnp.all(jnp.isfinite(data))) and float(rel) < _elimination.acceptance_tol(data.dtype):
            return __wrap(a, data, a.split)
        # non-finite: singular diagonal block. Finite but poor certified
        # residual: the matrix is too ill-conditioned for block-local
        # pivoting — the replicated LAPACK path pivots across the whole
        # matrix and recovers full f32 accuracy
        warnings.warn(
            "distributed inv residual too large (singular diagonal block or "
            "ill-conditioning beyond block-local pivoting); falling back to "
            "the replicated inverse, which gathers the full matrix to every "
            "device",
            UserWarning,
        )
    data = blocked.inv(a.larray) if a.larray.ndim == 2 else jnp.linalg.inv(a.larray)
    if not bool(jnp.all(jnp.isfinite(data))):
        raise RuntimeError("Inverse does not exist")
    return __wrap(a, data, a.split)


def __matmul_split(a: DNDarray, b: DNDarray, ndim: int) -> Optional[int]:
    """Split semantics of a matmul result, following the reference: row-split
    ``a`` gives a row-split result, column-split ``b`` a column-split result."""
    if ndim == 0:
        return None
    if b.ndim == 1:
        # matvec: result dims are a.shape[:-1]; a's split survives unless it was
        # the contracted axis
        return a.split if (a.split is not None and a.split < a.ndim - 1) else None
    if a.ndim == 1:
        # vecmat: result dims are b.shape[:-2] + b.shape[-1:]
        if b.split is None or b.split == b.ndim - 2:
            return None
        if b.split == b.ndim - 1:
            return ndim - 1
        return b.split  # batch dims
    if a.split == a.ndim - 2:
        return ndim - 2
    if b.split == b.ndim - 1:
        return ndim - 1
    if a.split is not None and a.split < a.ndim - 2:
        return a.split  # batch dims
    return None


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False, precision=GEMM_PRECISION) -> DNDarray:
    """
    Matrix multiplication (reference linalg/basics.py:424-1094). The reference's
    case analysis over ``(a.split, b.split)`` with block-cyclic ``Ibcast`` panel
    rounds is replaced by the sharded global ``jnp.matmul``: XLA SPMD partitions the
    contraction, inserts the panel collectives over ICI and overlaps them with MXU
    GEMMs. Split semantics of the result follow the reference: row-split ``a`` gives a
    row-split result, column-split ``b`` a column-split result.

    With fusion on (``HEAT_TPU_FUSION_GEMM``, default), the dispatch records a
    GEMM *producer* node in the deferred-execution DAG over pending or
    concrete operands: downstream bias-add/activation/cast chains then
    compile with the GEMM as one XLA program and the epilogue fuses into the
    MXU contraction (``core/fusion.py``).
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    if a.ndim == 0 or b.ndim == 0:
        raise ValueError("matmul requires at least 1-dimensional operands")
    dtype = types.promote_types(a.dtype, b.dtype)
    # static result shape + split bookkeeping, computed BEFORE any data access
    # so a pending operand chain can absorb the GEMM as a producer node;
    # shapes the static pass rejects fall through to the eager dispatch, whose
    # jnp.matmul raises the canonical error
    out_gshape = None
    try:
        if a.ndim == 1 and b.ndim == 1:
            out_gshape = ()
        elif b.ndim == 1:
            out_gshape = tuple(a.shape[:-1])
        elif a.ndim == 1:
            out_gshape = tuple(b.shape[:-2]) + (b.shape[-1],)
        else:
            out_gshape = tuple(
                np.broadcast_shapes(tuple(a.shape[:-2]), tuple(b.shape[:-2]))
            ) + (a.shape[-2], b.shape[-1])
    except ValueError:
        out_gshape = None
    if out_gshape is not None and _fusion.enabled():
        split = __matmul_split(a, b, len(out_gshape))
        deferred = _fusion.defer_matmul(a, b, dtype, precision, out_gshape, split)
        if deferred is not None:
            return deferred
    a._flush("linalg")
    b._flush("linalg")
    data = jnp.matmul(
        a.larray.astype(dtype.jnp_type()),
        b.larray.astype(dtype.jnp_type()),
        precision=precision,
    )
    split = __matmul_split(a, b, data.ndim)
    return __wrap(a, data, split)


def slogdet(a: DNDarray) -> Tuple[DNDarray, DNDarray]:
    """
    Sign and natural log of the absolute determinant, ``(sign, logabsdet)``
    (numpy-API completion beyond the reference snapshot, which has no
    slogdet). Split matrices ride the same blocked panel LU as :func:`det` —
    the (sign, log|det|) pair is what that kernel natively accumulates, so
    the result cannot overflow no matter the matrix size. Singular diagonal
    blocks fall back to the replicated ``jnp.linalg.slogdet`` with a warning.
    """
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("a must be a square matrix (or batch thereof)")
    a._flush("linalg")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)

    def __wrap_pair(s, l):
        s, l = jnp.asarray(s), jnp.asarray(l)
        return (
            DNDarray(s, tuple(s.shape), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True),
            DNDarray(l, tuple(l.shape), types.canonical_heat_type(l.dtype), None, a.device, a.comm, True),
        )

    if _elimination.can_distribute_elimination(a):
        unit, logabs, bad = _elimination.distributed_slogdet(a)
        if not bad:
            return __wrap_pair(unit, logabs)
        warnings.warn(
            "distributed slogdet hit a singular diagonal block (singular matrix "
            "or block-pivoting failure); falling back to the replicated "
            "slogdet, which gathers the full matrix to every device",
            UserWarning,
        )
    if a.larray.ndim == 2:
        s, l = blocked.slogdet(a.larray)
    else:
        s, l = jnp.linalg.slogdet(a.larray)
    return __wrap_pair(s, l)


def solve(a: DNDarray, b: DNDarray) -> DNDarray:
    """
    Solve the linear system ``a @ x = b`` (numpy-API completion beyond the
    reference snapshot, whose only solvers are the iterative cg/lanczos,
    reference linalg/solver.py:13-184). A 2-D split ``a`` runs the blocked
    panel Gauss-Jordan of :func:`inv` with the right-hand-side panels in
    place of the augmented identity — per step one (m, n) and one (m, k)
    psum-broadcast plus two MXU GEMM updates, never a full-operand gather.
    ``b`` may be a vector or a matrix of right-hand sides; the result keeps
    ``b``'s shape with ``a``'s row distribution. Singular diagonal blocks
    fall back to the replicated ``jnp.linalg.solve`` with a warning; a
    genuinely singular system raises like :func:`inv`.
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"a must be a square 2-D matrix, got shape {tuple(a.shape)}")
    if b.ndim not in (1, 2) or b.shape[0] != a.shape[0]:
        raise ValueError(
            f"b must be (n,) or (n, k) with n == {a.shape[0]}, got {tuple(b.shape)}"
        )
    a._flush("linalg")
    b._flush("linalg")
    dtype = types.promote_types(a.dtype, b.dtype)
    if not types.heat_type_is_inexact(dtype):
        dtype = types.float32
    # copying casts: astype(copy=False) would rebind the CALLER's arrays
    if a.dtype != dtype:
        a = a.astype(dtype)
    if b.dtype != dtype:
        b = b.astype(dtype)
    vector_rhs = b.ndim == 1
    if _elimination.can_distribute_elimination(a):
        if a.split == 1:
            # reshard A's rows once (one placement) and run the k-column panel
            # solve — far cheaper than materializing the full inverse
            a = __wrap(a, a.larray, 0)
        b2 = b if not vector_rhs else __wrap(b, b.larray[:, None], 0 if b.split == 0 else None)
        # RHS rows must follow A's row panels; pad rows must be ZERO so the
        # identity-extended system maps them to a zero solution block
        b_phys = a.comm.placed(b2.larray, 0, gshape=b2.shape, fill=0)
        data, rel = _elimination.distributed_solve(a, b_phys, int(b2.shape[1]))
        if bool(jnp.all(jnp.isfinite(data))) and float(rel) < _elimination.acceptance_tol(data.dtype):
            if vector_rhs:
                data = data[:, 0]
            # a is split 0 on this path (split=1 was resharded above)
            return __wrap(a, data, 0)
        warnings.warn(
            "distributed solve residual too large (singular diagonal block or "
            "ill-conditioning beyond block-local pivoting); falling back to "
            "the replicated solve, which gathers the full matrix to every "
            "device",
            UserWarning,
        )
    data = blocked.solve(a.larray, b.larray)
    if not bool(jnp.all(jnp.isfinite(data))):
        raise RuntimeError("Singular matrix: solve has no solution")
    return __wrap(a, data, b.split if b.split is not None and b.split < data.ndim else None)


def matrix_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm over the last two (or given) axes (reference
    linalg/basics.py:1095-1230)."""
    sanitation.sanitize_in(x)
    if axis is None:
        if x.ndim < 2:
            raise ValueError("matrix_norm requires at least 2 dimensions")
        axis = (x.ndim - 2, x.ndim - 1)
    axis = tuple(stride_tricks.sanitize_axis(x.shape, a) for a in axis)
    if _fusion.sink_ready(x):
        res = _fusion.defer_norm(x, ord, axis, keepdims, flatten=False)
        if res is not None:
            return res
    with _fusion.flush_reason("reduction"):
        data = jnp.linalg.norm(x.larray, ord=ord, axis=axis, keepdims=keepdims)
    data = jnp.asarray(data)
    return DNDarray(data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, x.device, x.comm, True)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector/matrix norm dispatch (reference linalg/basics.py:1231-1310). A
    pending fused chain on ``x`` is consumed as a reduction sink — the
    elementwise subgraph, the norm reduction, and its ``sqrt`` epilogue
    compile as one XLA program (core/fusion.py)."""
    sanitation.sanitize_in(x)
    if _fusion.sink_ready(x):
        res = _fusion.defer_norm(x, ord, axis, keepdims, flatten=False)
        if res is not None:
            return res
    with _fusion.flush_reason("reduction"):
        data = jnp.linalg.norm(x.larray, ord=ord, axis=axis, keepdims=keepdims)
    data = jnp.asarray(data)
    return DNDarray(data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, x.device, x.comm, True)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """
    Outer product of two vectors (reference linalg/basics.py:1372-1604 circulates
    panels around a Send/Recv ring; here the sharded broadcast-multiply — XLA emits
    the same systolic pattern for a (n,1)×(1,m) contraction).
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    a._flush("linalg")
    b._flush("linalg")
    dtype = types.promote_types(a.dtype, b.dtype)
    data = jnp.outer(a.larray.astype(dtype.jnp_type()), b.larray.astype(dtype.jnp_type()))
    if split is None:
        split = 0 if a.split is not None else (1 if b.split is not None else None)
    res = __wrap(a, data, split)
    if out is not None:
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of vector ``a`` onto vector ``b`` (reference
    linalg/basics.py:1605-1628)."""
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}-d and {b.ndim}-d")
    return (dot(a, b) / dot(b, b)) * b


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None):
    """Sum along diagonals (reference linalg/basics.py:1629-1770)."""
    sanitation.sanitize_in(a)
    if a.ndim < 2:
        raise ValueError("trace requires at least 2 dimensions")
    a._flush("linalg")
    data = jnp.trace(a.larray, offset=offset, axis1=axis1, axis2=axis2)
    data = jnp.asarray(data)
    if dtype is not None:
        data = data.astype(types.canonical_heat_type(dtype).jnp_type())
    res = DNDarray(data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, a.device, a.comm, True)
    if out is not None:
        out.larray = data.astype(out.dtype.jnp_type())
        return out
    if res.ndim == 0:
        return res.item()
    return res


def transpose(a: DNDarray, axes: Optional[List[int]] = None) -> DNDarray:
    """Permute array dimensions; the split axis follows the permutation (reference
    linalg/basics.py:2051-2120). A pending fused chain on ``a`` records a view
    node instead of flushing — the pad of a ragged split axis rides at the end
    of the remapped axis (``core/fusion.py``)."""
    sanitation.sanitize_in(a)
    if axes is None:
        axes = list(range(a.ndim))[::-1]
    axes = [stride_tricks.sanitize_axis(a.shape, ax) for ax in axes]
    split = axes.index(a.split) if a.split is not None else None
    if _fusion.view_ready(a):
        out_gshape = tuple(a.shape[ax] for ax in axes)
        res = _fusion.defer_view(
            a, "transpose", (tuple(int(ax) for ax in axes),), out_gshape, split
        )
        if res is not None:
            return res
    a._flush("linalg")
    data = jnp.transpose(a.larray, axes)
    return __wrap(a, data, split)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower triangle (reference linalg/basics.py:2121-2178)."""
    sanitation.sanitize_in(m)
    m._flush("linalg")
    data = jnp.tril(m.larray if m.ndim > 1 else jnp.tile(m.larray, (m.shape[0], 1)), k=k)
    if m.ndim == 1:
        return DNDarray(data, tuple(data.shape), m.dtype, None, m.device, m.comm, True)
    return __wrap(m, data, m.split)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper triangle (reference linalg/basics.py:2179-2235)."""
    sanitation.sanitize_in(m)
    m._flush("linalg")
    data = jnp.triu(m.larray if m.ndim > 1 else jnp.tile(m.larray, (m.shape[0], 1)), k=k)
    if m.ndim == 1:
        return DNDarray(data, tuple(data.shape), m.dtype, None, m.device, m.comm, True)
    return __wrap(m, data, m.split)


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product of flattened inputs (reference
    linalg/basics.py:2236-2270)."""
    sanitation.sanitize_in(x1)
    sanitation.sanitize_in(x2)
    x1._flush("linalg")
    x2._flush("linalg")
    data = jnp.vdot(x1.larray, x2.larray, precision=GEMM_PRECISION)
    return DNDarray(data, (), types.canonical_heat_type(data.dtype), None, x1.device, x1.comm, True)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdim: bool = False) -> DNDarray:
    """Vector dot product along an axis (reference linalg/basics.py:2271-2308).
    Pending fused chains on either operand are consumed as a reduction sink —
    the conj–multiply–sum pipeline traces into the same XLA program as the
    operand subgraphs (core/fusion.py)."""
    sanitation.sanitize_in(x1)
    sanitation.sanitize_in(x2)
    if axis is None:
        axis = -1
    if _fusion.sink_ready(x1) or _fusion.sink_ready(x2):
        res = _fusion.defer_vecdot(x1, x2, axis, keepdim)
        if res is not None:
            return res
    with _fusion.flush_reason("reduction"):
        a, b = jnp.broadcast_arrays(x1.larray, x2.larray)
    data = jnp.sum(jnp.conj(a) * b, axis=axis, keepdims=keepdim)
    return DNDarray(data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, x1.device, x1.comm, True)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm along an axis (reference linalg/basics.py:1311-1371). A
    pending fused chain on ``x`` is consumed as a reduction sink
    (core/fusion.py)."""
    sanitation.sanitize_in(x)
    flatten = axis is None and x.ndim > 1
    if _fusion.sink_ready(x):
        res = _fusion.defer_norm(
            x, ord if ord is not None else 2,
            None if flatten else axis,
            False if flatten else keepdims,
            flatten=flatten,
        )
        if res is not None:
            return res
    with _fusion.flush_reason("reduction"):
        if flatten:
            data = jnp.linalg.norm(x.larray.reshape(-1), ord=ord if ord is not None else 2)
        else:
            data = jnp.linalg.norm(x.larray, ord=ord if ord is not None else 2, axis=axis, keepdims=keepdims)
    data = jnp.asarray(data)
    return DNDarray(data, tuple(data.shape), types.canonical_heat_type(data.dtype), None, x.device, x.comm, True)


DNDarray.__matmul__ = lambda self, other: matmul(self, other)
DNDarray.__rmatmul__ = lambda self, other: matmul(
    other if isinstance(other, DNDarray) else factories.array(other, comm=self.comm), self
)
DNDarray.transpose = transpose
