"""
Singular value decomposition.

The reference ships only a stub (``heat/core/linalg/svd.py:5`` — commented-out
``__all__``; SVD is unimplemented there). This framework provides a working ``svd``:
local ``jnp.linalg.svd`` for unsplit arrays, and for tall-skinny row-split arrays a
TSQR-based two-step (QR via the distributed :func:`~.qr.qr`, then SVD of the small R)
— a strict capability superset of the reference.
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax.numpy as jnp

from .. import sanitation
from .. import types
from ..dndarray import DNDarray
from .basics import matmul
from .qr import qr as _qr

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, Vh")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """
    SVD ``a = U @ diag(S) @ Vh``. For row-split tall-skinny inputs the factorization
    runs as TSQR + small-R SVD entirely on-device.

    Parameters
    ----------
    a : DNDarray
        2-D input.
    full_matrices : bool
        Only ``False`` (thin SVD) is supported for split inputs.
    compute_uv : bool
        If False, return only the singular values.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D DNDarray, got {a.ndim}-d")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    m, n = a.shape
    if a.split == 0 and m >= n and compute_uv and not full_matrices:
        q, r = _qr(a)
        u_r, s, vh = jnp.linalg.svd(r.larray, full_matrices=False)
        u = matmul(q, DNDarray(u_r, (n, n), a.dtype, None, a.device, a.comm, True))
        return SVD(
            u,
            DNDarray(s, (n,), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True),
            DNDarray(vh, (n, n), a.dtype, None, a.device, a.comm, True),
        )
    if not compute_uv:
        s = jnp.linalg.svd(a.larray, compute_uv=False)
        return DNDarray(s, tuple(s.shape), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True)
    u, s, vh = jnp.linalg.svd(a.larray, full_matrices=full_matrices)
    return SVD(
        DNDarray(u, tuple(u.shape), a.dtype, None, a.device, a.comm, True),
        DNDarray(s, tuple(s.shape), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True),
        DNDarray(vh, tuple(vh.shape), a.dtype, None, a.device, a.comm, True),
    )
