"""
Singular value decomposition.

The reference ships only a stub (``heat/core/linalg/svd.py:5`` — commented-out
``__all__``; SVD is unimplemented there). This framework provides a working ``svd``
— local ``jnp.linalg.svd`` for unsplit arrays, a TSQR-based two-step for tall-skinny
row-split arrays (QR via the distributed :func:`~.qr.qr`, then SVD of the small R),
the transpose trick for column-split wide arrays — plus :func:`rsvd`, a fully
distributed randomized SVD (Halko/Martinsson/Tropp sketch + power iterations) whose
every step is sharded GEMMs/TSQR — a strict capability superset of the reference.
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import blocked
from .. import sanitation
from .. import types
from ..dndarray import DNDarray
from .basics import matmul, transpose
from .qr import qr as _qr

__all__ = ["svd", "rsvd"]

SVD = collections.namedtuple("SVD", "U, S, Vh")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """
    SVD ``a = U @ diag(S) @ Vh``. For row-split tall-skinny inputs the factorization
    runs as TSQR + small-R SVD entirely on-device.

    Parameters
    ----------
    a : DNDarray
        2-D input.
    full_matrices : bool
        Only ``False`` (thin SVD) is supported for split inputs.
    compute_uv : bool
        If False, return only the singular values.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D DNDarray, got {a.ndim}-d")
    a._flush("linalg")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    m, n = a.shape
    if a.split == 0 and m >= n and compute_uv and not full_matrices:
        q, r = _qr(a)
        # small-R SVD: QDWH polar + eigh (blocked.py) above the crossover,
        # the old jnp.linalg.svd bit-for-bit below it or with the gate off
        u_r, s, vh = blocked.svd(r.larray, full_matrices=False)
        u = matmul(q, DNDarray(u_r, (n, n), a.dtype, None, a.device, a.comm, True))
        return SVD(
            u,
            DNDarray(s, (n,), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True),
            DNDarray(vh, (n, n), a.dtype, None, a.device, a.comm, True),
        )
    if a.split == 1 and n > m and compute_uv and not full_matrices:
        # wide, column-split: a^T is tall-skinny row-split; a = (U' S Vh')^T
        # swaps the factors — U = Vh'^T (small, replicated), Vh = U'^T (split=1)
        ut, s, vht = svd(transpose(a, (1, 0)), full_matrices=False, compute_uv=True)
        return SVD(transpose(vht, (1, 0)), s, transpose(ut, (1, 0)))
    if not compute_uv:
        s = blocked.svd(a.larray, compute_uv=False)
        return DNDarray(s, tuple(s.shape), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True)
    u, s, vh = blocked.svd(a.larray, full_matrices=full_matrices)
    return SVD(
        DNDarray(u, tuple(u.shape), a.dtype, None, a.device, a.comm, True),
        DNDarray(s, tuple(s.shape), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True),
        DNDarray(vh, tuple(vh.shape), a.dtype, None, a.device, a.comm, True),
    )


def rsvd(
    a: DNDarray,
    rank: int,
    n_oversamples: int = 10,
    n_iter: int = 2,
    random_state: Optional[int] = None,
) -> SVD:
    """
    Randomized truncated SVD of rank ``rank`` (Halko, Martinsson & Tropp 2011,
    "Finding structure with randomness"). Every step is a sharded operation —
    sketch and power-iteration GEMMs distribute over the split axis (XLA inserts
    the psum over the contracted sharded axis), the orthonormalisation is the TSQR
    path of :func:`~.qr.qr` — so the factorisation scales to arrays whose split
    axis spans the whole mesh. Beyond-reference capability (the reference's svd is
    an empty stub; its closest machinery is the Lanczos tridiagonalisation,
    heat/core/linalg/solver.py:68).

    Parameters
    ----------
    a : DNDarray
        2-D input (any split).
    rank : int
        Target rank of the approximation.
    n_oversamples : int
        Extra sketch columns stabilising the range estimate.
    n_iter : int
        Subspace (power) iterations; 1-2 suffices unless the spectrum decays slowly.
    random_state : int, optional
        Seed for the Gaussian sketch.

    Returns
    -------
    SVD(U, S, Vh) with shapes (m, rank), (rank,), (rank, n); U inherits a row
    distribution when ``a.split == 0``.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"rsvd requires a 2-D DNDarray, got {a.ndim}-d")
    a._flush("linalg")
    m, n = a.shape
    if not (1 <= rank <= min(m, n)):
        raise ValueError(f"rank must be in [1, min(m, n)]={min(m, n)}, got {rank}")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    l = min(rank + int(n_oversamples), min(m, n))

    key = jax.random.PRNGKey(0 if random_state is None else int(random_state))
    omega_data = jax.random.normal(key, (n, l), dtype=a.dtype.jnp_type())
    omega = DNDarray(omega_data, (n, l), a.dtype, None, a.device, a.comm, True)

    # the sketch only has to find the dominant subspace — the QR re-orthonormalisation
    # restores it each round — so its GEMMs run at the fast MXU default; the final
    # projection/recovery GEMMs below stay at full precision
    fast = jax.lax.Precision.DEFAULT
    y = matmul(a, omega, precision=fast)  # (m, l), split follows a's rows
    at = transpose(a, (1, 0))
    for _ in range(int(n_iter)):
        # subspace iteration: y <- a (a^T y); re-orthonormalise to stop the
        # sketch collapsing onto the top singular vector
        y = _qr(y).Q
        y = matmul(a, matmul(at, y, precision=fast), precision=fast)
    q = _qr(y).Q  # (m, l) orthonormal, distributed for split=0
    b = matmul(transpose(q, (1, 0)), a)  # (l, n) small, contraction over rows
    u_b, s, vh = blocked.svd(b.resplit(None).larray, full_matrices=False)
    u = matmul(q, DNDarray(u_b[:, :rank], (l, rank), a.dtype, None, a.device, a.comm, True))
    return SVD(
        u,
        DNDarray(
            s[:rank], (rank,), types.canonical_heat_type(s.dtype), None, a.device, a.comm, True
        ),
        DNDarray(vh[:rank], (rank, n), a.dtype, None, a.device, a.comm, True),
    )
