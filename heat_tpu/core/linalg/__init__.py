"""Linear algebra subpackage (parity: reference heat/core/linalg/__init__.py)."""

from .basics import *
from .qr import *
from .solver import *
from .svd import *
