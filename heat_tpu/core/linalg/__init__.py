"""Linear algebra subpackage (parity: reference heat/core/linalg/__init__.py)."""

from . import blocked
from .basics import *
from .qr import *
from .solver import *
from .svd import *
