"""
Parallel I/O: HDF5, NetCDF and CSV.

Parity with the reference's ``heat/core/io.py`` (``__all__`` :29-43, HDF5/NetCDF slab
reads :57-660, ``load_csv`` byte-range splitting :713-925, extension dispatch
:662,1060). The reference has every rank read only its ``comm.chunk`` slab; in
single-controller SPMD the controller reads the slab for each device (for multi-host,
each host would read its addressable shards' slabs) and the sharding places them. All
I/O happens outside jit on the host.

Robustness (``doc/robustness_notes.md``): every save writes a same-directory
tempfile and ``os.replace``s it into place (a crash mid-save never truncates an
existing file; append modes update in place), every load/save attempt passes the
``io.read``/``io.write`` fault-injection sites, and transient ``OSError``/EIO
failures are retried with bounded exponential backoff
(:mod:`heat_tpu.robustness.retry`, counted as ``io.retries{site}``).
"""

from __future__ import annotations

import csv as csv_mod
import os
import tempfile
import time as _time
from typing import Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import devices
from . import factories
from . import types
from .communication import MeshCommunication, sanitize_comm
from .dndarray import DNDarray

# observability: load/save record bytes moved + duration when enabled
from ..monitoring.registry import STATE as _MON
from ..monitoring import instrument as _instr

# graceful degradation: every load/save attempt passes the deterministic
# fault-injection hooks and rides the shared bounded-backoff retry policy
# (transient OSError/EIO); saves are write-then-rename atomic (below)
from ..robustness import faultinject as _FI
from ..robustness import retry as _retry

#: file modes whose save semantics are a full rewrite — only these are made
#: atomic (append/update modes must touch the existing file in place)
_TRUNCATING_MODES = frozenset(("w", "w-", "x"))


def _atomic_write(path: str, mode: str, write, site: str) -> None:
    """Run ``write(target, mode)`` with the write-then-rename idiom and the
    shared retry policy.

    For truncating modes (and for a target that does not exist yet) the writer
    receives a same-directory tempfile and the result is ``os.replace``d into
    place — a crash mid-save can never truncate an existing file, readers only
    ever see the old or the new complete file (the idiom
    ``utils/checkpoint.py`` established). Append/update modes on an existing
    file operate in place: atomicity there would mean rewriting content the
    caller never passed us. Each attempt (including retries after a transient
    ``OSError``) re-checks the ``io.write`` fault site and starts from a fresh
    tempfile."""

    def attempt():
        _FI.check("io.write")
        if mode not in _TRUNCATING_MODES and os.path.exists(path):
            write(path, mode)
            return
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
        )
        os.close(fd)
        try:
            # the tempfile is a fresh target: a non-truncating mode on a
            # missing file has creation semantics, which "w" provides
            write(tmp, mode if mode in _TRUNCATING_MODES else "w")
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    _retry.policy().call(attempt, site=site)


def _load_sharded(reader, gshape, dtype, split, device, comm) -> Optional[DNDarray]:
    """
    Slab-wise distributed load: read each *addressable* device's slab separately
    (``reader(slices) -> np.ndarray``) and assemble the global array with
    ``jax.make_array_from_single_device_arrays`` — the reference's per-rank slab
    read (io.py:268-390) without ever materializing the full array on one host.
    In a multi-controller run each host reads only its own devices' slabs. Ragged
    split axes get the padded physical layout: even ceil(n/p) slabs, the tail
    zero-filled. Returns None when the layout calls for a plain replicated read.
    """
    comm = sanitize_comm(comm)
    if split is None or not isinstance(comm, MeshCommunication) or not comm.is_distributed():
        return None
    from .stride_tricks import sanitize_axis

    gshape = tuple(int(s) for s in gshape)
    split = sanitize_axis(gshape, split)  # same normalization/errors as factories.array
    htype = types.canonical_heat_type(dtype)
    np_dtype = np.dtype(htype.jnp_type())
    sharding = comm.sharding(len(gshape), split)
    pshape = comm.padded_shape(gshape, split)
    chunk = pshape[split] // comm.size
    n = gshape[split]
    this_process = jax.process_index()
    shards = []
    for r, dev in enumerate(comm.mesh.devices.ravel()):
        if dev.process_index != this_process:
            continue  # multi-controller: only this host's devices are addressable
        start = r * chunk
        stop_valid = min(start + chunk, n)
        slices = tuple(
            slice(start, max(start, stop_valid)) if d == split else slice(None)
            for d in range(len(gshape))
        )
        slab = np.asarray(reader(slices), dtype=np_dtype)
        if stop_valid - start < chunk:  # zero-fill the pad tail of the last shard(s)
            widths = [(0, 0)] * len(gshape)
            widths[split] = (0, chunk - max(stop_valid - start, 0))
            slab = np.pad(slab, widths)
        shards.append(jax.device_put(slab, dev))
    arr = jax.make_array_from_single_device_arrays(pshape, sharding, shards)
    return DNDarray(arr, gshape, htype, split, devices.sanitize_device(device), comm, True)

__all__ = ["load", "load_csv", "save_csv", "save", "supports_hdf5", "supports_netcdf"]

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4 as nc

    __NETCDF = True
except ImportError:
    __NETCDF = False

__HDF5_EXTENSIONS = frozenset([".h5", ".hdf5"])
__NETCDF_EXTENSIONS = frozenset([".nc", ".nc4", ".netcdf"])
__CSV_EXTENSION = ".csv"


def supports_hdf5() -> bool:
    """Whether HDF5 support (h5py) is available (reference io.py supports_hdf5)."""
    return __HDF5


def supports_netcdf() -> bool:
    """Whether NetCDF support (netCDF4) is available (reference io.py
    supports_netcdf)."""
    return __NETCDF


if __HDF5:
    __all__.extend(["load_hdf5", "save_hdf5"])

    def load_hdf5(
        path: str,
        dataset: str,
        dtype=types.float32,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """
        Load an HDF5 dataset into a (split) DNDarray (reference io.py:268-390: each
        rank reads its chunk slab; here the controller reads and the sharding places).
        """
        if not isinstance(path, str):
            raise TypeError(f"path must be str, not {type(path)}")
        if not isinstance(dataset, str):
            raise TypeError(f"dataset must be str, not {type(dataset)}")
        t0 = _time.perf_counter()

        def attempt():
            _FI.check("io.read")
            with h5py.File(path, "r") as handle:
                dset = handle[dataset]
                gshape = tuple(int(s) for s in dset.shape)
                res = _load_sharded(
                    lambda sl: dset[sl], gshape, dtype, split, device, comm
                )
                if res is None:
                    data = np.asarray(dset)
            if res is None:
                res = factories.array(
                    data, dtype=dtype, split=split, device=device, comm=comm
                )
            return res

        res = _retry.policy().call(attempt, site="load_hdf5")
        if _MON.enabled:
            _instr.record_io("load_hdf5", path, res.nbytes, _time.perf_counter() - t0)
        return res

    def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
        """
        Save a DNDarray to HDF5 (reference io.py:391-470: MPI-parallel writes when
        h5py is built against it, rank-serialised otherwise; one writer here).
        """
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, not {type(data)}")
        if not isinstance(path, str):
            raise TypeError(f"path must be str, not {type(path)}")
        t0 = _time.perf_counter()
        try:
            _save_hdf5_body(data, path, dataset, mode, **kwargs)
        finally:
            if _MON.enabled:
                _instr.record_io("save_hdf5", path, data.nbytes, _time.perf_counter() - t0)

    def _save_hdf5_body(data: DNDarray, path: str, dataset: str, mode: str, **kwargs) -> None:
        data._flush("io")
        arr = data.parray
        if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
            # multi-controller: a shard-wise write after a mode-'w' truncate would
            # leave only this host's slabs in the file — gather collectively
            # (numpy() runs process_allgather on every host) and let one writer
            # produce the complete file
            full = data.numpy()
            if jax.process_index() == 0:

                def write(target, m):
                    with h5py.File(target, m) as handle:
                        handle.create_dataset(dataset, data=full, **kwargs)

                _atomic_write(path, mode, write, site="save_hdf5")
            return

        def write(target, m):
            with h5py.File(target, m) as handle:
                if (
                    data.split is not None
                    and len(arr.sharding.device_set) > 1
                    and not arr.sharding.is_fully_replicated
                ):
                    # shard-wise write: fetch one device slab at a time (the
                    # reference's per-rank offset writes, io.py:391-470) instead of
                    # gathering the full array on the host first; pad rows of ragged
                    # layouts are clamped off against the logical extent
                    np_dtype = np.dtype(data.dtype.jnp_type())
                    dset = handle.create_dataset(dataset, shape=data.shape, dtype=np_dtype, **kwargs)
                    split = data.split % data.ndim
                    n = data.shape[split]
                    for shard in arr.addressable_shards:
                        idx = list(shard.index)
                        sl = idx[split]
                        start = sl.start or 0
                        if start >= n:
                            continue  # pure-pad shard
                        stop = n if sl.stop is None else min(sl.stop, n)
                        idx[split] = slice(start, stop)
                        block = np.asarray(shard.data)
                        take = [slice(None)] * data.ndim
                        take[split] = slice(0, stop - start)
                        dset[tuple(idx)] = block[tuple(take)]
                else:
                    handle.create_dataset(dataset, data=data.numpy(), **kwargs)

        _atomic_write(path, mode, write, site="save_hdf5")


if __NETCDF:
    __all__.extend(["load_netcdf", "save_netcdf"])

    def load_netcdf(
        path: str,
        variable: str,
        dtype=types.float32,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load a NetCDF variable into a (split) DNDarray (reference io.py:471-590);
        slab-wise per device like :func:`load_hdf5`."""
        t0 = _time.perf_counter()

        def attempt():
            _FI.check("io.read")
            with nc.Dataset(path, "r") as handle:
                var = handle.variables[variable]
                gshape = tuple(int(s) for s in var.shape)
                res = _load_sharded(
                    lambda sl: np.asarray(var[sl]), gshape, dtype, split, device, comm
                )
                if res is None:
                    data = np.asarray(var[:])
            if res is None:
                res = factories.array(
                    data, dtype=dtype, split=split, device=device, comm=comm
                )
            return res

        res = _retry.policy().call(attempt, site="load_netcdf")
        if _MON.enabled:
            _instr.record_io("load_netcdf", path, res.nbytes, _time.perf_counter() - t0)
        return res

    def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs) -> None:
        """Save a DNDarray to NetCDF (reference io.py:591-660)."""
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, not {type(data)}")
        t0 = _time.perf_counter()
        arr = data.numpy()  # collective in multi-controller runs
        if jax.process_index() != 0 and not data.parray.is_fully_addressable:
            return  # single writer

        def write(target, m):
            with nc.Dataset(target, m) as handle:
                for i, s in enumerate(arr.shape):
                    handle.createDimension(f"dim_{i}", s)
                var = handle.createVariable(
                    variable, arr.dtype, tuple(f"dim_{i}" for i in range(arr.ndim))
                )
                var[:] = arr

        _atomic_write(path, mode, write, site="save_netcdf")
        if _MON.enabled:
            _instr.record_io("save_netcdf", path, arr.nbytes, _time.perf_counter() - t0)


def load(path: str, *args, **kwargs) -> DNDarray:
    """
    Load data by file extension: ``.h5/.hdf5`` → HDF5, ``.nc/.nc4/.netcdf`` → NetCDF,
    ``.csv`` → CSV (reference io.py:662-712).

    Raises
    ------
    ValueError
        If the extension is unsupported or the backing library is missing.
    """
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    ext = os.path.splitext(path)[-1].lower()
    if ext in __HDF5_EXTENSIONS:
        if not __HDF5:
            raise RuntimeError("hdf5 is required for file extension {}".format(ext))
        return load_hdf5(path, *args, **kwargs)
    if ext in __NETCDF_EXTENSIONS:
        if not __NETCDF:
            raise RuntimeError("netcdf is required for file extension {}".format(ext))
        return load_netcdf(path, *args, **kwargs)
    if ext == __CSV_EXTENSION:
        return load_csv(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """
    Load a CSV file into a (split) DNDarray (reference io.py:713-925: per-rank byte
    ranges aligned to line breaks; one reader here, sharded placement).
    """
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, not {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, not {type(header_lines)}")
    t0 = _time.perf_counter()
    # native fast path: threaded C++ parser (heat_tpu/native/_csv.cpp — the
    # reference's per-rank byte-range line-aligned split, io.py:713-925, run
    # across host threads); falls back to the Python parser on any mismatch
    from .. import native

    def attempt():
        _FI.check("io.read")
        data = None
        if (
            encoding.lower().replace("-", "") in ("utf8", "ascii")
            and len(sep) == 1
            and sep.isascii()
            and native.available()
        ):
            with open(path, "rb") as handle:
                raw = handle.read()
            data = native.parse_csv(raw, sep, header_lines)
        if data is None:
            rows = []
            with open(path, "r", encoding=encoding, newline="") as handle:
                for i, line in enumerate(handle):
                    if i < header_lines:
                        continue
                    line = line.strip()
                    if not line:
                        continue
                    rows.append([float(v) for v in line.split(sep)])
            data = np.asarray(rows)
            if data.size == 0:
                data = np.empty((0, 0))  # match the native parser's empty shape
        return data

    data = _retry.policy().call(attempt, site="load_csv")
    res = factories.array(data, dtype=dtype, split=split, device=device, comm=comm)
    if _MON.enabled:
        _instr.record_io("load_csv", path, res.nbytes, _time.perf_counter() - t0)
    return res


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[str] = None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    **kwargs,
) -> None:
    """
    Save a DNDarray to CSV (reference io.py:926-1059: offset-seek parallel writes;
    one writer here).
    """
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if data.ndim > 2:
        raise ValueError("CSV supports at most 2 dimensions")
    t0 = _time.perf_counter()
    arr = data.numpy()
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)

    def write(target, m):
        with open(target, m, encoding=encoding, newline="") as handle:
            if header_lines:
                handle.write(header_lines)
                if not header_lines.endswith("\n"):
                    handle.write("\n")
            for row in arr:
                handle.write(
                    sep.join(
                        (f"%.{decimals}f" % v.item()) if decimals >= 0 else str(v.item()) for v in row
                    )
                )
                handle.write("\n")

    _atomic_write(path, "w", write, site="save_csv")
    if _MON.enabled:
        # written volume = the text file's actual size, not the array bytes
        _instr.record_io("save_csv", path, os.path.getsize(path), _time.perf_counter() - t0)


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save data by file extension (reference io.py:1060-1111)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    ext = os.path.splitext(path)[-1].lower()
    if ext in __HDF5_EXTENSIONS:
        if not __HDF5:
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return save_hdf5(data, path, *args, **kwargs)
    if ext in __NETCDF_EXTENSIONS:
        if not __NETCDF:
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return save_netcdf(data, path, *args, **kwargs)
    if ext == __CSV_EXTENSION:
        return save_csv(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")


DNDarray.save = save
if __HDF5:
    DNDarray.save_hdf5 = save_hdf5
if __NETCDF:
    DNDarray.save_netcdf = save_netcdf
