"""
The distributed n-dimensional array.

Parity with the reference's ``heat/core/dndarray.py`` (class at dndarray.py:38-86,
``lshape_map`` :573, ``balance_`` :474, ``redistribute_`` :1033, ``resplit_`` :1239,
``get_halo`` :360, distributed ``__getitem__``/``__setitem__`` :656-1681) — redesigned
single-controller SPMD for TPU:

* The reference stores *one process-local* ``torch.Tensor`` per MPI rank and moves data
  with explicit messages. Here a :class:`DNDarray` stores the **global** ``jax.Array``
  whose device placement is governed by its ``split`` metadata: ``split=k`` means the
  array is laid out with axis ``k`` partitioned over the communicator's device mesh
  (a ``NamedSharding``); ``split=None`` means replicated. XLA compiles any cross-shard
  data motion into ICI collectives — the reference's Send/Recv choreography
  (redistribute_/resplit_, dndarray.py:1033-1362) therefore collapses into a single
  resharding placement.
* ``larray`` returns the *logical* global ``jax.Array`` (the controller addresses all
  shards); per-device chunk geometry is still available via
  :attr:`lshape_map`/``comm.chunk`` — the layout math matches the reference exactly.
* Ragged layouts (split axis not divisible by the mesh size — reference
  communication.py:161-210 chunks any length): the array is stored in a **padded
  physical layout** — the split axis padded at the global end to ``ceil(n/p)*p`` and
  sharded evenly (:attr:`parray`, physical shape :attr:`pshape`). The pad content is
  unspecified; reductions/contractions across the split axis mask it with the
  operation's neutral element (`_operations.py`), in-bounds indexing is identical in
  logical and physical coordinates (pad at the end), and :attr:`larray` slices the
  pad off. ``balanced`` stays ``True`` — chunks differ by at most the pad of the
  last shards, mirroring the reference's max-1 imbalance.
"""

from __future__ import annotations

import numbers
from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices
from ._compat import shard_map as _shard_map
from .communication import Communication, MeshCommunication, sanitize_comm
from .devices import Device
from .stride_tricks import sanitize_axis

# observability: disabled-path cost is one truthiness check (see monitoring/)
from ..monitoring.registry import STATE as _MON
from ..monitoring import instrument as _instr

__all__ = ["DNDarray", "LocalIndex"]

import functools


@functools.lru_cache(maxsize=128)
def _build_halo_exchange(mesh, axis: str, p: int, split: int, halo_size: int,
                         pshape: Tuple[int, ...]):
    """One compiled ppermute halo-exchange program per (mesh, layout, halo)."""
    from jax.sharding import PartitionSpec as _P

    chunk = pshape[split] // p
    fwd = [(i, (i + 1) % p) for i in range(p)]  # receiver gets its PREV's data
    bwd = [(i, (i - 1) % p) for i in range(p)]  # receiver gets its NEXT's data

    def exchange(block):
        # block: my chunk with the split axis moved to the front
        blk = jnp.moveaxis(block, split, 0)
        i = jax.lax.axis_index(axis)
        last = blk[chunk - halo_size :]
        first = blk[:halo_size]
        from_prev = jax.lax.ppermute(last, axis, fwd)
        from_next = jax.lax.ppermute(first, axis, bwd)
        from_prev = jnp.where(i == 0, jnp.zeros_like(from_prev), from_prev)
        from_next = jnp.where(i == p - 1, jnp.zeros_like(from_next), from_next)
        stacked = jnp.concatenate([from_prev, blk, from_next], axis=0)
        return (
            jnp.moveaxis(from_prev, 0, split),
            jnp.moveaxis(from_next, 0, split),
            stacked[None],  # (1, chunk+2h, ...) — axis 0 is the shard axis
        )

    in_spec = _P(*([None] * split), axis)
    out_specs = (in_spec, in_spec, _P(axis))
    return jax.jit(
        _shard_map(
            exchange, mesh=mesh, in_specs=in_spec, out_specs=out_specs, check_vma=False
        )
    )

Scalar = Union[int, float, bool, complex]


class LocalIndex:
    """
    Indexing class for local operations (primarily for :attr:`DNDarray.lloc`).
    Reference parity: dndarray.py:22-36.
    """

    def __init__(self, obj: "DNDarray"):
        self.obj = obj

    def __getitem__(self, key):
        return self.obj.larray[key]

    def __setitem__(self, key, value):
        from .dndarray import DNDarray as _D

        if isinstance(value, _D):
            value = value.larray
        self.obj.larray = self.obj.larray.at[key].set(value)


class DNDarray:
    """
    Distributed N-Dimensional array: a global ``jax.Array`` plus Heat-style metadata.

    Parameters
    ----------
    array : jax.Array
        The global data (single-controller: all shards addressable).
    gshape : Tuple[int,...]
        The global shape.
    dtype : datatype
        The heat data type.
    split : int or None
        The axis on which the array is split across the device mesh.
    device : Device
        The device (platform) the data resides on.
    comm : Communication
        The communicator (device mesh) the array lives on.
    balanced : bool
        Whether the data are evenly distributed (always True here; kept for parity).

    Reference parity: dndarray.py:38-86.
    """

    # numpy binary ops defer to DNDarray's reflected operators instead of
    # consuming it through __array__ (np_row + dndarray stays a DNDarray)
    __array_priority__ = 100

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: Optional[bool] = True,
    ):
        gshape = tuple(int(s) for s in gshape)
        # Normalize to the canonical physical layout (padded + sharded) at the one
        # choke point every wrap goes through. Tracers are left untouched (placement
        # inside jit is the caller's concern); non-distributed cases are no-ops.
        if (
            split is not None
            and isinstance(comm, MeshCommunication)
            and not isinstance(array, jax.core.Tracer)
            and comm.is_distributed()
        ):
            array = comm.placed(array, split, gshape)
        self.__array = array
        self.__gshape = gshape
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = True if balanced is None else balanced
        self.__lshape_map = None
        self.__logical = None  # cached logical view of a padded physical array
        self.__halo_next = None
        self.__halo_prev = None
        self.__halo_stacked = None
        # deferred-execution state (core/fusion.py): when this array is the
        # result of a recorded elementwise chain, ``__array`` is None and
        # ``__lazy`` holds the pending expression node; ``__pshape`` carries
        # the (statically known) physical shape until materialization
        self.__lazy = None
        self.__pshape = None

    def __invalidate(self):
        """Drop caches derived from the physical array (logical view + halos)."""
        self.__logical = None
        self.__halo_prev = None
        self.__halo_next = None
        self.__halo_stacked = None

    # ------------------------------------------------------------------ constructors
    @staticmethod
    def __new_like__(proto: "DNDarray", data: jax.Array, dtype=None, split="same") -> "DNDarray":
        """Wrap ``data`` with metadata copied from ``proto`` (internal helper)."""
        from .types import canonical_heat_type

        dtype = proto.dtype if dtype is None else canonical_heat_type(dtype)
        split = proto.split if split == "same" else split
        return DNDarray(
            data, tuple(data.shape), dtype, split, proto.device, proto.comm, True
        )

    @classmethod
    def _deferred(
        cls, node, gshape, pshape, dtype, split, device, comm
    ) -> "DNDarray":
        """Construct a DNDarray whose data is a pending fusion expression
        (``core/fusion.py``). No placement happens here — materialization
        applies the canonical placement once per fused chain."""
        obj = object.__new__(cls)
        obj.__array = None
        obj.__gshape = tuple(int(v) for v in gshape)
        obj.__dtype = dtype
        obj.__split = split
        obj.__device = device
        obj.__comm = comm
        obj.__balanced = True
        obj.__lshape_map = None
        obj.__logical = None
        obj.__halo_next = None
        obj.__halo_prev = None
        obj.__halo_stacked = None
        obj.__lazy = node
        obj.__pshape = tuple(int(v) for v in pshape)
        return obj

    def _expr(self):
        """The pending fusion expression node, or None when concrete."""
        return self.__lazy

    def _flush(self, reason: str) -> None:
        """Materialize a pending expression, attributing the flush to
        ``reason`` in the ``fusion.flush_reason`` counter (no-op when
        concrete — the guard keeps reason bookkeeping off the hot path)."""
        if self.__lazy is not None:
            from . import fusion as _fusion

            with _fusion.flush_reason(reason):
                self.parray  # noqa: B018

    def flush_async(self, reason: str = "serving"):
        """Submit this array's pending expression to the serving layer's
        async flush scheduler (``heat_tpu/serving/scheduler.py``) and return
        a ``concurrent.futures.Future`` resolving to ``self`` once the fused
        kernel has been dispatched. Device dispatch of this flush then
        overlaps the host-side trace/key work of the next one (JAX dispatch
        is already asynchronous; the scheduler stops Python-side flush prep
        from serializing on one thread). A concrete array resolves
        immediately — scheduling is always safe."""
        from ..serving import scheduler as _scheduler

        return _scheduler.schedule(self, reason=reason)

    def _rebind_expr(self, node, split: Optional[int]) -> None:
        """Package-internal (``core/fusion.py``): replace this array's pending
        expression IN PLACE with ``node`` — a collective recorded OVER the old
        expression (``record_resplit``) — updating the split/pshape metadata
        to the node's output layout. The old root becomes an interior node of
        the new graph; its owner pointer is cleared so flush-time liveness
        logic never places it on this array's (now different) layout."""
        import weakref as _weakref

        old = self.__lazy
        if old is not None:
            old.owner = None
        self.__lazy = node
        self.__array = None
        node.owner = _weakref.ref(self)
        self.__split = split
        self.__pshape = tuple(int(v) for v in node.aval.shape)
        self.__lshape_map = None
        self.__invalidate()

    # ------------------------------------------------------------------ properties
    @property
    def larray(self) -> jax.Array:
        """
        The *logical* global ``jax.Array``. NOTE: in single-controller SPMD this is
        the global array (all shards addressable from the one controller); the
        reference's per-rank local tensor view corresponds to one shard of it
        (``self.larray.addressable_shards``). For ragged split axes this is a view
        of the padded physical array (:attr:`parray`) with the pad sliced off —
        sharded compute paths should prefer :attr:`parray`/:meth:`filled`.
        """
        if not self.is_padded:
            return self.parray
        if self.__logical is None:
            phys = self.parray
            idx = tuple(
                slice(0, self.__gshape[d]) if d == self.__split_axis else slice(None)
                for d in range(len(self.__gshape))
            )
            self.__logical = phys[idx]
        return self.__logical

    @larray.setter
    def larray(self, array: jax.Array):
        """Setter for larray; does not update metadata (parity: dndarray.py larray
        setter). Accepts a logical or physical array and re-establishes the
        canonical placement."""
        if (
            self.__split is not None
            and isinstance(self.__comm, MeshCommunication)
            and not isinstance(array, jax.core.Tracer)
            and self.__comm.is_distributed()
            and tuple(array.shape) in (self.__gshape, self.pshape)
        ):
            array = self.__comm.placed(array, self.__split, self.__gshape)
        if self.__lazy is not None:
            # overwriting an unflushed expression: the dead graph is dropped,
            # never executed (out=-style aliasing barrier)
            if _MON.enabled:
                _instr.fusion_elided_write()
            self.__lazy = None
        self.__array = array
        self.__pshape = None
        self.__invalidate()

    @property
    def parray(self) -> jax.Array:
        """The backing *physical* ``jax.Array``: the split axis padded at the global
        end to an even multiple of the mesh size and sharded over it. Equal to
        :attr:`larray` when no padding is needed. Pad content is unspecified.

        This accessor is the single materialization barrier of the deferred-
        execution engine: a pending elementwise expression (``core/fusion.py``)
        is flushed through one fused jitted kernel on first access, so every
        consumer of the physical array — reductions, collectives, printing,
        indexing, IO, linalg — flushes exactly where it used to execute."""
        if self.__array is None:
            from . import fusion as _fusion

            self.__array = _fusion.materialize_for(self)
            self.__lazy = None
            self.__pshape = None
        return self.__array

    @property
    def __split_axis(self) -> Optional[int]:
        """The split axis normalized to a non-negative index."""
        if self.__split is None:
            return None
        return int(self.__split) % max(len(self.__gshape), 1)

    @property
    def pshape(self) -> Tuple[int, ...]:
        """The physical (padded) global shape (statically known metadata —
        reading it never materializes a pending expression)."""
        if self.__array is None:
            return self.__pshape
        return tuple(self.__array.shape)

    @property
    def is_padded(self) -> bool:
        """Whether the physical layout carries pad rows on the split axis."""
        s = self.__split_axis
        return s is not None and len(self.__gshape) > 0 and self.pshape != self.__gshape

    @property
    def pad_count(self) -> int:
        """Number of pad positions on the split axis (0 when evenly divisible)."""
        s = self.__split_axis
        if s is None or not self.__gshape:
            return 0
        return int(self.pshape[s]) - self.__gshape[s]

    def filled(self, fill) -> jax.Array:
        """The physical array with the pad region set to ``fill`` — the form sharded
        reductions/contractions consume (``fill`` = the op's neutral element)."""
        if not self.is_padded:
            return self.parray
        phys = self.parray
        s = self.__split_axis
        n = self.__gshape[s]
        iota = jnp.arange(phys.shape[s])
        shape = [1] * len(self.__gshape)
        shape[s] = phys.shape[s]
        mask = iota.reshape(shape) < n
        return jnp.where(mask, phys, jnp.asarray(fill, dtype=phys.dtype))

    @property
    def balanced(self) -> bool:
        """True if the data are distributed evenly (always, by construction)."""
        return True

    @property
    def comm(self) -> Communication:
        """The communicator (device mesh) of the array."""
        return self.__comm

    @comm.setter
    def comm(self, comm: Communication):
        self.__comm = sanitize_comm(comm)

    @property
    def device(self) -> Device:
        """The device (platform) the array resides on."""
        return self.__device

    @property
    def dtype(self):
        """The heat datatype of the array."""
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        """The global shape."""
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        """The global shape (alias of :attr:`gshape`)."""
        return self.__gshape

    @property
    def ndim(self) -> int:
        """Number of dimensions."""
        return len(self.__gshape)

    @property
    def size(self) -> int:
        """Total (global) number of elements."""
        return int(np.prod(self.__gshape, dtype=np.int64)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        """Total (global) number of elements (alias of :attr:`size`)."""
        return self.size

    @property
    def lnumel(self) -> int:
        """Number of elements of the process-local portion (global here; see larray)."""
        return int(np.prod(self.lshape, dtype=np.int64)) if self.lshape else 1

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of the controller-addressable logical data (== global shape here)."""
        return self.__gshape

    @property
    def lshape_map(self) -> np.ndarray:
        """
        ``(n_devices, ndim)`` array of every device's owned-logical-data shape under
        the split, derived from the padded physical layout (``ceil(n/p)`` rows per
        device, clamped — consistent with ``larray``'s ``addressable_shards``; tail
        devices of a ragged axis may own 0 rows). The reference gathers the
        equivalent map with an Allreduce (dndarray.py:573-605 — no communication is
        needed here); its remainder-spread decomposition is ``comm.chunk``.
        """
        if self.__lshape_map is None:
            comm = self.__comm
            if isinstance(comm, MeshCommunication):
                self.__lshape_map = comm.lshape_map(self.__gshape, self.__split)
            else:
                self.__lshape_map = np.array([self.__gshape])
        return self.__lshape_map.copy()

    @property
    def nbytes(self) -> int:
        """Total bytes consumed by the global array."""
        return self.size * self.itemsize

    @property
    def gnbytes(self) -> int:
        """Alias for :attr:`nbytes`."""
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        """Bytes of the controller-addressable data."""
        return self.lnumel * self.itemsize

    @property
    def itemsize(self) -> int:
        """Bytes per element."""
        return int(np.dtype(self.__dtype.jnp_type()).itemsize)

    @property
    def split(self) -> Optional[int]:
        """The axis the array is split on (``None`` = replicated)."""
        return self.__split

    def stride(self) -> Tuple[int, ...]:
        """
        Steps (in elements) per dimension when traversing the local data,
        torch-like usage ``a.stride()`` (reference dndarray.py:308 forwards to
        ``torch.Tensor.stride``). jax arrays carry no stride attribute — XLA
        buffers are C-contiguous by construction — so the C-order strides are
        computed from :attr:`lshape`.
        """
        strides = []
        step = 1
        for dim in reversed(self.lshape):
            strides.append(step)
            step *= int(dim)
        return tuple(reversed(strides))

    @property
    def strides(self) -> Tuple[int, ...]:
        """
        Steps (in bytes) per dimension when traversing the local data,
        numpy-like (reference dndarray.py:315: element strides scaled by the
        storage element size).
        """
        return tuple(s * self.itemsize for s in self.stride())

    def is_distributed(self) -> bool:
        """
        Whether the array's data is split across multiple devices (reference
        dndarray.py:956: ``split is not None`` on a >1-process communicator).
        """
        return self.__split is not None and self.__comm.is_distributed()

    @property
    def lloc(self) -> LocalIndex:
        """Local item setter/getter on the underlying array (parity: dndarray.py lloc)."""
        return LocalIndex(self)

    @property
    def T(self) -> "DNDarray":
        """Transposed array (reverses all axes)."""
        from .linalg import basics

        return basics.transpose(self, None)

    @property
    def real(self) -> "DNDarray":
        """Real part."""
        from . import complex_math

        return complex_math.real(self)

    @property
    def imag(self) -> "DNDarray":
        """Imaginary part."""
        from . import complex_math

        return complex_math.imag(self)

    @property
    def halo_next(self) -> Optional[jax.Array]:
        """
        Halos received from the NEXT neighbor, as one sharded array: shard ``i``
        holds the first ``halo_size`` split-rows of shard ``i+1`` (the last
        shard's slot is zero — non-periodic, the reference's rank p-1 has
        ``halo_next=None``, dndarray.py:360-446). Set by :meth:`get_halo`.
        """
        return self.__halo_value(self.__halo_next)

    @property
    def halo_prev(self) -> Optional[jax.Array]:
        """
        Halos received from the PREVIOUS neighbor, as one sharded array: shard
        ``i`` holds the last ``halo_size`` split-rows of shard ``i-1`` (shard
        0's slot is zero — the reference's rank 0 has ``halo_prev=None``).
        Set by :meth:`get_halo`.
        """
        return self.__halo_value(self.__halo_prev)

    @property
    def array_with_halos(self) -> jax.Array:
        """
        After :meth:`get_halo`: the per-shard blocks with both halos attached,
        stacked as ``(p, chunk + 2*halo, ...)`` and sharded on axis 0 — the form
        a ``shard_map`` stencil kernel consumes per device (the reference's
        per-rank ``[halo_prev; local; halo_next]`` concat, dndarray.py:360-446).
        Outer boundaries are zero-filled. The split axis of the block sits at
        position 1; trailing axes follow in order (for ``split != 0`` the block
        is moved-axis so the halo'd axis is axis 1 — move it back after the
        stencil). Before any ``get_halo``, the plain logical global array.
        """
        if self.__halo_stacked is not None:
            return self.__halo_value(self.__halo_stacked)
        return self.larray

    @staticmethod
    def __halo_value(h):
        """Unwrap a halo slot: ``get_halo`` over a pending chain stores the
        halos as DEFERRED DNDarrays (``fusion.defer_halo``), materialized on
        first property read — chain + exchange as one fused program."""
        if isinstance(h, DNDarray):
            return h.parray
        return h

    # ------------------------------------------------------------------ layout ops
    def is_balanced(self, force_check: bool = False) -> bool:
        """Whether the array is balanced between devices (always True; parity:
        dndarray.py:932)."""
        return True

    def balance_(self) -> None:
        """
        Balances the array in place. JAX shardings are balanced by construction, so
        this is a no-op (reference dndarray.py:474-former Send/Recv chain)."""
        return None

    def create_lshape_map(self, force_check: bool = False) -> np.ndarray:
        """(Re)computes the lshape map (parity: dndarray.py:573)."""
        self.__lshape_map = None
        return self.lshape_map

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device counts and displacements along the split axis (parity:
        dndarray.py counts_displs)."""
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs(self.__gshape, self.__split)

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """
        In-place redistribution: changes the split axis. Physically a single resharding
        placement — XLA emits the all-to-all/all-gather (the reference's explicit
        Allgatherv / Isend-Irecv mesh, dndarray.py:1239-1362).

        Parameters
        ----------
        axis : int or None
            The new split axis; ``None`` gathers (replicates) the array.
        """
        axis = sanitize_axis(self.shape, axis)
        if axis == self.__split:
            return self
        comm = self.__comm
        if isinstance(comm, MeshCommunication) and comm.is_distributed():
            if _MON.enabled:
                # a genuine split change on a distributed mesh: XLA emits the
                # all-to-all/all-gather — the event every "how many resharding
                # collectives did this run cost?" question counts (recorded
                # and eager paths alike: the collective runs either way)
                _instr.resharding(self.__split, axis)
            if self.__lazy is not None:
                from . import fusion as _fusion

                if _fusion.collective_ready(self) and _fusion.record_resplit(self, axis):
                    # the resharding is now a node of the pending DAG: this
                    # array stays pending under the new split metadata and the
                    # chain + collective + any follow-on chain flush as ONE
                    # shard_map program (HEAT_TPU_FUSION_COLLECTIVES=0
                    # restores the flush barrier below)
                    return self
            self._flush("collective")
            # go through the logical view: the old axis's pad is dropped, the new
            # axis's pad (if ragged) is established by placed()
            self.__array = comm.placed(self.larray, axis, self.__gshape)
        self.__split = axis
        self.__lshape_map = None
        self.__invalidate()
        return self

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """
        Redistribution to an explicit target chunk map. Balanced shardings make every
        layout canonical, so this only validates the arguments and (re)applies the
        canonical placement (reference dndarray.py:1033-1237 moved data with chained
        Send/Recv).
        """
        if self.__split is None:
            return
        if target_map is not None:
            tm = np.asarray(target_map)
            if tm.sum(axis=0)[self.__split] != self.__gshape[self.__split]:
                raise ValueError(
                    f"target_map does not sum to the global shape on the split axis: "
                    f"{tm.sum(axis=0)[self.__split]} != {self.__gshape[self.__split]}"
                )
        comm = self.__comm
        if isinstance(comm, MeshCommunication) and comm.is_distributed():
            if _MON.enabled:
                # its own label: a redistribution keeps the split axis, so it
                # must NOT tick the resharding counter (which answers "how
                # many genuine split changes did this run pay?")
                _instr.redistribution()
            if self.__lazy is not None:
                from . import fusion as _fusion

                if _fusion.collective_ready(self):
                    # a pending expression materializes INTO the canonical
                    # placement (materialize_for applies placed() once per
                    # flush), so re-asserting it here would only break the
                    # chain — leave the graph pending
                    return
            self._flush("collective")
            self.__array = comm.placed(self.parray, self.__split, self.__gshape)
            self.__invalidate()

    def get_halo(self, halo_size: int) -> None:
        """
        Fetches halos of size ``halo_size`` from the neighboring shards via one
        ``shard_map``+``ppermute`` exchange (the reference's Isend/Irecv
        neighbor protocol, dndarray.py:360-446): fills :attr:`halo_prev` /
        :attr:`halo_next` with the adjacent shards' boundary slabs and
        :attr:`array_with_halos` with the stacked per-shard halo'd blocks.
        Outer boundaries (shard 0's prev, shard p-1's next) are zero — the
        reference leaves them ``None`` per rank.
        """
        if not isinstance(halo_size, int):
            raise TypeError(f"halo_size needs to be of Python type integer, {type(halo_size)} given")
        if halo_size < 0:
            raise ValueError(f"halo_size needs to be a positive Python integer, {halo_size} given")
        comm = self.__comm
        if (
            self.__split is None
            or not comm.is_distributed()
            or halo_size == 0
            or not isinstance(comm, MeshCommunication)
        ):
            # no exchange requested/possible: drop any previously fetched halos
            self.__halo_prev = self.__halo_next = self.__halo_stacked = None
            return
        split = self.__split_axis
        p = comm.size
        chunk = self.pshape[split] // p
        # the reference requires the halo to fit the smallest chunk
        # (dndarray.py:376-384); the physical layout's even chunk is the bound
        # here — ragged tails exchange zero-filled pad rows
        if halo_size > chunk:
            raise ValueError(
                f"halo_size {halo_size} needs to be smaller than the local chunk {chunk}"
            )
        if self.__lazy is not None:
            from . import fusion as _fusion

            if _fusion.collective_ready(self):
                halos = _fusion.defer_halo(self, halo_size)
                if halos is not None:
                    # the exchange is recorded over the pending chain: chain +
                    # ppermute compile as one program at the first halo read,
                    # and this array's own value rides that kernel as an
                    # extra output (the chain stays pending until then)
                    self.__halo_prev, self.__halo_next, self.__halo_stacked = halos
                    return
        self._flush("collective")
        fn = _build_halo_exchange(comm.mesh, comm.axis_name, p, split, halo_size, self.pshape)
        # zero-fill pads so ragged tails exchange zeros, not garbage
        phys = self.filled(0) if self.is_padded else self.parray
        # value-level fault site + checksum lane (ISSUE 12): the SDC
        # adversary perturbs the exchanged slabs, and with
        # HEAT_TPU_COLLECTIVE_CHECKSUM=1 every received halo is verified
        # against the controller's own view of the neighbor edges
        from ..robustness import faultinject as _FI
        from .communication import _verify_halo, collective_checksum_enabled

        prev, nxt, stacked = _FI.corrupt_value("collective.dispatch", tuple(fn(phys)))
        if collective_checksum_enabled():
            _verify_halo(comm, np.asarray(phys), split, halo_size, prev, nxt, stacked)
        self.__halo_prev, self.__halo_next, self.__halo_stacked = prev, nxt, stacked

    # ------------------------------------------------------------------ conversions
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """
        Returns a casted version of this array. If ``copy`` is False the cast is
        performed in-place (metadata update). Reference parity: dndarray.py astype.
        """
        from .types import canonical_heat_type

        dtype = canonical_heat_type(dtype)
        if self.__lazy is not None:
            from . import fusion as _fusion

            if _fusion.enabled():
                if not copy and dtype == self.__dtype:
                    return self  # no-op cast must not break the pending chain
                deferred = _fusion.defer_cast(self, dtype)
                if deferred is not None:
                    if copy:
                        return deferred
                    # in-place cast over a pending chain: rebind self to the
                    # freshly recorded cast node (same split/layout) so the
                    # chain stays fused — the arg-reduce index-type cast used
                    # to flush the whole sink program here
                    node = deferred._expr()
                    if node is not None:
                        self._rebind_expr(node, self.__split)
                    else:  # chain bound flushed at record: adopt the value
                        self.__lazy = None
                        self.__array = deferred.parray
                        self.__pshape = None
                        self.__invalidate()
                    self.__dtype = dtype
                    return self
        casted = self.parray.astype(dtype.jnp_type())
        if copy:
            return DNDarray(
                casted, self.shape, dtype, self.split, self.device, self.comm, True
            )
        self.__array = casted
        self.__invalidate()
        self.__dtype = dtype
        return self

    def item(self):
        """
        Returns the only element of a 1-element array as a Python scalar
        (parity: dndarray.py:974)."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to Python scalars")
        self._flush("export")
        return self.larray.reshape(()).item()

    def fill_diagonal(self, value: float) -> "DNDarray":
        """
        Fill the main diagonal of a 2-D array in place; returns self (reference
        dndarray.py:616-652 — there a per-rank offset loop over the chunk map;
        here one functional scatter on the physical array, in-bounds positions
        are identical logical/physical since the pad sits at the global end).
        """
        if self.ndim != 2:
            raise ValueError("Only 2D tensors supported at the moment")
        k = int(np.minimum(self.shape[0], self.shape[1]))
        idx = jnp.arange(k)
        self._flush("indexing")
        phys = self.parray
        self.__array = phys.at[idx, idx].set(jnp.asarray(value, dtype=phys.dtype))
        self.__invalidate()
        return self

    def numpy(self) -> np.ndarray:
        """The global logical array as a numpy array (parity: dndarray.py:995 — there
        a resplit(None) gather; here a device fetch). In a multi-controller run the
        shards on other hosts are gathered with ``process_allgather`` (every host
        gets the full array, like the reference's resplit(None))."""
        self._flush("export")
        arr = self.parray
        if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
            from jax.experimental import multihost_utils

            full = np.asarray(multihost_utils.process_allgather(arr, tiled=True))
            if self.is_padded:
                s = self.__split_axis
                idx = tuple(
                    slice(0, self.__gshape[d]) if d == s else slice(None)
                    for d in range(len(self.__gshape))
                )
                full = full[idx]
            return full
        return np.asarray(jax.device_get(self.larray))

    def __array__(self, dtype=None) -> np.ndarray:
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __dlpack__(self, **kwargs):
        """
        Tensor interchange (the analog of the reference's ``__torch_proxy__``,
        dndarray.py:86+ — there a torch-view hook, here the standard DLPack
        protocol): ``torch.from_dlpack(dndarray)`` consumes the logical array.
        Zero-copy for single-shard CPU/GPU arrays; sharded arrays gather to one
        buffer first (DLPack addresses a single contiguous tensor by design),
        and TPU-backed arrays stage through host memory (one device->host copy
        — jax only exports DLPack capsules for CPU/GPU buffers), so
        ``torch.from_dlpack`` works on the framework's primary platform too.
        """
        capsule = self.__dlpack_buffer().__dlpack__(**kwargs)
        # the capsule owns the exported buffer from here; dropping the staging
        # cache keeps a multi-GB gathered/host copy from living as long as
        # this DNDarray does
        self.__dlpack_cache = None
        return capsule

    def __dlpack_device__(self):
        return self.__dlpack_buffer().__dlpack_device__()

    def __dlpack_buffer(self) -> jax.Array:
        # torch.from_dlpack calls __dlpack_device__ then __dlpack__ back to
        # back — cache the staged buffer so a sharded/TPU array is gathered
        # and host-staged once per interchange (cleared again when __dlpack__
        # hands the buffer off)
        self._flush("export")
        phys = self.parray
        cached = getattr(self, "_DNDarray__dlpack_cache", None)
        if cached is not None and cached[0] is phys:
            return cached[1]
        arr = self.larray
        if hasattr(arr, "sharding") and len(getattr(arr.sharding, "device_set", [None])) > 1:
            arr = jax.device_put(arr, tuple(arr.sharding.device_set)[0])
        dev = next(iter(arr.devices())) if hasattr(arr, "devices") else None
        if dev is not None and dev.platform not in ("cpu", "gpu", "cuda", "rocm"):
            arr = jax.device_put(arr, jax.devices("cpu")[0])
        self.__dlpack_cache = (phys, arr)
        return arr

    def tolist(self, keepsplit: bool = False) -> list:
        """The array as a (nested) Python list (parity: dndarray.py tolist)."""
        return self.numpy().tolist()

    def cpu(self) -> "DNDarray":
        """Returns a copy of this array on the CPU device (parity: dndarray.py cpu())."""
        arr = jax.device_put(self.numpy(), jax.devices("cpu")[0])
        return DNDarray(arr, self.shape, self.dtype, None, devices.cpu, self.comm, True)

    # ------------------------------------------------------------------ magic
    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        val = self.item()
        if not isinstance(val, (int, np.integer)):
            raise TypeError("only integer scalar arrays can be converted to a scalar index")
        return int(val)

    def __iter__(self):
        # materialize once up front: per-row deferred view reads of a fresh
        # pending chain would otherwise compile one kernel per row
        self._flush("indexing")
        for i in range(len(self)):
            yield self[i]

    def __repr__(self) -> str:
        from . import printing

        self._flush("print")
        return printing.__str__(self)

    def __str__(self) -> str:
        from . import printing

        self._flush("print")
        return printing.__str__(self)

    # ------------------------------------------------------------------ indexing
    def __process_key(self, key):
        """
        Convert DNDarray keys to jax arrays and list keys to numpy. Host keys
        (lists / numpy arrays) deliberately STAY on the host — they are valid
        jnp index operands, and keeping them lets bounds validation run without
        a device round-trip that would serialize async dispatch.
        """
        def conv(k):
            if isinstance(k, DNDarray):
                return k.larray
            if isinstance(k, (list, np.ndarray)) and not isinstance(k, str):
                return np.asarray(k)
            return k

        if isinstance(key, tuple):
            return tuple(conv(k) for k in key)
        return conv(key)

    def __index_plan(self, key):
        """
        Resolve an indexing key into *physical* coordinates and infer the result's
        split axis (the reference's distributed ``__getitem__`` bookkeeping,
        dndarray.py:656-915, reduced to layout metadata: since the pad sits at the
        global END of the split axis, any in-bounds logical index is the identical
        physical index — only negative indices and open slice bounds need resolving
        against the logical extent).

        Returns ``(norm_key, new_split, fast)``: ``norm_key`` applies directly to
        :attr:`parray` when ``fast`` is True (otherwise the caller must index the
        logical :attr:`larray` with the original key); ``new_split`` is the split
        axis of the result (``None`` = replicated).
        """
        gshape = self.__gshape
        split = self.__split_axis
        ndim = len(gshape)
        jkey = self.__process_key(key)
        if not isinstance(jkey, tuple):
            jkey = (jkey,)

        # expand Ellipsis to the right number of full slices
        n_consumed = 0
        for k in jkey:
            if k is None or k is Ellipsis:
                continue
            n_consumed += k.ndim if (hasattr(k, "dtype") and k.dtype == np.bool_) else 1
        expanded = []
        seen_ellipsis = False
        for k in jkey:
            if k is Ellipsis:
                if seen_ellipsis:
                    raise IndexError("an index can only have a single ellipsis ('...')")
                seen_ellipsis = True
                expanded.extend([slice(None)] * (ndim - n_consumed))
            else:
                expanded.append(k)
        # implicit trailing full slices
        consumed = sum(
            (k.ndim if (hasattr(k, "dtype") and k.dtype == np.bool_) else 1)
            for k in expanded
            if k is not None
        )
        expanded.extend([slice(None)] * (ndim - consumed))

        n_advanced = sum(
            1 for k in expanded if hasattr(k, "ndim") and not isinstance(k, (int, np.integer))
        )
        in_ax = 0
        out_ax = 0
        new_split = None
        fast = True
        norm = []
        entries = []  # (kind, covers_split, bdim) per expanded key, for the
        # multi-advanced-key placement rules below
        for k in expanded:
            if k is None:
                norm.append(None)
                out_ax += 1
                entries.append(("none", False, 0))
            elif isinstance(k, slice):
                if in_ax == split:
                    start, stop, step = k.indices(gshape[split])
                    # a descending slice that reaches index 0 has stop=-1, which
                    # must stay "before the start", not wrap to the last element
                    norm.append(slice(start, None if (step < 0 and stop < 0) else stop, step))
                    new_split = out_ax
                    entries.append(("slice", True, 0))
                else:
                    norm.append(k)
                    entries.append(("slice", False, 0))
                in_ax += 1
                out_ax += 1
            elif isinstance(k, (bool, np.bool_)):
                # scalar bool key: numpy adds a leading axis — not an integer index
                fast = False
                norm.append(k)
                out_ax += 1
                entries.append(("other", False, 0))
            elif isinstance(k, (int, np.integer)):
                kk = int(k)
                if kk < 0:
                    kk += gshape[in_ax]
                if not 0 <= kk < gshape[in_ax]:
                    raise IndexError(
                        f"index {int(k)} is out of bounds for axis {in_ax} with size {gshape[in_ax]}"
                    )
                norm.append(kk)
                entries.append(("int", in_ax == split, 0))
                in_ax += 1
            elif hasattr(k, "dtype") and k.dtype == np.bool_:
                covers = range(in_ax, in_ax + k.ndim)
                if split in covers and self.is_padded:
                    d = split - in_ax
                    widths = [(0, 0)] * k.ndim
                    widths[d] = (0, self.pshape[split] - gshape[split])
                    k = jnp.pad(k, widths, constant_values=False)
                norm.append(k)
                # a boolean mask yields one output axis; the result's row order is
                # the mask's row order along the (former) split axis → keep split 0
                # only in the canonical 1-advanced-key case below
                if n_advanced == 1 and split in covers:
                    new_split = out_ax
                entries.append(("adv", split in covers, 1))
                in_ax += k.ndim
                out_ax += 1
            elif hasattr(k, "ndim"):  # integer array
                n = gshape[in_ax]
                if k.size and not isinstance(k, jax.core.Tracer):
                    # validate against the LOGICAL extent, like the scalar-int path
                    # and numpy — on a padded split axis jax would otherwise clamp
                    # (get) or drop (set) out-of-bounds entries silently, and a
                    # clamped __setitem__ corrupts the last valid element. Traced
                    # keys (indexing inside jit) cannot be validated eagerly and
                    # keep jax's documented clamp/drop semantics.
                    if isinstance(k, np.ndarray):  # host key: free bounds check
                        kmin, kmax = int(k.min()), int(k.max())
                    else:  # device key: one fetch for both bounds
                        kmin, kmax = (int(v) for v in np.asarray(jnp.stack([k.min(), k.max()])))
                    if kmin < -n or kmax >= n:
                        bad = kmax if kmax >= n else kmin
                        raise IndexError(
                            f"index {bad} is out of bounds for axis {in_ax} with size {n}"
                        )
                if in_ax == split:
                    if self.is_padded:
                        # negatives wrap at the LOGICAL extent, never exposing pad.
                        # Traced keys skip the eager bounds check above, so they
                        # additionally clamp at n-1 — jax's documented clamping,
                        # applied to the logical extent instead of the physical
                        # one (which would expose pad rows)
                        k = jnp.where(k < 0, k + n, k)
                        if isinstance(k, jax.core.Tracer):
                            k = jnp.clip(k, 0, max(n - 1, 0))
                    if n_advanced == 1 and k.ndim == 1:
                        new_split = out_ax
                norm.append(k)
                entries.append(("adv", in_ax == split, int(k.ndim)))
                in_ax += 1
                out_ax += k.ndim if n_advanced == 1 else 1
            else:
                fast = False
                norm.append(k)
                in_ax += 1
                out_ax += 1
                entries.append(("other", False, 0))
        if n_advanced > 1:
            # Multiple advanced keys (reference's fully distributed multi-key
            # getitem, dndarray.py:656-915): the keys broadcast into ONE block
            # of B axes, placed at the first advanced key's position when the
            # advanced keys are contiguous (scalar ints between them do not
            # separate, numpy rules) and at the FRONT otherwise. The result
            # stays distributed: along the block's leading axis when the split
            # axis was consumed by an advanced key, or along the surviving
            # slice axis when a slice kept it.
            new_split = None
            if fast:
                adv = [j for j, e in enumerate(entries) if e[0] == "adv"]
                between = entries[adv[0] : adv[-1] + 1]
                contiguous = all(e[0] in ("adv", "int") for e in between)
                B = max(e[2] for e in entries if e[0] == "adv")
                split_in_adv = any(e[1] for e in entries if e[0] == "adv")
                split_slice = next(
                    (j for j, e in enumerate(entries) if e[0] == "slice" and e[1]), None
                )
                if contiguous:
                    block_start = sum(
                        1 for e in entries[: adv[0]] if e[0] in ("slice", "none")
                    )
                else:
                    block_start = 0
                if B >= 1 and split_in_adv:
                    new_split = block_start
                elif split_slice is not None:
                    # output position of the surviving split slice
                    pos = B  # block axes precede it when moved to front
                    if contiguous:
                        pos = B if adv[0] < split_slice else 0
                    for j, e in enumerate(entries[:split_slice]):
                        if e[0] in ("slice", "none") and not (
                            contiguous and adv[0] <= j <= adv[-1]
                        ):
                            pos += 1
                    new_split = pos
        return tuple(norm), new_split, fast

    def _index_plan(self, key):
        """Package-internal alias of the name-mangled ``__index_plan`` — the
        fusion engine plans deferred basic-slice reads with it
        (``core/fusion.py:defer_getitem``)."""
        return self.__index_plan(key)

    def __getitem__(self, key) -> "DNDarray":
        """
        Global indexing: accepts ints, slices, ellipsis, newaxis, boolean masks,
        integer arrays and DNDarrays (reference's fully distributed ``__getitem__``,
        dndarray.py:656-915). Distribution is preserved whenever the split axis is
        consumed by a slice (including stepped/negative slices), by the single
        advanced key (1-D integer array / boolean mask), or by one of SEVERAL
        advanced keys — the result is then distributed along the broadcast
        block's leading axis (numpy's block-placement rules); in every case the
        result is re-placed on its inferred split axis.

        A basic read (ints/slices/Ellipsis/newaxis, non-scalar result) over a
        PENDING fused expression records a view node instead of flushing the
        chain (``core/fusion.py``; ``HEAT_TPU_FUSION_VIEWS=0`` restores the
        flush-at-read behavior); advanced keys and writes keep today's
        barrier semantics.
        """
        if self.__lazy is not None:
            from . import fusion as _fusion

            if _fusion.view_ready(self):
                res = _fusion.defer_getitem(self, key)
                if res is not None:
                    return res
        self._flush("indexing")
        norm, new_split, fast = self.__index_plan(key)
        if fast:
            result = self.parray[norm]
        else:
            result = self.larray[self.__process_key(key)]
        if np.isscalar(result) or (hasattr(result, "ndim") and result.ndim == 0):
            new_split = None
        return DNDarray(
            result, tuple(result.shape), self.__dtype, new_split, self.__device, self.__comm, True
        )

    def __setitem__(self, key, value):
        """
        Global assignment via functional update (reference dndarray.py:1363-1681).
        Runs directly on the physical array — in-bounds keys are identical in
        logical and physical coordinates.
        """
        if isinstance(value, DNDarray):
            value = value.larray
        elif isinstance(value, (list, tuple, np.ndarray)):
            value = jnp.asarray(value, dtype=self.dtype.jnp_type())
        # full-array boolean-mask assignment: .at does not take masks; use where
        self._flush("indexing")
        jkey = self.__process_key(key)
        if (
            isinstance(jkey, (jnp.ndarray, np.ndarray))
            and jkey.dtype == np.bool_
            and jkey.shape == self.__gshape
        ):
            if self.is_padded:
                s = self.__split_axis
                widths = [(0, 0)] * self.ndim
                widths[s] = (0, self.pshape[s] - self.__gshape[s])
                jkey = jnp.pad(jkey, widths, constant_values=False)
                if hasattr(value, "shape") and tuple(value.shape) == self.__gshape:
                    value = jnp.pad(value, widths)
            phys = self.parray
            self.__array = jnp.where(jkey, jnp.asarray(value, dtype=phys.dtype), phys)
            self.__invalidate()
            return
        norm, _, fast = self.__index_plan(key)
        if fast:
            self.__array = self.parray.at[norm].set(value)
        else:
            updated = self.larray.at[jkey].set(value)
            comm = self.__comm
            if isinstance(comm, MeshCommunication) and self.__split is not None and comm.is_distributed():
                updated = comm.placed(updated, self.__split, self.__gshape)
            self.__array = updated
        self.__invalidate()

    # dunder arithmetic/comparison operators are attached by the op modules
    # (arithmetics.py, relational.py, …) heat-style, see each module's tail.


# late import-cycle resolution helpers used by other modules
def __is_dndarray(obj) -> bool:
    return isinstance(obj, DNDarray)
