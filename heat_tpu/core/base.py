"""
Scikit-learn-compatible estimator base classes.

Parity with the reference's ``heat/core/base.py`` (``BaseEstimator`` :13-97,
``ClassificationMixin``/``ClusteringMixin``/``RegressionMixin`` :98-219, helper
predicates :221-270).
"""

from __future__ import annotations

import inspect
from typing import Dict, List

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "is_classifier",
    "is_estimator",
    "is_regressor",
    "is_transformer",
]


class BaseEstimator:
    """Abstract base for all estimators, i.e. parametrized analysis algorithms
    (reference base.py:13-97)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return sorted(
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        )

    def get_params(self, deep: bool = True) -> Dict[str, object]:
        """Parameters of this estimator as a dict; nested estimators are expanded when
        ``deep`` (reference base.py get_params)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set the parameters of this estimator; supports ``component__parameter``
        nesting (reference base.py set_params)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        nested = {}
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"invalid parameter {key} for estimator {self}")
            if delim:
                nested.setdefault(key, {})[sub_key] = value
            else:
                setattr(self, key, value)
                valid[key] = value
        for key, sub_params in nested.items():
            valid[key].set_params(**sub_params)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params(deep=False).items())
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """Mixin for all classifiers (reference base.py:98-144)."""

    _estimator_type = "classifier"

    def fit(self, x, y):
        """Fit the model to data ``x`` with labels ``y``."""
        raise NotImplementedError()

    def fit_predict(self, x, y):
        """Fit and return labels for ``x``."""
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        """Predict labels for ``x``."""
        raise NotImplementedError()


class ClusteringMixin:
    """Mixin for all clustering algorithms (reference base.py:145-175)."""

    _estimator_type = "clusterer"

    def fit(self, x):
        """Compute the clustering."""
        raise NotImplementedError()

    def fit_predict(self, x):
        """Compute the clustering and return the labels."""
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """Mixin for all regression estimators (reference base.py:176-219)."""

    _estimator_type = "regressor"

    def fit(self, x, y):
        """Fit the model to data ``x`` with continuous targets ``y``."""
        raise NotImplementedError()

    def fit_predict(self, x, y):
        """Fit and return predictions for ``x``."""
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        """Predict continuous targets for ``x``."""
        raise NotImplementedError()


def is_classifier(estimator) -> bool:
    """Whether the given estimator is a classifier (reference base.py:221)."""
    return getattr(estimator, "_estimator_type", None) == "classifier"


def is_estimator(estimator) -> bool:
    """Whether the given object is an estimator (reference base.py is_estimator)."""
    return isinstance(estimator, BaseEstimator)


def is_regressor(estimator) -> bool:
    """Whether the given estimator is a regressor (reference base.py is_regressor)."""
    return getattr(estimator, "_estimator_type", None) == "regressor"


def is_transformer(estimator) -> bool:
    """Whether the given estimator is a transformer (reference base.py is_transformer)."""
    return hasattr(estimator, "transform") and is_estimator(estimator)
