"""
NumPy-style dtype class hierarchy over JAX dtypes.

Parity with the reference's ``heat/core/types.py`` (hierarchy at types.py:64-416,
``canonical_heat_type`` :495, ``heat_type_of`` :565, ``can_cast`` :671,
``promote_types`` :836, ``result_type`` :868, ``finfo``/``iinfo`` :950-1007) with two
TPU-native extensions: ``bfloat16`` and ``float16`` are first-class dtypes (the
reference only smuggles them through MPI as int16 buffers,
communication.py:130-143) since they are the native MXU compute types.

Note on 64-bit types: JAX canonicalises 64-bit dtypes to 32-bit unless
``jax.config.jax_enable_x64`` is set. ``float64``/``int64``/``complex128`` are defined
and behave correctly under x64; without it they degrade to their 32-bit counterparts
(appropriate on TPU, where f64 is emulated).
"""

from __future__ import annotations

import builtins
from typing import Any, Iterable, Optional, Type, Union

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "datatype",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "bool",
    "bool_",
    "floating",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "flexible",
    "can_cast",
    "canonical_heat_type",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "iscomplex",
    "isreal",
    "issubdtype",
    "heat_type_of",
    "promote_types",
    "result_type",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "finfo",
    "iinfo",
]


class _DtypeMeta(type):
    def __repr__(cls):
        return f"ht.{cls.__name__}"

    def __str__(cls):
        return cls.__name__


class datatype(metaclass=_DtypeMeta):
    """
    Generic base class for the Heat-style data types. Instantiating a datatype *casts*:
    ``ht.float32(x)`` returns a :class:`~heat_tpu.core.dndarray.DNDarray` of that type
    (reference types.py:64-170).
    """

    _np: Any = None  # numpy-compatible dtype object (ml_dtypes for bfloat16)

    def __new__(cls, *value, split=None, device=None, comm=None):
        from . import factories

        if cls._np is None:
            raise TypeError(f"cannot instantiate abstract dtype {cls.__name__}")
        if len(value) == 0:
            value = ((0,),)  # cast of nothing: zero scalar, reference types.py:120
        if len(value) == 1:
            value = value[0]
            from .dndarray import DNDarray

            if isinstance(value, DNDarray):
                return value.astype(cls)
        return factories.array(value, dtype=cls, split=split, device=device, comm=comm)

    @classmethod
    def jnp_type(cls) -> np.dtype:
        """The corresponding JAX/numpy dtype object."""
        if cls._np is None:
            raise TypeError(f"abstract dtype {cls.__name__} has no concrete jnp type")
        return np.dtype(cls._np)

    @classmethod
    def char(cls) -> str:
        """The name of this dtype."""
        return cls.__name__


class bool(datatype):
    """Boolean: True or False."""

    _np = np.bool_


class number(datatype):
    """Generic numeric type."""


class integer(number):
    """Abstract integer type."""


class signedinteger(integer):
    """Abstract signed integer type."""


class unsignedinteger(integer):
    """Abstract unsigned integer type."""


class floating(number):
    """Abstract floating point type."""


class flexible(datatype):
    """Types with no predefined size (parity placeholder, reference types.py:416)."""


class complexfloating(number):
    """Abstract complex floating type."""


class int8(signedinteger):
    """8-bit signed integer."""

    _np = np.int8


class int16(signedinteger):
    """16-bit signed integer."""

    _np = np.int16


class int32(signedinteger):
    """32-bit signed integer."""

    _np = np.int32


class int64(signedinteger):
    """64-bit signed integer (degrades to int32 without jax x64)."""

    _np = np.int64


class uint8(unsignedinteger):
    """8-bit unsigned integer."""

    _np = np.uint8


class float16(floating):
    """16-bit IEEE half-precision float (TPU-native extension)."""

    _np = np.float16


class bfloat16(floating):
    """16-bit brain float — the native MXU compute type (TPU-native extension)."""

    _np = jnp.bfloat16


class float32(floating):
    """32-bit single-precision float. The default float type."""

    _np = np.float32


class float64(floating):
    """64-bit double-precision float (degrades to float32 without jax x64)."""

    _np = np.float64


class complex64(complexfloating):
    """64-bit complex (two float32)."""

    _np = np.complex64


class complex128(complexfloating):
    """128-bit complex (degrades to complex64 without jax x64)."""

    _np = np.complex128


# aliases, reference types.py __all__
bool_ = bool
byte = int8
short = int16
int = int32
long = int64
ubyte = uint8
half = float16
float = float32
float_ = float32
double = float64
cfloat = complex64
csingle = complex64
cdouble = complex128

_COMPLEX_TYPES = (complex64, complex128)
_FLOAT_TYPES = (float16, bfloat16, float32, float64)
_INT_TYPES = (int8, int16, int32, int64, uint8)
_CONCRETE = (bool,) + _INT_TYPES + _FLOAT_TYPES + _COMPLEX_TYPES

# numpy/jax dtype -> heat type
__np_to_heat = {t.jnp_type(): t for t in _CONCRETE}
# string name -> heat type (includes aliases)
__name_to_heat = {t.__name__: t for t in _CONCRETE}
__name_to_heat.update(
    {
        "bool_": bool,
        "byte": int8,
        "short": int16,
        "int": int32,
        "long": int64,
        "ubyte": uint8,
        "half": float16,
        "float": float32,
        "float_": float32,
        "double": float64,
        "cfloat": complex64,
        "csingle": complex64,
        "cdouble": complex128,
    }
)
# python builtin type -> heat type
__builtin_to_heat = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
}


def default_index_type() -> Type[datatype]:
    """The widest available index type: int64 under jax x64, else int32 (TPU
    default). Keeps index-producing ops (argmax, sort, nonzero, …) warning-free."""
    import jax

    return int64 if jax.config.jax_enable_x64 else int32


def canonical_heat_type(a_type: Any) -> Type[datatype]:
    """
    Canonicalize the builtin Python type, string, numpy/jax dtype, or heat type into a
    canonical heat type class. Reference parity: types.py:495-540.

    Raises
    ------
    TypeError
        If the type cannot be converted.
    """
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._np is None:
            raise TypeError(f"data type {a_type!r} is abstract and not understood")
        # collapse aliases onto canonical classes
        return __np_to_heat[a_type.jnp_type()]
    if a_type in __builtin_to_heat:
        return __builtin_to_heat[a_type]
    if isinstance(a_type, str):
        name = a_type.strip().lower()
        if name in __name_to_heat:
            return __name_to_heat[name]
        try:
            return __np_to_heat[np.dtype(name)]
        except (TypeError, KeyError):
            raise TypeError(f"data type '{a_type}' is not understood")
    try:
        return __np_to_heat[np.dtype(a_type)]
    except (TypeError, KeyError):
        raise TypeError(f"data type {a_type!r} is not understood")


def heat_type_of(obj: Any) -> Type[datatype]:
    """
    Returns the canonical heat data type of the given object: a scalar, an array
    (DNDarray / numpy / jax) or an iterable. Reference parity: types.py:565-630.
    """
    dt = getattr(obj, "dtype", None)
    if dt is not None:
        if isinstance(dt, type) and issubclass(dt, datatype):
            return canonical_heat_type(dt)
        return canonical_heat_type(dt)
    if isinstance(obj, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
        return __builtin_to_heat[type(obj)]
    if isinstance(obj, (list, tuple)) or hasattr(obj, "__iter__"):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"data type of {obj!r} is not understood")


def heat_type_is_exact(ht_dtype: Type[datatype]) -> builtins.bool:
    """Whether the type is an exact (integer/boolean) type. Reference types.py:632."""
    ht_dtype = canonical_heat_type(ht_dtype)
    return issubclass(ht_dtype, integer) or ht_dtype is bool


def heat_type_is_inexact(ht_dtype: Type[datatype]) -> builtins.bool:
    """Whether the type is an inexact (floating/complex) type. Reference types.py:650."""
    ht_dtype = canonical_heat_type(ht_dtype)
    return issubclass(ht_dtype, (floating, complexfloating))


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """
    Returns ``True`` if the first type is lower/equal in the type hierarchy.
    Accepts heat abstract classes (``ht.integer`` etc.) as the second argument.
    Reference parity: types.py (issubdtype).
    """

    def resolve(a):
        if isinstance(a, type) and issubclass(a, datatype):
            return a
        try:
            return canonical_heat_type(a)
        except TypeError:
            return heat_type_of(a)

    t1, t2 = resolve(arg1), resolve(arg2)
    if t1._np is None:
        # abstract-vs-abstract: subclass check
        return issubclass(t1, t2)
    t1 = canonical_heat_type(t1)
    return issubclass(t1, t2)


def can_cast(from_: Any, to: Any, casting: str = "intuitive") -> builtins.bool:
    """
    Returns ``True`` if a cast between data types can occur according to the casting
    rule.

    Parameters
    ----------
    from_ : scalar, DNDarray or type
        Source.
    to : type
        Target type.
    casting : str
        ``'no'``, ``'safe'``, ``'same_kind'``, ``'unsafe'`` (NumPy semantics) or
        ``'intuitive'`` (safe + allows integer to float32 and float to complex64).

    Reference parity: types.py:671-835.
    """
    if casting not in ("no", "safe", "same_kind", "unsafe", "intuitive"):
        raise ValueError(f"casting must be one of 'no','safe','same_kind','unsafe','intuitive', got {casting!r}")
    try:
        src = canonical_heat_type(from_)
    except TypeError:
        src = heat_type_of(from_)
    dst = canonical_heat_type(to)

    def proxy(t: Type[datatype]) -> np.dtype:
        # bfloat16 is outside numpy's lattice; treat as float16-equivalent for casting
        return np.dtype(np.float16) if t is bfloat16 else t.jnp_type()

    if casting == "unsafe":
        return True
    if casting == "no":
        return src is dst
    if casting == "intuitive":
        if src is dst or np.can_cast(proxy(src), proxy(dst), "safe"):
            return True
        if issubclass(src, (integer, bool)) and issubclass(dst, (floating, complexfloating)):
            return True
        if issubclass(src, floating) and issubclass(dst, complexfloating):
            return True
        return False
    return np.can_cast(proxy(src), proxy(dst), casting)


def promote_types(type1: Any, type2: Any) -> Type[datatype]:
    """
    Returns the data type with the smallest size and smallest scalar kind to which both
    ``type1`` and ``type2`` may be safely cast. Reference parity: types.py:836-867
    (NumPy promotion table; bfloat16 follows the JAX lattice).
    """
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1.jnp_type(), t2.jnp_type()))


def result_type(*arrays_and_types: Any) -> Type[datatype]:
    """
    Returns the data type that results from type promotions rules performed in an
    arithmetic operation. Reference parity: types.py:868-949.
    """
    operands = []
    for a in arrays_and_types:
        from .dndarray import DNDarray

        if isinstance(a, DNDarray):
            operands.append(a.dtype.jnp_type())
        elif isinstance(a, type) and issubclass(a, datatype):
            operands.append(canonical_heat_type(a).jnp_type())
        elif isinstance(a, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
            operands.append(a)  # keep python scalars weak, numpy value-based rules
        else:
            try:
                operands.append(canonical_heat_type(a).jnp_type())
            except TypeError:
                operands.append(np.asarray(a).dtype)
    return canonical_heat_type(jnp.result_type(*operands))


class finfo:
    """
    Machine limits for floating point types: ``bits``, ``eps``, ``max``, ``min``,
    ``tiny``. Reference parity: types.py:950-1006.
    """

    def __new__(cls, dtype: Type[datatype]):
        dtype = canonical_heat_type(dtype)
        if not issubclass(dtype, (floating, complexfloating)):
            raise TypeError(f"data type {dtype!r} not inexact")
        obj = object.__new__(cls)
        info = jnp.finfo(dtype.jnp_type())
        obj.bits = builtins.int(info.bits)
        obj.eps = builtins.float(info.eps)
        obj.max = builtins.float(info.max)
        obj.min = builtins.float(info.min)
        obj.tiny = builtins.float(info.tiny)
        return obj


class iinfo:
    """
    Machine limits for integer types: ``bits``, ``max``, ``min``.
    Reference parity: types.py:1007-1056.
    """

    def __new__(cls, dtype: Type[datatype]):
        dtype = canonical_heat_type(dtype)
        if not issubclass(dtype, (integer, bool)):
            raise TypeError(f"data type {dtype!r} not exact")
        obj = object.__new__(cls)
        if dtype is bool:
            obj.bits, obj.max, obj.min = 8, 1, 0
        else:
            info = jnp.iinfo(dtype.jnp_type())
            obj.bits = builtins.int(info.bits)
            obj.max = builtins.int(info.max)
            obj.min = builtins.int(info.min)
        return obj


def iscomplex(x):
    """Element-wise: is the element complex with nonzero imaginary part (reference
    types.py iscomplex)."""
    from . import factories
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        x = factories.array(x)
    if issubclass(x.dtype, complexfloating):
        return DNDarray.__new_like__(x, jnp.imag(x.larray) != 0, bool)
    return DNDarray.__new_like__(x, jnp.zeros(x.larray.shape, dtype=np.bool_), bool)


def isreal(x):
    """Element-wise: is the element real-valued (reference types.py isreal)."""
    from . import factories
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        x = factories.array(x)
    if issubclass(x.dtype, complexfloating):
        return DNDarray.__new_like__(x, jnp.imag(x.larray) == 0, bool)
    return DNDarray.__new_like__(x, jnp.ones(x.larray.shape, dtype=np.bool_), bool)
