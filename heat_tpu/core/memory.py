"""
Memory/layout operations.

Parity with the reference's ``heat/core/memory.py`` (``copy`` :13,
``sanitize_memory_layout`` :42). Physical layout is XLA's concern on TPU, so the
layout sanitizer validates and passes through.
"""

from __future__ import annotations

import jax.numpy as jnp

from .dndarray import DNDarray
from . import sanitation

__all__ = ["copy", "sanitize_memory_layout"]


def copy(a: DNDarray) -> DNDarray:
    """A (deep) copy of the array (reference memory.py:13-40)."""
    sanitation.sanitize_in(a)
    return DNDarray.__new_like__(a, jnp.copy(a.larray))


def sanitize_memory_layout(x, order: str = "C"):
    """
    Return the array in the given memory layout (reference memory.py:42-94 permutes
    torch strides). XLA chooses tilings on TPU; 'C'/'F' are validated and the array is
    returned unchanged.
    """
    if order not in ("C", "F"):
        raise ValueError(f"invalid memory layout, order must be 'C' or 'F', got {order}")
    return x


DNDarray.copy = copy
