"""
Arithmetic operations on DNDarrays.

Parity with the reference's ``heat/core/arithmetics.py`` (``__all__`` at
arithmetics.py:28-60). Every function funnels through the generic templates in
``_operations.py``; reductions (``sum``/``prod``) and scans (``cumsum``/``cumprod``)
across a split axis lower to XLA psum/scan collectives instead of MPI
Allreduce/Exscan.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divide",
    "floordiv",
    "floor_divide",
    "fmod",
    "invert",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "copysign",
    "hypot",
    "nanprod",
    "nansum",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise addition of two operands (reference arithmetics.py add)."""
    return _operations.__binary_op(jnp.add, t1, t2, out, where)


def bitwise_and(t1, t2) -> DNDarray:
    """Element-wise bitwise AND (reference arithmetics.py bitwise_and)."""
    __integer_guard(t1, t2)
    return _operations.__binary_op(jnp.bitwise_and, t1, t2)


def bitwise_or(t1, t2) -> DNDarray:
    """Element-wise bitwise OR (reference arithmetics.py bitwise_or)."""
    __integer_guard(t1, t2)
    return _operations.__binary_op(jnp.bitwise_or, t1, t2)


def bitwise_xor(t1, t2) -> DNDarray:
    """Element-wise bitwise XOR (reference arithmetics.py bitwise_xor)."""
    __integer_guard(t1, t2)
    return _operations.__binary_op(jnp.bitwise_xor, t1, t2)


def __integer_guard(*ts) -> None:
    from . import types

    for t in ts:
        dt = types.heat_type_of(t)
        if not (issubclass(dt, types.integer) or dt is types.bool):
            raise TypeError(f"Operation is not supported for float types, got {dt}")


def invert(a, out=None) -> DNDarray:
    """Element-wise bitwise NOT; boolean arrays invert logically (reference
    arithmetics.py invert)."""
    from . import types

    dt = types.heat_type_of(a)
    if issubclass(dt, (types.floating, types.complexfloating)):
        raise TypeError(f"Operation is not supported for float types, got {dt}")
    return _operations.__local_op(jnp.invert, a, out)


bitwise_not = invert


def cumprod(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product along ``axis`` (reference arithmetics.py cumprod; MPI
    Exscan there, XLA scan here)."""
    return _operations.__cum_op(a, jnp.cumprod, axis=axis, dtype=dtype, out=out)


cumproduct = cumprod


def cumsum(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along ``axis`` (reference arithmetics.py cumsum)."""
    return _operations.__cum_op(a, jnp.cumsum, axis=axis, dtype=dtype, out=out)


def diff(a, n: int = 1, axis: int = -1, prepend=None, append=None) -> DNDarray:
    """n-th discrete difference along ``axis`` (reference arithmetics.py diff; the
    neighbor-boundary exchange there is a shifted-slice subtraction here)."""
    from . import sanitation

    sanitation.sanitize_in(a)
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    kw = {}
    if prepend is not None:
        kw["prepend"] = prepend.larray if isinstance(prepend, DNDarray) else prepend
    if append is not None:
        kw["append"] = append.larray if isinstance(append, DNDarray) else append
    # prepend/append can cancel diff's shrink, making the result PHYSICAL-shaped
    # while the appended values sit after the pad — force the logical view then
    return _operations.__local_op(
        jnp.diff, a, None, force_logical=bool(kw), n=n, axis=axis, **kw
    )


def div(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise true division (reference arithmetics.py div)."""
    return _operations.__binary_op(jnp.true_divide, t1, t2, out, where)


divide = div


def floordiv(t1, t2) -> DNDarray:
    """Element-wise floor division (reference arithmetics.py floordiv)."""
    return _operations.__binary_op(jnp.floor_divide, t1, t2)


floor_divide = floordiv


def fmod(t1, t2) -> DNDarray:
    """Element-wise C-style (truncated) remainder (reference arithmetics.py fmod)."""
    return _operations.__binary_op(jnp.fmod, t1, t2)


def left_shift(t1, t2) -> DNDarray:
    """Element-wise bit shift left (reference arithmetics.py left_shift)."""
    __integer_guard(t1, t2)
    return _operations.__binary_op(jnp.left_shift, t1, t2)


def mod(t1, t2) -> DNDarray:
    """Element-wise Python-style modulo (reference arithmetics.py mod)."""
    return _operations.__binary_op(jnp.mod, t1, t2)


remainder = mod


def mul(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise multiplication (reference arithmetics.py mul)."""
    return _operations.__binary_op(jnp.multiply, t1, t2, out, where)


multiply = mul


def neg(a, out=None) -> DNDarray:
    """Element-wise negation (reference arithmetics.py neg)."""
    return _operations.__local_op(jnp.negative, a, out)


negative = neg


def pos(a, out=None) -> DNDarray:
    """Element-wise unary plus (reference arithmetics.py pos)."""
    return _operations.__local_op(jnp.positive, a, out)


positive = pos


def pow(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise exponentiation (reference arithmetics.py pow)."""
    return _operations.__binary_op(jnp.power, t1, t2, out, where)


power = pow


def prod(a, axis=None, out=None, keepdim=None, keepdims=None, where=None) -> DNDarray:
    """Product of elements over the given axis (reference arithmetics.py prod →
    __reduce_op with MPI.PROD; here a sharded jnp.prod). ``where`` restricts
    the product to the masked elements (numpy semantics)."""
    kwargs = {} if where is None else {"where": where}
    return _operations.__reduce_op(a, jnp.prod, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims), **kwargs)


def hypot(t1, t2, out=None) -> DNDarray:
    """Element-wise ``sqrt(t1**2 + t2**2)`` without intermediate overflow
    (numpy-API completion beyond the reference snapshot)."""
    return _operations.__binary_op(jnp.hypot, t1, t2, out)


def copysign(t1, t2, out=None) -> DNDarray:
    """Magnitude of ``t1`` with the sign of ``t2`` (numpy-API completion)."""
    return _operations.__binary_op(jnp.copysign, t1, t2, out)


def nansum(a, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Sum treating NaN as zero (numpy-API completion beyond the reference
    snapshot; rides the same sharded reduce template, NaN-aware neutral)."""
    return _operations.__reduce_op(a, jnp.nansum, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def nanprod(a, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Product treating NaN as one (numpy-API completion)."""
    return _operations.__reduce_op(a, jnp.nanprod, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def right_shift(t1, t2) -> DNDarray:
    """Element-wise bit shift right (reference arithmetics.py right_shift)."""
    __integer_guard(t1, t2)
    return _operations.__binary_op(jnp.right_shift, t1, t2)


def sub(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise subtraction (reference arithmetics.py sub)."""
    return _operations.__binary_op(jnp.subtract, t1, t2, out, where)


subtract = sub


def sum(a, axis=None, out=None, keepdim=None, keepdims=None, where=None) -> DNDarray:
    """Sum of elements over the given axis (reference arithmetics.py sum →
    __reduce_op with MPI.SUM at _operations.py:441; lowers to psum over ICI
    here). ``where`` restricts the sum to the masked elements (numpy
    semantics)."""
    kwargs = {} if where is None else {"where": where}
    return _operations.__reduce_op(a, jnp.sum, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims), **kwargs)


# ---------------------------------------------------------------------- operators
DNDarray.__add__ = lambda self, other: add(self, other)
DNDarray.__radd__ = lambda self, other: add(other, self)
DNDarray.__sub__ = lambda self, other: sub(self, other)
DNDarray.__rsub__ = lambda self, other: sub(other, self)
DNDarray.__mul__ = lambda self, other: mul(self, other)
DNDarray.__rmul__ = lambda self, other: mul(other, self)
DNDarray.__truediv__ = lambda self, other: div(self, other)
DNDarray.__rtruediv__ = lambda self, other: div(other, self)
DNDarray.__floordiv__ = lambda self, other: floordiv(self, other)
DNDarray.__rfloordiv__ = lambda self, other: floordiv(other, self)
DNDarray.__mod__ = lambda self, other: mod(self, other)
DNDarray.__rmod__ = lambda self, other: mod(other, self)
DNDarray.__pow__ = lambda self, other: pow(self, other)
DNDarray.__rpow__ = lambda self, other: pow(other, self)
DNDarray.__and__ = lambda self, other: bitwise_and(self, other)
DNDarray.__rand__ = lambda self, other: bitwise_and(other, self)
DNDarray.__or__ = lambda self, other: bitwise_or(self, other)
DNDarray.__ror__ = lambda self, other: bitwise_or(other, self)
DNDarray.__xor__ = lambda self, other: bitwise_xor(self, other)
DNDarray.__rxor__ = lambda self, other: bitwise_xor(other, self)
DNDarray.__lshift__ = lambda self, other: left_shift(self, other)
DNDarray.__rlshift__ = lambda self, other: left_shift(other, self)
DNDarray.__rshift__ = lambda self, other: right_shift(self, other)
DNDarray.__rrshift__ = lambda self, other: right_shift(other, self)
DNDarray.__invert__ = lambda self: invert(self)
DNDarray.__neg__ = lambda self: neg(self)
DNDarray.__pos__ = lambda self: pos(self)
DNDarray.sum = sum
DNDarray.prod = prod
DNDarray.cumsum = cumsum
DNDarray.cumprod = cumprod
