"""
Trigonometric and hyperbolic operations (all element-local).

Parity with the reference's ``heat/core/trigonometrics.py`` (``__all__`` at
trigonometrics.py:18-45).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "acos",
    "acosh",
    "asin",
    "asinh",
    "atan",
    "atan2",
    "atanh",
    "arccos",
    "arccosh",
    "arcsin",
    "arcsinh",
    "arctan",
    "arctan2",
    "arctanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def arccos(x, out=None) -> DNDarray:
    """Element-wise inverse cosine (reference trigonometrics.py arccos)."""
    return _operations.__local_op(jnp.arccos, x, out)


acos = arccos


def arccosh(x, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic cosine (reference trigonometrics.py arccosh)."""
    return _operations.__local_op(jnp.arccosh, x, out)


acosh = arccosh


def arcsin(x, out=None) -> DNDarray:
    """Element-wise inverse sine (reference trigonometrics.py arcsin)."""
    return _operations.__local_op(jnp.arcsin, x, out)


asin = arcsin


def arcsinh(x, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic sine (reference trigonometrics.py arcsinh)."""
    return _operations.__local_op(jnp.arcsinh, x, out)


asinh = arcsinh


def arctan(x, out=None) -> DNDarray:
    """Element-wise inverse tangent (reference trigonometrics.py arctan)."""
    return _operations.__local_op(jnp.arctan, x, out)


atan = arctan


def arctan2(t1, t2) -> DNDarray:
    """Element-wise quadrant-aware inverse tangent of t1/t2 (reference
    trigonometrics.py arctan2)."""
    return _operations.__binary_op(jnp.arctan2, t1, t2)


atan2 = arctan2


def arctanh(x, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic tangent (reference trigonometrics.py arctanh)."""
    return _operations.__local_op(jnp.arctanh, x, out)


atanh = arctanh


def cos(x, out=None) -> DNDarray:
    """Element-wise cosine (reference trigonometrics.py cos)."""
    return _operations.__local_op(jnp.cos, x, out)


def cosh(x, out=None) -> DNDarray:
    """Element-wise hyperbolic cosine (reference trigonometrics.py cosh)."""
    return _operations.__local_op(jnp.cosh, x, out)


def deg2rad(x, out=None) -> DNDarray:
    """Degrees to radians (reference trigonometrics.py deg2rad)."""
    return _operations.__local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x, out=None) -> DNDarray:
    """Radians to degrees (reference trigonometrics.py rad2deg)."""
    return _operations.__local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x, out=None) -> DNDarray:
    """Element-wise sine (reference trigonometrics.py sin)."""
    return _operations.__local_op(jnp.sin, x, out)


def sinh(x, out=None) -> DNDarray:
    """Element-wise hyperbolic sine (reference trigonometrics.py sinh)."""
    return _operations.__local_op(jnp.sinh, x, out)


def tan(x, out=None) -> DNDarray:
    """Element-wise tangent (reference trigonometrics.py tan)."""
    return _operations.__local_op(jnp.tan, x, out)


def tanh(x, out=None) -> DNDarray:
    """Element-wise hyperbolic tangent (reference trigonometrics.py tanh)."""
    return _operations.__local_op(jnp.tanh, x, out)
