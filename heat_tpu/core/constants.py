"""Constants (parity: reference heat/core/constants.py:7-19)."""

import math

__all__ = ["e", "Euler", "inf", "Inf", "Infty", "Infinity", "nan", "NaN", "pi"]

e: float = math.e
"""Euler's number."""
pi: float = math.pi
"""Archimedes' constant."""
inf: float = float("inf")
"""IEEE 754 positive infinity."""
nan: float = float("nan")
"""IEEE 754 Not a Number."""

# aliases
Euler = e
Inf = inf
Infty = inf
Infinity = inf
NaN = nan
