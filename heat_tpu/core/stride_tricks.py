"""
Shape/axis sanitation helpers.

Parity with the reference's ``heat/core/stride_tricks.py`` (``broadcast_shape`` :12,
``sanitize_axis`` :72, ``sanitize_shape`` :135, ``sanitize_slice`` :180).
"""

from __future__ import annotations

import numbers
from typing import Optional, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a: Tuple[int, ...], shape_b: Tuple[int, ...]) -> Tuple[int, ...]:
    """
    Infers, if possible, the broadcast output shape of two operands. Raises
    ``ValueError`` on incompatible shapes. Reference parity: stride_tricks.py:12-70.
    """
    return broadcast_shapes(shape_a, shape_b)


def broadcast_shapes(*shapes: Tuple[int, ...]) -> Tuple[int, ...]:
    """N-ary broadcast shape inference (NumPy rules)."""
    try:
        return tuple(np.broadcast_shapes(*shapes))
    except ValueError:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}")


def reduced_split(
    split: Optional[int],
    axis: Optional[Union[int, Tuple[int, ...]]],
    keepdims: bool = False,
    prepend: int = 0,
) -> Optional[int]:
    """
    The split axis of a reduction's result: ``None`` when the split axis itself is
    reduced (or a full reduction), otherwise the input split shifted left by the
    number of reduced axes before it (unless ``keepdims``) and right by ``prepend``
    leading result axes (e.g. a vector ``q`` in percentile). ``axis`` must already
    be sanitized (non-negative int, tuple of such, or None).
    """
    if split is None:
        return None
    axes = (axis,) if isinstance(axis, (int, np.integer)) else axis
    if axes is None or split in axes:
        return None
    if not keepdims:
        split -= sum(1 for a in axes if a < split)
    return split + prepend


def sanitize_axis(
    shape: Tuple[int, ...], axis: Optional[Union[int, Tuple[int, ...]]]
) -> Optional[Union[int, Tuple[int, ...]]]:
    """
    Checks conformity of an axis with respect to a given shape: resolves negative
    axes, verifies bounds. Axis may be ``None``, an int, or a tuple of ints.
    Reference parity: stride_tricks.py:72-133.

    Raises
    ------
    TypeError
        If the axis is not integral.
    ValueError
        If the axis is out of range.
    """
    if axis is None:
        return None
    ndim = len(shape)
    if isinstance(axis, (tuple, list)):
        return tuple(sanitize_axis(shape, a) for a in axis)
    if isinstance(axis, np.ndarray) and axis.ndim == 0:
        axis = axis.item()
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if ndim == 0 and axis in (-1, 0):
        return axis  # scalars accept the degenerate axes, reference stride_tricks.py:110
    if axis < -ndim or axis >= ndim:
        raise ValueError(f"axis {axis} is out of bounds for {ndim}-dimensional shape {shape}")
    return axis % ndim if ndim else axis


def sanitize_shape(shape: Union[int, Tuple[int, ...]], lval: int = 0) -> Tuple[int, ...]:
    """
    Verifies and normalizes the given shape: scalars become 1-tuples, all entries must
    be integral and ``>= lval``. Reference parity: stride_tricks.py:135-178.
    """
    if isinstance(shape, (int, np.integer)):
        shape = (shape,)
    shape = tuple(shape)
    out = []
    for dim in shape:
        if isinstance(dim, float) and not dim.is_integer():
            raise TypeError(f"expected integer shape entry, got {dim}")
        if not isinstance(dim, (int, np.integer, float)):
            raise TypeError(f"expected integer shape entry, got {type(dim)}")
        dim = int(dim)
        if dim < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
        out.append(dim)
    return tuple(out)


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """
    Resolves a slice against a dimension length: fills Nones, resolves negatives.
    Reference parity: stride_tricks.py:180-210.
    """
    if not isinstance(sl, slice):
        raise TypeError("can only be a slice")
    return slice(*sl.indices(max_dim))
