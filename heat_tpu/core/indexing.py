"""
Indexing operations.

Parity with the reference's ``heat/core/indexing.py`` (``nonzero`` :16, ``where``
:91). ``nonzero`` is eager (data-dependent output shape — fine outside jit; the
reference offsets local indices by the split displacement, unnecessary on a global
array).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from . import fusion
from . import sanitation
from .dndarray import DNDarray
from . import types

__all__ = ["count_nonzero", "nonzero", "where"]


def nonzero(x) -> DNDarray:
    """
    Indices of nonzero elements as an (n, ndim) array (reference indexing.py:16-89
    returns the transposed-stacked index layout of torch.nonzero).
    """
    sanitation.sanitize_in(x)
    idx = jnp.stack(jnp.nonzero(x.larray), axis=1) if x.ndim > 0 else jnp.nonzero(x.larray.reshape(1))[0]
    if x.ndim == 1:
        idx = idx.reshape(-1)
    split = 0 if x.split is not None else None
    return DNDarray(idx, tuple(idx.shape), types.canonical_heat_type(idx.dtype), split, x.device, x.comm, True)


def where(cond, x=None, y=None) -> DNDarray:
    """
    Either the nonzero indices (one argument) or element selection ``cond ? x : y``
    (three arguments) (reference indexing.py:91-131).
    """
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y must be given")
    sanitation.sanitize_in(cond)
    # deferred-execution fast path: a 3-argument select is elementwise glue
    # and fuses into the pending expression DAG (core/fusion.py)
    if fusion.enabled():
        deferred = fusion.defer_where(cond, x, y)
        if deferred is not None:
            return deferred
    xv = x.larray if isinstance(x, DNDarray) else x
    yv = y.larray if isinstance(y, DNDarray) else y
    res = jnp.where(cond.larray, xv, yv)
    split = cond.split
    if split is not None and res.ndim != cond.ndim:
        split = None
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), split, cond.device, cond.comm, True)


def count_nonzero(x, axis=None, keepdims: bool = False) -> DNDarray:
    """Number of nonzero elements along an axis (numpy-API completion; rides the
    sharded reduce template — the neutral-element table already knows
    ``jnp.count_nonzero``)."""
    from . import _operations

    return _operations.__reduce_op(
        x, jnp.count_nonzero, axis=axis, keepdims=keepdims
    )
