"""Version information (parity: reference heat/core/version.py:3-8)."""

major: int = 0
"""Major version number."""
minor: int = 1
"""Minor version number."""
micro: int = 0
"""Micro version number."""
extension: str = "dev"
"""Version extension tag."""

if not extension:
    __version__: str = f"{major}.{minor}.{micro}"
    """String containing the full version."""
else:
    __version__: str = f"{major}.{minor}.{micro}-{extension}"
