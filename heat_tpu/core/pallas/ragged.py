"""
In-register ragged reduce: reductions over canonically padded split-axis
operands with the pad masked to the op's neutral element *inside the tile*.

The PR 4 reduction sinks fall back to an eager flush whenever the eager path
computes on the *sliced logical view* of a padded operand — ``where=``-masked
reductions (the mask's extent is logical), flattened arg-reductions (flat
indices must be logical), and the moment/norm routes (they consume
``x.larray``). An in-trace pad slice is no substitute: the SPMD partitioner
then groups the ragged shards' partial sums differently from the eager
dispatch (reassociation). This kernel takes the third road the ISSUE names:
keep the *physical* padded layout, walk it in row tiles, and neutralize the
pad (and any ``where=`` mask) with the op's own neutral element in VMEM —
one pass, no materialized logical copy, no separate mask kernel.

Kernel shape: the operand is viewed 2-D (``(1, N)`` for vectors), row-tiled
at 128 rows per grid step with the full column extent resident in VMEM;
validity is decided per element from two baked bounds (the logical extent of
the padded axis and the tile-pad bound) plus the optional ``where`` mask, and
each tile folds into a running accumulator carried in the output block
(scalar and reduce-rows modes) or writes its own output rows (reduce-cols
mode). Arg-reductions carry a (best value, best flat index) pair with the
eager first-occurrence tie-break: within a tile the minimum flat index among
hits, across tiles strict improvement only (earlier tiles hold smaller
indices); the physical flat index is remapped to the logical one outside the
kernel (exact — one padded axis preserves C-order).

Lowered ops: ``sum`` / ``prod`` / ``min`` / ``max`` / ``argmin`` / ``argmax``
in-kernel; ``any``/``all`` ride max/min over an i32 cast, ``mean`` divides
the masked sum by the static logical count, ``nanmean`` accumulates a
dynamic non-NaN count beside the sum, and the Euclidean/Frobenius norms
square in-register and ``sqrt`` outside. Accumulating ops are restricted to
f32 and exact integer operands (integer accumulation is order-exact;
sub-32-bit floats keep the PR 4 low-float fallback); order-preserving
min/max/arg additionally admit bf16 bit-exactly.

Every callable consults :func:`heat_tpu.core.pallas.in_recovery` first and
re-emits the *XLA reference formulation* (the eager logical-view compute)
when the fusion ladder is replaying a failed flush — recovery lands on the
XLA path, never re-enters the failed kernel.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import in_recovery as _in_recovery

__all__ = ["plan", "sink_fn_for", "reference_fn"]

#: Row-tile height of the grid sweep (full column extent per tile).
TILE_R = 128

#: VMEM guardrails for the availability predicate: a row tile (and the
#: accumulator row) must fit comfortably beside double-buffered inputs.
MAX_COLS = 16384
MAX_ELEMS = 1 << 24

_ACC_OPS = ("sum", "prod", "mean", "nanmean", "norm2")
_ORDER_OPS = ("min", "max", "any", "all", "argmin", "argmax")
_INT_KINDS = "biu"  # numpy dtype kinds with order-exact accumulation


def _axmode(ndim, axis, split_ax):
    """Normalize the reduction axis against the 2-D kernel view. Returns
    ``("all" | 0 | 1, split2d)`` or None when the combination would leave the
    result split (the sink contract here is an unsplit result) or is not a
    2-D-expressible reduction."""
    if ndim == 1:
        if axis in (None, 0, (0,)):
            return "all", 1
        return None
    if ndim != 2:
        return None
    split2d = int(split_ax)
    if axis is None:
        return "all", split2d
    axes = (axis,) if isinstance(axis, int) else tuple(sorted(axis))
    if axes == (0, 1):
        return "all", split2d
    if len(axes) == 1 and axes[0] == split2d:
        # reducing exactly the padded axis: the surviving axis is unsplit
        return axes[0], split2d
    return None


def plan(
    kind: str,
    opname: str,
    shape,
    dtype,
    split_ax: int,
    n_log: int,
    axis,
    keepdims: bool,
    has_where: bool,
    extra=(),
    interpret: bool = True,
):
    """Build the static task descriptor for one padded-operand sink, or None
    when the kernel does not express this combination (the caller counts the
    ``fusion.sink_fallbacks`` label). ``shape`` is the PHYSICAL padded shape;
    ``extra`` carries per-kind statics (norm: ``(flatten,)``). The returned
    task bakes the expected logical result aval of the *eager* formulation,
    so the fused and hatch paths agree on shape and dtype by construction."""
    shape = tuple(int(s) for s in shape)
    dt = np.dtype(dtype)
    if kind == "where" and opname not in ("sum", "prod", "any", "all"):
        return None
    if kind == "argflat" and (opname not in ("argmin", "argmax") or axis is not None):
        return None
    if kind == "moment" and opname not in ("mean", "nanmean"):
        return None
    if kind == "norm" and opname != "norm2":
        return None
    if opname in _ACC_OPS and not (
        dt == np.dtype(np.float32) or dt.kind in _INT_KINDS
    ):
        return None  # bf16/f16 accumulation: PR 4 low-float discipline
    mode = _axmode(len(shape), axis, split_ax)
    if mode is None:
        return None
    r, c = (1, shape[0]) if len(shape) == 1 else shape
    if c > MAX_COLS or r * c > MAX_ELEMS:
        return None
    axisn = axis if (axis is None or isinstance(axis, int)) else tuple(sorted(axis))
    task = (
        kind, opname, shape, str(dt), int(split_ax), int(n_log),
        axisn, bool(keepdims), bool(has_where), tuple(extra), bool(interpret),
    )
    try:
        ref = reference_fn(task)
        avals = [jax.ShapeDtypeStruct(shape, dt)]
        if has_where:
            logical = list(shape)
            logical[split_ax] = n_log
            avals.append(jax.ShapeDtypeStruct(tuple(logical), np.dtype(bool)))
        out = jax.eval_shape(ref, *avals)
    except Exception:
        return None
    return task + (tuple(int(s) for s in out.shape), str(out.dtype))


def _unpack(task):
    (kind, opname, shape, dt, split_ax, n_log, axis, keepdims, has_where,
     extra, interpret, out_shape, out_dtype) = task
    return (kind, opname, shape, np.dtype(dt), split_ax, n_log, axis,
            keepdims, has_where, extra, interpret, out_shape, np.dtype(out_dtype))


def _logical_index(shape, split_ax, n_log):
    return tuple(
        slice(0, n_log) if d == split_ax else slice(None) for d in range(len(shape))
    )


@functools.lru_cache(maxsize=256)
def _reference_cached(key):
    (kind, opname, shape, split_ax, n_log, axis, keepdims, extra) = key
    idx = _logical_index(shape, split_ax, n_log)
    jop = {
        "sum": jnp.sum, "prod": jnp.prod, "any": jnp.any, "all": jnp.all,
        "argmin": jnp.argmin, "argmax": jnp.argmax,
        "mean": jnp.mean, "nanmean": jnp.nanmean,
    }.get(opname)

    def ref(v, *dyn):
        vl = v[idx]  # the eager logical view
        if kind == "where":
            return jop(vl, axis=axis, keepdims=keepdims, where=dyn[0])
        if kind == "argflat":
            return jop(vl, axis=None)
        if kind == "moment":
            return jop(vl, axis=axis, keepdims=keepdims)
        # norm2: vector_norm's full-array flatten, or norm on the view
        (flatten,) = extra
        if flatten:
            vl = vl.reshape(-1)
        return jnp.linalg.norm(vl, axis=axis, keepdims=keepdims)

    return ref


def reference_fn(task):
    """The XLA reference formulation of ``task`` — the eager logical-view
    compute, used for abstract eval at plan time and by the fusion ladder's
    recovery replay (in eager replay it runs op-at-a-time on concrete arrays,
    bit-identical to the hatch path). Accepts both the 11-field plan-time
    prefix and the full task."""
    return _reference_cached(
        (task[0], task[1], task[2], task[4], task[5], task[6], task[7], task[9])
    )


# ------------------------------------------------------------------ kernel
def _neutral(op, dt):
    if op == "sum":
        return np.zeros((), dt)[()]
    if op == "prod":
        return np.ones((), dt)[()]
    if op == "min":
        return np.array(np.inf if dt.kind == "f" else np.iinfo(dt).max, dt)[()]
    if op == "max":
        return np.array(-np.inf if dt.kind == "f" else np.iinfo(dt).min, dt)[()]
    raise AssertionError(op)


_COMBINE = {
    "sum": jnp.add, "prod": jnp.multiply, "min": jnp.minimum, "max": jnp.maximum,
}


@functools.lru_cache(maxsize=256)
def _reduce_call(op, r_pad, c, tile_r, dt_str, row_bound, col_bound, axmode,
                 has_where, with_count, interpret):
    """Memoized pallas callable for one masked-reduce signature. ``op`` is a
    core op (sum/prod/min/max); ``with_count`` adds a dynamic valid-count
    output (nanmean — NaN positions are already invalid in the mask the
    wrapper passes). Inputs are the tile-padded physical 2-D operand and,
    when ``has_where``, an i32 mask of the same shape."""
    dt = jnp.dtype(dt_str)
    neutral = _neutral(op, np.dtype(dt_str))
    combine = _COMBINE[op]
    grid = (r_pad // tile_r,)

    def kernel(*refs):
        x_ref = refs[0]
        m_ref = refs[1] if has_where else None
        out_ref = refs[1 + int(has_where)]
        cnt_ref = refs[2 + int(has_where)] if with_count else None
        i = pl.program_id(0)
        x = x_ref[...]
        rid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) + i * tile_r
        cid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        valid = (rid < row_bound) & (cid < col_bound)
        if m_ref is not None:
            valid = valid & (m_ref[...] != 0)
        vm = jnp.where(valid, x, jnp.asarray(neutral, dt))
        if axmode == 1:
            # reduce-cols: each tile owns its output rows, no carry
            out_ref[...] = jnp.asarray(
                getattr(jnp, op)(vm, axis=1, keepdims=True), dt
            )
        else:
            t = (
                getattr(jnp, op)(vm).reshape(1, 1)
                if axmode == "all"
                else getattr(jnp, op)(vm, axis=0, keepdims=True)
            )

            @pl.when(i == 0)
            def _():
                out_ref[...] = jnp.full_like(out_ref, neutral)

            out_ref[...] = combine(out_ref[...], jnp.asarray(t, dt))
        if cnt_ref is not None:
            n = valid.astype(jnp.int32)
            if axmode == 1:
                cnt_ref[...] = jnp.sum(n, axis=1, keepdims=True)
            else:
                tn = (
                    jnp.sum(n).reshape(1, 1)
                    if axmode == "all"
                    else jnp.sum(n, axis=0, keepdims=True)
                )

                @pl.when(i == 0)
                def _():
                    cnt_ref[...] = jnp.zeros_like(cnt_ref)

                cnt_ref[...] += tn

    if axmode == "all":
        out_sds = jax.ShapeDtypeStruct((1, 1), dt)
        out_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    elif axmode == 0:
        out_sds = jax.ShapeDtypeStruct((1, c), dt)
        out_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    else:
        out_sds = jax.ShapeDtypeStruct((r_pad, 1), dt)
        out_spec = pl.BlockSpec((tile_r, 1), lambda i: (i, 0))
    out_shape = [out_sds]
    out_specs = [out_spec]
    if with_count:
        cshape = (1, 1) if axmode == "all" else ((1, c) if axmode == 0 else (r_pad, 1))
        cspec = out_spec if axmode != "all" else pl.BlockSpec((1, 1), lambda i: (0, 0))
        out_shape.append(jax.ShapeDtypeStruct(cshape, jnp.int32))
        out_specs.append(
            cspec if axmode != 0 else pl.BlockSpec((1, c), lambda i: (0, 0))
        )
    in_specs = [pl.BlockSpec((tile_r, c), lambda i: (i, 0))]
    if has_where:
        in_specs.append(pl.BlockSpec((tile_r, c), lambda i: (i, 0)))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=tuple(out_specs) if len(out_specs) > 1 else out_specs[0],
        out_shape=tuple(out_shape) if len(out_shape) > 1 else out_shape[0],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _arg_call(op, r_pad, c, tile_r, dt_str, row_bound, col_bound, interpret):
    """Memoized pallas callable for a flattened arg-reduction: carries the
    (best value, best physical flat index) pair across row tiles with the
    eager first-occurrence tie-break."""
    dt = np.dtype(dt_str)
    is_min = op == "argmin"
    is_float = dt.kind == "f" or dt_str == "bfloat16"
    kdt = jnp.float32 if is_float else jnp.dtype(dt_str)
    worst = _neutral("min" if is_min else "max", np.dtype(np.float32)) if is_float \
        else _neutral("min" if is_min else "max", dt)
    intmax = np.iinfo(np.int32).max

    def kernel(x_ref, bv_ref, bi_ref):
        i = pl.program_id(0)
        x = x_ref[...].astype(kdt)
        rid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) + i * tile_r
        cid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        valid = (rid < row_bound) & (cid < col_bound)
        if is_float:
            # numpy/jnp arg-reductions let NaN win: fold NaN to the strongest
            # key so the first NaN's index is selected, exactly like eager
            x = jnp.where(jnp.isnan(x), jnp.asarray(
                -jnp.inf if is_min else jnp.inf, kdt), x)
        key = jnp.where(valid, x, jnp.asarray(worst, kdt))
        flat = rid * c + cid
        tbest = jnp.min(key) if is_min else jnp.max(key)
        hit = (key == tbest) & valid
        tidx = jnp.min(jnp.where(hit, flat, intmax))

        @pl.when(i == 0)
        def _():
            bv_ref[0, 0] = jnp.asarray(worst, kdt)
            bi_ref[0, 0] = intmax

        bv, bi = bv_ref[0, 0], bi_ref[0, 0]
        # strict improvement only: earlier tiles hold strictly smaller flat
        # indices, so a tie keeps the first occurrence
        take = (tbest < bv) if is_min else (tbest > bv)
        bv_ref[0, 0] = jnp.where(take, tbest, bv)
        bi_ref[0, 0] = jnp.where(take, tidx, bi)

    return pl.pallas_call(
        kernel,
        grid=(r_pad // tile_r,),
        in_specs=[pl.BlockSpec((tile_r, c), lambda i: (i, 0))],
        out_specs=(
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, 1), kdt),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        interpret=interpret,
    )


def _tile_r_pref(interpret: bool) -> int:
    """The preferred tall-operand tile height: the static 128, or the
    measured winner under ``HEAT_TPU_TUNING=1`` (ISSUE 18; one env read
    when off)."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return TILE_R
    try:
        return _tuning.lookup(
            "pallas.ragged.tile_r", context={"interpret": bool(interpret)}
        )
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return TILE_R


def _tile_geometry(r, interpret: bool = False):
    """(tile_r, r_pad): preferred-height tiles for tall operands, one
    sublane-aligned tile otherwise."""
    pref = _tile_r_pref(interpret)
    if r > pref:
        tile_r = pref
    else:
        tile_r = max(8, -(-r // 8) * 8) if r > 1 else 1
    return tile_r, -(-r // tile_r) * tile_r


def _execute(task, v, *dyn):
    """Run ``task``'s kernel on the physical operand (2-D view, tile pad,
    kernel, epilogue) and return the eager-shaped logical result."""
    (kind, opname, shape, dt, split_ax, n_log, axis, keepdims, has_where,
     extra, interpret, out_shape, out_dtype) = _unpack(task)
    ndim = len(shape)
    v2 = v.reshape(1, shape[0]) if ndim == 1 else v
    split2d = 1 if ndim == 1 else split_ax
    r, c = v2.shape
    row_bound = n_log if split2d == 0 else r
    col_bound = n_log if split2d == 1 else c
    mode = _axmode(ndim, axis, split_ax)[0]
    tile_r, r_pad = _tile_geometry(r, interpret)

    mask = None
    if has_where:
        logical = tuple(n_log if d == split_ax else s for d, s in enumerate(shape))
        m = jnp.broadcast_to(dyn[0], logical).astype(jnp.int32)
        m2 = m.reshape(1, -1) if ndim == 1 else m
        pad = [(0, v2.shape[d] - m2.shape[d]) for d in range(2)]
        mask = jnp.pad(m2, pad)  # physical extent; pad region False
    if r_pad != r:
        v2 = jnp.pad(v2, ((0, r_pad - r), (0, 0)))
        if mask is not None:
            mask = jnp.pad(mask, ((0, r_pad - r), (0, 0)))

    # core-op lowering: any/all ride max/min over an i32 cast, mean/nanmean
    # and the norms accumulate sums of (transformed) values
    x = v2
    count = None
    if opname in ("any", "all"):
        core = "max" if opname == "any" else "min"
        x = (v2 != 0).astype(jnp.int32)
    elif opname == "nanmean":
        core = "sum"
        nanm = jnp.isnan(v2)
        x = jnp.where(nanm, jnp.asarray(0, v2.dtype), v2)
        extra_mask = (~nanm).astype(jnp.int32)
        mask = extra_mask if mask is None else mask * extra_mask
        has_where = True
    elif opname == "norm2":
        core = "sum"
        x = (v2.astype(jnp.float32) ** 2)
    elif opname == "mean":
        core = "sum"
    elif opname in ("argmin", "argmax"):
        core = opname
    else:
        core = opname

    if core in ("argmin", "argmax"):
        call = _arg_call(
            core, r_pad, c, tile_r, str(v2.dtype), row_bound, col_bound, interpret
        )
        _, bi = call(x)
        p = bi[0, 0]
        if split2d == 1 and col_bound != c:
            p = (p // c) * col_bound + (p % c)
        res = p
    else:
        call = _reduce_call(
            core, r_pad, c, tile_r, str(x.dtype), row_bound, col_bound, mode,
            mask is not None, opname == "nanmean", interpret,
        )
        args = (x,) if mask is None else (x, mask)
        out = call(*args)
        if opname == "nanmean":
            s, count = out
            res = s / jnp.maximum(count, 1).astype(s.dtype)
        else:
            res = out
        if mode == 1 and r_pad != r:
            res = res[:r]  # drop the tile-pad rows of the per-row output
        if opname == "mean":
            rows_log = row_bound
            cols_log = col_bound
            n = {"all": rows_log * cols_log, 0: rows_log, 1: cols_log}[mode]
            res = res / jnp.asarray(n, res.dtype)
        elif opname == "norm2":
            res = jnp.sqrt(res)
        elif opname in ("any", "all"):
            res = res != 0
    return jnp.asarray(res).reshape(out_shape).astype(out_dtype)


_FNS: dict = {}


def sink_fn_for(task):
    """Memoized sink callable for one static task signature (one object per
    signature: node identity, the abstract-eval memo, and the trace-LRU key
    all hang off it). The callable replays the XLA reference formulation
    under ladder recovery and dispatches the pallas kernel otherwise."""
    fn = _FNS.get(task)
    if fn is None:
        def fn(v, *dyn, _t=task):
            if _in_recovery():
                return reference_fn(_t)(v, *dyn)
            return _execute(_t, v, *dyn)

        _FNS[task] = fn
    return fn
