"""
Pallas kernel tier: hand-tiled TPU kernels below XLA for the fusion-resistant
hot paths (ROADMAP item 2).

PRs 3-9 produced a *counted* list of places XLA fusion provably cannot follow
the eager surface — ``fusion.view_fallbacks{asymmetric-pad,stepped-split-slice}``,
the padded-operand and sub-32-bit reduction-sink fallbacks of PR 4, and the
plain-jnp online softmax inside ``ring_attention``'s ppermute loop. This
package is the escape hatch *below* XLA the SURVEY names (PAPER.md §0/§7):
three hand-tiled kernels behind existing call sites, each carrying its working
set in VMEM instead of materializing intermediates through HBM:

* ``flash_ring`` (:mod:`.flash`) — the per-hop (max, denominator, numerator)
  online-softmax update of ``_ring_attention_sharded`` as ONE kernel that
  walks the hop's K/V block tile by tile with the running triple resident in
  VMEM (the FlashAttention tiling, Dao et al. 2022 — PAPERS.md), reused by
  :func:`~heat_tpu.nn.scaled_dot_product_attention` for the multi-device
  GSPMD path that previously fell back to dense attention;
* ``ragged_reduce`` (:mod:`.ragged`) — reductions over canonically padded
  split-axis operands with the pad masked to the op's neutral element *inside
  the tile*, giving the PR 4 padded-operand sink fallbacks (where-masked
  reductions, flat arg-reductions, moments, norms) a fused in-register path
  instead of an eager flushing one (wired as an alternative sink executor in
  ``core/fusion.py``);
* ``kmeans_step`` (:mod:`.kmeans`) — distance tile → label argmin → one-hot
  centroid accumulation as one pass over the samples (f32 accumulation per
  the ``spatial/distance.py`` contract), behind
  :meth:`heat_tpu.cluster.KMeans.step` — BENCH_r05 shows the two-GEMM step is
  VMEM-resident and therefore bandwidth-bound; the fused kernel reads the
  sample tile once for both the assignment and the update.

**Availability.** Every kernel runs *compiled* only on a real TPU backend;
``HEAT_TPU_PALLAS_INTERPRET=1`` additionally admits any backend through the
pallas interpreter (``pallas_call(interpret=True)`` — the same kernel code
executed by the jaxpr interpreter), which is how the CPU-only tier-1 host
tests real kernel bodies. Per-kernel predicates on platform / shape / dtype
gate each dispatch; every refusal is counted in ``pallas.fallbacks``
{platform, shape, dtype, hatch} and every taken dispatch in
``pallas.dispatch`` {kernel}, both exported by
:func:`heat_tpu.monitoring.report.telemetry`. ``pallas.dispatch`` counts
*routing decisions* (a cached fused program re-executes without re-recording).

**Escape hatches.** ``HEAT_TPU_PALLAS=0`` disables the whole tier (counted
``hatch``), restoring the pre-PR XLA paths bit for bit;
``HEAT_TPU_PALLAS_<KERNEL>=0`` (e.g. ``HEAT_TPU_PALLAS_RAGGED_REDUCE=0``)
disables one kernel. Both are read per dispatch.

**Recovery.** Kernel call points consult the ``pallas.execute`` fault site
(:mod:`heat_tpu.robustness.faultinject`): direct call sites (attention,
kmeans) degrade to their XLA formulation in a ``try``/``except`` (counted
``pallas.fallbacks{execute}``); a pallas-bearing *fused flush* consults the
site once per ladder attempt exactly like ``collective.dispatch``, and the
ladder's recovery rungs run under :func:`recovery_mode`, in which every
pallas-backed sink callable re-emits its XLA reference formulation instead —
so a failing kernel degrades through the PR 6 ladder to the XLA path, and
only its own signature is poisoned.

**Numerics** (doc/pallas_notes.md): masking and arg-selection are bit-exact
vs the hatch by construction (the neutral fill and the first-index tie-break
replay the eager semantics); accumulations the tiling reorders (online
softmax rescaling, centroid sums, f32 masked sums) carry a documented bounded
divergence, pinned by the differential suite in ``tests/test_pallas.py``.
"""

from __future__ import annotations

import os
import threading

import jax

from ...monitoring.registry import STATE as _MON
from ...monitoring import instrument as _instr
from ...robustness import faultinject as _FI

__all__ = [
    "KERNELS",
    "enabled",
    "kernel_enabled",
    "interpret_forced",
    "use_interpret",
    "available",
    "dispatch",
    "execute_guard",
    "fallback",
    "in_recovery",
    "recovery_mode",
]

#: The registered kernels of the tier (also the ``pallas.dispatch`` labels).
KERNELS = ("flash_ring", "ragged_reduce", "kmeans_step")

#: dtypes each kernel accepts. ``ragged_reduce`` additionally restricts
#: *accumulating* ops to exact (integer/bool) or f32 operands at the plan
#: level — bf16 accumulation keeps the PR 4 low-float fallback discipline.
_KERNEL_DTYPES = {
    "flash_ring": ("float32", "bfloat16"),
    "ragged_reduce": ("float32", "bfloat16", "bool", "int8", "int16", "int32", "int64",
                      "uint8", "uint16", "uint32", "uint64"),
    "kmeans_step": ("float32", "bfloat16"),
}


def enabled() -> bool:
    """Whether the pallas kernel tier is globally enabled (default on).
    ``HEAT_TPU_PALLAS=0`` restores every pre-PR XLA path bit for bit (read
    per dispatch, same pattern as ``HEAT_TPU_FUSION``)."""
    val = os.environ.get("HEAT_TPU_PALLAS", "")
    return val.strip().lower() not in ("0", "false", "off")


def kernel_enabled(kernel: str) -> bool:
    """Per-kernel hatch: ``HEAT_TPU_PALLAS_<KERNEL>=0`` (kernel name
    upper-cased) disables one kernel while the rest of the tier stays on."""
    val = os.environ.get(f"HEAT_TPU_PALLAS_{kernel.upper()}", "")
    return val.strip().lower() not in ("0", "false", "off")


def interpret_forced() -> bool:
    """Whether ``HEAT_TPU_PALLAS_INTERPRET=1`` admits non-TPU backends via the
    pallas interpreter (the CPU-host test/bench mode; default off, so the
    production CPU path never pays interpreter overhead)."""
    return os.environ.get("HEAT_TPU_PALLAS_INTERPRET", "").strip().lower() in (
        "1", "true", "on",
    )


def use_interpret() -> bool:
    """Whether kernel call sites should pass ``interpret=True``: anywhere but
    a real TPU backend. (On TPU the Mosaic compiler takes the kernel.)"""
    return jax.default_backend() != "tpu"


def fallback(kind: str) -> None:
    """Count one refused/degraded pallas dispatch (kind: platform / shape /
    dtype / hatch / execute)."""
    if _MON.enabled:
        _instr.pallas_fallback(kind)


def available(kernel: str, dtype=None, shape_ok: bool = True) -> bool:
    """Whether ``kernel`` may take this dispatch. Checks, in order: the master
    and per-kernel hatches (counted ``hatch``), the platform (TPU, or any
    backend under ``HEAT_TPU_PALLAS_INTERPRET=1`` — counted ``platform``),
    the kernel's dtype set (counted ``dtype``), and the caller's precomputed
    shape predicate (counted ``shape``). Refusals restore the pre-PR XLA
    path; only a refusal of an *eligible* site is counted, so the counters
    read as "work the tier declined", not "ops that never applied"."""
    if kernel not in KERNELS:
        raise ValueError(f"unknown pallas kernel {kernel!r} (have {KERNELS})")
    if not (enabled() and kernel_enabled(kernel)):
        fallback("hatch")
        return False
    if jax.default_backend() != "tpu" and not interpret_forced():
        fallback("platform")
        return False
    if dtype is not None and str(dtype) not in _KERNEL_DTYPES[kernel]:
        fallback("dtype")
        return False
    if not shape_ok:
        fallback("shape")
        return False
    return True


def dispatch(kernel: str) -> None:
    """Count one taken routing decision into ``kernel``
    (``pallas.dispatch{kernel}``)."""
    if _MON.enabled:
        _instr.pallas_dispatch(kernel)


def execute_guard() -> None:
    """The ``pallas.execute`` fault site: consulted wherever a pallas kernel
    is about to be dispatched (direct call sites before running the kernel;
    pallas-bearing fused flushes once per ladder attempt, see
    ``fusion._flush_ladder``). Raises the planned exception under an
    installed :mod:`~heat_tpu.robustness.faultinject` plan."""
    _FI.check("pallas.execute")


# ------------------------------------------------------------------ recovery
#: Thread-local recovery depth: >0 while the fusion ladder replays a failed
#: flush (rung 2 donation-free rebuild / rung 3 per-op eager replay) or a
#: poisoned/breaker-routed signature skips straight to eager. Pallas-backed
#: sink callables consult it and re-emit their XLA reference formulation, so
#: recovery lands on the XLA path instead of re-entering the failed kernel.
_TLS = threading.local()


def in_recovery() -> bool:
    """Whether the current thread is inside a fusion-ladder recovery replay
    (pallas-backed callables must take their XLA reference path)."""
    return getattr(_TLS, "depth", 0) > 0


class recovery_mode:
    """Context manager marking ladder recovery on this thread (nestable)."""

    __slots__ = ()

    def __enter__(self):
        _TLS.depth = getattr(_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth = getattr(_TLS, "depth", 0) - 1
        return False
