"""
Flash-attention inner tile: the per-hop online-softmax update of
:func:`heat_tpu.nn.ring_attention` as ONE pallas kernel.

``_ring_attention_sharded`` rescales a running (max, denominator, numerator)
triple once per ``ppermute`` hop — exactly the flash-attention recurrence
(Dao et al. 2022, PAPERS.md) — but the plain-jnp body materializes the score
matrix, the probability matrix, and the rescaled accumulator as three
separate HBM-round-tripping passes per hop. This kernel walks the hop's K/V
block tile by tile with the triple resident in VMEM: per (batch·head, q-tile)
grid cell a ``fori_loop`` over K tiles computes the score tile on the MXU
(f32 accumulation), folds it into the running (m, l, acc) with the standard
rescaling identity, and writes the updated triple once at the end.

Layout: the caller presents ``q`` as ``(bh, sq, d)`` (batch and heads merged
— they are embarrassingly parallel grid dimensions), ``k``/``v`` as
``(bh, sk, d)``, the triple as ``(bh, sq)`` / ``(bh, sq)`` / ``(bh, sq, d)``
(all f32). Causality is decided from global position vectors ``q_pos`` /
``k_pos`` passed as i32 row vectors — they may be traced (the ring's K-block
index is ``(axis_index + t) % p``), so nothing about the mask is baked.

Numerics: the final running max is exact (max is associative); the
denominator and numerator accumulate per K tile instead of once per block,
so f32 results carry a bounded reordering divergence vs the jnp formulation
(pinned at tight tolerance in ``tests/test_pallas.py``); a single-K-tile
call replays the jnp algebra operation for operation.

:func:`attention_local` wraps one init→update→normalize round over a whole
(K, V) — the single-pass flash attention
:func:`~heat_tpu.nn.scaled_dot_product_attention` uses for the multi-device
GSPMD path that previously fell back to dense.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["tile_update", "attention_local", "attention_decode", "shape_ok"]

#: Q/K tile extents. Blocks are (1, TILE, d) per grid cell; sequences that
#: are not tile multiples use a single whole-sequence tile when small (the
#: interpret/test regime) — :func:`shape_ok` refuses the rest.
TILE_Q = 128
TILE_K = 128
MAX_HEAD_DIM = 256
MAX_SEQ_SINGLE_TILE = 256
#: The sq=1 decode carve-out (ISSUE 19): a single query row keeps the score
#: tile at (1, tk) whatever the key extent, so the K side only needs lane
#: alignment (%8) up to this VMEM-bounded capacity — bucketed KV-cache
#: capacities (320, 1536, mined edges) no longer silently fall back to jnp.
MAX_SEQ_DECODE = 4096


def _tile(n: int, pref: int) -> int:
    if n % pref == 0:
        return pref
    return n  # single tile (shape_ok bounds this to MAX_SEQ_SINGLE_TILE)


def _tile_prefs(interpret: bool):
    """Preferred (tile_q, tile_k): the static 128s, or the measured winner
    under ``HEAT_TPU_TUNING=1`` (ISSUE 18; one env read when off). The
    tuned preference rides the same :func:`_tile` rails — a preference that
    does not divide the sequence degrades to the single-tile path exactly
    like the static one."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return TILE_Q, TILE_K
    try:
        return _tuning.lookup(
            "pallas.flash.tile", context={"interpret": bool(interpret)}
        )
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return TILE_Q, TILE_K


def shape_ok(sq: int, sk: int, head_dim: int) -> bool:
    """Whether the kernel's tiling expresses these extents: head_dim within
    the VMEM budget, and each sequence either a 128-multiple or small enough
    for a single whole-sequence tile. The ``sq == 1`` decode case (ISSUE 19)
    relaxes the K side to any lane-aligned (%8) capacity up to
    :data:`MAX_SEQ_DECODE` — the (1, tk) score tile never grows with sk."""
    if head_dim > MAX_HEAD_DIM or head_dim < 1:
        return False
    if sq < 1 or sk < 1:
        return False
    if sq == 1:
        return sk % TILE_K == 0 or sk <= MAX_SEQ_SINGLE_TILE or (
            sk % 8 == 0 and sk <= MAX_SEQ_DECODE
        )
    for s in (sq, sk):
        if s % TILE_Q != 0 and s > MAX_SEQ_SINGLE_TILE:
            return False
    return True


def _decode_tile_pref(interpret: bool) -> int:
    """Preferred K-tile extent of the M=1 decode case: the static 128, or
    the measured winner under ``HEAT_TPU_TUNING=1`` (knob
    ``pallas.flash.decode_tile``, ISSUE 19 — the decode walk is all K side,
    so its tile trades VMEM residency differently than the square update's).
    Rides the same :func:`_tile` rails: a preference that does not divide
    the capacity degrades to the single-tile path."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return TILE_K
    try:
        return int(_tuning.lookup(
            "pallas.flash.decode_tile", context={"interpret": bool(interpret)}
        ))
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return TILE_K


def _train_tile_pref(interpret: bool):
    """Preferred (tile_q, tile_k) of the causal TRAINING update (sq > 1 with
    gradients flowing — the ISSUE 20 transformer block), or None to keep the
    generic preference. Training sequences are long and causal, so half the
    score tiles are masked out: the winning tile trades differently than the
    bidirectional square update's, hence its own knob
    (``pallas.flash.train_tile``, ISSUE 18 discipline — one env read when
    tuning is off, bit-identical rails either way)."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return None
    try:
        tq, tk = _tuning.lookup(
            "pallas.flash.train_tile", context={"interpret": bool(interpret)}
        )
        return int(tq), int(tk)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return None


@functools.lru_cache(maxsize=128)
def _update_call(bh, sq, sk, d, causal, scale, interpret, tq_pref=TILE_Q, tk_pref=TILE_K,
                 per_bh_qpos=False):
    tq = _tile(sq, tq_pref)
    tk = _tile(sk, tk_pref)
    nk = sk // tk
    scale = float(scale)

    def kernel(q_ref, k_ref, v_ref, qp_ref, kp_ref, m_ref, l_ref, o_ref,
               mo_ref, lo_ref, oo_ref):
        q = q_ref[0]  # (tq, d) f32
        m0 = m_ref[0].reshape(tq, 1)
        l0 = l_ref[0].reshape(tq, 1)
        acc0 = o_ref[0]  # (tq, d)
        qp = qp_ref[0].reshape(tq, 1)

        def body(j, carry):
            m, l, acc = carry
            kblk = k_ref[0, pl.ds(j * tk, tk), :]
            vblk = v_ref[0, pl.ds(j * tk, tk), :]
            s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
            if causal:
                kp = kp_ref[0, pl.ds(j * tk, tk)].reshape(1, tk)
                s = jnp.where(qp >= kp, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)  # 0 on the -inf -> finite transition
            p = jnp.exp(s - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.dot(
                p, vblk, preferred_element_type=jnp.float32
            )
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
        mo_ref[0] = m.reshape(tq)
        lo_ref[0] = l.reshape(tq)
        oo_ref[0] = acc

    grid = (bh, sq // tq)
    f32 = jnp.float32
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda b, i: (b, i, 0)),   # q
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),   # k (full block)
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),   # v
            # q_pos: shared (1, sq) row vector, or — the ragged decode case
            # (ISSUE 19) — a per-(batch·head) (bh, sq) matrix so every
            # request masks at its own cache length
            pl.BlockSpec((1, tq), (lambda b, i: (b, i)) if per_bh_qpos else (lambda b, i: (0, i))),
            pl.BlockSpec((1, sk), lambda b, i: (0, 0)),         # k_pos
            pl.BlockSpec((1, tq), lambda b, i: (b, i)),         # m
            pl.BlockSpec((1, tq), lambda b, i: (b, i)),         # l
            pl.BlockSpec((1, tq, d), lambda b, i: (b, i, 0)),   # o
        ],
        out_specs=(
            pl.BlockSpec((1, tq), lambda b, i: (b, i)),
            pl.BlockSpec((1, tq), lambda b, i: (b, i)),
            pl.BlockSpec((1, tq, d), lambda b, i: (b, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq), f32),
            jax.ShapeDtypeStruct((bh, sq), f32),
            jax.ShapeDtypeStruct((bh, sq, d), f32),
        ),
        interpret=interpret,
    )


def tile_update(q, k, v, m, l, o, *, scale, causal, q_pos, k_pos, interpret,
                train=False):
    """One online-softmax update of the running triple with a (K, V) block.

    ``q``: (bh, sq, d) f32; ``k``/``v``: (bh, sk, d); ``m``/``l``: (bh, sq)
    f32; ``o``: (bh, sq, d) f32; ``q_pos``/``k_pos``: i32 global sequence
    positions, traced values allowed. ``q_pos`` is shape (sq,) — one row
    vector shared across batch·head — or (bh, sq): per-(batch·head)
    positions, the ragged decode case (ISSUE 19) where every request masks
    at its own cache length. ``train=True`` marks the causal training-shape
    call (ISSUE 20): under ``HEAT_TPU_TUNING=1`` it consults the
    ``pallas.flash.train_tile`` knob instead of the generic tile preference.
    Returns the updated ``(m, l, o)``."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    qp = jnp.asarray(q_pos, jnp.int32)
    per_bh = qp.ndim == 2 and qp.shape[0] != 1
    tq_pref, tk_pref = _tile_prefs(bool(interpret))
    if train and sq > 1:
        pref = _train_tile_pref(bool(interpret))
        if pref is not None:
            tq_pref, tk_pref = pref
    if sq == 1:
        tk_pref = _decode_tile_pref(bool(interpret))
    call = _update_call(
        bh, sq, sk, d, bool(causal), float(scale), bool(interpret),
        tq_pref, tk_pref, per_bh,
    )
    qp = qp.reshape(bh, sq) if per_bh else qp.reshape(1, sq)
    kp = jnp.asarray(k_pos, jnp.int32).reshape(1, sk)
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    return call(q, k32, v32, qp, kp, m, l, o)


def attention_local(q, k, v, *, causal, scale, interpret, train=False):
    """Single-pass flash attention over whole (K, V) via one init → update →
    normalize round of the ring-step kernel. Operands are
    ``(batch, seq, heads, head_dim)`` like
    :func:`~heat_tpu.nn.scaled_dot_product_attention`; returns the attention
    output in the same layout and ``q``'s dtype. ``train=True`` routes the
    tile preference through the training-shape knob (ISSUE 20)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bh = b * h

    def merge(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], d)

    qm = merge(q).astype(jnp.float32)
    m0 = jnp.full((bh, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bh, sq), jnp.float32)
    o0 = jnp.zeros((bh, sq, d), jnp.float32)
    q_pos = jnp.arange(sq, dtype=jnp.int32)
    k_pos = jnp.arange(sk, dtype=jnp.int32)
    m, l, acc = tile_update(
        qm, merge(k), merge(v), m0, l0, o0,
        scale=scale, causal=causal, q_pos=q_pos, k_pos=k_pos, interpret=interpret,
        train=train,
    )
    out = acc / l[..., None]
    out = jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
    return out.astype(q.dtype)


def attention_decode(q, k, v, lengths, *, scale, interpret):
    """Flash attention's M=1 decode case (ISSUE 19): one new query row per
    request against a persistent KV cache, masked at each request's own
    (traced) valid length.

    ``q``: (batch, 1, heads, head_dim); ``k``/``v``: (batch, capacity,
    heads, head_dim) — the bucketed cache; ``lengths``: (batch,) i32 valid
    key counts, ``1 <= lengths[b] <= capacity`` (a zero-length row would
    leave the running max at -inf and poison the rescale). Runs ONE
    init → update → normalize round with a per-(batch·head) ``q_pos`` of
    ``lengths - 1`` against ``k_pos = arange(capacity)`` under the causal
    mask — exactly "attend to the first ``lengths[b]`` keys". Returns
    (batch, 1, heads, head_dim) in ``q``'s dtype."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bh = b * h

    def merge(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, x.shape[1], d)

    qm = merge(q).astype(jnp.float32)
    m0 = jnp.full((bh, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bh, sq), jnp.float32)
    o0 = jnp.zeros((bh, sq, d), jnp.float32)
    q_pos = jnp.repeat(
        jnp.asarray(lengths, jnp.int32).reshape(b, 1) - 1, h, axis=1
    ).reshape(bh, sq)
    k_pos = jnp.arange(sk, dtype=jnp.int32)
    m, l, acc = tile_update(
        qm, merge(k), merge(v), m0, l0, o0,
        scale=scale, causal=True, q_pos=q_pos, k_pos=k_pos, interpret=interpret,
    )
    out = acc / l[..., None]
    out = jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
    return out.astype(q.dtype)
