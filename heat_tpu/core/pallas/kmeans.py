"""
Fused k-means assign+update: distance tile → label argmin → one-hot centroid
accumulation in ONE pass over the samples.

BENCH_r05 pins the two-GEMM Lloyd step as VMEM-resident and therefore
bandwidth-bound: the XLA formulation reads the sample block once for the
distance GEMM and again for the ``onehot.T @ x`` update GEMM, with the
(n, k) distance matrix and the (n, k) one-hot mask materialized in between.
This kernel streams the samples in 128-row tiles and, per tile, computes the
quadratic-expansion distance block on the MXU (f32 accumulation, the
``spatial/distance.py`` contract), takes the label argmin (first-index
tie-break, like ``jnp.argmin``), and folds the one-hot-masked centroid sums
and counts into running (k, f)/(k, 1) accumulators carried in the output
blocks — the sample tile is read exactly once for both phases and the
distance/one-hot intermediates never leave VMEM.

The sample tile mask (``row < n_logical``) covers both the grid's tile pad
and the canonical ragged split pad in one comparison, so the kernel accepts
the padded physical layout directly. The mean/shift epilogue stays outside
(plain jnp on (k, f) accumulators — bandwidth-irrelevant).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_step", "shape_ok"]

#: Sample-tile height; centers stay whole in VMEM.
TILE_N = 128
MAX_FEATURES = 2048
MAX_CLUSTERS = 1024


def shape_ok(n: int, f: int, k: int) -> bool:
    """Whether the (samples, features, clusters) extents fit the kernel's
    VMEM plan: whole (k, f) centers + accumulator blocks beside one sample
    tile."""
    return 1 <= f <= MAX_FEATURES and 1 <= k <= MAX_CLUSTERS and n >= 1


@functools.lru_cache(maxsize=64)
def _step_call(n_pad, f, k, dt_str, n_log, tile_n, interpret):
    tiles = n_pad // tile_n

    def kernel(x_ref, c_ref, lab_ref, sums_ref, cnt_ref):
        i = pl.program_id(0)
        xb = x_ref[...].astype(jnp.float32)  # (tile_n, f)
        c = c_ref[...].astype(jnp.float32)   # (k, f)
        x2 = jnp.sum(xb * xb, axis=1, keepdims=True)        # (tile_n, 1)
        c2 = jnp.sum(c * c, axis=1, keepdims=True).T         # (1, k)
        xc = jnp.dot(xb, c.T, preferred_element_type=jnp.float32)
        d2 = jnp.maximum(x2 - 2.0 * xc + c2, 0.0)            # (tile_n, k)
        lab = jnp.argmin(d2, axis=1).astype(jnp.int32)       # (tile_n,)
        rid = jax.lax.broadcasted_iota(jnp.int32, (tile_n, 1), 0) + i * tile_n
        valid = rid < n_log                                  # (tile_n, 1)
        cid = jax.lax.broadcasted_iota(jnp.int32, (tile_n, k), 1)
        onehot = ((lab[:, None] == cid) & valid).astype(jnp.float32)
        lab_ref[...] = jnp.where(valid, lab[:, None], 0)

        @pl.when(i == 0)
        def _():
            sums_ref[...] = jnp.zeros_like(sums_ref)
            cnt_ref[...] = jnp.zeros_like(cnt_ref)

        sums_ref[...] += jnp.dot(
            onehot.T, xb, preferred_element_type=jnp.float32
        )
        cnt_ref[...] += jnp.sum(onehot, axis=0, keepdims=True).T  # (k, 1)

    return pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((tile_n, f), lambda i: (i, 0)),
            pl.BlockSpec((k, f), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, f), lambda i: (0, 0)),
            pl.BlockSpec((k, 1), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((k, f), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
        ),
        interpret=interpret,
    )


def _tile_n_pref(interpret: bool) -> int:
    """The preferred sample-tile height: the static 128, or the measured
    winner under ``HEAT_TPU_TUNING=1`` (ISSUE 18; one env read when off)."""
    from ... import tuning as _tuning

    if not _tuning.enabled():
        return TILE_N
    try:
        return _tuning.lookup(
            "pallas.kmeans.tile_n", context={"interpret": bool(interpret)}
        )
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return TILE_N


def fused_step(x_phys, centers, n_log: int, interpret: bool):
    """One fused assignment+update pass. ``x_phys`` is the (possibly
    canonically padded) physical sample block ``(n_phys, f)``; ``centers``
    ``(k, f)``; ``n_log`` the logical sample count. Returns
    ``(labels (n_phys,) i32 — pad rows 0, sums (k, f) f32, counts (k,) f32)``.
    """
    n_phys, f = x_phys.shape
    k = centers.shape[0]
    pref = _tile_n_pref(bool(interpret))
    if n_phys > pref:
        tile_n = pref
    else:
        tile_n = max(8, -(-n_phys // 8) * 8) if n_phys > 1 else 1
    n_pad = -(-n_phys // tile_n) * tile_n
    xp = jnp.pad(x_phys, ((0, n_pad - n_phys), (0, 0))) if n_pad != n_phys else x_phys
    call = _step_call(
        n_pad, f, k, str(x_phys.dtype), int(n_log), tile_n, bool(interpret)
    )
    labels, sums, counts = call(xp, centers)
    return labels[:n_phys, 0], sums, counts[:, 0]
