"""
Array manipulation operations.

Parity with the reference's ``heat/core/manipulations.py`` (``__all__`` at
manipulations.py:25-60). The comm-heavy reference paths — ``concatenate``'s chunk-map
matching (:188), ``reshape``'s Alltoallv re-chunking (:1878), ``sort``'s parallel
sample-sort (:2263), ``unique``'s Allgatherv dedup (:3051), ``roll``'s neighbor sends
(:1985) — are global jnp operations here whose collectives XLA emits from the sharding;
data-dependent-shape ops (``unique``, ``nonzero``) run eagerly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import factories
from . import fusion as _fusion
from . import sanitation
from . import stride_tricks
from . import types
from ._compat import shard_map as _shard_map
from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = [
    "argsort",
    "balance",
    "broadcast_to",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "isin",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "searchsorted",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "take",
    "take_along_axis",
    "tile",
    "topk",
    "unique",
    "vsplit",
    "vstack",
]


def __wrap(proto: DNDarray, data: jax.Array, split) -> DNDarray:
    # data is the logical result; DNDarray.__init__ establishes the canonical
    # (padded, sharded) physical placement for ragged split axes
    return DNDarray(
        data, tuple(data.shape), types.canonical_heat_type(data.dtype), split, proto.device, proto.comm, True
    )


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Out-of-place balance (reference manipulations.py balance). Balanced by
    construction here; returns (a copy of) the array."""
    sanitation.sanitize_in(array)
    if copy:
        from .memory import copy as _copy

        return _copy(array)
    return array


def broadcast_to(x: DNDarray, shape) -> DNDarray:
    """Broadcast an array to a new shape (view semantics; numpy parity). A
    pending fused chain on ``x`` records a view node instead of flushing
    (``core/fusion.py``)."""
    sanitation.sanitize_in(x)
    shape = stride_tricks.sanitize_shape(shape)
    new_split = None if x.split is None else len(shape) - (x.ndim - x.split)
    if new_split is not None and new_split < 0:
        new_split = None
    if _fusion.view_ready(x):
        res = _fusion.defer_view(x, "broadcast_to", (), tuple(shape), new_split)
        if res is not None:
            return res
    data = jnp.broadcast_to(x.larray, shape)
    return __wrap(x, data, new_split)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns of a 2-D array (reference manipulations.py
    column_stack)."""
    proto = arrays[0]
    data = jnp.column_stack([a.larray if isinstance(a, DNDarray) else jnp.asarray(a) for a in arrays])
    split = proto.split if proto.split == 0 else None
    return __wrap(proto, data, split)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """
    Join arrays along an existing axis (reference manipulations.py:188-540, which
    redistributes operands to matching chunk maps — a plain sharded concat here).
    """
    if not isinstance(arrays, (tuple, list)) or len(arrays) == 0:
        raise TypeError("arrays must be a non-empty sequence of DNDarrays")
    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    proto = arrays[0]
    axis = stride_tricks.sanitize_axis(proto.shape, axis)
    for a in arrays[1:]:
        if a.ndim != proto.ndim:
            raise ValueError("all input arrays must have the same number of dimensions")
        for d in range(proto.ndim):
            if d != axis and a.shape[d] != proto.shape[d]:
                raise ValueError(
                    "array shapes must match except along the concatenation axis: "
                    f"{tuple(proto.shape)} vs {tuple(a.shape)} on axis {d}"
                )
    out_dtype = arrays[0].dtype
    for a in arrays[1:]:
        out_dtype = types.promote_types(out_dtype, a.dtype)
    data = jnp.concatenate([a.larray.astype(out_dtype.jnp_type()) for a in arrays], axis=axis)
    split = proto.split
    return __wrap(proto, data, split)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract a diagonal (2-D input) or construct a diagonal array (1-D input)
    (reference manipulations.py diag)."""
    sanitation.sanitize_in(a)
    if a.ndim > 2:
        raise ValueError("input must be 1- or 2-dimensional")
    if a.ndim == 2:
        return diagonal(a, offset=offset)
    data = jnp.diag(a.larray, k=offset)
    return __wrap(a, data, a.split)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Diagonal of the array along (dim1, dim2) (reference manipulations.py
    diagonal)."""
    sanitation.sanitize_in(a)
    dim1 = stride_tricks.sanitize_axis(a.shape, dim1)
    dim2 = stride_tricks.sanitize_axis(a.shape, dim2)
    if dim1 == dim2:
        raise ValueError("dim1 and dim2 must be different")
    data = jnp.diagonal(a.larray, offset=offset, axis1=dim1, axis2=dim2)
    # the two diagonal dims are removed and the diagonal appended last; a batch
    # split shifts left past any removed lower axes, a split on dim1/dim2 is lost
    split = a.split
    if split is not None:
        if split in (dim1, dim2):
            split = None
        else:
            split -= sum(1 for d in (dim1, dim2) if d < split)
    return __wrap(a, data, split)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along the 3rd axis (reference manipulations.py dsplit)."""
    return split(x, indices_or_sections, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a new size-1 axis (reference manipulations.py expand_dims). A
    pending fused chain on ``a`` records a view node instead of flushing."""
    sanitation.sanitize_in(a)
    axis = stride_tricks.sanitize_axis(tuple(a.shape) + (1,), axis)
    split = a.split
    if split is not None and axis <= split:
        split += 1
    if _fusion.view_ready(a):
        out_gshape = tuple(a.shape[:axis]) + (1,) + tuple(a.shape[axis:])
        res = _fusion.defer_view(a, "expand_dims", (int(axis),), out_gshape, split)
        if res is not None:
            return res
    data = jnp.expand_dims(a.larray, axis)
    return __wrap(a, data, split)


def flatten(a: DNDarray) -> DNDarray:
    """Flatten to one dimension (reference manipulations.py flatten). A
    pending fused chain records a (reshape) view node instead of flushing."""
    sanitation.sanitize_in(a)
    split = None if a.split is None else 0
    if _fusion.view_ready(a):
        res = _fusion.defer_view(a, "reshape", (), (a.size,), split)
        if res is not None:
            return res
    data = a.larray.reshape(-1)
    return __wrap(a, data, split)


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along the given axes (reference manipulations.py
    flip). A pending fused chain records a view node instead of flushing
    (flips touching a padded split axis keep the eager fallback, counted)."""
    sanitation.sanitize_in(a)
    axis = stride_tricks.sanitize_axis(a.shape, axis)
    if _fusion.view_ready(a):
        if axis is None:
            axes_t = tuple(range(a.ndim))
        elif isinstance(axis, (int, np.integer)):
            axes_t = (int(axis),)
        else:
            axes_t = tuple(int(v) for v in axis)
        res = _fusion.defer_view(a, "flip", (axes_t,), tuple(a.shape), a.split)
        if res is not None:
            return res
    data = jnp.flip(a.larray, axis=axis)
    return __wrap(a, data, a.split)


def fliplr(a: DNDarray) -> DNDarray:
    """Flip left/right (axis 1) (reference manipulations.py fliplr)."""
    if a.ndim < 2:
        raise IndexError("input must be at least 2-dimensional")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """Flip up/down (axis 0) (reference manipulations.py flipud)."""
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split horizontally (axis 1, or 0 for 1-D) (reference manipulations.py hsplit)."""
    return split(x, indices_or_sections, axis=1 if x.ndim > 1 else 0)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack horizontally (reference manipulations.py hstack)."""
    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    axis = 0 if arrays[0].ndim == 1 else 1
    return concatenate(arrays, axis=axis)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference manipulations.py moveaxis)."""
    sanitation.sanitize_in(x)
    data = jnp.moveaxis(x.larray, source, destination)
    split = x.split
    if split is not None:
        order = list(range(x.ndim))
        src = [source] if isinstance(source, int) else list(source)
        dst = [destination] if isinstance(destination, int) else list(destination)
        src = [s % x.ndim for s in src]
        dst = [d % x.ndim for d in dst]
        rest = [a for a in order if a not in src]
        new_order = [None] * x.ndim
        for s, d in zip(src, dst):
            new_order[d] = s
        it = iter(rest)
        for i in range(x.ndim):
            if new_order[i] is None:
                new_order[i] = next(it)
        split = new_order.index(split)
    return __wrap(x, data, split)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """
    Pad an array (reference manipulations.py:1128-1360, which pads only the edge ranks
    on the split axis — here a global jnp.pad; the sharding handles placement).
    """
    sanitation.sanitize_in(array)
    kw = {"constant_values": constant_values} if mode == "constant" else {}
    # normalize heat-style pad_width (list of tuples, possibly partial) to numpy form
    data = jnp.pad(array.larray, pad_width, mode=mode, **kw)
    return __wrap(array, data, array.split)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten (view when possible) (reference manipulations.py ravel)."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference manipulations.py redistribute)."""
    from .memory import copy as _copy

    out = _copy(arr)
    out.redistribute_(lshape_map=lshape_map, target_map=target_map)
    return out


def repeat(a, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements of an array (reference manipulations.py repeat)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if isinstance(repeats, DNDarray):
        repeats = repeats.larray
    elif isinstance(repeats, (list, tuple, np.ndarray)):
        repeats = jnp.asarray(repeats)
    data = jnp.repeat(a.larray, repeats, axis=axis)
    split = (None if a.split is None else 0) if axis is None else a.split
    return __wrap(a, data, split)


def reshape(a: DNDarray, *shape, new_split: Optional[int] = None, **kwargs) -> DNDarray:
    """
    Reshape without changing data (reference manipulations.py:1817-1984; the
    Alltoallv re-chunk there is XLA's resharding here). ``new_split`` sets the split
    axis of the result (default: preserves a split at axis position 0 when split).
    """
    sanitation.sanitize_in(a)
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = tuple(int(s) for s in shape)
    if new_split is None:
        new_split = kwargs.get("new_split", None)
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError("can only specify one unknown dimension")
    # static resolution of the one free dimension: a pending fused chain can
    # then record a view node without touching data (core/fusion.py); shapes
    # the static pass cannot resolve keep the eager path, whose jnp reshape
    # raises the canonical error
    resolved = shape
    if neg:
        known = int(np.prod([s for s in shape if s != -1], dtype=np.int64))
        if known > 0 and a.size % known == 0:
            resolved = tuple(a.size // known if s == -1 else s for s in shape)
        else:
            resolved = None
    if resolved is not None and _fusion.view_ready(a):
        ns = new_split
        if ns is None:
            ns = None if a.split is None else (
                a.split
                if a.split < len(resolved) and resolved[a.split] == a.shape[a.split]
                else 0
            )
        ns = stride_tricks.sanitize_axis(resolved, ns)
        res = _fusion.defer_view(a, "reshape", (), resolved, ns)
        if res is not None:
            return res
    data = a.larray.reshape(shape)
    if new_split is None:
        new_split = None if a.split is None else (a.split if a.split < data.ndim and
                                                  data.shape[a.split] == a.shape[a.split] else 0)
    new_split = stride_tricks.sanitize_axis(tuple(data.shape), new_split)
    return __wrap(a, data, new_split)


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place split-axis change (reference manipulations.py resplit; one
    resharding placement here)."""
    from .memory import copy as _copy

    out = _copy(arr)
    out.resplit_(axis)
    return out


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Roll elements along the given axes (reference manipulations.py:1985-2110 with
    neighbor sends on the split axis; global jnp.roll here)."""
    sanitation.sanitize_in(x)
    data = jnp.roll(x.larray, shift, axis=axis)
    return __wrap(x, data, x.split)


def rot90(m: DNDarray, k: int = 1, axes=(0, 1)) -> DNDarray:
    """Rotate by 90 degrees in the plane of the given axes (reference
    manipulations.py rot90)."""
    sanitation.sanitize_in(m)
    axes = tuple(stride_tricks.sanitize_axis(m.shape, a) for a in axes)
    if len(set(axes)) != 2:
        raise ValueError("axes must be different")
    data = jnp.rot90(m.larray, k=k, axes=axes)
    split = m.split
    if split in axes and k % 2 == 1:
        split = axes[0] if split == axes[1] else axes[1]
    return __wrap(m, data, split)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack arrays row-wise (reference manipulations.py row_stack)."""
    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    arrays2 = [a if a.ndim > 1 else expand_dims(a, 0) for a in arrays]
    return concatenate(arrays2, axis=0)


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape of the array (reference manipulations.py shape)."""
    sanitation.sanitize_in(a)
    return a.shape


def argsort(a: DNDarray, axis: int = -1, descending: bool = False):
    """Indices that would sort the array (numpy-API completion beyond the
    reference snapshot): the index half of :func:`sort`, riding the exact-rank
    distributed machinery along split axes."""
    return sort(a, axis=axis, descending=descending)[1]


def searchsorted(a: DNDarray, v, side: str = "left", sorter=None) -> DNDarray:
    """Insertion indices keeping ``a`` sorted (numpy-API completion). ``a`` is
    gathered (it is the small sorted haystack in typical use); ``v`` stays local."""
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    sanitation.sanitize_in(a)
    vv = v.larray if isinstance(v, DNDarray) else jnp.asarray(v)
    srt = sorter.larray if isinstance(sorter, DNDarray) else sorter
    res = jnp.searchsorted(a.larray, vv, side=side, sorter=srt)
    idx_t = types.default_index_type()
    vsplit = v.split if isinstance(v, DNDarray) else None
    return DNDarray(
        res.astype(idx_t.jnp_type()), tuple(res.shape), idx_t, vsplit, a.device, a.comm, True
    )


def take(a: DNDarray, indices, axis=None) -> DNDarray:
    """Take elements along an axis (numpy-API completion): routed through the
    distribution-preserving advanced-indexing machinery. Multi-dimensional
    index arrays gather flat and reshape back, so the result keeps numpy's
    indices-shaped output (``a.shape[:axis] + indices.shape + a.shape[axis+1:]``)."""
    sanitation.sanitize_in(a)
    idx = indices.larray if isinstance(indices, DNDarray) else indices
    idx = np.asarray(idx) if not isinstance(idx, jnp.ndarray) else idx
    idx_shape = tuple(np.shape(idx))
    if axis is None:
        flat = reshape(a, (-1,) if a.ndim != 1 else a.shape)
        if np.ndim(idx) == 0:
            return flat[int(idx)]
        res = flat[idx.reshape(-1)]
        return reshape(res, idx_shape) if len(idx_shape) != 1 else res
    axis = stride_tricks.sanitize_axis(a.shape, axis)
    key = tuple([slice(None)] * axis + [idx.reshape(-1) if np.ndim(idx) > 1 else idx])
    res = a[key]
    if np.ndim(idx) > 1:
        res = reshape(res, a.shape[:axis] + idx_shape + a.shape[axis + 1 :])
    return res


def take_along_axis(a: DNDarray, indices, axis: int) -> DNDarray:
    """Take values along an axis using an index array of matching rank
    (numpy-API completion; local formulation)."""
    sanitation.sanitize_in(a)
    idx = indices.larray if isinstance(indices, DNDarray) else jnp.asarray(indices)
    res = jnp.take_along_axis(a.larray, idx, axis=axis)
    split_meta = a.split if (a.split is None or int(a.split) % a.ndim != int(axis) % a.ndim) else None
    return __wrap(a, res, split_meta)


def isin(element: DNDarray, test_elements, invert: bool = False) -> DNDarray:
    """Whether each element is contained in ``test_elements`` (numpy-API
    completion; elementwise against the replicated test set)."""
    sanitation.sanitize_in(element)
    t = test_elements.larray if isinstance(test_elements, DNDarray) else jnp.asarray(test_elements)
    res = jnp.isin(element.larray, t, invert=invert)
    from . import types as _t

    return DNDarray(
        res, tuple(res.shape), _t.canonical_heat_type(res.dtype), element.split,
        element.device, element.comm, True,
    )


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """
    Sort along an axis; returns ``(sorted_values, original_indices)``. Sorting
    along the split axis (any ndim, 4- and 8-byte dtypes) runs the exact-rank
    distributed sort (`_sort.py` — the reference's parallel sample-sort,
    manipulations.py:2263-3050, re-derived for static shapes: ppermute rank
    ring + reduce-scatter exchange, no gather); other cases sort along a local
    axis or fall back to the global formulation.
    """
    from . import _sort as _dsort

    sanitation.sanitize_in(a)
    axis = stride_tricks.sanitize_axis(a.shape, axis)
    if axis is None:
        axis = a.ndim - 1
    idx_t = types.default_index_type()
    if _dsort.can_distribute_sort(a, axis):
        vals_p, idx_p = _dsort.distributed_sort(a, axis, descending=descending)
        v = DNDarray(vals_p, a.shape, a.dtype, a.split, a.device, a.comm, True)
        i = DNDarray(
            idx_p.astype(idx_t.jnp_type()), a.shape, idx_t, a.split, a.device, a.comm, True
        )
        if out is not None:
            if not isinstance(out, tuple) or len(out) != 2:
                raise TypeError("out must be a tuple of two DNDarrays")
            # logical values: out may carry a different split (or none) — its
            # larray setter re-establishes out's own placement
            out[0].larray = v.larray.astype(out[0].dtype.jnp_type())
            out[1].larray = i.larray.astype(out[1].dtype.jnp_type())
            return out
        return v, i
    idx = jnp.argsort(a.larray, axis=axis, descending=descending, stable=True)
    vals = jnp.take_along_axis(a.larray, idx, axis=axis)
    v = __wrap(a, vals, a.split)
    i = DNDarray(
        idx.astype(idx_t.jnp_type()), tuple(idx.shape), idx_t, a.split, a.device, a.comm, True
    )
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a tuple of two DNDarrays")
        out[0].larray = vals.astype(out[0].dtype.jnp_type())
        out[1].larray = idx.astype(out[1].dtype.jnp_type())
        return out
    return v, i


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """
    Split into multiple sub-arrays along an axis (reference manipulations.py split).
    """
    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.numpy().tolist()
    if isinstance(indices_or_sections, (int, np.integer)):
        if x.shape[axis] % int(indices_or_sections) != 0:
            raise ValueError("array split does not result in an equal division")
    parts = jnp.split(x.larray, indices_or_sections, axis=axis)
    split_meta = x.split if x.split != axis else None
    return [__wrap(x, p, split_meta) for p in parts]


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 axes (reference manipulations.py squeeze). A pending
    fused chain records a view node instead of flushing (squeezes of a padded
    split axis keep the eager fallback, counted)."""
    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    removed = (
        [i for i, s in enumerate(x.shape) if s == 1]
        if axis is None
        else ([axis] if isinstance(axis, int) else list(axis))
    )
    split = x.split
    if split is not None:
        if split in removed:
            split = None
        else:
            split -= sum(1 for r in removed if r < split)
    if _fusion.view_ready(x):
        out_gshape = tuple(s for i, s in enumerate(x.shape) if i not in removed)
        res = _fusion.defer_view(
            x, "squeeze", (tuple(int(r) for r in removed),), out_gshape, split
        )
        if res is not None:
            return res
    data = jnp.squeeze(x.larray, axis=axis)
    return __wrap(x, data, split)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join arrays along a new axis (reference manipulations.py stack)."""
    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    proto = arrays[0]
    for a in arrays[1:]:
        if a.shape != proto.shape:
            raise ValueError("all input arrays must have the same shape")
    data = jnp.stack([a.larray for a in arrays], axis=axis)
    split = proto.split
    if split is not None and axis <= split:
        split += 1
    result = __wrap(proto, data, split)
    if out is not None:
        out.larray = data.astype(out.dtype.jnp_type())
        return out
    return result


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference manipulations.py swapaxes)."""
    sanitation.sanitize_in(x)
    axis1 = stride_tricks.sanitize_axis(x.shape, axis1)
    axis2 = stride_tricks.sanitize_axis(x.shape, axis2)
    data = jnp.swapaxes(x.larray, axis1, axis2)
    split = x.split
    if split == axis1:
        split = axis2
    elif split == axis2:
        split = axis1
    return __wrap(x, data, split)


def tile(x: DNDarray, reps) -> DNDarray:
    """Construct an array by repeating ``x`` the number of times given by reps
    (reference manipulations.py tile)."""
    sanitation.sanitize_in(x)
    if isinstance(reps, DNDarray):
        reps = reps.numpy().tolist()
    data = jnp.tile(x.larray, reps)
    split = x.split if x.split is not None and data.ndim == x.ndim else None
    return __wrap(x, data, split)


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """
    The ``k`` largest (or smallest) elements along a dimension; returns
    ``(values, indices)``. Along the split axis (k ≤ chunk) this runs the
    reference's distributed formulation — local top-k + allgather of the p·k
    candidates + re-select (reference manipulations.py topk) — as one shard_map
    program; otherwise a global lax.top_k.
    """
    from . import _sort as _dsort

    sanitation.sanitize_in(a)
    dim = stride_tricks.sanitize_axis(a.shape, dim)
    if _dsort.can_distribute_topk(a, dim, k):
        vals_p, idx_p = _dsort.distributed_topk(a, dim, k, largest=largest)
        gshape = tuple(k if d == dim else s for d, s in enumerate(a.shape))
        v = DNDarray(vals_p, gshape, a.dtype, None, a.device, a.comm, True)
        idx_t = types.default_index_type()
        i = DNDarray(idx_p.astype(idx_t.jnp_type()), gshape, idx_t, None, a.device, a.comm, True)
        if out is not None:
            out[0].larray = v.larray.astype(out[0].dtype.jnp_type())
            out[1].larray = i.larray.astype(out[1].dtype.jnp_type())
            return out
        return v, i
    moved = jnp.moveaxis(a.larray, dim, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    vals = jnp.moveaxis(vals, -1, dim)
    idx = jnp.moveaxis(idx, -1, dim)
    split = a.split if a.split != dim else None
    v = __wrap(a, vals, split)
    idx_t = types.default_index_type()
    i = DNDarray(idx.astype(idx_t.jnp_type()), tuple(idx.shape), idx_t, split, a.device, a.comm, True)
    if out is not None:
        out[0].larray = vals.astype(out[0].dtype.jnp_type())
        out[1].larray = idx.astype(out[1].dtype.jnp_type())
        return out
    return v, i


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """
    Unique elements of the array (reference manipulations.py:3051+: local unique +
    Allgatherv + global dedup — the same structure here: a shard_map local-unique
    compresses each chunk BEFORE anything is gathered, so only the per-shard
    unique values travel; the final cross-shard dedup runs on that reduced set.
    ``return_inverse``/``axis`` fall back to the global formulation (the inverse
    is a full-size map anyway).
    """
    from . import _sort as _dsort

    sanitation.sanitize_in(a)
    dt = np.dtype(a.dtype.jnp_type())
    if (
        not return_inverse
        and axis is None
        and a.ndim == 1
        and _dsort.can_distribute_sort(a, 0)
        and not (dt.kind == "f" and bool(jnp.isnan(a.larray).any()))
        # NaN != NaN breaks the local compression (duplicate-mask sentinels sort
        # BELOW NaN); NaN-bearing arrays use the global path, whose NaN handling
        # matches the replicated case
    ):
        comm = a.comm
        p = comm.size
        c = a.pshape[0] // p
        if dt.kind == "f":
            sentinel = np.inf
        elif dt.kind == "b":
            sentinel = True
        else:
            sentinel = np.iinfo(dt).max
        phys = a.filled(sentinel) if a.is_padded else a.parray

        from jax.sharding import PartitionSpec as _P

        def local(v):
            v = jnp.sort(v.reshape(c))
            fresh = jnp.concatenate([jnp.ones((1,), bool), v[1:] != v[:-1]])
            count = fresh.sum()
            # compress: uniques first, sentinel tail (stable via sort on masked)
            masked = jnp.where(fresh, v, jnp.asarray(sentinel, dtype=v.dtype))
            return jnp.sort(masked), count.astype(jnp.int32).reshape(1)

        fn = jax.jit(
            _shard_map(
                local, mesh=comm.mesh, in_specs=_P(comm.axis_name),
                out_specs=(_P(comm.axis_name), _P(comm.axis_name)), check_vma=False,
            )
        )
        packed, counts = fn(phys)
        if packed.is_fully_addressable:
            # pure D2H: copy each shard's compressed prefix off-device — only
            # the per-shard unique values ever leave a device
            by_rank = {}
            for shard in packed.addressable_shards:
                r = (shard.index[0].start or 0) // c
                by_rank[r] = np.asarray(shard.data)
            cnt = {}
            for shard in counts.addressable_shards:
                r = shard.index[0].start or 0
                for j, v_ in enumerate(np.asarray(shard.data)):
                    cnt[r + j] = int(v_)
            ranks = list(by_rank)
            ranks.sort()  # `sorted` builtin is shadowed by the keyword arg
            parts = [by_rank[r][: cnt[r]] for r in ranks]
        else:  # multi-controller: gather counts (tiny) first, then only the
            # compressed prefixes up to the largest per-shard unique count —
            # the collective moves O(p * max_uniques), not O(n)
            counts_np = np.asarray(jax.device_put(counts, comm.sharding(1, None)))
            k = max(int(counts_np.max()), 1)
            trimmed = packed.reshape(p, c)[:, :k]  # stays sharded on axis 0
            packed_np = np.asarray(jax.device_put(trimmed, comm.sharding(2, None)))
            parts = [packed_np[r, : int(counts_np[r])] for r in range(p)]
        vals = jnp.unique(jnp.asarray(np.concatenate(parts)))
        if a.is_padded:
            # pad sentinels can masquerade as a genuine extreme value: drop the
            # trailing sentinel unless the logical data really contains it
            has_sent = bool(jnp.any(a.larray == sentinel))
            if not has_sent and vals.size and bool(vals[-1] == sentinel):
                vals = vals[:-1]
        return DNDarray(vals, tuple(vals.shape), a.dtype, None, a.device, a.comm, True)
    res = jnp.unique(a.larray, return_inverse=return_inverse, axis=axis)
    if return_inverse:
        vals, inv = res
        v = DNDarray(vals, tuple(vals.shape), a.dtype, None, a.device, a.comm, True)
        idx_t = types.default_index_type()
        i = DNDarray(inv.astype(idx_t.jnp_type()), tuple(inv.shape), idx_t, None, a.device, a.comm, True)
        return v, i
    vals = res
    return DNDarray(vals, tuple(vals.shape), a.dtype, None, a.device, a.comm, True)


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split vertically (axis 0) (reference manipulations.py vsplit)."""
    return split(x, indices_or_sections, axis=0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack vertically (reference manipulations.py vstack)."""
    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    arrays = [a if a.ndim > 1 else expand_dims(a, 0) for a in arrays]
    return concatenate(arrays, axis=0)


DNDarray.expand_dims = expand_dims
DNDarray.flatten = flatten
DNDarray.ravel = ravel
DNDarray.reshape = reshape
DNDarray.resplit = resplit
DNDarray.squeeze = squeeze
DNDarray.unique = unique
