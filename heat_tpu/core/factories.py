"""
Array creation functions.

Parity with the reference's ``heat/core/factories.py`` (``arange`` :40, ``array``
:150, ``asarray`` :434, ``empty`` :488, ``eye`` :586, the generic ``__factory``
:665-718, ``full`` :789, ``linspace`` :896, ``logspace`` :982, ``meshgrid`` :1045,
``ones`` :1128, ``zeros`` :1225 and the ``*_like`` variants).

**Sharded at birth.** The reference allocates only the rank-local slab per process
(``comm.chunk``, factories.py:665-718); the equivalent here is that no factory ever
materialises the global array on one device: on-device factories (zeros/ones/full/
arange/linspace/eye/…) run as one jitted program with ``out_shardings`` set, so each
device generates only its shard; host data (``array(numpy_obj, split=k)``) is placed
with ``jax.make_array_from_callback``, which copies each device's slab directly —
both paths also create the padded physical layout for ragged split axes in place.
"""

from __future__ import annotations

import functools
import operator
from typing import Iterable, List, Optional, Sequence, Tuple, Type, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import devices
from .communication import Communication, MeshCommunication, sanitize_comm
from .devices import Device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape
from . import types
from .types import datatype, canonical_heat_type

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_numpy",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def __place(data: jax.Array, split: Optional[int], comm: Communication) -> jax.Array:
    """Apply the canonical (padded, sharded) placement implied by ``split``."""
    if isinstance(comm, MeshCommunication) and split is not None:
        return comm.placed(data, split)
    return data


def __distributed(split: Optional[int], comm: Communication) -> bool:
    return (
        split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    )


@functools.lru_cache(maxsize=512)
def __sharded_builder(kind: str, pshape: Tuple[int, ...], jdtype: str, sharding):
    """One jitted generator program per (kind, physical shape, dtype, sharding):
    with ``out_shardings`` set, every device materialises only its own shard — the
    TPU-native analog of the reference's local-slab allocation
    (factories.py:665-718)."""
    dt = np.dtype(jdtype)
    nelem = functools.reduce(operator.mul, pshape, 1)

    if kind == "full":

        def f(v):
            return jnp.full(pshape, v, dtype=dt)

    elif kind == "affine":
        # start + step * global_index along a flat iota — arange and linspace
        if dt.kind in "iu":
            cdt = dt
        else:
            cdt = np.float64 if jax.config.jax_enable_x64 else np.float32

        def f(start, step):
            idx = jnp.arange(nelem, dtype=cdt)
            return (start + idx * step).reshape(pshape).astype(dt)

    elif kind == "affine_pinned":
        # linspace with endpoint=True: start + i*step can miss ``stop`` by float
        # rounding at i = num-1, diverging from jnp.linspace's replicated path —
        # pin the last logical sample to stop exactly.
        cdt = np.float64 if jax.config.jax_enable_x64 else np.float32

        def f(start, step, last, stop_v):
            idx = jnp.arange(nelem, dtype=cdt)
            # the pin compares an INTEGER iota: a float32 iota rounds above 2^24
            # and would pin interior elements to stop as well
            ii = jnp.arange(nelem, dtype=np.int64 if jax.config.jax_enable_x64 else np.int32)
            vals = jnp.where(ii == last, stop_v, start + idx * step)
            return vals.reshape(pshape).astype(dt)

    elif kind == "eye":

        def f():
            r = jax.lax.broadcasted_iota(jnp.int32, pshape, 0)
            c = jax.lax.broadcasted_iota(jnp.int32, pshape, 1)
            return (r == c).astype(dt)

    else:  # pragma: no cover
        raise ValueError(kind)

    return jax.jit(f, out_shardings=sharding)


def __host_placed(
    data: np.ndarray, split: int, comm: MeshCommunication, jdtype
) -> jax.Array:
    """
    Place host (numpy) data split on ``split`` without staging the global array on
    any device: ``jax.make_array_from_callback`` copies each device's slab straight
    from host memory (the io.py slab-read pattern generalised, and the analog of the
    reference's per-rank local slab copy factories.py:150-433). The final shard's
    pad (ragged axes) is zero-filled here.
    """
    data = np.ascontiguousarray(np.asarray(data, dtype=np.dtype(jdtype)))
    gshape = data.shape
    split = int(split) % data.ndim
    pshape = comm.padded_shape(gshape, split)
    sharding = comm.sharding(data.ndim, split)
    n = gshape[split]

    def cb(index: Tuple[slice, ...]) -> np.ndarray:
        sl = index[split]
        start = sl.start or 0
        stop = pshape[split] if sl.stop is None else sl.stop
        valid_stop = min(stop, n)
        idx = list(index)
        idx[split] = slice(start, max(start, valid_stop))
        chunk = data[tuple(idx)]
        if stop > valid_stop:  # zero-fill the pad tail of the last shard(s)
            widths = [(0, 0)] * data.ndim
            widths[split] = (0, stop - max(start, valid_stop))
            chunk = np.pad(chunk, widths)
        return chunk

    return jax.make_array_from_callback(pshape, sharding, cb)


def __sanitize_split(split: Optional[int], is_split: Optional[int], shape) -> Optional[int]:
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    s = split if split is not None else is_split
    return sanitize_axis(tuple(shape), s)


def array(
    obj,
    dtype: Optional[Type[datatype]] = None,
    copy: bool = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """
    Create a :class:`~heat_tpu.core.dndarray.DNDarray`.

    Parameters
    ----------
    obj : array_like
        Input data: scalar, (nested) sequence, numpy/jax array or DNDarray.
    dtype : datatype, optional
        Desired data type; inferred from ``obj`` if omitted.
    copy : bool
        Whether to force a copy (jax arrays are immutable; kept for parity).
    ndmin : int
        Minimum number of dimensions; prepends size-1 axes as needed.
    order : str
        Memory layout 'C' or 'F' (layout is XLA's concern; validated only).
    split : int, optional
        Axis to split the (global) data along across the device mesh.
    is_split : int, optional
        Axis along which ``obj`` is *already* the process-local chunk of a larger
        array. In single-controller SPMD the controller holds all data, so this is
        equivalent to ``split`` with the global shape inferred from ``obj`` (reference
        factories.py:150-433 infers it with an Allreduce across ranks).
    device, comm :
        Placement overrides.

    Reference parity: factories.py:150-433.
    """
    if order not in ("C", "F"):
        raise ValueError(f"invalid memory layout, order must be 'C' or 'F', got {order}")
    device = devices.sanitize_device(device if device is not None else (obj.device if isinstance(obj, DNDarray) else None))
    comm = sanitize_comm(comm if comm is not None else (obj.comm if isinstance(obj, DNDarray) else None))

    host_data = None
    if isinstance(obj, DNDarray):
        data = obj.larray
        if split is None and is_split is None:
            split = obj.split
    elif isinstance(obj, (jnp.ndarray, jax.Array)):
        data = obj
    else:
        # host data: keep it in host memory so a split placement can copy each
        # device's slab directly without staging the global array on one device
        host_data = np.asarray(obj)
        data = host_data

    if dtype is not None:
        dtype = canonical_heat_type(dtype)
    elif host_data is None:
        dtype = canonical_heat_type(data.dtype)
    else:
        # let jnp's promotion rules (x32 by default) pick the dtype without
        # converting the whole host buffer
        probe = host_data[:0] if host_data.ndim else host_data
        dtype = canonical_heat_type(jnp.asarray(probe).dtype)

    if ndmin > 0 and data.ndim < ndmin:
        data = data.reshape((1,) * (ndmin - data.ndim) + tuple(data.shape))

    split = __sanitize_split(split, is_split, data.shape)
    gshape = tuple(data.shape)

    if host_data is not None and __distributed(split, comm):
        placed = __host_placed(data, split, comm, dtype.jnp_type())
        return DNDarray(placed, gshape, dtype, split, device, comm, True)

    data = jnp.asarray(data, dtype=dtype.jnp_type())
    data = __place(data, split, comm)
    return DNDarray(data, gshape, dtype, split, device, comm, True)


def asarray(
    obj,
    dtype: Optional[Type[datatype]] = None,
    order: str = "C",
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
) -> DNDarray:
    """Convert ``obj`` to a DNDarray without forcing a copy when avoidable
    (reference factories.py:434-487)."""
    if isinstance(obj, DNDarray) and (dtype is None or canonical_heat_type(dtype) is obj.dtype):
        return obj
    return array(obj, dtype=dtype, copy=False, order=order, is_split=is_split, device=device)


def __factory(
    shape,
    dtype,
    split,
    local_factory,
    device,
    comm,
    order: str = "C",
    fill_value=None,
) -> DNDarray:
    """Abstract factory: every device generates only its own shard (reference
    factories.py:665-718 allocates only the rank-local slab)."""
    shape = sanitize_shape(shape)
    dtype = canonical_heat_type(dtype)
    split = sanitize_axis(shape, split)
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    # 0-size arrays take the local path: XLA canonicalises an empty output to a
    # replicated sharding, which trips the out_shardings assertion in the builder
    if __distributed(split, comm) and len(shape) and all(shape):
        pshape = comm.padded_shape(shape, split)
        build = __sharded_builder(
            "full", pshape, np.dtype(dtype.jnp_type()).name, comm.sharding(len(shape), split)
        )
        if fill_value is None:
            fill_value = 1 if local_factory is jnp.ones else 0
        data = build(fill_value)
        return DNDarray(data, shape, dtype, split, device, comm, True)
    data = local_factory(shape, dtype=dtype.jnp_type())
    data = __place(data, split, comm)
    return DNDarray(data, shape, dtype, split, device, comm, True)


def __factory_like(a, dtype, split, factory, device, comm, order="C", **kwargs) -> DNDarray:
    """Abstract '*_like' factory (reference factories.py:719-788)."""
    shape = a.shape if hasattr(a, "shape") else np.shape(a)
    if dtype is None:
        try:
            dtype = types.heat_type_of(a)
        except TypeError:
            dtype = types.float32
    if split is None and isinstance(a, DNDarray):
        split = a.split
    if device is None and isinstance(a, DNDarray):
        device = a.device
    if comm is None and isinstance(a, DNDarray):
        comm = a.comm
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def arange(
    *args,
    dtype: Optional[Type[datatype]] = None,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """
    ``arange([start,] stop[, step])``: evenly spaced values within the half-open
    interval (reference factories.py:40-149; there each rank computes its sub-range
    analytically — here the sharding achieves the same placement).
    """
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    elif len(args) == 3:
        start, stop, step = args
    else:
        raise TypeError(f"arange takes 1 to 3 positional arguments, got {len(args)}")
    comm_r = sanitize_comm(comm)
    if step == 0:
        raise ValueError("arange: step must not be zero")
    num = max(0, int(np.ceil((stop - start) / step)))
    if __distributed(sanitize_axis((num,), split), comm_r) and num:
        if dtype is not None:
            dt = canonical_heat_type(dtype)
        else:
            dt = canonical_heat_type(
                jnp.asarray(np.arange(0, 1, dtype=np.result_type(start, stop, step))).dtype
            )
        pshape = (comm_r.padded_dim(num),)
        build = __sharded_builder(
            "affine", pshape, np.dtype(dt.jnp_type()).name, comm_r.sharding(1, 0)
        )
        data = build(start, step)
        return DNDarray(
            data, (num,), dt, 0, devices.sanitize_device(device), comm_r, True
        )
    data = jnp.arange(start, stop, step, dtype=dtype.jnp_type() if dtype is not None else None)
    return array(data, dtype=dtype, split=split, device=device, comm=comm)


def empty(
    shape,
    dtype: Type[datatype] = types.float32,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
    order: str = "C",
) -> DNDarray:
    """Uninitialized array of the given shape (reference factories.py:488-536; XLA
    has no uninitialized allocation — zeros are used)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Empty array with the properties of ``a`` (reference factories.py:537-585)."""
    return __factory_like(a, dtype, split, empty, device, comm, order=order)


def eye(
    shape,
    dtype: Type[datatype] = types.float32,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """2-D array with ones on the diagonal (reference factories.py:586-664)."""
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = tuple(shape)
        n, m = (shape[0], shape[0]) if len(shape) == 1 else (shape[0], shape[1])
    dtype = canonical_heat_type(dtype)
    comm_r = sanitize_comm(comm)
    split_s = sanitize_axis((n, m), split)
    if __distributed(split_s, comm_r) and n and m:
        pshape = comm_r.padded_shape((n, m), split_s)
        build = __sharded_builder(
            "eye", pshape, np.dtype(dtype.jnp_type()).name, comm_r.sharding(2, split_s)
        )
        return DNDarray(
            build(), (n, m), dtype, split_s, devices.sanitize_device(device), comm_r, True
        )
    data = jnp.eye(n, m, dtype=dtype.jnp_type())
    return array(data, dtype=dtype, split=split, device=device, comm=comm)


def from_numpy(a: np.ndarray, split=None, device=None, comm=None) -> DNDarray:
    """Create a DNDarray from a numpy array (convenience; TPU-native extension)."""
    return array(a, split=split, device=device, comm=comm)


def full(
    shape,
    fill_value,
    dtype: Type[datatype] = types.float32,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
    order: str = "C",
) -> DNDarray:
    """Array of given shape filled with ``fill_value``; dtype defaults to float32
    like the reference (factories.py:789-835)."""
    if dtype is None:
        dtype = types.float32

    def local_factory(shape, dtype=None):
        return jnp.full(shape, fill_value, dtype=dtype)

    return __factory(shape, dtype, split, local_factory, device, comm, order, fill_value=fill_value)


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Full array with the properties of ``a`` (reference factories.py:846-895)."""
    if dtype is None and isinstance(a, DNDarray):
        dtype = a.dtype
    return __factory_like(a, dtype, split, full, device, comm, fill_value=fill_value, order=order)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype: Optional[Type[datatype]] = None,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
):
    """Evenly spaced numbers over an interval (reference factories.py:896-981)."""
    num = int(num)
    if num < 0:  # num == 0 is a valid empty result, as in numpy
        raise ValueError(f"number of samples 'num' must be non-negative, got {num}")
    # numpy-exact step: delta / div when div > 0, else NaN (np.linspace returns
    # step=nan for num=0 and for num=1 with endpoint=True)
    div = num - 1 if endpoint else num
    step = (stop - start) / div if div > 0 else float("nan")
    comm_r = sanitize_comm(comm)
    if __distributed(sanitize_axis((num,), split), comm_r) and num:
        if dtype is not None:
            dt = canonical_heat_type(dtype)
        else:
            dt = types.float64 if jax.config.jax_enable_x64 else types.float32
        pshape = (comm_r.padded_dim(num),)
        kind = "affine_pinned" if endpoint and num > 1 else "affine"
        build = __sharded_builder(
            kind, pshape, np.dtype(dt.jnp_type()).name, comm_r.sharding(1, 0)
        )
        if kind == "affine_pinned":
            data = build(float(start), float(step), num - 1, float(stop))
        else:
            data = build(float(start), float(step) if num > 1 else 0.0)
        ht = DNDarray(data, (num,), dt, 0, devices.sanitize_device(device), comm_r, True)
    else:
        data = jnp.linspace(start, stop, num, endpoint=endpoint,
                            dtype=dtype.jnp_type() if dtype is not None else None)
        ht = array(data, dtype=dtype, split=split, device=device, comm=comm)
    if retstep:
        return ht, step
    return ht


def logspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    base: float = 10.0,
    dtype: Optional[Type[datatype]] = None,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """Numbers spaced evenly on a log scale (reference factories.py:982-1044):
    ``base ** linspace(start, stop)`` — rides linspace's sharded-at-birth path."""
    comm_r = sanitize_comm(comm)
    if __distributed(sanitize_axis((int(num),), split), comm_r):
        fdt = types.float64 if jax.config.jax_enable_x64 else types.float32
        lin = linspace(start, stop, num=num, endpoint=endpoint, dtype=fdt,
                       split=split, device=device, comm=comm)
        out = DNDarray(
            jnp.power(jnp.asarray(base, dtype=fdt.jnp_type()), lin.parray), (int(num),), fdt,
            0, lin.device, lin.comm, True,
        )
        if dtype is not None:
            return out.astype(canonical_heat_type(dtype))
        return out
    data = jnp.logspace(start, stop, int(num), endpoint=endpoint, base=base,
                        dtype=dtype.jnp_type() if dtype is not None else None)
    return array(data, dtype=dtype, split=split, device=device, comm=comm)


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from coordinate vectors (reference factories.py:1045-1127;
    there the split of the last/second argument distributes the grid — the resulting
    split metadata matches)."""
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    if not arrays:
        return []
    dnd = [a if isinstance(a, DNDarray) else array(a) for a in arrays]
    splits = [a.split for a in dnd]
    grids = jnp.meshgrid(*[a.larray for a in dnd], indexing=indexing)
    # the reference splits the output grid along the dim corresponding to the
    # (first) split input vector
    out_split = None
    for i, s in enumerate(splits):
        if s is not None:
            if len(dnd) == 1:
                out_split = 0
            elif indexing == "xy":
                out_split = 0 if i == 1 else (1 if i == 0 else i)
            else:
                out_split = i
            break
    proto = dnd[0]
    return [
        array(g, split=out_split, device=proto.device, comm=proto.comm) for g in grids
    ]


def ones(
    shape,
    dtype: Type[datatype] = types.float32,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
    order: str = "C",
) -> DNDarray:
    """Array of ones (reference factories.py:1128-1176)."""
    return __factory(shape, dtype, split, jnp.ones, device, comm, order)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Ones with the properties of ``a`` (reference factories.py:1177-1224)."""
    return __factory_like(a, dtype, split, ones, device, comm, order=order)


def zeros(
    shape,
    dtype: Type[datatype] = types.float32,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
    order: str = "C",
) -> DNDarray:
    """Array of zeros (reference factories.py:1225-1273)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Zeros with the properties of ``a`` (reference factories.py:1274-1325)."""
    return __factory_like(a, dtype, split, zeros, device, comm, order=order)
