"""
Statistical operations.

Parity with the reference's ``heat/core/statistics.py`` (``__all__`` at
statistics.py:22-41). The reference's distributed machinery — pairwise moment merging
over Allreduced (μ, n) tuples (:51-118, :741-866), custom ``MPI_ARGMAX``/``MPI_ARGMIN``
ops over packed (value, index) buffers (:1218), distributed selection for
``median``/``percentile`` (:867-1074) — all lowers to sharded jnp reductions here: XLA
emits the psum/pmax collectives and the (value, index) argmax pattern is a native
variadic reduce.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import _operations
from . import factories
from . import fusion as _fusion
from . import sanitation
from . import stride_tricks
from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "nanmax",
    "nanmean",
    "nanmin",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]

# builtins shadowed by min/max
_builtin_min = min
_builtin_max = max


def argmax(x, axis=None, out=None, **kwargs) -> DNDarray:
    """
    Indices of the maximum values along an axis; flattened-index result for
    ``axis=None`` (reference statistics.py argmax via the packed (value,index)
    MPI_ARGMAX op, :1218)."""
    res = _operations.__reduce_op(x, jnp.argmax, axis=axis, out=None, keepdims=_operations.resolve_keepdims(kwargs.get("keepdim"), kwargs.get("keepdims")))
    res = res.astype(types.default_index_type(), copy=False)
    if out is not None:
        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def argmin(x, axis=None, out=None, **kwargs) -> DNDarray:
    """Indices of the minimum values along an axis (reference statistics.py argmin)."""
    res = _operations.__reduce_op(x, jnp.argmin, axis=axis, out=None, keepdims=_operations.resolve_keepdims(kwargs.get("keepdim"), kwargs.get("keepdims")))
    res = res.astype(types.default_index_type(), copy=False)
    if out is not None:
        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def average(x, axis=None, weights=None, returned: bool = False):
    """
    Weighted average over the given axis (reference statistics.py average).

    Returns ``(average, sum_of_weights)`` if ``returned``.
    """
    sanitation.sanitize_in(x)
    w = weights.larray if isinstance(weights, DNDarray) else weights
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    avg, wsum = jnp.average(x.larray, axis=axis, weights=w, returned=True)
    if w is not None and bool(jnp.any(wsum == 0)):
        # numpy raises when any normalization slice sums to zero; jnp.average
        # silently returns nan/inf — wsum already carries the per-slice sums
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    split = stride_tricks.reduced_split(x.split, axis)
    res = DNDarray(avg, tuple(avg.shape), types.canonical_heat_type(avg.dtype), split, x.device, x.comm, True)
    if returned:
        wret = DNDarray(
            jnp.broadcast_to(wsum, avg.shape),
            tuple(avg.shape),
            types.canonical_heat_type(jnp.asarray(wsum).dtype),
            split,
            x.device,
            x.comm,
            True,
        )
        return res, wret
    return res


def bincount(x, weights=None, minlength: int = 0) -> DNDarray:
    """Count occurrences of each value in a non-negative int array (reference
    statistics.py bincount; eager — data-dependent output length)."""
    sanitation.sanitize_in(x)
    w = weights.larray if isinstance(weights, DNDarray) else weights
    res = jnp.bincount(x.larray, weights=w, minlength=minlength)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def bucketize(input, boundaries, out_int32: bool = False, right: bool = False, out=None) -> DNDarray:
    """Index of the bucket each element falls into (reference statistics.py
    bucketize)."""
    sanitation.sanitize_in(input)
    b = boundaries.larray if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    res = jnp.searchsorted(b, input.larray, side="right" if right else "left")
    idx_t = types.int32 if out_int32 else types.default_index_type()
    res = res.astype(idx_t.jnp_type())
    result = DNDarray.__new_like__(input, res, idx_t)
    if out is not None:
        out.larray = res.astype(out.dtype.jnp_type())
        return out
    return result


def digitize(x, bins, right: bool = False) -> DNDarray:
    """Indices of the bins each value belongs to (numpy semantics; reference
    statistics.py digitize)."""
    sanitation.sanitize_in(x)
    b = bins.larray if isinstance(bins, DNDarray) else jnp.asarray(bins)
    res = jnp.digitize(x.larray, b, right=right)
    return DNDarray.__new_like__(x, res, types.canonical_heat_type(res.dtype))


def cov(m, y=None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Estimate the covariance matrix (reference statistics.py cov)."""
    sanitation.sanitize_in(m)
    if ddof is not None and not isinstance(ddof, int):
        raise TypeError("ddof must be an integer")
    yv = y.larray if isinstance(y, DNDarray) else y
    with jax.default_matmul_precision("highest"):
        # the covariance GEMM must not drop to the TPU's bf16 default pass
        res = jnp.cov(m.larray, y=yv, rowvar=rowvar, bias=bias, ddof=ddof)
    res = jnp.atleast_2d(res)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, m.device, m.comm, True)


def __f64_edges(data, nbins, lo=None, hi=None):
    """Equal-width bin edges built on the host in float64 and cast to the
    working dtype — numpy computes edges in f64, and jnp's f32 edge
    arithmetic can land an exact-edge sample one bin off (fuzz cases 49/93).
    An f32 data value that IS an f64 edge stays bit-exact through the cast.

    Range validation matches numpy/torch (ADVICE r5): non-finite bounds —
    supplied or data-derived — and decreasing ranges raise ``ValueError``
    instead of producing garbage or decreasing edges; an equal range is
    expanded by ±0.5 first (numpy ``_get_outer_edges`` semantics), so only a
    genuinely reversed range rejects."""
    if lo is None:
        if data.size == 0:
            lo, hi = 0.0, 1.0
        else:
            lo, hi = float(jnp.min(data)), float(jnp.max(data))
        if not (np.isfinite(lo) and np.isfinite(hi)):
            raise ValueError(
                f"autodetected range of [{lo}, {hi}] is not finite"
            )
    elif not (np.isfinite(lo) and np.isfinite(hi)):
        raise ValueError(f"supplied range of [{lo}, {hi}] is not finite")
    if lo == hi:
        lo, hi = lo - 0.5, hi + 0.5
    if lo > hi:
        raise ValueError("max must be larger than min in range parameter.")
    edges64 = np.linspace(lo, hi, int(nbins) + 1, dtype=np.float64)
    return jnp.asarray(edges64.astype(np.result_type(data.dtype, np.float32)))


def histc(input, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins in [min, max] (torch semantics; reference
    statistics.py histc)."""
    sanitation.sanitize_in(input)
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo, hi = None, None  # derive from the data, in f64, like histogram
    hist, _ = jnp.histogram(input.larray, bins=__f64_edges(input.larray, bins, lo, hi))
    hist = hist.astype(input.dtype.jnp_type())
    res = DNDarray(hist, tuple(hist.shape), input.dtype, None, input.device, input.comm, True)
    if out is not None:
        out.larray = hist.astype(out.dtype.jnp_type())
        return out
    return res


def histogram(a, bins=10, range=None, normed=None, weights=None, density=None):
    """Histogram of a dataset, numpy semantics: returns ``(hist, bin_edges)``
    (reference statistics.py histogram)."""
    sanitation.sanitize_in(a)
    w = weights.larray if isinstance(weights, DNDarray) else weights
    if isinstance(bins, (int, np.integer)) and not isinstance(a.larray, jax.core.Tracer):
        # f64 host-side edges for exact-edge parity with numpy. Under jit/vmap
        # the data is a Tracer and float(jnp.min/max) would raise
        # ConcretizationTypeError (ADVICE r5) — fall back to the pure-jnp path
        # below, which traces fine (accepting jnp's f32 edge arithmetic there).
        lo, hi = (float(range[0]), float(range[1])) if range is not None else (None, None)
        bins = __f64_edges(a.larray, bins, lo, hi)
    hist, edges = jnp.histogram(a.larray, bins=bins, range=range, weights=w, density=density or normed)
    h = DNDarray(hist, tuple(hist.shape), types.canonical_heat_type(hist.dtype), None, a.device, a.comm, True)
    e = DNDarray(edges, tuple(edges.shape), types.canonical_heat_type(edges.dtype), None, a.device, a.comm, True)
    return h, e


def __moment(x, axis, keepdims, moment_fn, sink_op=None, sink_kwargs=None):
    """Shared moment template. When ``sink_op`` names the equivalent jnp
    reduction (mean/var/std/nanmean) and ``x`` carries a pending fused chain,
    the moment becomes a *sink* of that chain (core/fusion.py): the
    elementwise subgraph, the reduction, and its scalar epilogues (``/n``,
    ``-mu**2``) trace as one XLA program instead of flushing the intermediate.
    Multi-step moments (kurtosis/skew) pass no ``sink_op`` and keep the
    flushing path."""
    sanitation.sanitize_in(x)
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    split = stride_tricks.reduced_split(x.split, axis, keepdims)
    if sink_op is not None and _fusion.sink_ready(x):
        res = _fusion.defer_moment(x, sink_op, axis, keepdims, sink_kwargs or {}, split)
        if res is not None:
            return res
    with _fusion.flush_reason("reduction"):
        operand = x.larray
    res = moment_fn(operand, axis)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), split, x.device, x.comm, True)


def kurtosis(x, axis=None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """
    Kurtosis (Fisher's definition when ``Fischer``, i.e. normal ==> 0.0) along an axis
    (reference statistics.py kurtosis; the reference merges per-rank partial moments —
    here a sharded global moment computation).
    """

    def _kurt(a, ax):
        mu = jnp.mean(a, axis=ax, keepdims=True)
        d = a - mu
        m2 = jnp.mean(d**2, axis=ax)
        m4 = jnp.mean(d**4, axis=ax)
        n = a.size if ax is None else a.shape[ax]
        if unbiased:
            k = 1.0 / (n - 2) / (n - 3) * ((n**2 - 1.0) * m4 / m2**2 - 3 * (n - 1) ** 2) + 3
        else:
            k = m4 / m2**2
        return k - 3 if Fischer else k

    return __moment(x, axis, False, _kurt)


def skew(x, axis=None, unbiased: bool = True) -> DNDarray:
    """Sample skewness along an axis (reference statistics.py skew)."""

    def _skew(a, ax):
        mu = jnp.mean(a, axis=ax, keepdims=True)
        d = a - mu
        m2 = jnp.mean(d**2, axis=ax)
        m3 = jnp.mean(d**3, axis=ax)
        g1 = m3 / jnp.power(m2, 1.5)
        n = a.size if ax is None else a.shape[ax]
        if unbiased:
            return jnp.sqrt(n * (n - 1.0)) / (n - 2.0) * g1
        return g1

    return __moment(x, axis, False, _skew)


def max(x, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Maximum along an axis (reference statistics.py max → MPI.MAX reduce)."""
    return _operations.__reduce_op(x, jnp.max, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def maximum(x1, x2, out=None) -> DNDarray:
    """Element-wise maximum of two arrays (reference statistics.py maximum)."""
    return _operations.__binary_op(jnp.maximum, x1, x2, out)


def mean(x, axis=None, keepdims: Optional[bool] = None, keepdim: Optional[bool] = None) -> DNDarray:
    """
    Arithmetic mean along an axis (reference statistics.py:741-866: per-rank partial
    moments merged via Allreduce; here the sharded jnp.mean lowers to the same psum).
    ``keepdims`` extends the reference's signature to numpy's; the torch-style
    ``keepdim`` spelling the neighboring reducers use (``sum``/``prod``,
    reference arithmetics.py:860+) is accepted as an alias. Passing both with
    conflicting values raises, like the other reducers.
    """
    keep = _operations.resolve_keepdims(keepdim, keepdims)
    return __moment(x, axis, keep, lambda a, ax: jnp.mean(a, axis=ax, keepdims=keep), sink_op=jnp.mean)


def median(x, axis=None, keepdim: bool = False) -> DNDarray:
    """Median along an axis (reference statistics.py:867-1074 distributed
    selection — routed through the distributed-percentile path for 1-D split
    arrays; a sharded global sort/select otherwise)."""
    from . import _sort as _dsort

    ax = stride_tricks.sanitize_axis(x.shape, axis) if isinstance(x, DNDarray) else axis
    if isinstance(x, DNDarray) and isinstance(ax, (int, type(None))) and _dsort.can_distribute_sort(x, ax):
        return percentile(x, 50.0, axis=ax, interpolation="linear", keepdim=keepdim)

    def _med(a, ax):
        return jnp.median(a, axis=ax, keepdims=keepdim)

    return __moment(x, axis, keepdim, _med)


def nanmax(x, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Maximum ignoring NaN (numpy-API completion beyond the reference
    snapshot; same sharded reduce template)."""
    return _operations.__reduce_op(x, jnp.nanmax, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def nanmin(x, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Minimum ignoring NaN (numpy-API completion)."""
    return _operations.__reduce_op(x, jnp.nanmin, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def nanmean(x, axis=None, keepdims: bool = False) -> DNDarray:
    """Mean ignoring NaN (numpy-API completion)."""
    return __moment(x, axis, keepdims, lambda a, ax: jnp.nanmean(a, axis=ax, keepdims=keepdims), sink_op=jnp.nanmean)


def min(x, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Minimum along an axis (reference statistics.py min → MPI.MIN reduce)."""
    return _operations.__reduce_op(x, jnp.min, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims))


def minimum(x1, x2, out=None) -> DNDarray:
    """Element-wise minimum of two arrays (reference statistics.py minimum)."""
    return _operations.__binary_op(jnp.minimum, x1, x2, out)


def percentile(x, q, axis=None, out=None, interpolation: str = "linear", keepdim: bool = False) -> DNDarray:
    """
    q-th percentile along an axis (reference statistics.py:1256+ distributed
    selection). Interpolation: 'linear', 'lower', 'higher', 'midpoint', 'nearest'.
    """
    sanitation.sanitize_in(x)
    if interpolation not in ("linear", "lower", "higher", "midpoint", "nearest"):
        raise ValueError(f"unsupported interpolation method {interpolation!r}")
    axis = stride_tricks.sanitize_axis(x.shape, axis)
    # working float dtype: f32 stays f32, f64 stays f64 under x64, exact
    # dtypes promote to the default float (the WEAK float operand is what
    # gives int64 -> f64 under x64; a strong jnp.float32 would pin ints to
    # f32) — a hardcoded f32 here silently downcast f64 split-axis medians
    # (caught by the x64 surface fuzz)
    ft = jnp.result_type(x.dtype.jnp_type(), float)
    qv = q.larray if isinstance(q, DNDarray) else jnp.asarray(q, dtype=ft)
    from . import _sort as _dsort

    if isinstance(axis, (int, type(None))) and _dsort.can_distribute_sort(x, axis):
        # distributed selection (reference statistics.py:867-1074/:1256+): exact-
        # rank distributed sort along the split axis, then fetch only the
        # bracketing order statistics (a tiny cross-shard gather), for any ndim
        ax = 0 if axis is None else int(axis) % x.ndim
        sv_p, _ = _dsort.distributed_sort(x, ax)
        sv = DNDarray(sv_p, x.shape, x.dtype, x.split, x.device, x.comm, True)
        n = x.shape[ax]
        rest = tuple(s for d, s in enumerate(x.shape) if d != ax)
        # bracketing indices on the HOST when q is a host value: a host key
        # keeps the getitem bounds check free of device round-trips (a jnp idx
        # forces a blocking fetch per percentile call); traced q (percentile
        # under jit) stays in jnp and getitem skips the eager check
        xp = jnp if isinstance(qv, jax.core.Tracer) else np
        qf = xp.asarray(qv, dtype=np.dtype(ft)) / 100.0 * (n - 1)
        lo = xp.clip(xp.floor(qf).astype(xp.int32), 0, n - 1)
        hi = xp.clip(xp.ceil(qf).astype(xp.int32), 0, n - 1)
        nq = int(np.prod(np.shape(qf), dtype=np.int64)) if np.shape(qf) else 1
        idx = xp.concatenate([lo.reshape(-1), hi.reshape(-1)])  # (2*nq,) tiny gather
        key = (slice(None),) * ax + (idx,)
        # single advanced key on the split axis: the DNDarray getitem keeps the
        # order and gathers only 2*nq rows
        picked = sv[key].larray.astype(ft)
        pm = jnp.moveaxis(picked, ax, 0).reshape((2, nq) + rest)
        qshape = tuple(jnp.shape(qf))
        v_lo, v_hi = pm[0].reshape(qshape + rest), pm[1].reshape(qshape + rest)
        lo_b = lo.astype(ft).reshape(qshape + (1,) * len(rest))
        qf_b = qf.reshape(qshape + (1,) * len(rest))
        if interpolation == "lower":
            res = v_lo
        elif interpolation == "higher":
            res = v_hi
        elif interpolation == "midpoint":
            res = (v_lo + v_hi) / 2.0
        elif interpolation == "nearest":
            # half-fraction rounds DOWN — jnp.percentile's convention (numpy
            # rounds half to even); matching jnp keeps split and replicated
            # arrays returning identical results
            res = jnp.where(qf_b - lo_b <= 0.5, v_lo, v_hi)
        else:  # linear
            frac = qf_b - jnp.floor(qf_b)
            res = v_lo * (1.0 - frac) + v_hi * frac
        if np.dtype(x.dtype.jnp_type()).kind == "f":
            # numpy/jnp propagate NaN for every q; the selection sorts NaN to the
            # end, so poison explicitly to keep split == replicated results
            nan_mask = jnp.isnan(x.larray).any(axis=ax).reshape((1,) * len(qshape) + rest)
            res = jnp.where(nan_mask, jnp.asarray(np.nan, dtype=ft), res)
        if keepdim:
            kshape = tuple(1 if d == ax else s for d, s in enumerate(x.shape))
            res = res.reshape(qshape + kshape)
    else:
        # jnp.percentile only takes rank<=1 q; numpy allows any q shape —
        # flatten around the call and restore the q dimensions in front
        qf = jnp.asarray(qv)
        res = jnp.percentile(
            x.larray.astype(ft), qf.reshape(-1) if qf.ndim > 1 else qf,
            axis=axis, method=interpolation, keepdims=keepdim,
        )
        if qf.ndim > 1:
            res = res.reshape(tuple(qf.shape) + tuple(res.shape[1:]))
    # the split axis survives when it is not the reduced axis; a vector q prepends
    # qv.ndim leading axes, shifting the surviving split accordingly
    split = stride_tricks.reduced_split(x.split, axis, keepdim, prepend=int(qv.ndim))
    result = DNDarray(
        jnp.asarray(res), tuple(jnp.shape(res)), types.canonical_heat_type(jnp.asarray(res).dtype),
        split, x.device, x.comm, True,
    )
    if out is not None:
        sanitation.sanitize_out(out, result.shape, None, x.device)
        out.larray = result.larray.astype(out.dtype.jnp_type())
        return out
    return result


def std(x, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Standard deviation along an axis with ``ddof`` delta degrees of freedom
    (reference statistics.py std). Accepts both ``keepdim`` (torch-style, the
    reference's spelling) and ``keepdims`` (numpy's)."""
    if not isinstance(ddof, int) or ddof < 0:
        raise ValueError(f"ddof must be a non-negative integer, got {ddof}")
    keep = _operations.resolve_keepdims(kwargs.get("keepdim"), kwargs.get("keepdims"))
    return __moment(
        x, axis, keep, lambda a, ax: jnp.std(a, axis=ax, ddof=ddof, keepdims=keep),
        sink_op=jnp.std, sink_kwargs={"ddof": ddof},
    )


def var(x, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance along an axis with ``ddof`` delta degrees of freedom (reference
    statistics.py:1704-1847: pairwise moment merging over Allreduce; sharded jnp.var
    here). Accepts both ``keepdim`` and ``keepdims`` spellings."""
    if not isinstance(ddof, int) or ddof < 0:
        raise ValueError(f"ddof must be a non-negative integer, got {ddof}")
    keep = _operations.resolve_keepdims(kwargs.get("keepdim"), kwargs.get("keepdims"))
    return __moment(
        x, axis, keep, lambda a, ax: jnp.var(a, axis=ax, ddof=ddof, keepdims=keep),
        sink_op=jnp.var, sink_kwargs={"ddof": ddof},
    )


DNDarray.argmax = argmax
DNDarray.argmin = argmin
DNDarray.average = average
DNDarray.max = max
DNDarray.mean = mean
DNDarray.median = median
DNDarray.min = min
DNDarray.std = std
DNDarray.var = var
