"""
Version-compatibility shims for the jax API surface this framework targets.

``shard_map`` graduated from ``jax.experimental.shard_map`` to ``jax.shard_map``
(and its replication-checking kwarg was renamed ``check_rep`` → ``check_vma``)
across jax releases; this module presents the *new* calling convention —
``shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)`` — on
every jax this image can carry, so all collective/kernel builders in the
framework write one spelling and never branch on the jax version themselves.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6: public top-level API with the check_vma spelling
    _shard_map = jax.shard_map
    _LEGACY_SHARD_MAP = False
except AttributeError:  # jax 0.4.x: experimental module with check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY_SHARD_MAP = True

try:  # jax >= 0.5: top-level double-precision context manager
    enable_x64 = jax.enable_x64
except AttributeError:  # jax 0.4.x: experimental module
    from jax.experimental import enable_x64  # noqa: F401

__all__ = ["enable_x64", "set_cpu_device_count", "shard_map"]


def set_cpu_device_count(n: int) -> None:
    """Configure the number of virtual CPU devices BEFORE backend init.

    jax >= 0.5 exposes the ``jax_num_cpu_devices`` config option; 0.4.x only
    honors the ``--xla_force_host_platform_device_count`` XLA flag, which must
    land in ``XLA_FLAGS`` before the CPU backend is created (callers —
    ``distributed_init`` — already require that ordering).
    """
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={int(n)}"
        )


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern keyword signature on any jax.

    ``check_vma`` maps to the legacy ``check_rep`` kwarg on jax versions that
    predate the rename; ``None`` leaves the jax default in place either way.
    """
    if check_vma is not None:
        kwargs["check_rep" if _LEGACY_SHARD_MAP else "check_vma"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
