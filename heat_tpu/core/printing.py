"""
Global and local printing of DNDarrays.

Parity with the reference's ``heat/core/printing.py`` (modes :30-149,
``set_printoptions`` :150, formatting :184-295). The reference gathers a truncated
copy to rank 0 (``_torch_data`` resplits to None, :208); here the controller already
addresses the global array, so formatting is a numpy repr with heat-style framing.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_printoptions", "global_printing", "local_printing", "print0", "set_printoptions"]

# default print options (numpy-aligned, reference printing.py:13-28)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)
__LOCAL_PRINTING = False


def get_printoptions() -> dict:
    """Returns the currently configured printing options (reference printing.py
    get_printoptions)."""
    return dict(__PRINT_OPTIONS)


def set_printoptions(
    precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None
):
    """
    Configures the printing options (reference printing.py:150-183).

    Parameters
    ----------
    profile : str, optional
        ``'default'``, ``'short'`` or ``'full'`` preset overridden by the explicit
        options.
    """
    global __PRINT_OPTIONS
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
    for key, val in (
        ("precision", precision),
        ("threshold", threshold),
        ("edgeitems", edgeitems),
        ("linewidth", linewidth),
        ("sci_mode", sci_mode),
    ):
        if val is not None:
            __PRINT_OPTIONS[key] = val


def local_printing() -> None:
    """Print only the process-local data (reference printing.py:30-60)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = True


def global_printing() -> None:
    """Print the global array (default; reference printing.py:61-99)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = False


def print0(*args, **kwargs) -> None:
    """Print from rank 0 only (reference printing.py:100-149). One controller here —
    plain print."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def __str__(dndarray) -> str:
    """Returns the string representation of the given array (reference
    printing.py:184-295)."""
    opts = __PRINT_OPTIONS
    with np.printoptions(
        precision=opts["precision"],
        threshold=int(opts["threshold"]) if opts["threshold"] != float("inf") else np.iinfo(np.int64).max,
        edgeitems=opts["edgeitems"],
        linewidth=opts["linewidth"],
    ):
        body = np.array2string(
            np.asarray(dndarray.numpy()), separator=", ", prefix="DNDarray("
        )
    return (
        f"DNDarray({body}, dtype=ht.{dndarray.dtype.__name__}, "
        f"device={dndarray.device}, split={dndarray.split})"
    )
