"""
Input/output validation and distribution sanitation.

Parity with the reference's ``heat/core/sanitation.py`` (``sanitize_distribution``
:31-158, ``sanitize_out`` :259, plus ``sanitize_in``/``sanitize_sequence``/
``scalar_to_1d``). Under balanced JAX shardings, "matching the distribution" of
operands needs no data motion — XLA reshards lazily — so these helpers validate
metadata compatibility instead of chaining Send/Recv.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = [
    "sanitize_distribution",
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_sequence",
    "scalar_to_1d",
]


def sanitize_distribution(*args: DNDarray, target: DNDarray, diff_map=None) -> Union[DNDarray, Tuple[DNDarray, ...]]:
    """
    Distribute every arg like ``target`` (reference sanitation.py:31-158, which
    physically redistributes via ``redistribute_``). Balanced shardings mean the only
    action needed is aligning the logical split where shapes allow it.
    """
    out = []
    tsplit = target.split
    tshape = target.shape
    for arg in args:
        sanitize_in(arg)
        if arg.split == tsplit or tsplit is None or arg.split is None:
            out.append(arg)
        else:
            out.append(arg.resplit_(tsplit) if arg.shape == tshape else arg)
    return out[0] if len(out) == 1 else tuple(out)


def sanitize_in(x: Any) -> None:
    """Verify ``x`` is a DNDarray; raise TypeError otherwise (reference
    sanitation.py sanitize_in)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_in_tensor(x: Any) -> None:
    """Verify ``x`` is a jax array (the reference checks torch.Tensor)."""
    if not isinstance(x, (jnp.ndarray, np.ndarray)):
        raise TypeError(f"input needs to be an array, but was {type(x)}")


def sanitize_infinity(x: DNDarray) -> Union[int, float]:
    """Largest representable value of ``x``'s dtype (reference sanitation.py
    sanitize_infinity)."""
    dt = np.dtype(x.dtype.jnp_type())
    if dt.kind in "iu":
        return int(np.iinfo(dt).max)
    return float("inf")


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Verify that ``tensor`` is a legal local shard of ``array`` (reference
    sanitation.py sanitize_lshape)."""
    gshape = array.shape
    tshape = tuple(tensor.shape)
    if tshape == gshape:
        return
    split = array.split
    if split is None:
        raise ValueError(f"local tensor of shape {tshape} is not a chunk of global shape {gshape}")
    non_split_ok = all(t == g for d, (t, g) in enumerate(zip(tshape, gshape)) if d != split)
    if not non_split_ok or tshape[split] > gshape[split]:
        raise ValueError(f"local tensor of shape {tshape} is not a chunk of global shape {gshape} on split {split}")


def sanitize_out(
    out: Any,
    output_shape: Tuple[int, ...],
    output_split,
    output_device,
    output_comm=None,
) -> None:
    """
    Validate that ``out`` is a DNDarray suitable to receive a result of the given
    global shape/split/device (reference sanitation.py:259-386). Broadcasting of the
    result into ``out`` is permitted per NumPy rules.
    """
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    out_proto = np.broadcast_shapes(tuple(output_shape), tuple(out.shape))
    if out_proto != tuple(out.shape):
        raise ValueError(
            f"Expecting output buffer of shape {tuple(output_shape)}, got {tuple(out.shape)}"
        )


def sanitize_sequence(seq: Any) -> list:
    """Check that ``seq`` is a sequence and return it as a list (reference
    sanitation.py sanitize_sequence)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        return seq.tolist()
    if isinstance(seq, (np.ndarray, jnp.ndarray)):
        return list(np.asarray(seq))
    raise TypeError(f"seq must be a list, tuple, DNDarray or array, got {type(seq)}")


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Turn a scalar DNDarray into a 1-D DNDarray with one element (reference
    sanitation.py scalar_to_1d)."""
    if x.ndim != 0:
        return x
    return DNDarray.__new_like__(x, x.larray.reshape(1), split=None)
