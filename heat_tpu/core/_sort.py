"""
Distributed sort machinery: exact-rank parallel sort over the mesh.

The reference implements ``sort`` as a parallel sample-sort — local sort, gather
pivots, global pivot select, ``Alltoallv`` exchange, merge (reference
heat/core/manipulations.py:2263-3050) — and distributed selection for
median/percentile (statistics.py:867-1074). Sample-sort's bucket sizes are
data-dependent, which fights XLA's static shapes; the TPU-native redesign keeps
the same structure but computes each element's **exact global rank** so every
exchange has a static shape:

1. local stable sort of each shard's chunk along the split axis;
2. a ring of ``ppermute`` steps (p-1 hops) circulates the sorted chunks; each
   shard counts, per element, how many elements of every other chunk precede it
   — ``searchsorted`` with ``side='right'`` for lower shard ids and ``'left'``
   for higher ones, so ties are broken by (shard, local position) and ranks are
   unique even for constant data;
3. the payload is scattered into an (N, …) buffer at its rank positions and one
   ``psum_scatter`` (reduce-scatter over ICI) delivers to each shard exactly its
   c = N/p slot-ordered output rows — no merge pass needed.

N-D arrays sort along the split axis by flattening the non-split axes into
independent columns of the same machinery (the reference's sample-sort handles
any axis the same way, manipulations.py:2263-2301). 64-bit dtypes ride the same
path under x64: the float total-order transform has a u64 form and integer keys
are width-agnostic.

Pad sentinels (ragged axes) carry the key-space maximum and the largest global
indices, so they take the final ranks and the result lands back in the
canonical padded physical layout.

Honest cost note: the exchange materialises a transient full-length (N, R)
scatter buffer per device and the reduce-scatter moves O(N) bytes per device —
compute and the final layout are fully distributed, peak memory is not (3
transient N-length buffers). The O(N/p) exchange needs ``ragged_all_to_all``
(each shard's destination ranks are ascending, so its sends are p contiguous
segments), which XLA:TPU implements but XLA:CPU — the test mesh — has no thunk
for; swap the exchange when deploying sorts at HBM-limit scale.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .communication import MeshCommunication

__all__ = ["distributed_sort", "distributed_sort_1d", "can_distribute_sort"]


def can_distribute_sort(a, axis: Optional[int] = 0) -> bool:
    """
    Whether sorting ``a`` (a DNDarray) along ``axis`` takes the distributed
    exact-rank path: the axis must be the split axis of a genuinely distributed
    array, with at least one row per device, and the dtype must have a total
    order expressible as integer keys (bool/int of any width; floats at 4 bytes,
    or 8 under x64).
    """
    comm = a.comm
    dt = np.dtype(a.dtype.jnp_type())
    if a.split is None or a.ndim == 0:
        return False
    split = int(a.split) % a.ndim
    if axis is None:
        if a.ndim != 1:
            return False
        axis = 0
    if int(axis) % a.ndim != split:
        return False
    if not (isinstance(comm, MeshCommunication) and comm.is_distributed()):
        return False
    if a.pshape[split] < comm.size:
        return False
    if dt.kind in "biu":
        return True
    if dt.kind == "f":
        return dt.itemsize <= 4 or bool(jax.config.jax_enable_x64)
    return False


def _float_to_key(v: jax.Array, descending: bool) -> jax.Array:
    """
    Map floats to unsigned keys whose unsigned order is a TOTAL order matching
    numpy's sort order: -inf < … < -0 = +0 < … < +inf < NaN (all NaNs
    canonicalized, so negative-payload NaNs don't sort first), with the unsigned
    maximum reserved above everything for the pad sentinel. Descending
    complements the key, which puts NaN first — the order of a flipped
    ascending sort. f32 uses u32 keys; f64 (under x64) the identical u64 form.
    """
    wide = np.dtype(v.dtype).itemsize == 8
    ft, ut = (jnp.float64, jnp.uint64) if wide else (jnp.float32, jnp.uint32)
    bits = 64 if wide else 32
    f = v.astype(ft)
    f = jnp.where(jnp.isnan(f), np.asarray(np.nan, ft), f)  # canonical +NaN bits
    u = jax.lax.bitcast_convert_type(f, ut)
    sign = jnp.asarray(1 << (bits - 1), ut)
    key = jnp.where((u >> (bits - 1)).astype(bool), ~u, u | sign)
    # canonical +NaN maps below the all-ones sentinel: cap just under it
    key = jnp.minimum(key, jnp.asarray(np.iinfo(np.dtype(ut)).max - 1, ut))
    return ~key if descending else key


def _key_to_float(k: jax.Array, dtype, descending: bool) -> jax.Array:
    wide = np.dtype(k.dtype).itemsize == 8
    ft, ut = (jnp.float64, jnp.uint64) if wide else (jnp.float32, jnp.uint32)
    bits = 64 if wide else 32
    if descending:
        k = ~k
    sign = jnp.asarray(1 << (bits - 1), ut)
    u = jnp.where((k >> (bits - 1)).astype(bool), k ^ sign, ~k)
    return jax.lax.bitcast_convert_type(u, ft).astype(dtype)


def _sort_key(v: jax.Array, descending: bool) -> jax.Array:
    """Monotone key so the kernel always sorts ascending. Floats go through the
    total-order bit transform; integers use bitwise NOT for descending (no
    INT_MIN negation overflow)."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        return _float_to_key(v, descending)
    return ~v if descending else v


def _unkey(k: jax.Array, dtype, descending: bool) -> jax.Array:
    if np.dtype(dtype).kind == "f":
        return _key_to_float(k, dtype, descending)
    return ~k if descending else k


@functools.lru_cache(maxsize=128)
def _build_sort(mesh, axis_name: str, p: int, pshape: Tuple[int, ...], axis: int, jdtype: str):
    """Compile the exact-rank sort for one (mesh, physical shape, sort axis, dtype)."""
    n_phys = pshape[axis]
    c = n_phys // p
    ndim = len(pshape)
    rest = tuple(s for d, s in enumerate(pshape) if d != axis)
    R = int(np.prod(rest, dtype=np.int64)) if rest else 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    # column-wise searchsorted over the flattened non-split axes
    _ss_l = jax.vmap(lambda o, s: jnp.searchsorted(o, s, side="left"), in_axes=1, out_axes=1)
    _ss_r = jax.vmap(lambda o, s: jnp.searchsorted(o, s, side="right"), in_axes=1, out_axes=1)

    def local(v):
        vm = jnp.moveaxis(v, axis, 0).reshape(c, R)
        order = jnp.argsort(vm, axis=0, stable=True)  # (c, R)
        sv = jnp.take_along_axis(vm, order, axis=0)
        me = jax.lax.axis_index(axis_name)
        sidx = (me * c + order).astype(jnp.int32)

        def step(carry, _):
            other_v = jax.lax.ppermute(carry[0], axis_name, perm)
            other_id = jax.lax.ppermute(carry[1], axis_name, perm)
            lo = _ss_l(other_v, sv)
            hi = _ss_r(other_v, sv)
            # ties: lower shard ids precede me, higher follow — unique ranks
            cnt = jnp.where(other_id < me, hi, lo)
            return (other_v, other_id), cnt

        _, cnts = jax.lax.scan(step, (sv, me), None, length=p - 1)
        rank = jnp.arange(c)[:, None] + cnts.sum(axis=0)  # (c, R)

        # exchange: scatter to rank slots, reduce-scatter my window back
        cols = jnp.arange(R)[None, :]
        buf_v = jnp.zeros((n_phys, R), dtype=sv.dtype).at[rank, cols].set(sv)
        buf_i = jnp.zeros((n_phys, R), dtype=jnp.int32).at[rank, cols].set(sidx)
        out_v = jax.lax.psum_scatter(buf_v, axis_name, scatter_dimension=0, tiled=True)
        out_i = jax.lax.psum_scatter(buf_i, axis_name, scatter_dimension=0, tiled=True)
        back = lambda o: jnp.moveaxis(o.reshape((c,) + rest), 0, axis)
        return back(out_v), back(out_i)

    spec = P(*([None] * axis + [axis_name]))
    return jax.jit(
        jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=(spec, spec), check_vma=False)
    )


def distributed_sort(a, axis: int = 0, descending: bool = False) -> Tuple[jax.Array, jax.Array]:
    """
    Sort a split DNDarray along its split axis over the mesh; returns
    ``(values, indices)`` as *physical* (padded, sharded) arrays in the
    canonical layout — pad sentinels take the final slots along the sort axis
    (they carry the maximal key AND the largest global indices, so they rank
    after every valid element, NaN included), valid data the prefix. Indices are
    global positions along the sort axis (argsort semantics).
    """
    comm: MeshCommunication = a.comm
    axis = int(axis) % a.ndim
    dt = np.dtype(a.dtype.jnp_type())
    phys = a.parray
    if dt.kind == "b":
        phys = phys.astype(jnp.uint8)
    key = _sort_key(phys, descending)
    if a.is_padded:
        # pad sentinel in KEY space: the unsigned/int maximum outranks every
        # valid key (for floats the total-order transform caps valid keys below
        # the unsigned maximum, so even NaN stays under the sentinel)
        kdt = np.dtype(key.dtype)
        sentinel = np.iinfo(kdt).max if kdt.kind in "iu" else np.inf
        n = a.shape[axis]
        mask = (jnp.arange(key.shape[axis]) < n).reshape(
            tuple(-1 if d == axis else 1 for d in range(a.ndim))
        )
        key = jnp.where(mask, key, jnp.asarray(sentinel, dtype=key.dtype))
    fn = _build_sort(
        comm.mesh, comm.axis_name, comm.size, tuple(phys.shape), axis, np.dtype(key.dtype).str
    )
    out_k, out_i = fn(key)
    if dt.kind == "f":
        out_v = _unkey(out_k, dt, descending)
    else:
        out_v = _unkey(out_k, out_k.dtype, descending)
    return out_v.astype(dt), out_i


def distributed_sort_1d(a, descending: bool = False) -> Tuple[jax.Array, jax.Array]:
    """1-D convenience wrapper over :func:`distributed_sort` (round-2 API)."""
    return distributed_sort(a, axis=0, descending=descending)


def can_distribute_topk(a, dim: int, k: int) -> bool:
    """
    Whether ``topk`` along ``dim`` takes the distributed path: ``dim`` must be
    the split axis of a key-able distributed array and ``k`` must fit in one
    shard's chunk (each shard's local top-k then provably contains its global
    winners; k > c degenerates to a gather and uses the global formulation).
    """
    if not can_distribute_sort(a, dim):
        return False
    comm: MeshCommunication = a.comm
    c = a.pshape[int(dim) % a.ndim] // comm.size
    return 0 < k <= c


@functools.lru_cache(maxsize=128)
def _build_topk(mesh, axis_name: str, p: int, pshape: Tuple[int, ...], dim: int, k: int, jdtype: str):
    """Compile local-topk + allgather(p*k candidates) + reselect — the
    reference's distributed topk (manipulations.py topk: local torch.topk +
    Allgather + re-select), with only p*k candidates crossing the mesh."""
    c = pshape[dim] // p

    def local(kv):
        km = jnp.moveaxis(kv, dim, -1)  # (..., c)
        lv, lp = jax.lax.top_k(km, k)  # per-shard candidates (keys descending)
        me = jax.lax.axis_index(axis_name)
        gi = (me * c + lp).astype(jnp.int32)
        gv = jax.lax.all_gather(lv, axis_name, axis=km.ndim - 1, tiled=True)  # (..., p*k)
        gidx = jax.lax.all_gather(gi, axis_name, axis=km.ndim - 1, tiled=True)
        fv, fp = jax.lax.top_k(gv, k)  # ties pick the lowest gathered index = lowest shard
        fidx = jnp.take_along_axis(gidx, fp, axis=-1)
        return jnp.moveaxis(fv, -1, dim), jnp.moveaxis(fidx, -1, dim)

    spec = P(*([None] * dim + [axis_name]))
    return jax.jit(
        jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=(P(), P()), check_vma=False)
    )


def distributed_topk(a, dim: int, k: int, largest: bool = True) -> Tuple[jax.Array, jax.Array]:
    """
    The k largest (smallest) elements along the split axis; returns replicated
    ``(values, global_indices)`` with the ``dim`` extent reduced to ``k``,
    values sorted the torch way (descending for largest, ascending for
    smallest). Runs entirely as local-topk + a p*k-candidate allgather.
    """
    comm: MeshCommunication = a.comm
    dim = int(dim) % a.ndim
    dt = np.dtype(a.dtype.jnp_type())
    phys = a.parray
    if dt.kind == "b":
        phys = phys.astype(jnp.uint8)
    # keys make top_k dtype-agnostic: largest=True wants ascending keys (top_k
    # takes the maxima), largest=False complemented keys (minima win)
    key = _sort_key(phys, not largest)
    if a.is_padded:
        # pad sentinel at the key-space MINIMUM: pads always lose; ties between
        # a valid extreme and a pad resolve to the valid one (lower gathered
        # index — pads live in the trailing shards' trailing slots)
        kdt = np.dtype(key.dtype)
        sentinel = np.iinfo(kdt).min if kdt.kind in "iu" else -np.inf
        n = a.shape[dim]
        mask = (jnp.arange(key.shape[dim]) < n).reshape(
            tuple(-1 if d == dim else 1 for d in range(a.ndim))
        )
        key = jnp.where(mask, key, jnp.asarray(sentinel, dtype=key.dtype))
    fn = _build_topk(
        comm.mesh, comm.axis_name, comm.size, tuple(phys.shape), dim, int(k),
        np.dtype(key.dtype).str,
    )
    out_k, out_i = fn(key)
    if dt.kind == "f":
        out_v = _unkey(out_k, dt, not largest)
    else:
        out_v = _unkey(out_k, out_k.dtype, not largest)
    return out_v.astype(dt), out_i
