"""
Distributed sort machinery: exact-rank parallel sort over the mesh.

The reference implements ``sort`` as a parallel sample-sort — local sort, gather
pivots, global pivot select, ``Alltoallv`` exchange, merge (reference
heat/core/manipulations.py:2263-3050) — and distributed selection for
median/percentile (statistics.py:867-1074). Sample-sort's bucket sizes are
data-dependent, which fights XLA's static shapes; the TPU-native redesign keeps
the same structure but computes each element's **exact global rank** so every
exchange has a static shape:

1. local stable sort of each shard's chunk along the split axis;
2. a ring of ``ppermute`` steps (p-1 hops) circulates the sorted chunks; each
   shard counts, per element, how many elements of every other chunk precede it
   — ``searchsorted`` with ``side='right'`` for lower shard ids and ``'left'``
   for higher ones, so ties are broken by (shard, local position) and ranks are
   unique even for constant data;
3. the payload is scattered into an (N, …) buffer at its rank positions and one
   ``psum_scatter`` (reduce-scatter over ICI) delivers to each shard exactly its
   c = N/p slot-ordered output rows — no merge pass needed.

N-D arrays sort along the split axis by flattening the non-split axes into
independent columns of the same machinery (the reference's sample-sort handles
any axis the same way, manipulations.py:2263-2301). 64-bit dtypes ride the same
path under x64: the float total-order transform has a u64 form and integer keys
are width-agnostic.

Pad sentinels (ragged axes) carry the key-space maximum and the largest global
indices, so they take the final ranks and the result lands back in the
canonical padded physical layout.

Exchange memory (round 3, VERDICT r2 #4): the default exchange is a ring
reduce of per-destination-window contributions — at each of p-1 ppermute hops
a device adds its (c, R) scatter-contribution for the block currently passing
by, so the peak live buffer is **O(N/p)** per device at the same communication
volume as a dense reduce-scatter (proven on the compiled multi-chip v5e HLO in
tests/test_hlo_contract.py via AOT compilation, and numerically on the CPU
mesh — the ring is platform-independent). ``jax.lax.ragged_all_to_all`` (the
design round 2's docstring sketched) was built and REJECTED: XLA:TPU lowers a
1-D ragged exchange by padding every element to a 128-lane row
(``s32[c,1,128]`` staging buffers — 128x the payload, measured 1.09 GB vs the
dense path's 43 MB at 4M elements), and XLA:CPU has no thunk for it at all.
The dense scatter + psum_scatter exchange is kept behind
``exchange='dense'`` for A/B testing.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ._compat import shard_map as _shard_map
from .communication import MeshCommunication

__all__ = ["distributed_sort", "distributed_sort_1d", "can_distribute_sort"]


def can_distribute_sort(a, axis: Optional[int] = 0) -> bool:
    """
    Whether sorting ``a`` (a DNDarray) along ``axis`` takes the distributed
    exact-rank path: the axis must be the split axis of a genuinely distributed
    array, with at least one row per device, and the dtype must have a total
    order expressible as integer keys (bool/int of any width; floats at 4 bytes,
    or 8 under x64).
    """
    comm = a.comm
    dt = np.dtype(a.dtype.jnp_type())
    if a.split is None or a.ndim == 0:
        return False
    split = int(a.split) % a.ndim
    if axis is None:
        if a.ndim != 1:
            return False
        axis = 0
    if int(axis) % a.ndim != split:
        return False
    if not (isinstance(comm, MeshCommunication) and comm.is_distributed()):
        return False
    if a.pshape[split] < comm.size:
        return False
    if dt.kind in "biu":
        return True
    if dt.kind == "f":
        return dt.itemsize <= 4 or bool(jax.config.jax_enable_x64)
    return False


def _float_to_key(v: jax.Array, descending: bool) -> jax.Array:
    """
    Map floats to unsigned keys whose unsigned order is a TOTAL order matching
    numpy's sort order: -inf < … < -0 = +0 < … < +inf < NaN (all NaNs
    canonicalized, so negative-payload NaNs don't sort first), with the unsigned
    maximum reserved above everything for the pad sentinel. Descending
    complements the key, which puts NaN first — the order of a flipped
    ascending sort. f32 uses u32 keys; f64 (under x64) the identical u64 form.
    """
    wide = np.dtype(v.dtype).itemsize == 8
    ft, ut = (jnp.float64, jnp.uint64) if wide else (jnp.float32, jnp.uint32)
    bits = 64 if wide else 32
    f = v.astype(ft)
    f = jnp.where(jnp.isnan(f), np.asarray(np.nan, ft), f)  # canonical +NaN bits
    u = jax.lax.bitcast_convert_type(f, ut)
    sign = jnp.asarray(1 << (bits - 1), ut)
    key = jnp.where((u >> (bits - 1)).astype(bool), ~u, u | sign)
    # canonical +NaN maps below the all-ones sentinel: cap just under it
    key = jnp.minimum(key, jnp.asarray(np.iinfo(np.dtype(ut)).max - 1, ut))
    return ~key if descending else key


def _key_to_float(k: jax.Array, dtype, descending: bool) -> jax.Array:
    wide = np.dtype(k.dtype).itemsize == 8
    ft, ut = (jnp.float64, jnp.uint64) if wide else (jnp.float32, jnp.uint32)
    bits = 64 if wide else 32
    if descending:
        k = ~k
    sign = jnp.asarray(1 << (bits - 1), ut)
    u = jnp.where((k >> (bits - 1)).astype(bool), k ^ sign, ~k)
    return jax.lax.bitcast_convert_type(u, ft).astype(dtype)


def _sort_key(v: jax.Array, descending: bool) -> jax.Array:
    """Monotone key so the kernel always sorts ascending. Floats go through the
    total-order bit transform; integers use bitwise NOT for descending (no
    INT_MIN negation overflow)."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        return _float_to_key(v, descending)
    return ~v if descending else v


def _unkey(k: jax.Array, dtype, descending: bool) -> jax.Array:
    if np.dtype(dtype).kind == "f":
        return _key_to_float(k, dtype, descending)
    return ~k if descending else k


@functools.lru_cache(maxsize=128)
def _build_sort(
    mesh, axis_name: str, p: int, pshape: Tuple[int, ...], axis: int, jdtype: str,
    exchange: str = "ring",
):
    """Compile the exact-rank sort for one (mesh, physical shape, sort axis, dtype).
    ``exchange``: 'ring' (default — O(N/p) peak memory) or 'dense' (transient
    full-length scatter buffer + psum_scatter; kept for A/B testing)."""
    n_phys = pshape[axis]
    c = n_phys // p
    rest = tuple(s for d, s in enumerate(pshape) if d != axis)
    R = int(np.prod(rest, dtype=np.int64)) if rest else 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    # column-wise searchsorted over the flattened non-split axes
    _ss_l = jax.vmap(lambda o, s: jnp.searchsorted(o, s, side="left"), in_axes=1, out_axes=1)
    _ss_r = jax.vmap(lambda o, s: jnp.searchsorted(o, s, side="right"), in_axes=1, out_axes=1)

    def local(v):
        vm = jnp.moveaxis(v, axis, 0).reshape(c, R)
        order = jnp.argsort(vm, axis=0, stable=True)  # (c, R)
        sv = jnp.take_along_axis(vm, order, axis=0)
        me = jax.lax.axis_index(axis_name)
        sidx = (me * c + order).astype(jnp.int32)

        def step(carry, _):
            other_v = jax.lax.ppermute(carry[0], axis_name, perm)
            other_id = jax.lax.ppermute(carry[1], axis_name, perm)
            lo = _ss_l(other_v, sv)
            hi = _ss_r(other_v, sv)
            # ties: lower shard ids precede me, higher follow — unique ranks.
            # Accumulated in the carry: stacking per-hop counts as scan outputs
            # would retain a (p-1, c, R) = O(N) buffer
            cnt = carry[2] + jnp.where(other_id < me, hi, lo).astype(jnp.int32)
            return (other_v, other_id, cnt), None

        (_, _, cnts), _ = jax.lax.scan(
            step, (sv, me, jnp.zeros((c, R), jnp.int32)), None, length=p - 1
        )
        rank = jnp.arange(c, dtype=jnp.int32)[:, None] + cnts  # (c, R)
        cols = jnp.arange(R)[None, :]
        back = lambda o: jnp.moveaxis(o.reshape((c,) + rest), 0, axis)

        if exchange == "ring":
            # ring reduce of per-window contributions (the textbook
            # reduce-scatter ring, one (c, R) block in flight per device):
            # at hop t the block for output window b = (me - t - 1) mod p
            # passes by and I add my scatter-contribution for it. Peak live
            # memory O(c·R); total bytes moved match the dense psum_scatter.
            def contrib(b):
                m = (rank >= b * c) & (rank < (b + 1) * c)
                slot = jnp.where(m, rank - b * c, c)  # c = discard row
                cv = jnp.zeros((c + 1, R), sv.dtype).at[slot, cols].set(sv)[:c]
                ci = jnp.zeros((c + 1, R), sidx.dtype).at[slot, cols].set(sidx)[:c]
                return cv, ci

            def hop(carry, t):
                av, ai = carry
                av = jax.lax.ppermute(av, axis_name, perm)
                ai = jax.lax.ppermute(ai, axis_name, perm)
                b = (me - t - 1) % p
                cv, ci = contrib(b)
                return (av + cv, ai + ci), None

            (av, ai), _ = jax.lax.scan(hop, contrib(me), jnp.arange(p - 1))
            # the scan leaves window (me+1) % p here; one hop forward delivers
            # every window to its home device
            out_v = jax.lax.ppermute(av, axis_name, perm)
            out_i = jax.lax.ppermute(ai, axis_name, perm)
            return back(out_v), back(out_i)

        # dense exchange: scatter to rank slots, reduce-scatter my window back
        buf_v = jnp.zeros((n_phys, R), dtype=sv.dtype).at[rank, cols].set(sv)
        buf_i = jnp.zeros((n_phys, R), dtype=jnp.int32).at[rank, cols].set(sidx)
        out_v = jax.lax.psum_scatter(buf_v, axis_name, scatter_dimension=0, tiled=True)
        out_i = jax.lax.psum_scatter(buf_i, axis_name, scatter_dimension=0, tiled=True)
        return back(out_v), back(out_i)

    spec = P(*([None] * axis + [axis_name]))
    return jax.jit(
        _shard_map(local, mesh=mesh, in_specs=spec, out_specs=(spec, spec), check_vma=False)
    )


def distributed_sort(a, axis: int = 0, descending: bool = False) -> Tuple[jax.Array, jax.Array]:
    """
    Sort a split DNDarray along its split axis over the mesh; returns
    ``(values, indices)`` as *physical* (padded, sharded) arrays in the
    canonical layout — pad sentinels take the final slots along the sort axis
    (they carry the maximal key AND the largest global indices, so they rank
    after every valid element, NaN included), valid data the prefix. Indices are
    global positions along the sort axis (argsort semantics).
    """
    comm: MeshCommunication = a.comm
    axis = int(axis) % a.ndim
    dt = np.dtype(a.dtype.jnp_type())
    phys = a.parray
    if dt.kind == "b":
        phys = phys.astype(jnp.uint8)
    key = _sort_key(phys, descending)
    if a.is_padded:
        # pad sentinel in KEY space: the unsigned/int maximum outranks every
        # valid key (for floats the total-order transform caps valid keys below
        # the unsigned maximum, so even NaN stays under the sentinel)
        kdt = np.dtype(key.dtype)
        sentinel = np.iinfo(kdt).max if kdt.kind in "iu" else np.inf
        n = a.shape[axis]
        mask = (jnp.arange(key.shape[axis]) < n).reshape(
            tuple(-1 if d == axis else 1 for d in range(a.ndim))
        )
        key = jnp.where(mask, key, jnp.asarray(sentinel, dtype=key.dtype))
    fn = _build_sort(
        comm.mesh, comm.axis_name, comm.size, tuple(phys.shape), axis, np.dtype(key.dtype).name
    )
    out_k, out_i = fn(key)
    if dt.kind == "f":
        out_v = _unkey(out_k, dt, descending)
    else:
        out_v = _unkey(out_k, out_k.dtype, descending)
    return out_v.astype(dt), out_i


def distributed_sort_1d(a, descending: bool = False) -> Tuple[jax.Array, jax.Array]:
    """1-D convenience wrapper over :func:`distributed_sort` (round-2 API)."""
    return distributed_sort(a, axis=0, descending=descending)


def can_distribute_topk(a, dim: int, k: int) -> bool:
    """
    Whether ``topk`` along ``dim`` takes the distributed path: ``dim`` must be
    the split axis of a key-able distributed array and ``k`` must fit in one
    shard's chunk (each shard's local top-k then provably contains its global
    winners; k > c degenerates to a gather and uses the global formulation).
    """
    if not can_distribute_sort(a, dim):
        return False
    comm: MeshCommunication = a.comm
    c = a.pshape[int(dim) % a.ndim] // comm.size
    return 0 < k <= c


@functools.lru_cache(maxsize=128)
def _build_topk(mesh, axis_name: str, p: int, pshape: Tuple[int, ...], dim: int, k: int, jdtype: str):
    """Compile local-topk + allgather(p*k candidates) + reselect — the
    reference's distributed topk (manipulations.py topk: local torch.topk +
    Allgather + re-select), with only p*k candidates crossing the mesh."""
    c = pshape[dim] // p

    def local(kv):
        km = jnp.moveaxis(kv, dim, -1)  # (..., c)
        lv, lp = jax.lax.top_k(km, k)  # per-shard candidates (keys descending)
        me = jax.lax.axis_index(axis_name)
        gi = (me * c + lp).astype(jnp.int32)
        gv = jax.lax.all_gather(lv, axis_name, axis=km.ndim - 1, tiled=True)  # (..., p*k)
        gidx = jax.lax.all_gather(gi, axis_name, axis=km.ndim - 1, tiled=True)
        fv, fp = jax.lax.top_k(gv, k)  # ties pick the lowest gathered index = lowest shard
        fidx = jnp.take_along_axis(gidx, fp, axis=-1)
        return jnp.moveaxis(fv, -1, dim), jnp.moveaxis(fidx, -1, dim)

    spec = P(*([None] * dim + [axis_name]))
    return jax.jit(
        _shard_map(local, mesh=mesh, in_specs=spec, out_specs=(P(), P()), check_vma=False)
    )


def distributed_topk(a, dim: int, k: int, largest: bool = True) -> Tuple[jax.Array, jax.Array]:
    """
    The k largest (smallest) elements along the split axis; returns replicated
    ``(values, global_indices)`` with the ``dim`` extent reduced to ``k``,
    values sorted the torch way (descending for largest, ascending for
    smallest). Runs entirely as local-topk + a p*k-candidate allgather.
    """
    comm: MeshCommunication = a.comm
    dim = int(dim) % a.ndim
    dt = np.dtype(a.dtype.jnp_type())
    phys = a.parray
    if dt.kind == "b":
        phys = phys.astype(jnp.uint8)
    # keys make top_k dtype-agnostic: largest=True wants ascending keys (top_k
    # takes the maxima), largest=False complemented keys (minima win)
    key = _sort_key(phys, not largest)
    if a.is_padded:
        # pad sentinel at the key-space MINIMUM: pads always lose; ties between
        # a valid extreme and a pad resolve to the valid one (lower gathered
        # index — pads live in the trailing shards' trailing slots)
        kdt = np.dtype(key.dtype)
        sentinel = np.iinfo(kdt).min if kdt.kind in "iu" else -np.inf
        n = a.shape[dim]
        mask = (jnp.arange(key.shape[dim]) < n).reshape(
            tuple(-1 if d == dim else 1 for d in range(a.ndim))
        )
        key = jnp.where(mask, key, jnp.asarray(sentinel, dtype=key.dtype))
    fn = _build_topk(
        comm.mesh, comm.axis_name, comm.size, tuple(phys.shape), dim, int(k),
        np.dtype(key.dtype).name,
    )
    out_k, out_i = fn(key)
    if dt.kind == "f":
        out_v = _unkey(out_k, dt, not largest)
    else:
        out_v = _unkey(out_k, out_k.dtype, not largest)
    return out_v.astype(dt), out_i
