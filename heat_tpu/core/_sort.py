"""
Distributed sort machinery: exact-rank parallel sort over the mesh.

The reference implements ``sort`` as a parallel sample-sort — local sort, gather
pivots, global pivot select, ``Alltoallv`` exchange, merge (reference
heat/core/manipulations.py:2263-3050) — and distributed selection for
median/percentile (statistics.py:867-1074). Sample-sort's bucket sizes are
data-dependent, which fights XLA's static shapes; the TPU-native redesign keeps
the same structure but computes each element's **exact global rank** so every
exchange has a static shape:

1. local stable sort of each shard's chunk;
2. a ring of ``ppermute`` steps (p-1 hops) circulates the sorted chunks; each
   shard counts, per element, how many elements of every other chunk precede it
   — ``searchsorted`` with ``side='right'`` for lower shard ids and ``'left'``
   for higher ones, so ties are broken by (shard, local position) and ranks are
   unique even for constant data;
3. the payload is scattered into an (N, …) buffer at its rank positions and one
   ``psum_scatter`` (reduce-scatter over ICI) delivers to each shard exactly its
   c = N/p slot-ordered output rows — no merge pass needed.

Pad sentinels (ragged axes) carry the dtype's extreme value and the largest
global indices, so they take the final ranks and the result lands back in the
canonical padded physical layout.

Honest cost note: the exchange materialises a transient full-length (N,) scatter
buffer per device and the reduce-scatter moves O(N) bytes per device — compute
and the final layout are fully distributed, peak memory is not (3 transient
N-length buffers). The O(N/p) exchange needs ``ragged_all_to_all`` (each shard's
destination ranks are ascending, so its sends are p contiguous segments), which
XLA:TPU implements but XLA:CPU — the test mesh — has no thunk for; swap the
exchange when deploying sorts at HBM-limit scale.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .communication import MeshCommunication

__all__ = ["distributed_sort_1d", "can_distribute_sort"]


def can_distribute_sort(a) -> bool:
    """Whether ``a`` (a DNDarray) takes the distributed 1-D sort path."""
    comm = a.comm
    dt = np.dtype(a.dtype.jnp_type())
    return (
        a.ndim == 1
        and a.split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
        and a.pshape[0] >= comm.size
        and (dt.kind in "biu" or (dt.kind == "f" and dt.itemsize <= 4))
    )


def _float_to_key(v: jax.Array, descending: bool) -> jax.Array:
    """
    Map floats to uint32 keys whose unsigned order is a TOTAL order matching
    numpy's sort order: -inf < … < -0 = +0 < … < +inf < NaN (all NaNs
    canonicalized, so negative-payload NaNs don't sort first), with uint32-max
    reserved above everything for the pad sentinel. Descending complements the
    key, which puts NaN first — the order of a flipped ascending sort.
    """
    f = v.astype(jnp.float32)
    f = jnp.where(jnp.isnan(f), jnp.float32(np.nan), f)  # canonical +NaN bits
    u = jax.lax.bitcast_convert_type(f, jnp.uint32)
    key = jnp.where(u >> 31, ~u, u | jnp.uint32(0x80000000))
    # canonical +NaN maps to 0xFFC00000 < 0xFFFFFFFE: cap below the sentinel
    key = jnp.minimum(key, jnp.uint32(0xFFFFFFFE))
    return ~key if descending else key


def _key_to_float(k: jax.Array, dtype, descending: bool) -> jax.Array:
    if descending:
        k = ~k
    u = jnp.where(k >> 31, k ^ jnp.uint32(0x80000000), ~k)
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(dtype)


def _sort_key(v: jax.Array, descending: bool) -> jax.Array:
    """Monotone key so the kernel always sorts ascending. Floats go through the
    total-order bit transform; integers use bitwise NOT for descending (no
    INT_MIN negation overflow)."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        return _float_to_key(v, descending)
    return ~v if descending else v


def _unkey(k: jax.Array, dtype, descending: bool) -> jax.Array:
    if np.dtype(dtype).kind == "f":
        return _key_to_float(k, dtype, descending)
    return ~k if descending else k


@functools.lru_cache(maxsize=128)
def _build_sort(mesh, axis: str, p: int, n_phys: int, jdtype: str):
    """Compile the exact-rank sort for one (mesh, physical length, dtype)."""
    c = n_phys // p
    perm = [(i, (i + 1) % p) for i in range(p)]

    def local(v):
        v = v.reshape(c)
        order = jnp.argsort(v, stable=True)
        sv = v[order]
        me = jax.lax.axis_index(axis)
        sidx = (me * c + order).astype(jnp.int32)

        def step(carry, _):
            other_v = jax.lax.ppermute(carry[0], axis, perm)
            other_id = jax.lax.ppermute(carry[1], axis, perm)
            lo = jnp.searchsorted(other_v, sv, side="left")
            hi = jnp.searchsorted(other_v, sv, side="right")
            # ties: lower shard ids precede me, higher follow — unique ranks
            cnt = jnp.where(other_id < me, hi, lo)
            return (other_v, other_id), cnt

        _, cnts = jax.lax.scan(step, (sv, me), None, length=p - 1)
        rank = jnp.arange(c) + cnts.sum(axis=0)

        # exchange: scatter to rank slots, reduce-scatter my window back
        buf_v = jnp.zeros((n_phys,), dtype=sv.dtype).at[rank].set(sv)
        buf_i = jnp.zeros((n_phys,), dtype=jnp.int32).at[rank].set(sidx)
        out_v = jax.lax.psum_scatter(buf_v, axis, scatter_dimension=0, tiled=True)
        out_i = jax.lax.psum_scatter(buf_i, axis, scatter_dimension=0, tiled=True)
        return out_v, out_i

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=P(axis), out_specs=(P(axis), P(axis)), check_vma=False
        )
    )


def distributed_sort_1d(a, descending: bool = False) -> Tuple[jax.Array, jax.Array]:
    """
    Sort a 1-D split DNDarray over the mesh; returns ``(values, indices)`` as
    *physical* (padded, sharded) arrays in the canonical layout — pad sentinels
    take the final slots (they carry the maximal key AND the largest global
    indices, so they rank after every valid element, NaN included), valid data
    the prefix.
    """
    comm: MeshCommunication = a.comm
    dt = np.dtype(a.dtype.jnp_type())
    phys = a.parray
    if dt.kind == "b":
        phys = phys.astype(jnp.uint8)
    key = _sort_key(phys, descending)
    if a.is_padded:
        # pad sentinel in KEY space: the unsigned/int maximum outranks every
        # valid key (for floats the total-order transform caps valid keys below
        # uint32-max, so even NaN stays under the sentinel)
        kdt = np.dtype(key.dtype)
        sentinel = np.iinfo(kdt).max if kdt.kind in "iu" else np.inf
        n = a.shape[0]
        mask = jnp.arange(key.shape[0]) < n
        key = jnp.where(mask, key, jnp.asarray(sentinel, dtype=key.dtype))
    fn = _build_sort(comm.mesh, comm.axis_name, comm.size, phys.shape[0], np.dtype(key.dtype).str)
    out_k, out_i = fn(key)
    out_v = _unkey(out_k, jnp.float32 if dt.kind == "f" else out_k.dtype, descending)
    return out_v.astype(dt), out_i
