"""
Deferred-execution fusion engine for eager elementwise chains.

The NumPy-eager surface dispatches one standalone XLA executable per
``__binary_op``/``__local_op`` call, so a chain of k elementwise ops pays ~2k
full memory round-trips where a single fused kernel pays 2 (the lazy-tensor
technique of torch-xla/LTC and Dask's deferred expression graphs, applied to
the dispatch layer). With ``HEAT_TPU_FUSION=1`` (the default) the hot
templates stop executing elementwise ops immediately and instead record nodes
in a small expression DAG carried by the result :class:`~.dndarray.DNDarray`;
the first *materialization barrier* flushes the pending subgraph through one
jitted fused kernel.

Design (see ``doc/fusion_notes.md`` for the full narrative):

* **Recording.** ``defer_binary``/``defer_local``/``defer_where``/
  ``defer_cast`` accept the exact operand set the eager template would have
  executed and return a deferred ``DNDarray`` (or ``None`` — caller falls back
  to the unchanged eager path). Only whitelisted, shape-preserving jnp
  elementwise callables are recorded; everything else (reductions,
  cumulatives, collectives, ``out=`` writes, shape-changing ops, operands
  traced inside someone else's ``jit``) keeps today's op-at-a-time execution.
  Scalar operands enter the trace as runtime *arguments* with the exact aval
  eager dispatch gives them (Python scalars weak-typed, np scalars strong) so
  XLA cannot constant-fold them (``x / 3.0`` must stay a division, not become
  ``x * (1/3.0)``); the one exception is a static integer exponent of
  ``power``, baked as a constant because eager lowers it via
  ``lax.integer_pow`` at trace time. The eager template's dtype cast-back
  rule is replayed *inside* the trace for the same reason. The single
  remaining numeric difference a fused kernel can exhibit is *excess
  precision*: XLA contracts adjacent multiply→add into an FMA inside one
  kernel (strictly more accurate, one rounding instead of two) — per-op
  results are bit-identical to eager, and the differential suite pins both
  properties.
* **Barriers.** ``DNDarray.parray`` is the single materialization choke
  point: every existing barrier — reductions and cumulatives across the
  templates, collectives, ``.larray``/``.numpy()``/``item()``, printing,
  indexing reads and writes, ``out=`` aliasing, halos, IO, linalg — already
  reads ``parray``/``larray``, so the flush happens exactly where execution
  used to. Writing into a ``DNDarray`` that still carries an unflushed
  expression simply *drops* the dead graph (counted as
  ``fusion.elided_writes`` — deferred work that never had to run).
* **Ragged/padded layouts.** The padded-physical fast path is preserved
  inside fused traces: when every split-axis operand carries the canonical
  padded layout the nodes record the *physical* arrays and the pad rides
  through the fused kernel exactly as it rides through the eager one.
  Asymmetric pad situations (an operand that would need ``pad_physical``,
  ``where=`` over padded operands, ``force_logical`` ops) fall back to eager.
* **Trace cache.** Flushing builds a positional replay program from the
  DAG and compiles it once per ``(graph structure, leaf avals incl.
  weak-type, leaf shardings, donation mask)`` key, held in a bounded LRU
  (``HEAT_TPU_FUSION_CACHE_SIZE``). Steady-state loops (lasso updates,
  statistics pipelines) hit the cache every iteration.
* **Donation.** On accelerator backends, leaf buffers whose owning
  ``DNDarray`` has died (dead intermediates of a rebound chain) and that
  match the fused output's shape/dtype are donated to XLA so the chain runs
  in place. CPU ignores donation; ``HEAT_TPU_FUSION_DONATE=0`` disables it.
* **Bounded graphs.** A chain that grows past ``HEAT_TPU_FUSION_MAX_CHAIN``
  ops without hitting a barrier is flushed at record time, so unbounded
  rebind loops compile a small set of fixed-size kernels instead of one
  kernel per chain length.
* **Escape hatch.** ``HEAT_TPU_FUSION=0`` restores the pre-fusion
  op-at-a-time execution bit for bit (read per dispatch, same pattern as
  ``HEAT_TPU_BLOCKED_LINALG``).

Monitoring: ``fusion.ops_deferred`` (labelled binary/local/where/cast),
``fusion.flushes``/``fusion.kernels_compiled``/``fusion.cache_hits``,
``fusion.elided_writes``, and the ``fusion.chain_length`` histogram, all
through ``monitoring/instrument.py``; :func:`cache_info` reports
entries/hits/misses/evictions of the trace LRU.
"""

from __future__ import annotations

import builtins
import collections
import functools
import os
import sys
import weakref
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..monitoring.registry import STATE as _MON
from ..monitoring import instrument as _instr
from .dndarray import DNDarray

__all__ = [
    "enabled",
    "is_deferred",
    "pending_count",
    "flush",
    "flush_pending",
    "defer_binary",
    "defer_local",
    "defer_where",
    "defer_cast",
    "materialize_for",
    "cache_info",
    "clear_cache",
]


# ------------------------------------------------------------------ gates
def enabled() -> bool:
    """Whether deferred-execution fusion is globally enabled (default on).

    ``HEAT_TPU_FUSION=0`` (or ``false``/``off``) restores the pre-fusion
    op-at-a-time dispatch bit for bit. Read per dispatch, so a mid-process
    flip is honored immediately (pending graphs recorded before the flip
    still flush through the fused path — their results are bit-identical).
    """
    val = os.environ.get("HEAT_TPU_FUSION", "")
    return val.strip().lower() not in ("0", "false", "off")


def _donate_enabled() -> bool:
    val = os.environ.get("HEAT_TPU_FUSION_DONATE", "")
    return val.strip().lower() not in ("0", "false", "off")


def _max_chain() -> int:
    try:
        return int(os.environ.get("HEAT_TPU_FUSION_MAX_CHAIN", "64"))
    except ValueError:
        return 64


def _cache_max() -> int:
    # sized for shape-diverse workloads (test suites, exploratory sessions):
    # a fused CPU/TPU executable is a few hundred KB at most, and an evicted
    # entry costs a full XLA recompile on its next appearance — measured 267
    # evictions across four op-heavy test files at 256 entries
    try:
        return int(os.environ.get("HEAT_TPU_FUSION_CACHE_SIZE", "4096"))
    except ValueError:
        return 4096


# ------------------------------------------------------------------ whitelists
#
# Only elementwise, shape-preserving jnp callables are recordable: the fused
# replay applies them positionally on traced operands, so anything with
# data-dependent shapes, axis semantics, or non-jnp identity falls back to the
# eager template. Matched by object identity — a lambda or partial never
# matches.
_BINARY_NAMES = (
    "add", "subtract", "multiply", "true_divide", "divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "arctan2", "hypot",
    "maximum", "minimum", "copysign", "nextafter", "ldexp", "heaviside",
    "logaddexp", "logaddexp2", "gcd", "lcm",
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "logical_and", "logical_or", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift", "right_shift",
)
_UNARY_NAMES = (
    "abs", "absolute", "negative", "positive", "sign", "signbit", "sqrt",
    "cbrt", "square", "reciprocal", "exp", "exp2", "expm1", "log", "log2",
    "log10", "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "deg2rad",
    "rad2deg", "degrees", "radians", "floor", "ceil", "trunc", "rint",
    "round", "clip", "isnan", "isinf", "isfinite", "isneginf", "isposinf",
    "logical_not", "invert", "bitwise_not", "conj", "conjugate", "real",
    "imag", "angle", "i0", "sinc",
)

ELEMENTWISE_BINARY = frozenset(
    getattr(jnp, n) for n in _BINARY_NAMES if hasattr(jnp, n)
)
ELEMENTWISE_UNARY = frozenset(
    getattr(jnp, n) for n in _UNARY_NAMES if hasattr(jnp, n)
)

#: jnp comparison ops whose bool result the eager template deliberately does
#: NOT cast back to the promoted dtype (see ``__binary_op``).
_EQ_NE = (jnp.equal, jnp.not_equal)

#: ops that make trace-time lowering decisions from a *static* scalar operand
#: (integer exponents -> lax.integer_pow); their int scalars are baked as
#: constants so the fused trace lowers identically to the eager dispatch.
_STATIC_SCALAR_OPS = frozenset(
    op for op in (getattr(jnp, "power", None), getattr(jnp, "float_power", None)) if op is not None
)

_SCALARS = (
    builtins.int, builtins.float, builtins.bool, builtins.complex,
    np.number, np.bool_,
)


def _static_kwargs(kw: dict) -> bool:
    """Whether every kwarg value can be baked into a trace / cache key."""
    return all(
        v is None or isinstance(v, (builtins.int, builtins.float, builtins.bool, str, np.number, np.bool_))
        for v in kw.values()
    )


# ------------------------------------------------------------------ graph
class _Leaf:
    """A concrete array input of a pending graph.

    ``owner`` is a weakref to the ``DNDarray`` the array was taken from (used
    for the donation liveness check); ``None`` for raw numpy/jax operands.
    """

    __slots__ = ("array", "owner")

    def __init__(self, array, owner=None):
        self.array = array
        self.owner = owner


class _Node:
    """One recorded elementwise op of the expression DAG.

    ``args`` holds ``_Node`` / ``_Leaf`` / baked scalar constants in
    positional order. ``op_key`` is the structural identity used in trace
    cache keys (op name + process-stable object id, plus any baked
    parameters). ``cast`` replays the eager binary template's dtype cast-back:
    ``(promoted_np_dtype, is_eq_ne)`` or ``None``. ``value`` is filled when
    the owning array materializes, turning the node into a leaf for any other
    pending graph that references it.
    """

    __slots__ = ("fn", "op_key", "args", "kwargs", "cast", "aval", "nops", "value", "owner", "rc")

    def __init__(self, fn, op_key, args, kwargs, cast, aval):
        self.fn = fn
        self.op_key = op_key
        self.args = args
        self.kwargs = kwargs  # tuple(sorted(items)) — hashable
        self.cast = cast
        self.aval = aval
        self.value = None
        self.owner = None
        self.rc = 0  # how many recorded parents reference this node
        n = 1
        for a in args:
            if isinstance(a, _Node) and a.value is None:
                n += a.nops
                a.rc += 1
        self.nops = n  # DAG overcount is fine: only used for the flush bound


#: Live deferred DNDarrays (weak, id-keyed — DNDarray is unhashable by
#: design): the monitoring-export / global barrier set.
_PENDING: dict = {}


def _register_pending(d: "DNDarray") -> None:
    key = id(d)
    _PENDING[key] = weakref.ref(d, lambda _r, _k=key: _PENDING.pop(_k, None))


def is_deferred(x) -> bool:
    """Whether ``x`` is a DNDarray carrying an unmaterialized expression."""
    return isinstance(x, DNDarray) and x._expr() is not None


def _pending_arrays():
    out = []
    for ref in list(_PENDING.values()):
        d = ref()
        if d is not None and d._expr() is not None:
            out.append(d)
    return out


def pending_count() -> int:
    """Number of live DNDarrays with unflushed expressions."""
    return len(_pending_arrays())


def flush(x: DNDarray) -> DNDarray:
    """Materialize ``x``'s pending expression (no-op when concrete)."""
    x.parray  # noqa: B018 — property access is the materialization point
    return x


def flush_pending() -> int:
    """Materialize every live pending graph (the monitoring-export barrier:
    exported counters then account for all recorded work). Returns the number
    of arrays flushed."""
    n = 0
    for d in _pending_arrays():
        d.parray  # noqa: B018
        n += 1
    return n


# ------------------------------------------------------------------ recording
def _op_key(fn) -> tuple:
    return (getattr(fn, "__name__", repr(fn)), id(fn))


def _usable_leaf(arr) -> bool:
    """A concrete array can enter a graph — anything but a tracer (recording
    inside someone else's jit must stay eager)."""
    return not isinstance(arr, jax.core.Tracer)


def _input_of(t: DNDarray):
    """The graph input standing for ``t``'s physical array: its pending node,
    or a ``_Leaf`` over its (concrete) ``parray``. Returns None if unusable."""
    node = t._expr()
    if node is not None:
        return node if node.value is None else _Leaf(node.value, node.owner)
    arr = t.parray
    if not _usable_leaf(arr):
        return None
    return _Leaf(arr, weakref.ref(t))


def _aval_in(x):
    if isinstance(x, _Node):
        return x.aval
    return jax.ShapeDtypeStruct(
        x.array.shape, x.array.dtype, weak_type=bool(getattr(x.array, "weak_type", False))
    )


@functools.lru_cache(maxsize=4096)
def _eval_node_cached(op_key, tmpl, kwargs, cast, avals):
    """Abstract-eval one op (with its cast-back rule) once per structural
    signature; repeated chain steps cost a dict hit instead of a trace."""
    del op_key  # identity is carried by tmpl[0]'s fn via closure below

    def f(*xs):
        it = iter(xs)
        args = [next(it) if a is _SLOT else a[2] for a in tmpl[1]]
        return _apply(tmpl[0], args, dict(kwargs), cast)

    return jax.eval_shape(f, *avals)


_SLOT = object()  # placeholder marking tracer positions in baked arg templates


def _const_key(a):
    """Cache-key form of a baked scalar constant. The *type* is part of the
    key: a Python ``2.0`` (weakly typed in jax promotion) and an
    ``np.float64(2.0)`` (strong) hash/compare equal but trace differently."""
    return ("c", type(a), a)


def _apply(fn, args, kwargs, cast):
    """Apply one recorded op exactly as the eager template would have,
    including the binary dtype cast-back (run on traced values so weak-type
    promotion is bit-identical)."""
    r = fn(*args, **kwargs)
    if cast is not None:
        promoted, is_eq_ne = cast
        if r.dtype != promoted and np.dtype(r.dtype).kind != "b" and not is_eq_ne:
            r = r.astype(promoted)
    return r


def _eval_node(fn, op_key, args, kwargs, cast):
    """Predicted output aval of a node (shape + dtype; weak leaves were
    refused so the strong-type abstract eval matches the eager result)."""
    tmpl = (fn, tuple(_SLOT if isinstance(a, (_Node, _Leaf)) else _const_key(a) for a in args))
    avals = tuple(_aval_in(a) for a in args if isinstance(a, (_Node, _Leaf)))
    try:
        return _eval_node_cached(op_key, tmpl, kwargs, cast, avals)
    except TypeError:  # unhashable template entry — eval uncached
        def f(*xs):
            it = iter(xs)
            real = [next(it) if isinstance(a, (_Node, _Leaf)) else a for a in args]
            return _apply(fn, real, dict(kwargs), cast)

        return jax.eval_shape(f, *avals)


def _finish(node: _Node, gshape, dtype, split, device, comm, kind: str) -> DNDarray:
    """Wrap a freshly recorded node in a deferred DNDarray, register it, and
    enforce the chain-length bound."""
    d = DNDarray._deferred(node, gshape, tuple(node.aval.shape), dtype, split, device, comm)
    node.owner = weakref.ref(d)
    _register_pending(d)
    if _MON.enabled:
        _instr.fusion_defer(kind)
    if node.nops >= _max_chain():
        # flush at record time: unbounded rebind loops then compile a small
        # set of fixed-size fused kernels instead of one per chain length
        d.parray  # noqa: B018
    return d


def defer_binary(
    operation,
    ops_in,
    promoted,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    device,
    comm,
    where,
    fn_kwargs: dict,
) -> Optional[DNDarray]:
    """Record one eager ``__binary_op`` dispatch as a graph node.

    ``ops_in`` is the template's normalized operand list — ``('d', DNDarray)``
    / ``('s', scalar)`` / ``('a', jnp array)`` — exactly what the eager path
    would execute on. Returns the deferred result, or None to fall back.
    """
    from .types import canonical_heat_type

    if operation not in ELEMENTWISE_BINARY:
        return None
    if fn_kwargs and not _static_kwargs(fn_kwargs):
        return None
    if isinstance(where, _SCALARS) and not isinstance(where, (builtins.bool, np.bool_)):
        return None

    dnds = [t for k, t in ops_in if k == "d"]
    padded = [t for t in dnds if t.is_padded]
    phys = False
    if padded:
        # mirror of the eager padded-physical fast path, restricted to the
        # symmetric cases; anything needing pad_physical / logical slicing
        # inside the trace falls back to eager
        if out_split is None or where is not None:
            return None
        for k, t in ops_in:
            if k == "s":
                continue
            shp = tuple(t.shape)
            ndim_t = len(shp)
            ax_t = ndim_t - (len(out_shape) - out_split)
            if ax_t < 0 or ndim_t == 0 or shp[ax_t] == 1:
                if k == "d" and t.is_padded:
                    return None  # its contribution would be a logical slice
            elif (
                k == "d"
                and t.split is not None
                and int(t.split) % ndim_t == ax_t
                and shp[ax_t] == out_shape[out_split]
                and t.comm is comm
            ):
                phys = True
            else:
                return None
        if not phys:
            return None

    # collect graph inputs (no materialization happens here)
    args = []
    for k, t in ops_in:
        if k == "d":
            inp = _input_of(t)
            if inp is None:
                return None
            args.append(inp)
        elif k == "s":
            if operation in _STATIC_SCALAR_OPS and isinstance(
                t, (builtins.int, np.integer)
            ) and not isinstance(t, (builtins.bool, np.bool_)):
                # jnp.power inspects a STATIC integer exponent at trace time
                # and lowers to integer_pow (repeated squaring) — exactly what
                # the eager dispatch does. Baked as a constant so the fused
                # trace takes the same lowering; the value is part of the
                # trace-cache key.
                args.append(t)
            else:
                # a scalar enters the trace as a runtime ARGUMENT with the
                # exact aval eager dispatch gives it (Python scalars
                # weak-typed, np scalars strong) — never as a baked constant,
                # which XLA would fold (x / 3.0 -> x * (1/3.0)) and break
                # bit-for-bit parity with the op-at-a-time path
                args.append(_Leaf(jnp.asarray(t)))
        else:  # raw jnp array operand
            if not _usable_leaf(t):
                return None
            args.append(_Leaf(t))

    kwargs = tuple(sorted(fn_kwargs.items()))
    cast = (np.dtype(promoted.jnp_type()), operation in _EQ_NE)
    okey = ("binary", _op_key(operation), kwargs, (str(cast[0]), cast[1]))
    try:
        aval = _eval_node(operation, okey, args, kwargs, cast)
    except Exception:
        return None  # abstract eval rejected the combination: eager handles
    node = _Node(operation, okey, tuple(args), kwargs, cast, aval)

    if where is not None:
        w_in = None
        if isinstance(where, DNDarray):
            if where.is_padded:
                return None
            w_in = _input_of(where)
        elif isinstance(where, (builtins.bool, np.bool_)):
            w_in = _Leaf(jnp.asarray(where))
        else:
            w = jnp.asarray(where)
            if not _usable_leaf(w):
                return None
            w_in = _Leaf(w)
        if w_in is None:
            return None
        node = _where_glue(w_in, node, out_shape)
        if node is None:
            return None

    # expected physical layout of the result: the broadcast the trace
    # computes must BE the canonical padded layout (eager parity — the
    # eager result is either logical or canonically padded)
    expected = tuple(out_shape)
    if phys:
        expected = comm.padded_shape(out_shape, out_split)
    if tuple(node.aval.shape) != expected:
        return None

    res_dtype = canonical_heat_type(node.aval.dtype)
    return _finish(node, tuple(out_shape), res_dtype, out_split, device, comm, "binary")


def _where_fn_for(shape: Tuple[int, ...]):
    """Canonical glue callable replaying the eager ``where=`` select
    (``jnp.where(w, r, zeros(out_shape, r.dtype))``), memoized per shape so
    node keys and eval caches see one object per shape."""
    fn = _WHERE_FNS.get(shape)
    if fn is None:
        def fn(w, r, _shape=shape):
            return jnp.where(w, r, jnp.zeros(_shape, dtype=r.dtype))

        _WHERE_FNS[shape] = fn
    return fn


_WHERE_FNS: dict = {}


def _where_glue(w_in, op_node: _Node, out_shape) -> Optional[_Node]:
    shape = tuple(out_shape)
    fn = _where_fn_for(shape)
    okey = ("where_glue", shape)
    args = (w_in, op_node)
    try:
        aval = _eval_node(fn, okey, args, (), None)
    except Exception:
        return None
    return _Node(fn, okey, args, (), None, aval)


def defer_local(operation, x: DNDarray, kwargs: dict, force_logical: bool) -> Optional[DNDarray]:
    """Record one eager ``__local_op`` dispatch (elementwise unary on the
    physical array). Returns the deferred result, or None to fall back."""
    from .types import canonical_heat_type

    if operation not in ELEMENTWISE_UNARY:
        return None
    if kwargs and not _static_kwargs(kwargs):
        return None
    if force_logical and x.is_padded:
        return None
    inp = _input_of(x)
    if inp is None:
        return None
    kw = tuple(sorted(kwargs.items()))
    okey = ("local", _op_key(operation), kw)
    try:
        aval = _eval_node(operation, okey, (inp,), kw, None)
    except Exception:
        return None
    if tuple(aval.shape) != tuple(x.pshape):
        return None  # shape-changing call (e.g. degenerate clip): eager handles
    node = _Node(operation, okey, (inp,), kw, None, aval)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish(node, tuple(x.shape), res_dtype, x.split, x.device, x.comm, "local")


def defer_where(cond: DNDarray, x, y) -> Optional[DNDarray]:
    """Record a 3-argument ``ht.where`` select as one elementwise node
    (operands may themselves be pending). Returns None to fall back."""
    from .types import canonical_heat_type

    args = []
    for t in (cond, x, y):
        if isinstance(t, DNDarray):
            if t.is_padded:
                return None
            inp = _input_of(t)
            if inp is None:
                return None
            args.append(inp)
        elif isinstance(t, _SCALARS):
            args.append(_Leaf(jnp.asarray(t)))  # runtime arg: see defer_binary
        else:
            a = jnp.asarray(t)
            if not _usable_leaf(a):
                return None
            args.append(_Leaf(a))
    okey = ("where", _op_key(jnp.where))
    try:
        aval = _eval_node(jnp.where, okey, tuple(args), (), None)
    except Exception:
        return None
    split = cond.split
    if split is not None and len(aval.shape) != cond.ndim:
        split = None
    node = _Node(jnp.where, okey, tuple(args), (), None, aval)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish(
        node, tuple(aval.shape), res_dtype, split, cond.device, cond.comm, "where"
    )


def _cast_fn_for(np_dtype):
    fn = _CAST_FNS.get(np_dtype)
    if fn is None:
        def fn(a, _dt=np_dtype):
            return a.astype(_dt)

        _CAST_FNS[np_dtype] = fn
    return fn


_CAST_FNS: dict = {}


def defer_cast(x: DNDarray, heat_dtype) -> Optional[DNDarray]:
    """Record ``astype`` glue (``x.parray.astype(dtype)``) as a graph node so
    a cast inside a chain fuses instead of materializing. None = fall back."""
    dt = np.dtype(heat_dtype.jnp_type())
    inp = _input_of(x)
    if inp is None:
        return None
    fn = _cast_fn_for(dt)
    okey = ("cast", str(dt))
    aval = jax.ShapeDtypeStruct(tuple(x.pshape), dt)
    node = _Node(fn, okey, (inp,), (), None, aval)
    return _finish(node, tuple(x.shape), heat_dtype, x.split, x.device, x.comm, "cast")


# ------------------------------------------------------------------ flush
_TRACE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_cache_stats = {"hits": 0, "misses": 0, "evictions": 0}


def cache_info() -> dict:
    """Trace-cache statistics (entries/hits/misses/evictions)."""
    return {"entries": len(_TRACE_CACHE), **_cache_stats}


def clear_cache() -> None:
    """Drop every cached fused executable (kept traces are re-built lazily)."""
    _TRACE_CACHE.clear()


def _topo(root: _Node):
    """Post-order of the pending (value-less) subgraph under ``root``."""
    order, seen = [], set()
    stack = [(root, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for a in node.args:
            if isinstance(a, _Node) and a.value is None and id(a) not in seen:
                stack.append((a, False))
    return order


def _donatable(arr, owner_ref, out_aval) -> bool:
    """A leaf buffer may be donated to the fused call iff its owning DNDarray
    is dead, nothing else references the buffer (strict refcount bound), the
    backend actually implements donation, and the buffer aliases the output
    (same shape/dtype) so XLA can reuse it in place. The caller additionally
    verifies the flushed subgraph is *private* — no node in it is referenced
    by another live pending graph that could replay from the same leaves."""
    if owner_ref is not None and owner_ref() is not None:
        return False
    if tuple(arr.shape) != tuple(out_aval.shape) or arr.dtype != out_aval.dtype:
        return False
    try:
        platform = next(iter(arr.devices())).platform
    except Exception:
        return False
    if platform not in ("tpu", "gpu", "cuda", "rocm"):
        return False
    # exactly: leaf_arrays slot + the _Leaf.array slot + the caller's local +
    # getrefcount's argument = 4. One more means another live reference — a
    # second graph's leaf, a user-held .larray, a node.value field — and the
    # buffer must survive this call.
    return sys.getrefcount(arr) <= 4


def materialize_for(d: DNDarray):
    """Flush the pending subgraph behind ``d`` through one fused, cached,
    jitted kernel and return the canonical (placed) physical array."""
    from .communication import MeshCommunication

    root = d._expr()
    if root is None:  # pragma: no cover — callers check
        raise RuntimeError("materialize_for() on a concrete DNDarray")
    if root.value is not None:
        return root.value

    topo = _topo(root)
    index_of = {id(n): i for i, n in enumerate(topo)}

    leaf_ids: dict = {}
    leaf_arrays: list = []
    leaf_owners: list = []

    def leaf_index(arr, owner):
        key = id(arr)
        i = leaf_ids.get(key)
        if i is None:
            i = len(leaf_arrays)
            leaf_ids[key] = i
            leaf_arrays.append(arr)
            leaf_owners.append(owner)
        return i

    program = []  # (fn, specs, kwargs, cast) per node, positional
    key_prog = []
    internal_rc: dict = {}
    for n in topo:
        specs = []
        key_specs = []
        for a in n.args:
            if isinstance(a, _Node):
                if a.value is not None:
                    i = leaf_index(a.value, a.owner)
                    specs.append(("l", i))
                    key_specs.append(("l", i))
                else:
                    internal_rc[id(a)] = internal_rc.get(id(a), 0) + 1
                    specs.append(("n", index_of[id(a)]))
                    key_specs.append(("n", index_of[id(a)]))
            elif isinstance(a, _Leaf):
                i = leaf_index(a.array, a.owner)
                specs.append(("l", i))
                key_specs.append(("l", i))
            else:
                specs.append(("c", a))
                key_specs.append(_const_key(a))
        program.append((n.fn, tuple(specs), dict(n.kwargs), n.cast))
        cast_key = None if n.cast is None else (str(n.cast[0]), n.cast[1])
        key_prog.append((n.op_key, tuple(key_specs), n.kwargs, cast_key))

    out_aval = root.aval
    donate = ()
    if _donate_enabled():
        # donation is only safe when this subgraph is private: every non-root
        # node's recorded parents all sit inside the subgraph, so no other
        # live pending graph can ever replay these nodes from their leaves
        private = all(
            n is root or n.rc == internal_rc.get(id(n), 0) for n in topo
        )
        if private:
            donate_idx = []
            for i in range(len(leaf_arrays)):
                arr = leaf_arrays[i]
                if _donatable(arr, leaf_owners[i], out_aval):
                    donate_idx.append(i)
                del arr
            donate = tuple(donate_idx)

    leaf_key = tuple(
        (
            tuple(a.shape),
            str(a.dtype),
            bool(getattr(a, "weak_type", False)),
            getattr(a, "sharding", None),
        )
        for a in leaf_arrays
    )
    try:
        key = (tuple(key_prog), leaf_key, donate)
        fused = _TRACE_CACHE.get(key)
    except TypeError:  # unhashable sharding — compile uncached
        key, fused = None, None

    compiled = fused is None
    if fused is None:
        prog = tuple(program)

        def replay(*leaves):
            vals = []
            for fn, specs, kw, cast in prog:
                args = [
                    vals[i] if tag == "n" else (leaves[i] if tag == "l" else i)
                    for tag, i in specs
                ]
                vals.append(_apply(fn, args, kw, cast))
            return vals[-1]

        fused = jax.jit(replay, donate_argnums=donate)
        if key is not None:
            _TRACE_CACHE[key] = fused
            _cache_stats["misses"] += 1
            limit = _cache_max()
            while len(_TRACE_CACHE) > limit:
                _TRACE_CACHE.popitem(last=False)
                _cache_stats["evictions"] += 1
    else:
        _TRACE_CACHE.move_to_end(key)
        _cache_stats["hits"] += 1

    if _MON.enabled:
        _instr.fusion_flush(len(topo), cache_hit=not compiled, compiled=compiled)

    value = fused(*leaf_arrays)

    # canonical placement — the step DNDarray.__init__ applies to every eager
    # intermediate, applied once per fused chain here
    split = d.split
    comm = d.comm
    if (
        split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    ):
        value = comm.placed(value, split, d.shape)
    root.value = value
    return value
