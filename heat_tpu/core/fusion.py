"""
Deferred-execution fusion engine for eager elementwise chains.

The NumPy-eager surface dispatches one standalone XLA executable per
``__binary_op``/``__local_op`` call, so a chain of k elementwise ops pays ~2k
full memory round-trips where a single fused kernel pays 2 (the lazy-tensor
technique of torch-xla/LTC and Dask's deferred expression graphs, applied to
the dispatch layer). With ``HEAT_TPU_FUSION=1`` (the default) the hot
templates stop executing elementwise ops immediately and instead record nodes
in a small expression DAG carried by the result :class:`~.dndarray.DNDarray`;
the first *materialization barrier* flushes the pending subgraph through one
jitted fused kernel.

Design (see ``doc/fusion_notes.md`` for the full narrative):

* **Recording.** ``defer_binary``/``defer_local``/``defer_where``/
  ``defer_cast`` accept the exact operand set the eager template would have
  executed and return a deferred ``DNDarray`` (or ``None`` — caller falls back
  to the unchanged eager path). Only whitelisted, shape-preserving jnp
  elementwise callables are recorded here; structural ops and GEMMs have
  their own node kinds (see below), and everything else (collectives,
  ``out=`` writes, data-dependent-shape ops, operands traced inside someone
  else's ``jit``) keeps today's op-at-a-time execution.
  Scalar operands enter the trace as runtime *arguments* with the exact aval
  eager dispatch gives them (Python scalars weak-typed, np scalars strong) so
  XLA cannot constant-fold them (``x / 3.0`` must stay a division, not become
  ``x * (1/3.0)``); the one exception is a static integer exponent of
  ``power``, baked as a constant because eager lowers it via
  ``lax.integer_pow`` at trace time. The eager template's dtype cast-back
  rule is replayed *inside* the trace for the same reason. The single
  remaining numeric difference a fused kernel can exhibit is *excess
  precision*: XLA contracts adjacent multiply→add into an FMA inside one
  kernel (strictly more accurate, one rounding instead of two) — per-op
  results are bit-identical to eager, and the differential suite pins both
  properties.
* **Barriers.** ``DNDarray.parray`` is the single materialization choke
  point: every existing barrier — reductions and cumulatives across the
  templates, collectives, ``.larray``/``.numpy()``/``item()``, printing,
  indexing reads and writes, ``out=`` aliasing, halos, IO, linalg — already
  reads ``parray``/``larray``, so the flush happens exactly where execution
  used to. Writing into a ``DNDarray`` that still carries an unflushed
  expression simply *drops* the dead graph (counted as
  ``fusion.elided_writes`` — deferred work that never had to run).
* **Ragged/padded layouts.** The padded-physical fast path is preserved
  inside fused traces: when every split-axis operand carries the canonical
  padded layout the nodes record the *physical* arrays and the pad rides
  through the fused kernel exactly as it rides through the eager one.
  Asymmetric pad situations (an operand that would need ``pad_physical``,
  ``where=`` over padded operands, ``force_logical`` ops) fall back to eager.
* **Trace cache.** Flushing builds a positional replay program from the
  DAG and compiles it once per ``(graph structure, leaf avals incl.
  weak-type, leaf shardings, donation mask)`` key, held in a bounded LRU
  (``HEAT_TPU_FUSION_CACHE_SIZE``). Steady-state loops (lasso updates,
  statistics pipelines) hit the cache every iteration.
* **Donation.** On accelerator backends, leaf buffers whose owning
  ``DNDarray`` has died (dead intermediates of a rebound chain) and that
  match the fused output's shape/dtype are donated to XLA so the chain runs
  in place. CPU ignores donation; ``HEAT_TPU_FUSION_DONATE=0`` disables it.
* **Bounded graphs.** A chain that grows past ``HEAT_TPU_FUSION_MAX_CHAIN``
  ops without hitting a barrier is flushed at record time, so unbounded
  rebind loops compile a small set of fixed-size kernels instead of one
  kernel per chain length.
* **View nodes.** Structural ops — ``transpose``, ``broadcast_to``,
  ``expand_dims``/``squeeze``, ``flip``/``fliplr``/``flipud``, basic-slice
  ``__getitem__`` reads, and split-preserving ``reshape``/``flatten`` — over a
  *pending* chain record a view ``_Node`` instead of flushing it: the data
  movement happens in-register inside the fused kernel, so a mid-chain
  transpose or strided read costs zero extra HBM passes. Each node carries its
  own split-axis remapping and padded-ragged rule: pad either rides through
  unchanged (transpose and friends keep the pad at the end of the remapped
  split axis), or the node re-establishes the canonical padded layout in-trace
  (a split-axis slice pads its ragged result with zeros — pad content is
  unspecified by contract). The cases where neither rule applies — an
  asymmetric pad situation (flip/squeeze/reshape across a padded split axis)
  or a stepped split-axis slice — keep today's eager fallback, counted in
  ``fusion.view_fallbacks``. ``HEAT_TPU_FUSION_VIEWS=0`` (read per dispatch)
  restores views-as-barriers bit for bit.
* **GEMM producers.** ``linalg.matmul``/``dot`` (``@``) record a *producer*
  ``_Node`` over pending or concrete operands at the declared ``precision``
  instead of dispatching a standalone GEMM: downstream bias-add / activation /
  cast chains then flush with it as ONE XLA program, and XLA fuses the
  epilogue into the MXU GEMM (a loss epilogue additionally rides the
  reduction sinks below — ``act(x @ w + b)`` → ``mean`` is one kernel).
  Sub-32-bit float GEMMs fall back (same excess-precision reasoning as
  ``_low_float`` sinks: a fused epilogue could legally read the f32
  accumulator before the bf16 output rounding), as do padded operands (the
  eager path contracts the sliced logical view — an in-trace pad slice would
  reassociate the ragged shards' partial products). ``HEAT_TPU_FUSION_GEMM=0``
  (read per dispatch) restores GEMMs-as-barriers bit for bit.
* **Collective nodes.** Resharding (``resplit_``/``redistribute_``), the halo
  ppermute exchange (``get_halo``), the ring chunk shift
  (``communication.shift``) and the DNDarray ``Alltoall`` re-chunk record a
  *collective* ``_Node`` over a pending chain instead of flushing it: the
  split-axis chain, the cross-device transfer, and the *next* chain compile
  as ONE shard_map program, letting XLA overlap the ICI collective with the
  elementwise compute (ROADMAP item 1). Each callable replays the exact
  eager dispatch in-trace — resplit drops the old axis's pad and
  re-establishes the new axis's canonical pad around a
  ``with_sharding_constraint``; halo zero-fills the pad slabs like the eager
  ``filled(0)``; shift/alltoall inline the named collective's cached
  shard_map program — with the mesh/axis-name/split metadata in the node key
  (and therefore the trace-LRU key). Inexpressible pad motion takes the
  counted eager fallback ``fusion.collective_fallbacks``. Library consumers
  whose program is itself a shard_map pipeline trace the pending chain INTO
  their program via :func:`flush_through` (the TSQR merge).
  ``HEAT_TPU_FUSION_COLLECTIVES=0`` (read per dispatch) restores the
  flush-barrier behavior bit for bit.
* **Reduction sinks.** Reductions, cumulatives, moments and norms are *sinks*
  of the pending DAG rather than flush triggers: ``__reduce_op``/``__cum_op``
  (and the statistics/linalg epilogue routes) record a sink ``_Node`` whose
  callable replays the exact eager reduction — operand prep (pad fill with the
  op's neutral element, or the logical slice), the reduction itself with its
  axis/keepdims/``where=``/``initial`` arguments, and the split-axis NaN
  re-assertion — so the elementwise subgraph, the reduction, and the sharded
  cross-device combine (XLA's psum over the leaf shardings) land in **one**
  XLA program. The sink result is itself a deferred ``DNDarray``, so
  post-reduction scalar epilogues (``mean``'s ``/n``, ``norm``'s ``sqrt``, a
  user's ``loss * scale``) re-root a new pending chain at the sink and fuse
  too. The chain the sink consumed stays pending (and replayable) — a sink
  reads it in-register without ever writing the intermediate to HBM.
  ``HEAT_TPU_FUSION_SINKS=0`` keeps fusion on but restores
  reductions-as-barriers bit for bit.
* **Escape hatch.** ``HEAT_TPU_FUSION=0`` restores the pre-fusion
  op-at-a-time execution bit for bit (read per dispatch, same pattern as
  ``HEAT_TPU_BLOCKED_LINALG``).
* **Recovery ladder.** A fused flush executes arbitrarily far from the ops
  that recorded it, so a compile error or RESOURCE_EXHAUSTED inside the flush
  must never surface as a raw crash at some unrelated materialization point.
  The deferred design makes the strong guarantee cheap: the expression DAG is
  *retained* at flush time, so any failure can always be replayed. The ladder
  (``_flush_ladder``): (1) run the fused kernel; on failure — classified
  compile / oom / runtime under ``fusion.flush_failures`` — (2) retry once
  with buffer donation disabled (an aliased in-place kernel is the riskier
  allocation plan; skipped when nothing was donated), then (3) fall back to
  per-op eager replay of the retained DAG, which is bit-identical to
  ``HEAT_TPU_FUSION=0`` by construction (same ops, same order, no fused
  kernel to contract FMAs in). A flush that recovers counts
  ``fusion.flush_recovered``; a signature that needed eager replay is
  *poisoned* (``fusion.poisoned_signatures``, capped set, cleared with
  :func:`clear_cache`): subsequent identical chains skip straight to eager
  replay — a circuit breaker, not a retry tax, for known-bad kernels.
  Deterministic fault injection for all of this rides the
  ``fusion.compile``/``fusion.execute`` sites of
  :mod:`heat_tpu.robustness.faultinject`.
* **Shadow-replay audit.** Exceptions are not the only failure mode: silent
  data corruption inside a fused kernel produces a wrong *value* nothing
  re-checks. With ``HEAT_TPU_AUDIT_RATE=N`` every Nth fused flush also runs
  the retained per-op eager replay (the ladder's rung-3 program) and
  compares outputs under the documented carve-out tolerances
  (:mod:`heat_tpu.robustness.integrity`); a mismatch counts
  ``robustness.integrity{mismatch}``, poisons the signature, evicts the L1
  executable and quarantines the L2 entry, then raises ``IntegrityError``
  or serves the trusted eager value per ``HEAT_TPU_AUDIT_ACTION``
  (``raise``/``degrade``, default degrade). The value-level fault site
  ``faultinject.corrupt("fusion.execute", ...)`` is the seeded adversary
  the audit is proven against. Off by default (one env read per flush).

Monitoring: ``fusion.ops_deferred`` (labelled binary/local/where/cast/view/
gemm/collective), ``fusion.reduction_sinks`` (labelled reduce/cum/moment/
norm/vecdot), ``fusion.view_fallbacks`` (labelled asymmetric-pad/
stepped-split-slice), ``fusion.collective_fallbacks`` (labelled
tracer-operand/abstract-eval/layout/padded-operand — collectives over
pending chains that had to take the flushing eager path),
``fusion.flushes``/``fusion.kernels_compiled``/``fusion.cache_hits``,
``fusion.flush_reason`` (labelled reduction/cumulative/print/indexing/io/
collective/out-alias/export/chain-bound/linalg/other — *why* each chain
broke), ``fusion.elided_writes``, the recovery-ladder counters
``fusion.flush_failures{compile,oom,runtime}`` / ``fusion.flush_recovered`` /
``fusion.poisoned_signatures``, and the ``fusion.chain_length`` histogram,
all through ``monitoring/instrument.py``; :func:`cache_info` reports
entries/hits/misses/evictions of the trace LRU plus the poisoned-signature
count.
"""

from __future__ import annotations

import builtins
import collections
import functools
import os
import sys
import threading
import time
import weakref
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..monitoring.registry import STATE as _MON
from ..monitoring import flight as _FL
from ..monitoring import instrument as _instr
from ..monitoring import trace as _trace
from ..robustness import breaker as _BRK
from ..robustness import faultinject as _FI
from ..robustness import integrity as _INTEG
from . import pallas as _PL
from .dndarray import DNDarray

__all__ = [
    "enabled",
    "sinks_enabled",
    "sink_ready",
    "views_enabled",
    "view_ready",
    "gemm_enabled",
    "collectives_enabled",
    "collective_ready",
    "is_deferred",
    "pending_count",
    "flush",
    "flush_pending",
    "flush_reason",
    "defer_binary",
    "defer_local",
    "defer_where",
    "defer_cast",
    "defer_view",
    "defer_getitem",
    "defer_matmul",
    "record_resplit",
    "defer_halo",
    "defer_shift",
    "defer_alltoall",
    "flush_through",
    "defer_reduce",
    "defer_moment",
    "defer_cum",
    "defer_norm",
    "defer_vecdot",
    "defer_ragged_reduce",
    "materialize_for",
    "cache_info",
    "clear_cache",
]


# ------------------------------------------------------------------ gates
def enabled() -> bool:
    """Whether deferred-execution fusion is globally enabled (default on).

    ``HEAT_TPU_FUSION=0`` (or ``false``/``off``) restores the pre-fusion
    op-at-a-time dispatch bit for bit. Read per dispatch, so a mid-process
    flip is honored immediately (pending graphs recorded before the flip
    still flush through the fused path — their results are bit-identical).
    """
    val = os.environ.get("HEAT_TPU_FUSION", "")
    return val.strip().lower() not in ("0", "false", "off")


def sinks_enabled() -> bool:
    """Whether reductions sink into pending graphs (default on).

    ``HEAT_TPU_FUSION_SINKS=0`` keeps elementwise fusion on but restores the
    pre-sink behavior bit for bit: every reduction/cumulative flushes its
    operand and executes as a standalone dispatch. Read per dispatch.
    """
    val = os.environ.get("HEAT_TPU_FUSION_SINKS", "")
    return val.strip().lower() not in ("0", "false", "off")


def sink_ready(x) -> bool:
    """Whether ``x`` carries a live pending expression a reduction may sink
    into (fusion + sinks enabled, pending node not yet materialized through
    another root)."""
    if not isinstance(x, DNDarray):
        return False
    node = x._expr()
    if node is None or node.value is not None:
        return False
    return enabled() and sinks_enabled()


def views_enabled() -> bool:
    """Whether structural/view ops record DAG nodes over pending chains
    (default on). ``HEAT_TPU_FUSION_VIEWS=0`` keeps elementwise fusion on but
    restores the pre-view behavior bit for bit: every transpose / broadcast /
    basic-slice read / reshape over a pending chain flushes it and executes
    as a standalone dispatch. Read per dispatch."""
    val = os.environ.get("HEAT_TPU_FUSION_VIEWS", "")
    return val.strip().lower() not in ("0", "false", "off")


def view_ready(x) -> bool:
    """Whether ``x`` carries a pending expression a structural op may record
    a view node over (fusion + views enabled)."""
    if not isinstance(x, DNDarray) or x._expr() is None:
        return False
    return enabled() and views_enabled()


def gemm_enabled() -> bool:
    """Whether ``matmul``/``dot`` record GEMM producer nodes (default on).
    ``HEAT_TPU_FUSION_GEMM=0`` keeps elementwise fusion on but restores the
    pre-producer behavior bit for bit: every GEMM flushes its operands and
    dispatches standalone. Read per dispatch."""
    val = os.environ.get("HEAT_TPU_FUSION_GEMM", "")
    return val.strip().lower() not in ("0", "false", "off")


def collectives_enabled() -> bool:
    """Whether collectives (resharding / halo exchange / ring shift /
    all-to-all) record DAG nodes over pending chains (default on).
    ``HEAT_TPU_FUSION_COLLECTIVES=0`` keeps elementwise fusion on but restores
    the pre-collective behavior bit for bit: every ``resplit_`` /
    ``redistribute_`` / ``get_halo`` / ``comm.shift`` / DNDarray ``Alltoall``
    over a pending chain flushes it first and dispatches the collective
    standalone. Read per dispatch."""
    val = os.environ.get("HEAT_TPU_FUSION_COLLECTIVES", "")
    return val.strip().lower() not in ("0", "false", "off")


def collective_ready(x) -> bool:
    """Whether ``x`` carries a live pending expression a collective may record
    a node over (fusion + collectives enabled, pending node not yet
    materialized through another root)."""
    if not isinstance(x, DNDarray):
        return False
    node = x._expr()
    if node is None or node.value is not None:
        return False
    return enabled() and collectives_enabled()


def _donate_enabled() -> bool:
    val = os.environ.get("HEAT_TPU_FUSION_DONATE", "")
    return val.strip().lower() not in ("0", "false", "off")


def _donate_forced() -> bool:
    """``HEAT_TPU_FUSION_DONATE=force``: admit donation candidates on
    backends whose runtime ignores the donation mask (CPU — jax warns and
    keeps the input alive). The mask still reaches ``jax.jit``, the L1 key
    and the ``fusion.donated`` accounting are exactly what a TPU process
    would produce, and results are bit-identical either way — this is how
    the decode steady-state re-donation contract (ISSUE 19) is testable on
    the CPU mesh harness."""
    return os.environ.get("HEAT_TPU_FUSION_DONATE", "").strip().lower() == "force"


def _tuned_bound(knob: str, default: int) -> int:
    """Measured chain/cache bound under ``HEAT_TPU_TUNING=1`` (one env read
    when off): the tuning layer mines the PR 13 cost cards for the
    compile-vs-replay tradeoff; any failure serves the static default."""
    from .. import tuning as _tuning

    if not _tuning.enabled():
        return default
    try:
        v = _tuning.lookup(knob)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return default
    return default if v is None else int(v)


def _max_chain() -> int:
    # an explicit env bound always wins; unset, the default may come from
    # the cost-card-mined tuning knob (fusion.max_chain, ISSUE 18)
    raw = os.environ.get("HEAT_TPU_FUSION_MAX_CHAIN", "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            return 64
    return _tuned_bound("fusion.max_chain", 64)


def _cache_max() -> int:
    # sized for shape-diverse workloads (test suites, exploratory sessions):
    # a fused CPU/TPU executable is a few hundred KB at most, and an evicted
    # entry costs a full XLA recompile on its next appearance — measured 267
    # evictions across four op-heavy test files at 256 entries. An explicit
    # env size always wins; unset, the default may come from the
    # cost-card-mined working-set knob (fusion.cache_size, ISSUE 18).
    raw = os.environ.get("HEAT_TPU_FUSION_CACHE_SIZE", "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            return 4096
    return _tuned_bound("fusion.cache_size", 4096)


def _l1_cache():
    """The flush's L1 slice: ``(cache, tenant)`` — the shared trace LRU, or,
    with ``HEAT_TPU_TENANCY`` armed and this thread tagged by the serving
    scheduler, the tenant's bounded partition (ISSUE 15: one tenant's
    shape-diverse burst evicts only its own entries; the persistent L2 stays
    shared). Untagged work — library calls, tests, anything outside a
    ``tenancy.tenant_context`` — always gets the shared cache, so the armed
    knob alone changes nothing (one env read when off)."""
    spec = os.environ.get("HEAT_TPU_TENANCY", "").strip()
    if not spec or spec.lower() in ("0", "false", "off"):
        return _TRACE_CACHE, None
    from ..serving import tenancy as _tenancy

    tenant = _tenancy.current_tenant()
    if tenant is None:
        return _TRACE_CACHE, None
    return _tenancy.l1_partition(tenant), tenant


# ------------------------------------------------------------------ whitelists
#
# Only elementwise, shape-preserving jnp callables are recordable: the fused
# replay applies them positionally on traced operands, so anything with
# data-dependent shapes, axis semantics, or non-jnp identity falls back to the
# eager template. Matched by object identity — a lambda or partial never
# matches.
_BINARY_NAMES = (
    "add", "subtract", "multiply", "true_divide", "divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "arctan2", "hypot",
    "maximum", "minimum", "copysign", "nextafter", "ldexp", "heaviside",
    "logaddexp", "logaddexp2", "gcd", "lcm",
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "logical_and", "logical_or", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift", "right_shift",
)
_UNARY_NAMES = (
    "abs", "absolute", "negative", "positive", "sign", "signbit", "sqrt",
    "cbrt", "square", "reciprocal", "exp", "exp2", "expm1", "log", "log2",
    "log10", "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "deg2rad",
    "rad2deg", "degrees", "radians", "floor", "ceil", "trunc", "rint",
    "round", "clip", "isnan", "isinf", "isfinite", "isneginf", "isposinf",
    "logical_not", "invert", "bitwise_not", "conj", "conjugate", "real",
    "imag", "angle", "i0", "sinc",
)

ELEMENTWISE_BINARY = frozenset(
    getattr(jnp, n) for n in _BINARY_NAMES if hasattr(jnp, n)
)
ELEMENTWISE_UNARY = frozenset(
    getattr(jnp, n) for n in _UNARY_NAMES if hasattr(jnp, n)
)

#: jnp comparison ops whose bool result the eager template deliberately does
#: NOT cast back to the promoted dtype (see ``__binary_op``).
_EQ_NE = (jnp.equal, jnp.not_equal)

#: ops that make trace-time lowering decisions from a *static* scalar operand
#: (integer exponents -> lax.integer_pow); their int scalars are baked as
#: constants so the fused trace lowers identically to the eager dispatch.
_STATIC_SCALAR_OPS = frozenset(
    op for op in (getattr(jnp, "power", None), getattr(jnp, "float_power", None)) if op is not None
)

_SCALARS = (
    builtins.int, builtins.float, builtins.bool, builtins.complex,
    np.number, np.bool_,
)


def _static_kwargs(kw: dict) -> bool:
    """Whether every kwarg value can be baked into a trace / cache key."""
    return all(
        v is None or isinstance(v, (builtins.int, builtins.float, builtins.bool, str, np.number, np.bool_))
        for v in kw.values()
    )


# ------------------------------------------------------------------ graph
class _Leaf:
    """A concrete array input of a pending graph.

    ``owner`` is a weakref to the ``DNDarray`` the array was taken from (used
    for the donation liveness check); ``None`` for raw numpy/jax operands.
    """

    __slots__ = ("array", "owner")

    def __init__(self, array, owner=None):
        self.array = array
        self.owner = owner


class _Node:
    """One recorded elementwise op of the expression DAG.

    ``args`` holds ``_Node`` / ``_Leaf`` / baked scalar constants in
    positional order. ``op_key`` is the structural identity used in trace
    cache keys (op name + process-stable object id, plus any baked
    parameters). ``skey`` is the *cross-process-stable* twin of ``op_key``
    (no object ids — op names and static parameters only), set by the defer
    site; it keys the serving layer's persistent disk cache and shape-corpus
    entries (``heat_tpu/serving/``), and doubles as the rebuild recipe the
    AOT warmup driver uses to reconstruct the exact callable in a fresh
    process. ``None`` means the node has no process-independent identity
    (collective nodes close over mesh/comm objects) — programs containing
    one stay in-memory-only. ``cast`` replays the eager binary template's
    dtype cast-back: ``(promoted_np_dtype, is_eq_ne)`` or ``None``.
    ``value`` is filled when the owning array materializes, turning the node
    into a leaf for any other pending graph that references it.
    """

    __slots__ = (
        "fn", "op_key", "skey", "args", "kwargs", "cast", "aval", "nops",
        "value", "owner", "rc",
    )

    def __init__(self, fn, op_key, args, kwargs, cast, aval, skey=None):
        self.fn = fn
        self.op_key = op_key
        self.skey = skey
        self.args = args
        self.kwargs = kwargs  # tuple(sorted(items)) — hashable
        self.cast = cast
        self.aval = aval
        self.value = None
        self.owner = None
        self.rc = 0  # how many recorded parents reference this node
        n = 1
        for a in args:
            if isinstance(a, _Node) and a.value is None:
                if a.nops >= n:
                    n = a.nops + 1
                a.rc += 1
        # recorded DEPTH (longest pending path), only used for the flush
        # bound: rebind loops still grow it one per op, but a diamond-shaped
        # DAG (one sub-chain referenced by several parents — the
        # coordinate-sweep pattern) no longer multiplies toward the bound the
        # way a subtree-size sum did
        self.nops = n


#: Live deferred DNDarrays (weak, id-keyed — DNDarray is unhashable by
#: design): the monitoring-export / global barrier set.
_PENDING: dict = {}


def _register_pending(d: "DNDarray") -> None:
    key = id(d)
    _PENDING[key] = weakref.ref(d, lambda _r, _k=key: _PENDING.pop(_k, None))


def is_deferred(x) -> bool:
    """Whether ``x`` is a DNDarray carrying an unmaterialized expression."""
    return isinstance(x, DNDarray) and x._expr() is not None


def _pending_arrays():
    out = []
    for ref in list(_PENDING.values()):
        d = ref()
        if d is not None and d._expr() is not None:
            out.append(d)
    return out


def pending_count() -> int:
    """Number of live DNDarrays with unflushed expressions."""
    return len(_pending_arrays())


def flush(x: DNDarray) -> DNDarray:
    """Materialize ``x``'s pending expression (no-op when concrete)."""
    x.parray  # noqa: B018 — property access is the materialization point
    return x


def flush_pending(reason: str = "export") -> int:
    """Materialize every live pending graph (the monitoring-export barrier:
    exported counters then account for all recorded work). Returns the number
    of arrays flushed."""
    n = 0
    with flush_reason(reason):
        for d in _pending_arrays():
            d.parray  # noqa: B018
            n += 1
    return n


# ------------------------------------------------------------------ flush reasons
#: Reason stack read by ``materialize_for`` when attributing a flush to the
#: ``fusion.flush_reason`` labelled counter. Barrier sites push the reason of
#: the *outermost* barrier (e.g. printing wins over the ``.numpy()`` it calls
#: internally); a flush with no annotated barrier reports ``other``. The
#: stack is *per-thread* (``threading.local``) so concurrent flushes driven
#: by the serving scheduler (``heat_tpu/serving/scheduler.py``) attribute
#: their reasons independently instead of racing on one list.
_REASON_TLS = threading.local()


def _reason_stack() -> list:
    st = getattr(_REASON_TLS, "stack", None)
    if st is None:
        st = ["other"]
        _REASON_TLS.stack = st
    return st


class _ReasonCtx:
    """Tiny non-generator context manager (barrier sites sit on hot paths)."""

    __slots__ = ("reason", "pushed")

    def __init__(self, reason: str):
        self.reason = reason
        self.pushed = False

    def __enter__(self):
        # outermost barrier wins: only annotate when no reason is active yet
        st = _reason_stack()
        if len(st) == 1:
            st.append(self.reason)
            self.pushed = True
        return self

    def __exit__(self, *exc):
        if self.pushed:
            _reason_stack().pop()
        return False


def flush_reason(reason: str) -> _ReasonCtx:
    """Context manager annotating why any flush inside the block happened
    (``fusion.flush_reason{reason}``). Taxonomy: reduction / cumulative /
    print / indexing / io / collective / out-alias / export / chain-bound /
    linalg."""
    return _ReasonCtx(reason)


# ------------------------------------------------------------------ recording
def _op_key(fn) -> tuple:
    return (getattr(fn, "__name__", repr(fn)), id(fn))


def _usable_leaf(arr) -> bool:
    """A concrete array can enter a graph — anything but a tracer (recording
    inside someone else's jit must stay eager)."""
    return not isinstance(arr, jax.core.Tracer)


def _input_of(t: DNDarray):
    """The graph input standing for ``t``'s physical array: its pending node,
    or a ``_Leaf`` over its (concrete) ``parray``. Returns None if unusable."""
    node = t._expr()
    if node is not None:
        return node if node.value is None else _Leaf(node.value, node.owner)
    arr = t.parray
    if not _usable_leaf(arr):
        return None
    return _Leaf(arr, weakref.ref(t))


def _aval_in(x):
    if isinstance(x, _Node):
        return x.aval
    return jax.ShapeDtypeStruct(
        x.array.shape, x.array.dtype, weak_type=bool(getattr(x.array, "weak_type", False))
    )


#: Capacity of the abstract-eval memo below. Kept equal to the trace LRU's
#: default so the two caches can't shear under eviction pressure (ISSUE 8
#: satellite): both are surfaced in :func:`cache_info` and cleared together
#: by :func:`clear_cache`.
_EVAL_CACHE_SIZE = 4096


@functools.lru_cache(maxsize=_EVAL_CACHE_SIZE)
def _eval_node_cached(op_key, tmpl, kwargs, cast, avals):
    """Abstract-eval one op (with its cast-back rule) once per structural
    signature; repeated chain steps cost a dict hit instead of a trace."""
    del op_key  # identity is carried by tmpl[0]'s fn via closure below

    def f(*xs):
        it = iter(xs)
        args = [next(it) if a is _SLOT else a[2] for a in tmpl[1]]
        return _apply(tmpl[0], args, dict(kwargs), cast)

    return jax.eval_shape(f, *avals)


_SLOT = object()  # placeholder marking tracer positions in baked arg templates


def _const_key(a):
    """Cache-key form of a baked scalar constant. The *type* is part of the
    key: a Python ``2.0`` (weakly typed in jax promotion) and an
    ``np.float64(2.0)`` (strong) hash/compare equal but trace differently."""
    return ("c", type(a), a)


def _apply(fn, args, kwargs, cast):
    """Apply one recorded op exactly as the eager template would have,
    including the binary dtype cast-back (run on traced values so weak-type
    promotion is bit-identical)."""
    r = fn(*args, **kwargs)
    if cast is not None:
        promoted, is_eq_ne = cast
        if r.dtype != promoted and np.dtype(r.dtype).kind != "b" and not is_eq_ne:
            r = r.astype(promoted)
    return r


def _eval_node(fn, op_key, args, kwargs, cast):
    """Predicted output aval of a node (shape + dtype; weak leaves were
    refused so the strong-type abstract eval matches the eager result)."""
    tmpl = (fn, tuple(_SLOT if isinstance(a, (_Node, _Leaf)) else _const_key(a) for a in args))
    avals = tuple(_aval_in(a) for a in args if isinstance(a, (_Node, _Leaf)))
    try:
        return _eval_node_cached(op_key, tmpl, kwargs, cast, avals)
    except TypeError:  # unhashable template entry — eval uncached
        def f(*xs):
            it = iter(xs)
            real = [next(it) if isinstance(a, (_Node, _Leaf)) else a for a in args]
            return _apply(fn, real, dict(kwargs), cast)

        return jax.eval_shape(f, *avals)


def _finish(node: _Node, gshape, dtype, split, device, comm, kind: str) -> DNDarray:
    """Wrap a freshly recorded node in a deferred DNDarray, register it, and
    enforce the chain-length bound."""
    d = DNDarray._deferred(node, gshape, tuple(node.aval.shape), dtype, split, device, comm)
    node.owner = weakref.ref(d)
    _register_pending(d)
    if _MON.enabled:
        _instr.fusion_defer(kind)
    if node.nops >= _max_chain():
        # flush at record time: unbounded rebind loops then compile a small
        # set of fixed-size fused kernels instead of one per chain length
        with flush_reason("chain-bound"):
            d.parray  # noqa: B018
    return d


def defer_binary(
    operation,
    ops_in,
    promoted,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    device,
    comm,
    where,
    fn_kwargs: dict,
) -> Optional[DNDarray]:
    """Record one eager ``__binary_op`` dispatch as a graph node.

    ``ops_in`` is the template's normalized operand list — ``('d', DNDarray)``
    / ``('s', scalar)`` / ``('a', jnp array)`` — exactly what the eager path
    would execute on. Returns the deferred result, or None to fall back.
    """
    from .types import canonical_heat_type

    if operation not in ELEMENTWISE_BINARY:
        return None
    if fn_kwargs and not _static_kwargs(fn_kwargs):
        return None
    if isinstance(where, _SCALARS) and not isinstance(where, (builtins.bool, np.bool_)):
        return None

    dnds = [t for k, t in ops_in if k == "d"]
    padded = [t for t in dnds if t.is_padded]
    phys = False
    if padded:
        # mirror of the eager padded-physical fast path, restricted to the
        # symmetric cases; anything needing pad_physical / logical slicing
        # inside the trace falls back to eager
        if out_split is None or where is not None:
            return None
        for k, t in ops_in:
            if k == "s":
                continue
            shp = tuple(t.shape)
            ndim_t = len(shp)
            ax_t = ndim_t - (len(out_shape) - out_split)
            if ax_t < 0 or ndim_t == 0 or shp[ax_t] == 1:
                if k == "d" and t.is_padded:
                    return None  # its contribution would be a logical slice
            elif (
                k == "d"
                and t.split is not None
                and int(t.split) % ndim_t == ax_t
                and shp[ax_t] == out_shape[out_split]
                and t.comm is comm
            ):
                phys = True
            else:
                return None
        if not phys:
            return None

    # collect graph inputs (no materialization happens here)
    args = []
    for k, t in ops_in:
        if k == "d":
            inp = _input_of(t)
            if inp is None:
                return None
            args.append(inp)
        elif k == "s":
            if operation in _STATIC_SCALAR_OPS and isinstance(
                t, (builtins.int, np.integer)
            ) and not isinstance(t, (builtins.bool, np.bool_)):
                # jnp.power inspects a STATIC integer exponent at trace time
                # and lowers to integer_pow (repeated squaring) — exactly what
                # the eager dispatch does. Baked as a constant so the fused
                # trace takes the same lowering; the value is part of the
                # trace-cache key.
                args.append(t)
            else:
                # a scalar enters the trace as a runtime ARGUMENT with the
                # exact aval eager dispatch gives it (Python scalars
                # weak-typed, np scalars strong) — never as a baked constant,
                # which XLA would fold (x / 3.0 -> x * (1/3.0)) and break
                # bit-for-bit parity with the op-at-a-time path
                args.append(_Leaf(jnp.asarray(t)))
        else:  # raw jnp array operand
            if not _usable_leaf(t):
                return None
            args.append(_Leaf(t))

    kwargs = tuple(sorted(fn_kwargs.items()))
    cast = (np.dtype(promoted.jnp_type()), operation in _EQ_NE)
    okey = ("binary", _op_key(operation), kwargs, (str(cast[0]), cast[1]))
    try:
        aval = _eval_node(operation, okey, args, kwargs, cast)
    except Exception:
        return None  # abstract eval rejected the combination: eager handles
    skey = ("binary", operation.__name__, kwargs, (str(cast[0]), cast[1]))
    node = _Node(operation, okey, tuple(args), kwargs, cast, aval, skey=skey)

    if where is not None:
        w_in = None
        if isinstance(where, DNDarray):
            if where.is_padded:
                return None
            w_in = _input_of(where)
        elif isinstance(where, (builtins.bool, np.bool_)):
            w_in = _Leaf(jnp.asarray(where))
        else:
            w = jnp.asarray(where)
            if not _usable_leaf(w):
                return None
            w_in = _Leaf(w)
        if w_in is None:
            return None
        node = _where_glue(w_in, node, out_shape)
        if node is None:
            return None

    # expected physical layout of the result: the broadcast the trace
    # computes must BE the canonical padded layout (eager parity — the
    # eager result is either logical or canonically padded)
    expected = tuple(out_shape)
    if phys:
        expected = comm.padded_shape(out_shape, out_split)
    if tuple(node.aval.shape) != expected:
        return None

    res_dtype = canonical_heat_type(node.aval.dtype)
    return _finish(node, tuple(out_shape), res_dtype, out_split, device, comm, "binary")


def _where_fn_for(shape: Tuple[int, ...]):
    """Canonical glue callable replaying the eager ``where=`` select
    (``jnp.where(w, r, zeros(out_shape, r.dtype))``), memoized per shape so
    node keys and eval caches see one object per shape."""
    fn = _WHERE_FNS.get(shape)
    if fn is None:
        def fn(w, r, _shape=shape):
            return jnp.where(w, r, jnp.zeros(_shape, dtype=r.dtype))

        _WHERE_FNS[shape] = fn
    return fn


_WHERE_FNS: dict = {}


def _where_glue(w_in, op_node: _Node, out_shape) -> Optional[_Node]:
    shape = tuple(out_shape)
    fn = _where_fn_for(shape)
    okey = ("where_glue", shape)
    args = (w_in, op_node)
    try:
        aval = _eval_node(fn, okey, args, (), None)
    except Exception:
        return None
    return _Node(fn, okey, args, (), None, aval, skey=okey)


def defer_local(operation, x: DNDarray, kwargs: dict, force_logical: bool) -> Optional[DNDarray]:
    """Record one eager ``__local_op`` dispatch (elementwise unary on the
    physical array). Returns the deferred result, or None to fall back."""
    from .types import canonical_heat_type

    if operation not in ELEMENTWISE_UNARY:
        return None
    if kwargs and not _static_kwargs(kwargs):
        return None
    if force_logical and x.is_padded:
        return None
    inp = _input_of(x)
    if inp is None:
        return None
    kw = tuple(sorted(kwargs.items()))
    okey = ("local", _op_key(operation), kw)
    try:
        aval = _eval_node(operation, okey, (inp,), kw, None)
    except Exception:
        return None
    if tuple(aval.shape) != tuple(x.pshape):
        return None  # shape-changing call (e.g. degenerate clip): eager handles
    node = _Node(
        operation, okey, (inp,), kw, None, aval,
        skey=("local", operation.__name__, kw),
    )
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish(node, tuple(x.shape), res_dtype, x.split, x.device, x.comm, "local")


def defer_where(cond: DNDarray, x, y) -> Optional[DNDarray]:
    """Record a 3-argument ``ht.where`` select as one elementwise node
    (operands may themselves be pending). Returns None to fall back."""
    from .types import canonical_heat_type

    args = []
    for t in (cond, x, y):
        if isinstance(t, DNDarray):
            if t.is_padded:
                return None
            inp = _input_of(t)
            if inp is None:
                return None
            args.append(inp)
        elif isinstance(t, _SCALARS):
            args.append(_Leaf(jnp.asarray(t)))  # runtime arg: see defer_binary
        else:
            a = jnp.asarray(t)
            if not _usable_leaf(a):
                return None
            args.append(_Leaf(a))
    okey = ("where", _op_key(jnp.where))
    try:
        aval = _eval_node(jnp.where, okey, tuple(args), (), None)
    except Exception:
        return None
    split = cond.split
    if split is not None and len(aval.shape) != cond.ndim:
        split = None
    node = _Node(jnp.where, okey, tuple(args), (), None, aval, skey=("where",))
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish(
        node, tuple(aval.shape), res_dtype, split, cond.device, cond.comm, "where"
    )


def _cast_fn_for(np_dtype):
    fn = _CAST_FNS.get(np_dtype)
    if fn is None:
        def fn(a, _dt=np_dtype):
            return a.astype(_dt)

        _CAST_FNS[np_dtype] = fn
    return fn


_CAST_FNS: dict = {}


def defer_cast(x: DNDarray, heat_dtype) -> Optional[DNDarray]:
    """Record ``astype`` glue (``x.parray.astype(dtype)``) as a graph node so
    a cast inside a chain fuses instead of materializing. None = fall back."""
    dt = np.dtype(heat_dtype.jnp_type())
    inp = _input_of(x)
    if inp is None:
        return None
    fn = _cast_fn_for(dt)
    okey = ("cast", str(dt))
    aval = jax.ShapeDtypeStruct(tuple(x.pshape), dt)
    node = _Node(fn, okey, (inp,), (), None, aval, skey=okey)
    return _finish(node, tuple(x.shape), heat_dtype, x.split, x.device, x.comm, "cast")


# ------------------------------------------------------------------ view nodes
#
# A view node records one structural op — pure data movement, no arithmetic —
# over a pending chain, so a transpose / broadcast / basic-slice read /
# split-preserving reshape mid-chain moves data in-register instead of
# breaking the chain with a flush. The callable operates on the PHYSICAL
# array; the per-node padded-ragged rule is one of:
#
# * pad passthrough — the op keeps the padded split extent intact and the pad
#   at the global end of the (possibly remapped) split axis: transpose,
#   expand_dims, squeeze/flip on non-split axes, extent-preserving
#   broadcast_to and reshape;
# * in-trace re-pad — the raw result is the full logical array whose
#   canonical layout is ragged on the result split axis (a basic split-axis
#   slice): the node appends a ``jnp.pad`` establishing the canonical padded
#   layout (zero pad content — unspecified by contract);
# * eager fallback, counted in ``fusion.view_fallbacks`` — asymmetric pad
#   situations (flip/squeeze/reshape across a padded split axis, a padded
#   broadcast source) and stepped split-axis slices, whose pad motion has no
#   cheap in-trace form.
#
# Every static parameter (permutation, targets, encoded index keys, pad
# widths) is part of the node's ``op_key`` and therefore of the trace-LRU key.

_VIEW_FNS: dict = {}


def _decode_key_entry(e):
    """Inverse of the hashable index-key encoding (slices are unhashable on
    py3.10, so ``defer_getitem`` stores them as ``('s', start, stop, step)``
    tuples)."""
    if isinstance(e, tuple) and len(e) == 4 and e[0] == "s":
        return slice(e[1], e[2], e[3])
    return e  # int / None (newaxis)


def _view_fn_for(kind: str, params: tuple, padw):
    """Memoized view callable per static signature (node identity, the
    abstract-eval cache, and the trace LRU all see one object per signature).
    ``padw`` appends an in-trace canonical re-pad of a ragged result."""
    key = (kind, params, padw)
    fn = _VIEW_FNS.get(key)
    if fn is not None:
        return fn
    if kind == "transpose":
        (axes,) = params

        def base(v, _a=axes):
            return jnp.transpose(v, _a)
    elif kind == "flip":
        (axes,) = params

        def base(v, _a=axes):
            return jnp.flip(v, axis=_a)
    elif kind == "expand_dims":
        (axis,) = params

        def base(v, _a=axis):
            return jnp.expand_dims(v, _a)
    elif kind == "squeeze":
        (axes,) = params

        def base(v, _a=axes):
            return jnp.squeeze(v, axis=_a)
    elif kind == "broadcast_to":
        (target,) = params

        def base(v, _t=target):
            return jnp.broadcast_to(v, _t)
    elif kind == "reshape":
        (target,) = params

        def base(v, _t=target):
            return v.reshape(_t)
    elif kind == "getitem":
        (enc,) = params
        idx = tuple(_decode_key_entry(e) for e in enc)

        def base(v, _i=idx):
            return v[_i]
    else:  # pragma: no cover — internal kinds only
        raise ValueError(f"unknown view kind {kind!r}")
    if padw is None:
        fn = base
    else:

        def fn(v, _b=base, _w=padw):
            return jnp.pad(_b(v), _w)

    _VIEW_FNS[key] = fn
    return fn


def _view_fallback(kind: str) -> None:
    if _MON.enabled:
        _instr.fusion_view_fallback(kind)


def defer_view(
    x: DNDarray, kind: str, params: tuple, out_gshape, out_split, res_dtype=None
) -> Optional[DNDarray]:
    """Record one structural op over ``x``'s pending expression as a view
    node. ``params`` are the op's static parameters (``broadcast_to`` /
    ``reshape`` derive their physical target internally); ``out_gshape`` /
    ``out_split`` are the logical result shape and remapped split axis the
    eager dispatch would produce. Returns the deferred result, or None to
    fall back to the (flushing) eager path."""
    from .communication import MeshCommunication
    from .types import canonical_heat_type

    out_gshape = tuple(int(s) for s in out_gshape)
    comm = x.comm
    distributed = (
        out_split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    )
    expected = comm.padded_shape(out_gshape, out_split) if distributed else out_gshape
    padded = x.is_padded
    s_ax = None if x.split is None else int(x.split) % max(x.ndim, 1)

    if padded:
        # per-node pad legality: either the pad rides through unchanged or
        # the node can re-establish the canonical layout in-trace; anything
        # else falls back (counted — deferred work the engine had to give up)
        if kind in ("flip", "squeeze"):
            (axes,) = params
            if s_ax in axes:
                _view_fallback("asymmetric-pad")
                return None
        elif kind == "reshape":
            k = out_split
            if (
                k is None
                or out_gshape[k] != x.shape[s_ax]
                or int(np.prod(out_gshape[:k], dtype=np.int64))
                != int(np.prod(x.shape[:s_ax], dtype=np.int64))
            ):
                # the padded split extent must survive as its own axis with an
                # unchanged leading block — otherwise the physical reshape
                # would interleave pad rows into logical positions
                _view_fallback("asymmetric-pad")
                return None
        elif kind == "broadcast_to":
            if out_split is None or x.shape[s_ax] != out_gshape[out_split]:
                _view_fallback("asymmetric-pad")
                return None
        elif kind == "getitem":
            (enc,) = params
            in_ax = 0
            for e in enc:
                if e is None:
                    continue
                if isinstance(e, tuple) and e[0] == "s":
                    if in_ax == s_ax and e[3] != 1:
                        # a stepped split-axis slice reorders/strides through
                        # the pad boundary — no cheap in-trace form
                        _view_fallback("stepped-split-slice")
                        return None
                    in_ax += 1
                else:  # integer index
                    in_ax += 1

    if kind in ("broadcast_to", "reshape"):
        # these two take a target shape: the PHYSICAL one, so the pad (when
        # present) broadcasts/regroups along for the ride
        params = (expected,)

    inp = _input_of(x)
    if inp is None:
        return None
    fn = _view_fn_for(kind, params, None)
    okey = ("view", kind, params, None)
    try:
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        if padded:
            _view_fallback("asymmetric-pad")
        return None  # invalid op for this shape: the eager dispatch raises
    if tuple(aval.shape) != expected:
        if tuple(aval.shape) != out_gshape or not distributed:
            if padded:
                _view_fallback("asymmetric-pad")
            return None
        # the raw result is the full logical array whose canonical layout is
        # ragged on the result split axis (split-axis slice shrank it):
        # re-establish the padded layout in-trace (pad content unspecified)
        padw = tuple(
            (0, int(expected[d]) - int(out_gshape[d])) for d in range(len(expected))
        )
        fn = _view_fn_for(kind, params, padw)
        okey = ("view", kind, params, padw)
        try:
            aval = _eval_node(fn, okey, (inp,), (), None)
        except Exception:
            return None
        if tuple(aval.shape) != expected:
            return None
    # the view okey carries only the kind + static parameters — already
    # process-stable, so it doubles as the serving-layer skey
    node = _Node(fn, okey, (inp,), (), None, aval, skey=okey)
    dtype = res_dtype if res_dtype is not None else canonical_heat_type(aval.dtype)
    return _finish(node, out_gshape, dtype, out_split, x.device, x.comm, "view")


def defer_getitem(x: DNDarray, key) -> Optional[DNDarray]:
    """Record a basic ``__getitem__`` read (ints / slices / Ellipsis /
    newaxis) over ``x``'s pending expression as a view node; the normalized
    key is the exact one the eager fast path applies to :attr:`parray`.
    Advanced keys (arrays, masks) and 0-d element reads return None — the
    caller keeps today's flush-at-read behavior (a scalar read gains nothing
    from deferral, and per-element probing of a fresh chain would otherwise
    compile one kernel per index)."""
    if not view_ready(x):
        return None
    norm, new_split, fast = x._index_plan(key)
    if not fast:
        return None
    enc = []
    for k in norm:
        if k is None:
            enc.append(None)
        elif isinstance(k, slice):
            enc.append(("s", k.start, k.stop, k.step))
        elif isinstance(k, (builtins.int, np.integer)) and not isinstance(
            k, (builtins.bool, np.bool_)
        ):
            enc.append(int(k))
        else:
            return None  # advanced key: the eager (flushing) path handles it
    # logical result shape via a zero-copy numpy probe (basic keys only)
    probe = np.broadcast_to(np.uint8(0), tuple(x.shape))
    out_gshape = tuple(int(s) for s in probe[tuple(norm)].shape)
    if out_gshape == ():
        return None  # scalar element read: flush (see docstring)
    return defer_view(
        x, "getitem", (tuple(enc),), out_gshape, new_split, res_dtype=x.dtype
    )


# ------------------------------------------------------------------ GEMM producers
#
# A GEMM producer node records the exact eager ``linalg.matmul``/``dot``
# dispatch — the promoted-dtype casts and the declared ``precision`` — so the
# downstream bias-add/activation/cast chain flushes with the GEMM as ONE XLA
# program and the backend fuses the epilogue into the MXU contraction (a
# terminal reduction additionally rides the sink path: ``act(x@w+b).mean()``
# is one kernel). Fallbacks for bit parity: sub-32-bit float GEMMs (a fused
# epilogue may legally read the f32 accumulator before the narrow output
# rounding — the ``_low_float`` class) and padded operands (the eager path
# contracts the sliced logical view; an in-trace pad slice would reassociate
# the ragged shards' partial products).

_GEMM_FNS: dict = {}


def _gemm_fn_for(op: str, cast_dt, precision):
    key = (op, None if cast_dt is None else str(cast_dt), str(precision))
    fn = _GEMM_FNS.get(key)
    if fn is not None:
        return fn
    jfn = jnp.matmul if op == "matmul" else jnp.dot
    if cast_dt is None:

        def fn(a, b, _f=jfn, _p=precision):
            return _f(a, b, precision=_p)
    else:

        def fn(a, b, _f=jfn, _dt=cast_dt, _p=precision):
            return _f(a.astype(_dt), b.astype(_dt), precision=_p)

    _GEMM_FNS[key] = fn
    return fn


def _precision_token(p):
    """Process-stable (picklable, id-free) form of a declared GEMM
    ``precision`` — None, a string alias, a ``lax.Precision`` member (by
    name), or a pair of either — for the serving layer's disk-cache and
    corpus keys. Returns the sentinel ``False`` when inexpressible (the
    program then stays in-memory-only)."""
    if p is None or isinstance(p, str):
        return p
    if isinstance(p, (tuple, list)):
        toks = tuple(_precision_token(q) for q in p)
        return False if any(t is False for t in toks) else toks
    name = getattr(p, "name", None)
    return ("P", name) if isinstance(name, str) else False


def _precision_from_token(tok):
    """Inverse of :func:`_precision_token` (the warmup rebuild path)."""
    if tok is None or isinstance(tok, str):
        return tok
    if isinstance(tok, tuple) and len(tok) == 2 and tok[0] == "P":
        return jax.lax.Precision[tok[1]]
    return tuple(_precision_from_token(t) for t in tok)


def defer_matmul(
    a: DNDarray,
    b: DNDarray,
    promoted,
    precision,
    out_gshape,
    out_split,
    op: str = "matmul",
) -> Optional[DNDarray]:
    """Record one ``matmul``/``dot`` dispatch as a GEMM producer node over
    (possibly pending) operands. ``promoted`` is the heat-promoted dtype both
    operands are cast to (None = the op's own jnp promotion, the ``dot``
    path); ``out_gshape``/``out_split`` follow the caller's reference split
    bookkeeping. Returns the deferred result, or None to fall back to the
    (flushing) eager dispatch."""
    from .communication import MeshCommunication
    from .types import canonical_heat_type

    if not (enabled() and gemm_enabled()):
        return None
    try:
        hash(precision)
    except TypeError:
        return None
    cast_dt = None if promoted is None else np.dtype(promoted.jnp_type())
    if cast_dt is not None:
        low = cast_dt.itemsize < 4 and bool(jnp.issubdtype(cast_dt, jnp.floating))
    else:
        low = _low_float(a) or _low_float(b)
    if low:
        return None  # sub-32-bit float GEMM: flush for bit parity (see above)
    if a.is_padded or b.is_padded:
        return None  # eager contracts the sliced logical view: flush
    in_a = _input_of(a)
    in_b = _input_of(b)
    if in_a is None or in_b is None:
        return None
    fn = _gemm_fn_for(op, cast_dt, precision)
    okey = ("gemm", op, None if cast_dt is None else str(cast_dt), str(precision))
    try:
        aval = _eval_node(fn, okey, (in_a, in_b), (), None)
    except Exception:
        return None  # dimension mismatch etc.: the eager dispatch raises it
    out_gshape = tuple(int(s) for s in out_gshape)
    comm = a.comm
    expected = out_gshape
    if (
        out_split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    ):
        expected = comm.padded_shape(out_gshape, out_split)
    if tuple(aval.shape) != expected:
        return None
    ptok = _precision_token(precision)
    skey = (
        None
        if ptok is False
        else ("gemm", op, None if cast_dt is None else str(cast_dt), ptok)
    )
    node = _Node(fn, okey, (in_a, in_b), (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish(node, out_gshape, res_dtype, out_split, a.device, a.comm, "gemm")


# ------------------------------------------------------------------ reduction sinks
#
# A sink node replays the EXACT eager reduction dispatch inside the fused
# trace: operand prep (``pre`` — the padded-physical pass-through, the
# neutral-element pad fill, the logical pad slice, or a static flatten), the
# jnp reduction with its axis/keepdims/static kwargs, optional dynamic kwarg
# operands (``where=`` masks ride as runtime leaves), and the split-axis
# NaN re-assertion of ``__reduce_op``. The sink callable is memoized per
# static signature so node identity, the abstract-eval cache, and the trace
# LRU key all see one object per signature; every static parameter is also
# part of ``op_key`` and therefore of the trace-cache key.

def _low_float(x: DNDarray) -> bool:
    """Sub-32-bit float operand: eager rounds to bf16/f16 after every op, but
    a fused producer feeding an f32-upcast accumulator legally skips the final
    narrow rounding (XLA excess precision) — arithmetic-accumulating sinks
    flush instead to preserve bit parity (order-preserving min/max and boolean
    any/all remain sinkable; see ``__reduce_op``)."""
    dt = np.dtype(x.dtype.jnp_type())
    # NB: ml_dtypes extended floats (bfloat16) report numpy kind 'V', so the
    # float test must go through jnp.issubdtype, not dt.kind
    return dt.itemsize < 4 and bool(jnp.issubdtype(dt, jnp.floating))


def _sink_fallback(kind: str) -> None:
    """One reduction over a pending chain that had to take the eager
    (flushing) fallback (kind: padded-operand — the eager path computes on
    the sliced logical view and no pallas route applied; low-float — the
    sub-32-bit excess-precision carve-out)."""
    if _MON.enabled:
        _instr.fusion_sink_fallback(kind)


def _ragged_pallas_ok(x: DNDarray) -> bool:
    """Whether the pallas ragged-reduce sink may serve this padded operand.
    A canonically padded operand is by construction *distributed* (sharded
    leaves), and a compiled ``pallas_call`` has no GSPMD partitioning rule —
    so this route requires the interpreter (``HEAT_TPU_PALLAS_INTERPRET=1``,
    under which the kernel discharges to partitionable jax ops; the CPU test
    and bench regime). The hatches are consulted here without counting — the
    caller counts the sink-level fallback, and ``HEAT_TPU_PALLAS=0`` must
    restore the pre-PR counter stream exactly."""
    del x
    if not (_PL.enabled() and _PL.kernel_enabled("ragged_reduce")):
        return False
    return _PL.interpret_forced()


def _defer_ragged(
    x: DNDarray, kind: str, opname: str, axis, keepdims: bool,
    where_arr=None, extra=(), sink_label: str = "reduce",
) -> Optional[DNDarray]:
    """Record one padded-operand reduction as a pallas ragged-reduce sink
    (``heat_tpu/core/pallas/ragged.py``): the pending chain, the in-tile pad
    masking, and the reduction compile as one program — the fused path the
    PR 4 ``padded-operand`` fallbacks lacked. Returns None (caller counts the
    fallback) when the kernel does not express the combination or the
    registry refuses the dispatch."""
    from .types import canonical_heat_type

    if not _ragged_pallas_ok(x):
        return None
    from .pallas import ragged as _plragged

    xsplit = int(x.split) % max(x.ndim, 1)
    n_log = int(x.shape[xsplit])
    dt = np.dtype(x.dtype.jnp_type())
    task = _plragged.plan(
        kind, opname, tuple(x.pshape), dt, xsplit, n_log, axis, keepdims,
        where_arr is not None, extra, _PL.use_interpret(),
    )
    if task is None:
        return None
    if not _PL.available("ragged_reduce", dtype=dt):
        return None
    inp = _input_of(x)
    if inp is None:
        return None
    args = (inp,)
    if where_arr is not None:
        if not _usable_leaf(where_arr):
            return None
        args = (inp, _Leaf(where_arr))
    fn = _plragged.sink_fn_for(task)
    okey = ("sink", "pallas", task)
    try:
        aval = _eval_node(fn, okey, args, (), None)
    except Exception:
        return None
    out_shape, out_dtype = task[-2], task[-1]
    if tuple(aval.shape) != tuple(out_shape) or str(aval.dtype) != out_dtype:
        return None  # plan/trace disagreement: let the eager path decide
    # no cross-process skey: a pallas custom call is not serializable through
    # the serving layer's executable cache — these programs stay in-memory
    node = _Node(fn, okey, args, (), None, aval, skey=None)
    _PL.dispatch("ragged_reduce")
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(out_shape), res_dtype, None, x.device, x.comm, sink_label
    )


def defer_ragged_reduce(
    x: DNDarray, op, axis, keepdims: bool, fn_kwargs: dict, out_gshape
) -> Optional[DNDarray]:
    """The ``__reduce_op`` entry to the pallas ragged sink, for the two
    padded-operand cases the PR 4 sinks flush: ``where=``-masked reductions
    (the mask's extent is logical) and flattened arg-reductions (flat indices
    must be logical). Returns None to fall back (caller counts it)."""
    opname = getattr(op, "__name__", None)
    if opname in ("argmin", "argmax"):
        if keepdims or axis is not None or fn_kwargs:
            return None
        res = _defer_ragged(x, "argflat", opname, None, False)
    else:
        where_arr = fn_kwargs.get("where")
        if where_arr is None or len(fn_kwargs) != 1:
            return None  # initial= etc. keep the eager fallback
        res = _defer_ragged(
            x, "where", opname, axis, keepdims, where_arr=where_arr
        )
    if res is not None and tuple(res.shape) != tuple(out_gshape):
        return None  # pragma: no cover — plan bakes the eager aval
    return res


_SINK_FNS: dict = {}


def _sink_fn_for(op, pre, axis, keepdims, static_kw, dyn_names, nanfix):
    key = (id(op), pre, axis, keepdims, static_kw, dyn_names, nanfix)
    fn = _SINK_FNS.get(key)
    if fn is not None:
        return fn

    def fn(operand, *dyn):
        v = operand
        for step in pre:
            if step[0] == "fill":
                # in-trace x.filled(neutral): mask the pad rows with the
                # reduce op's neutral element (0 would corrupt min/prod/all)
                _, s_ax, n, neutral = step
                shape = [1] * v.ndim
                shape[s_ax] = v.shape[s_ax]
                mask = jnp.arange(v.shape[s_ax]).reshape(shape) < n
                v = jnp.where(mask, v, jnp.asarray(neutral, dtype=v.dtype))
            elif step[0] == "slice":
                # in-trace x.larray: static slice dropping the pad rows
                _, s_ax, n = step
                v = v[tuple(
                    slice(0, n) if d == s_ax else slice(None) for d in range(v.ndim)
                )]
            elif step[0] == "reshape":
                v = v.reshape(step[1])
        kw = dict(static_kw)
        kw.update(zip(dyn_names, dyn))
        if keepdims is None:  # op without a keepdims parameter (cumulatives)
            r = op(v, axis=axis, **kw)
        else:
            r = op(v, axis=axis, keepdims=keepdims, **kw)
        r = jnp.asarray(r)
        if nanfix:
            # __reduce_op's split-axis NaN re-assertion for max/min (the SPMD
            # pmax/pmin combine drops NaN), replayed inside the trace
            hasnan = jnp.any(jnp.isnan(v), axis=axis, keepdims=bool(keepdims))
            r = jnp.where(hasnan, jnp.asarray(jnp.nan, r.dtype), r)
        return r

    _SINK_FNS[key] = fn
    return fn


def _split_sink_kwargs(fn_kwargs: dict):
    """Partition reduction kwargs into statically baked values and dynamic
    array operands (``where=`` masks). Returns ``(static_items, dyn_names,
    dyn_leaves)`` or None when a value can be neither baked nor lifted."""
    static_items, dyn_names, dyn_leaves = [], [], []
    for k, v in sorted(fn_kwargs.items()):
        if v is None or isinstance(
            v, (builtins.int, builtins.float, builtins.bool, str, np.number, np.bool_)
        ):
            # scalars here (``initial=``) are baked: eager evaluates them at
            # its own trace time too, so the lowering is identical
            static_items.append((k, v))
        else:
            arr = jnp.asarray(v)
            if not _usable_leaf(arr):
                return None
            dyn_names.append(k)
            dyn_leaves.append(_Leaf(arr))
    return tuple(static_items), tuple(dyn_names), tuple(dyn_leaves)


def _finish_sink(node: _Node, gshape, dtype, split, device, comm, kind: str) -> DNDarray:
    """Wrap a recorded sink node in a deferred DNDarray (the sink result roots
    a NEW pending chain — scalar epilogues fuse into the same kernel)."""
    d = DNDarray._deferred(node, gshape, tuple(node.aval.shape), dtype, split, device, comm)
    node.owner = weakref.ref(d)
    _register_pending(d)
    if _MON.enabled:
        _instr.fusion_sink(kind)
    if node.nops >= _max_chain():
        with flush_reason("chain-bound"):
            d.parray  # noqa: B018
    return d


def defer_reduce(
    x: DNDarray,
    op,
    axis,
    keepdims: bool,
    fn_kwargs: dict,
    pre,
    nanfix: bool,
    out_gshape,
    out_split,
    expected_pshape,
    kind: str = "reduce",
) -> Optional[DNDarray]:
    """Record one eager ``__reduce_op`` dispatch as a sink of ``x``'s pending
    graph. ``pre`` is the operand-prep recipe the eager path would apply
    (computed by the caller, which owns the pad semantics); ``expected_pshape``
    is the physical result shape the eager dispatch would produce. Returns the
    deferred result, or None to fall back to the flushing path."""
    from .types import canonical_heat_type

    inp = _input_of(x)
    if inp is None:
        return None
    parts = _split_sink_kwargs(fn_kwargs)
    if parts is None:
        return None
    static_items, dyn_names, dyn_leaves = parts
    try:
        fn = _sink_fn_for(op, pre, axis, keepdims, static_items, dyn_names, nanfix)
    except TypeError:  # unhashable static parameter
        return None
    okey = (
        "sink", kind, _op_key(op), pre, axis, keepdims, static_items, dyn_names, nanfix,
    )
    args = (inp, *dyn_leaves)
    try:
        aval = _eval_node(fn, okey, args, (), None)
    except Exception:
        return None  # abstract eval rejected the combination: eager handles
    if tuple(aval.shape) != tuple(expected_pshape):
        return None
    opname = getattr(op, "__name__", None)
    skey = (
        None
        if opname is None
        else ("sink", kind, opname, pre, axis, keepdims, static_items, dyn_names, nanfix)
    )
    node = _Node(fn, okey, args, (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(out_gshape), res_dtype, out_split, x.device, x.comm, kind
    )


def defer_moment(
    x: DNDarray, op, axis, keepdims: bool, fn_kwargs: dict, out_split
) -> Optional[DNDarray]:
    """Sink a logical-view moment reduction (``mean``/``var``/``std``/
    ``nanmean`` — ``jnp`` callables taking axis/keepdims) into ``x``'s pending
    graph; the ``/n`` and ``-mu**2`` epilogues live inside the jnp op and fuse
    with it. The eager ``__moment`` computes on ``x.larray``, so padded
    operands are pad-sliced in-trace."""
    if _low_float(x):
        _sink_fallback("low-float")
        return None
    if x.is_padded:
        # an in-trace pad slice would make the SPMD partitioner group the
        # ragged shards' partial sums differently than the eager dispatch on
        # the sliced logical view (reassociation) — but the pallas ragged
        # kernel masks the pad in-register instead (ISSUE 10): mean/nanmean
        # with an unsplit result take it; the rest keep the counted flush
        opname = getattr(op, "__name__", None)
        if not fn_kwargs and out_split is None and opname in ("mean", "nanmean"):
            res = _defer_ragged(
                x, "moment", opname, axis, keepdims, sink_label="moment"
            )
            if res is not None:
                return res
        _sink_fallback("padded-operand")
        return None
    pre = ()
    inp = _input_of(x)
    if inp is None:
        return None
    parts = _split_sink_kwargs(fn_kwargs)
    if parts is None:
        return None
    static_items, dyn_names, dyn_leaves = parts
    fn = _sink_fn_for(op, pre, axis, keepdims, static_items, dyn_names, False)
    okey = ("sink", "moment", _op_key(op), pre, axis, keepdims, static_items, dyn_names)
    args = (inp, *dyn_leaves)
    try:
        aval = _eval_node(fn, okey, args, (), None)
    except Exception:
        return None
    from .types import canonical_heat_type

    opname = getattr(op, "__name__", None)
    skey = (
        None
        if opname is None
        else ("sink_moment", opname, axis, keepdims, static_items, dyn_names)
    )
    node = _Node(fn, okey, args, (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(aval.shape), res_dtype, out_split, x.device, x.comm, "moment"
    )


def defer_app(
    fn,
    opname: str,
    operands,
    *,
    static=(),
    sink: bool = False,
    out_split=None,
    kind: str = "app",
):
    """Record one jax-traceable n-ary callable application as a graph node —
    the generation decode chain's recorder (ISSUE 19).

    ``operands`` are DNDarrays (pending or concrete) and/or raw jax/numpy
    arrays, applied positionally; ``static`` is a hashable tuple of
    JSON-stable parameters (ints/floats/strs/bools) already baked into
    ``fn``'s closure — together with ``opname`` it gives the node its
    cross-process-stable identity, so the CALLER owns uniqueness: one
    memoized ``fn`` object per ``(opname, static)``, or the trace cache and
    the L2 digest shear. ``sink=True`` tags the root of a multi-output
    chain: ``materialize_for`` then widens the flush so every interior node
    with a live owner (the appended KV caches) rides the SAME kernel as an
    extra output. Returns the deferred result, or None to fall back (caller
    runs the eager reference path)."""
    from .types import canonical_heat_type

    first_dnd = None
    args = []
    for op in operands:
        if isinstance(op, DNDarray):
            if op.is_padded:
                return None
            if first_dnd is None:
                first_dnd = op
            inp = _input_of(op)
            if inp is None:
                return None
            args.append(inp)
        else:
            arr = jnp.asarray(op)
            if not _usable_leaf(arr):
                return None
            args.append(_Leaf(arr, None))
    if first_dnd is None:
        return None  # device/comm placement must come from a DNDarray operand
    tag = "sink" if sink else "app"
    okey = (tag, kind, opname, _op_key(fn), static)
    try:
        aval = _eval_node(fn, okey, tuple(args), (), None)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return None  # abstract eval rejected the combination: eager handles
    skey = (tag, kind, opname, static)
    node = _Node(fn, okey, tuple(args), (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    finish = _finish_sink if sink else _finish
    return finish(
        node, tuple(aval.shape), res_dtype, out_split,
        first_dnd.device, first_dnd.comm, kind,
    )


#: ``(kind, opname) -> builder(static) -> fn`` — the cross-process rebuild
#: hook for :func:`defer_app` nodes (ISSUE 20). A recording module registers
#: one builder per opname it emits, returning the SAME memoized callable the
#: live recorder would use for that static tuple, so the serving warmup can
#: AOT-compile app/sink programs straight from the corpus instead of counting
#: them as rebuild errors. Keyed by the skey fields only — builders must not
#: close over live state.
_APP_REBUILDERS: dict = {}


def register_app_rebuilder(kind: str, opname: str, builder) -> None:
    """Register the warmup rebuild hook for ``defer_app(kind=..., opname=...)``
    nodes: ``builder(static) -> fn`` with ``fn`` the memoized jax-traceable
    callable whose closure bakes exactly ``static``."""
    _APP_REBUILDERS[(str(kind), str(opname))] = builder


def app_rebuilder(kind: str, opname: str):
    """The registered rebuild hook for ``(kind, opname)``, or None. The
    warmup driver lazily imports ``heat_tpu.nn.<kind>`` before asking, so a
    recording module's import-time registrations are visible cross-process."""
    return _APP_REBUILDERS.get((str(kind), str(opname)))


_CUM_FNS: dict = {}


def _cum_fn_for(op, axis: int, dt, comm_cum=None, cum_opname=None):
    """Memoized cumulative sink callable: the chunk-local jnp cumulative (or
    the ``comm.Cum`` shard_map pipeline) plus the optional dtype cast. Shared
    by :func:`defer_cum` and the serving warmup rebuild (comm-less form)."""
    key = (id(op), axis, None if dt is None else str(dt),
           None if comm_cum is None else id(comm_cum), cum_opname)
    fn = _CUM_FNS.get(key)
    if fn is None:
        def fn(v, _op=op, _axis=axis, _dt=dt, _comm=comm_cum, _name=cum_opname):
            if _comm is not None:
                r = _comm.Cum(v, op=_name, split=_axis)
            else:
                r = _op(v, axis=_axis)
            if _dt is not None:
                r = r.astype(_dt)
            return r

        _CUM_FNS[key] = fn
    return fn


def defer_cum(
    x: DNDarray, op, axis: int, cast_dtype, comm_cum, cum_opname
) -> Optional[DNDarray]:
    """Sink one eager ``__cum_op`` dispatch: the chunk-local cumulative (or,
    along a distributed split axis, the ``comm.Cum`` shard_map pipeline — the
    block-total exchange then lands in the same XLA program as the fused
    chain) plus the optional dtype cast."""
    from .types import canonical_heat_type

    if _low_float(x):
        return None  # bf16/f16 prefix accumulation: flush for bit parity
    inp = _input_of(x)
    if inp is None:
        return None
    dt = None if cast_dtype is None else np.dtype(cast_dtype.jnp_type())
    fn = _cum_fn_for(op, axis, dt, comm_cum, cum_opname)
    okey = ("sink", "cum", _op_key(op), axis, None if dt is None else str(dt),
            None if comm_cum is None else id(comm_cum), cum_opname)
    try:
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        return None  # e.g. shard_map refuses abstract eval on this jax: eager
    if tuple(aval.shape) != tuple(x.pshape):
        return None
    opname = getattr(op, "__name__", None)
    skey = (
        # the comm-bound form closes over the mesh pipeline: no stable identity
        None
        if comm_cum is not None or opname is None
        else ("sink_cum", opname, axis, None if dt is None else str(dt))
    )
    node = _Node(fn, okey, (inp,), (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(x.shape), res_dtype, x.split, x.device, x.comm, "cum"
    )


def defer_norm(
    x: DNDarray, ord, axis, keepdims: bool, flatten: bool
) -> Optional[DNDarray]:
    """Sink a ``jnp.linalg.norm`` call (``norm``/``vector_norm``/
    ``matrix_norm`` consume ``x.larray``); the ``sqrt`` epilogue lives inside
    the jnp op. ``flatten`` replays ``vector_norm``'s full-array reshape."""
    if _low_float(x):
        _sink_fallback("low-float")
        return None
    if x.is_padded:
        # in-trace pad slice would reassociate (see defer_moment) — the
        # pallas ragged kernel serves the sqrt-sum-of-squares orders instead:
        # default/Euclidean/Frobenius, i.e. exactly the cases where the jnp
        # default ord reproduces the requested one
        logical_nd = 1 if flatten else x.ndim
        ord_ok = (
            ord is None
            or (ord == 2 and (logical_nd == 1 or isinstance(axis, int)))
            or (ord == "fro" and axis is None and logical_nd == 2)
        )
        if ord_ok:
            res = _defer_ragged(
                x, "norm", "norm2", axis, keepdims, extra=(bool(flatten),),
                sink_label="norm",
            )
            if res is not None:
                return res
        _sink_fallback("padded-operand")
        return None
    pre = (("reshape", (-1,)),) if flatten else ()
    try:
        hash(ord)
    except TypeError:
        return None
    fn = _sink_fn_for(jnp.linalg.norm, pre, axis, keepdims, (("ord", ord),), (), False)
    okey = ("sink", "norm", pre, axis, keepdims, ("ord", str(ord)))
    skey = ("sink_norm", pre, axis, keepdims, ord)
    inp = _input_of(x)
    if inp is None:
        return None
    try:
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        return None
    from .types import canonical_heat_type

    node = _Node(fn, okey, (inp,), (), None, aval, skey=skey)
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(aval.shape), res_dtype, None, x.device, x.comm, "norm"
    )


def _vecdot_fn_for(axis, keepdim: bool):
    """Memoized vecdot sink callable (shared with the warmup rebuild)."""
    key = ("vecdot", axis, keepdim)
    fn = _SINK_FNS.get(key)
    if fn is None:
        def fn(a, b, _axis=axis, _keep=keepdim):
            aa, bb = jnp.broadcast_arrays(a, b)
            return jnp.sum(jnp.conj(aa) * bb, axis=_axis, keepdims=_keep)

        _SINK_FNS[key] = fn
    return fn


def defer_vecdot(x1: DNDarray, x2: DNDarray, axis, keepdim: bool) -> Optional[DNDarray]:
    """Sink ``vecdot``'s broadcast–conj–multiply–sum pipeline over two (possibly
    pending) operands; the trace replays the eager body verbatim."""
    if _low_float(x1) or _low_float(x2):
        _sink_fallback("low-float")
        return None
    if x1.is_padded or x2.is_padded:
        _sink_fallback("padded-operand")
        return None  # eager consumes larray; a two-operand pad slice is rare
    fn = _vecdot_fn_for(axis, keepdim)
    args = []
    for t in (x1, x2):
        inp = _input_of(t)
        if inp is None:
            return None
        args.append(inp)
    okey = ("sink", "vecdot", axis, keepdim)
    try:
        aval = _eval_node(fn, okey, tuple(args), (), None)
    except Exception:
        return None
    from .types import canonical_heat_type

    node = _Node(
        fn, okey, tuple(args), (), None, aval, skey=("sink_vecdot", axis, keepdim)
    )
    res_dtype = canonical_heat_type(aval.dtype)
    return _finish_sink(
        node, tuple(aval.shape), res_dtype, None, x1.device, x1.comm, "vecdot"
    )


# ------------------------------------------------------------------ collective nodes
#
# A collective node records one cross-device data motion — a resharding
# placement (``resplit_``/``redistribute_``), the halo ppermute exchange
# (``get_halo``), a ring chunk shift (``communication.shift``), or an axis
# re-chunking ``Alltoall`` — over a PENDING chain, so a split-axis elementwise
# chain, its cross-device combine, and the *next* chain compile as ONE
# shard_map program and XLA overlaps the ICI transfer with the elementwise
# compute (ROADMAP item 1; the communication-avoiding thesis of Demmel et
# al., PAPERS.md, applied to the eager op surface). Each callable replays the
# EXACT eager dispatch inside the trace:
#
# * ``resplit`` replays ``comm.placed(larray, new_split)``: a static slice
#   drops the old split axis's pad, ``jnp.pad`` re-establishes the new axis's
#   canonical pad, and ``lax.with_sharding_constraint`` pins the new layout —
#   XLA emits the same all-to-all/all-gather the eager ``device_put`` pays
#   (when replayed eagerly by the recovery ladder the callable issues the
#   real ``device_put``, i.e. the retained barrier path);
# * ``halo`` replays ``get_halo``: the pad slabs are zero-filled in-trace
#   exactly like the eager ``filled(0)``, then the cached shard_map ppermute
#   exchange runs inside the trace; the stacked per-shard block is the
#   recorded node and ``halo_prev``/``halo_next`` are slice views of it
#   (bit-identical to the exchange's own outputs — pure data movement);
# * ``ppermute`` (``communication.shift``) and ``alltoall`` replay the named
#   collective's cached shard_map program (``_collective_fn`` — the builder
#   WITHOUT the dispatch-site fault check, which the flush path owns).
#
# The mesh / axis-name / split metadata — and the comm's two-tier topology
# annotation (``MeshCommunication.tiers``, ISSUE 11): a tiered and a flat
# comm over the SAME devices build equal-hashing meshes but may inline
# different collective programs — is part of every node's ``op_key`` and
# therefore of the trace-LRU key. Cases the in-trace pad rules cannot
# express take the counted eager fallback ``fusion.collective_fallbacks``.
# ``HEAT_TPU_FUSION_COLLECTIVES=0`` (read per dispatch) restores the
# flush-barrier behavior bit for bit.

_COLL_FNS: dict = {}


def _comm_topo(comm):
    """The topology component of a collective node key: the ``(dcn, ici)``
    tier annotation of a two-tier comm, None for a flat one."""
    return getattr(comm, "tiers", None)


def _collective_fallback(kind: str) -> None:
    if _MON.enabled:
        _instr.fusion_collective_fallback(kind)


def _fill0_step(v, s_ax: int, n: int):
    """In-trace ``x.filled(0)``: zero the pad slab of the split axis (the
    exact mask/where the eager dispatch executes)."""
    shape = [1] * v.ndim
    shape[s_ax] = v.shape[s_ax]
    mask = jnp.arange(v.shape[s_ax]).reshape(shape) < n
    return jnp.where(mask, v, jnp.asarray(0, dtype=v.dtype))


def _resplit_fn_for(mesh, axis_name, gshape, pshape_old, old_ax, new_ax, pshape_new):
    """Memoized resharding callable physical(old layout) -> physical(new
    layout), replaying the eager ``placed(larray, new_split)`` dispatch."""
    key = ("resplit", mesh, axis_name, gshape, pshape_old, old_ax, new_ax)
    fn = _COLL_FNS.get(key)
    if fn is not None:
        return fn
    from jax.sharding import NamedSharding, PartitionSpec

    ndim = len(gshape)
    idx = None
    if old_ax is not None and pshape_old[old_ax] != gshape[old_ax]:
        idx = tuple(
            slice(0, gshape[d]) if d == old_ax else slice(None) for d in range(ndim)
        )
    padw = None
    if new_ax is not None and pshape_new[new_ax] != gshape[new_ax]:
        padw = tuple((0, int(pshape_new[d]) - int(gshape[d])) for d in range(ndim))
    spec = (
        PartitionSpec()
        if new_ax is None
        else PartitionSpec(*([None] * new_ax), axis_name)
    )
    sharding = NamedSharding(mesh, spec)

    def fn(v, _i=idx, _w=padw, _s=sharding):
        if _i is not None:
            v = v[_i]  # drop the old axis's pad (the eager larray view)
        if _w is not None:
            v = jnp.pad(v, _w)  # canonical pad of the new axis (zeros, placed())
        if isinstance(v, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(v, _s)
        return jax.device_put(v, _s)  # eager replay: the barrier path's placement

    _COLL_FNS[key] = fn
    return fn


def record_resplit(x: DNDarray, axis) -> bool:
    """Record an in-place ``resplit_(axis)`` over ``x``'s pending expression
    as a collective node: ``x`` STAYS pending under the new split metadata and
    the resharding executes inside the eventual fused flush. Returns False to
    fall back to the flushing eager path."""
    from .communication import MeshCommunication

    comm = x.comm
    if not isinstance(comm, MeshCommunication):
        return False
    gshape = tuple(x.shape)
    nd = max(len(gshape), 1)
    old_ax = None if x.split is None else int(x.split) % nd
    new_ax = None if axis is None else int(axis) % nd
    pshape_old = tuple(x.pshape)
    pshape_new = tuple(comm.padded_shape(gshape, new_ax))
    inp = _input_of(x)
    if inp is None:
        _collective_fallback("tracer-operand")
        return False
    try:
        fn = _resplit_fn_for(
            comm.mesh, comm.axis_name, gshape, pshape_old, old_ax, new_ax, pshape_new
        )
        okey = (
            "collective", "resplit", comm.mesh, comm.axis_name, _comm_topo(comm),
            pshape_old, old_ax, new_ax,
        )
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        _collective_fallback("abstract-eval")
        return False
    if tuple(aval.shape) != pshape_new:
        _collective_fallback("layout")
        return False
    node = _Node(fn, okey, (inp,), (), None, aval)
    x._rebind_expr(node, axis)
    _register_pending(x)
    if _MON.enabled:
        _instr.fusion_defer("collective")
    if node.nops >= _max_chain():
        with flush_reason("chain-bound"):
            x.parray  # noqa: B018
    return True


def _halo_slice_fn_for(which: str, h: int, chunk: int, split: int):
    """Memoized view callable deriving ``halo_prev``/``halo_next`` from the
    stacked exchange block (bit-identical to the exchange's own outputs: the
    stacked rows are ``[from_prev; blk; from_next]`` per shard, so a
    per-shard slice + reshape + moveaxis IS the global prev/next array)."""
    key = ("haloslice", which, h, chunk, split)
    fn = _COLL_FNS.get(key)
    if fn is not None:
        return fn
    sl = slice(0, h) if which == "prev" else slice(chunk + h, chunk + 2 * h)

    def fn(st, _sl=sl, _split=split):
        return jnp.moveaxis(st[:, _sl].reshape((-1,) + st.shape[2:]), 0, _split)

    _COLL_FNS[key] = fn
    return fn


def defer_halo(x: DNDarray, halo_size: int):
    """Record ``get_halo``'s ppermute exchange over ``x``'s pending chain:
    returns ``(halo_prev, halo_next, stacked)`` as DEFERRED DNDarrays (the
    chain and the exchange then compile as one program at the first halo
    read, with the chain's own value riding the same kernel as an extra
    output), or None to fall back to the flushing eager path."""
    from .communication import MeshCommunication
    from .dndarray import _build_halo_exchange

    comm = x.comm
    if not isinstance(comm, MeshCommunication):
        return None
    split = int(x.split) % x.ndim
    p = comm.size
    pshape = tuple(x.pshape)
    chunk = pshape[split] // p
    h = int(halo_size)
    inp = _input_of(x)
    if inp is None:
        _collective_fallback("tracer-operand")
        return None
    fill = (split, int(x.shape[split])) if x.is_padded else None
    key = ("halo", comm.mesh, comm.axis_name, p, split, h, pshape, fill)
    fn = _COLL_FNS.get(key)
    if fn is None:
        try:
            ex = _build_halo_exchange(comm.mesh, comm.axis_name, p, split, h, pshape)
        except Exception:
            _collective_fallback("abstract-eval")
            return None

        def fn(v, _ex=ex, _fill=fill):
            if _fill is not None:
                v = _fill0_step(v, _fill[0], _fill[1])  # eager filled(0) replay
            return _ex(v)[2]  # stacked per-shard block; prev/next are slices

        _COLL_FNS[key] = fn
    okey = (
        "collective", "halo", comm.mesh, comm.axis_name, _comm_topo(comm),
        p, split, h, pshape, fill,
    )
    try:
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        _collective_fallback("abstract-eval")
        return None
    node = _Node(fn, okey, (inp,), (), None, aval)
    stacked = _finish(
        node, tuple(aval.shape), x.dtype, 0, x.device, comm, "collective"
    )
    halo_gshape = pshape[:split] + (p * h,) + pshape[split + 1 :]
    out = [None, None, stacked]
    for i, which in enumerate(("prev", "next")):
        vfn = _halo_slice_fn_for(which, h, chunk, split)
        vkey = ("collective", "haloslice", which, h, chunk, split)
        st_in = stacked._expr()
        try:
            vaval = _eval_node(vfn, vkey, (st_in,), (), None)
        except Exception:
            _collective_fallback("abstract-eval")
            return None
        vnode = _Node(vfn, vkey, (st_in,), (), None, vaval)
        out[i] = _finish(
            vnode, halo_gshape, x.dtype, split, x.device, comm, "view"
        )
    return tuple(out)


def defer_shift(x: DNDarray, steps: int) -> Optional[DNDarray]:
    """Record ``communication.shift`` (ring chunk rotation) over ``x``'s
    pending chain: in-trace pad zero-fill + the cached ppermute shard_map
    program. Returns the deferred result, or None to fall back."""
    from .communication import MeshCommunication

    comm = x.comm
    if not isinstance(comm, MeshCommunication):
        return None
    s_ax = int(x.split) % x.ndim
    p = comm.size
    inp = _input_of(x)
    if inp is None:
        _collective_fallback("tracer-operand")
        return None
    shift_n = int(steps) % p
    fill = (s_ax, int(x.shape[s_ax])) if x.is_padded else None
    try:
        cfn = comm._collective_fn("ppermute", s_ax, x.ndim, shift=shift_n)
    except Exception:
        _collective_fallback("abstract-eval")
        return None
    key = ("shift", comm.mesh, comm.axis_name, _comm_topo(comm), s_ax, x.ndim, shift_n, fill)
    fn = _COLL_FNS.get(key)
    if fn is None:

        def fn(v, _c=cfn, _fill=fill):
            if _fill is not None:
                v = _fill0_step(v, _fill[0], _fill[1])
            return _c(v)

        _COLL_FNS[key] = fn
    okey = (
        "collective", "ppermute", comm.mesh, comm.axis_name, _comm_topo(comm),
        s_ax, shift_n, fill,
    )
    try:
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        _collective_fallback("abstract-eval")
        return None
    if tuple(aval.shape) != tuple(x.pshape):
        _collective_fallback("layout")
        return None
    node = _Node(fn, okey, (inp,), (), None, aval)
    return _finish(
        node, tuple(x.shape), x.dtype, x.split, x.device, comm, "collective"
    )


def defer_alltoall(x: DNDarray, split_axis: int, concat_axis: int) -> Optional[DNDarray]:
    """Record a DNDarray ``Alltoall`` re-chunk (split moves from
    ``concat_axis`` to ``split_axis``) over ``x``'s pending chain, replaying
    the named collective's shard_map program in-trace. The caller has already
    validated even partitioning of both axes. Returns None to fall back."""
    from .communication import MeshCommunication

    comm = x.comm
    if not isinstance(comm, MeshCommunication):
        return None
    if x.is_padded:
        _collective_fallback("padded-operand")
        return None
    inp = _input_of(x)
    if inp is None:
        _collective_fallback("tracer-operand")
        return None
    try:
        fn = comm._collective_fn("alltoall", concat_axis, x.ndim, sa=split_axis)
        okey = (
            "collective", "alltoall", comm.mesh, comm.axis_name, _comm_topo(comm),
            concat_axis, split_axis, x.ndim,
        )
        aval = _eval_node(fn, okey, (inp,), (), None)
    except Exception:
        _collective_fallback("abstract-eval")
        return None
    if tuple(aval.shape) != tuple(x.shape):
        _collective_fallback("layout")
        return None
    node = _Node(fn, okey, (inp,), (), None, aval)
    return _finish(
        node, tuple(x.shape), x.dtype, split_axis, x.device, comm, "collective"
    )


# ------------------------------------------------------------------ flush
_TRACE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_cache_stats = {"hits": 0, "misses": 0, "evictions": 0}

#: Poisoned graph signatures (recovery-ladder circuit breaker): trace keys
#: whose fused execution failed and had to be recovered by eager replay.
#: Identical future chains skip the fused attempt entirely. Ordered so the
#: cap evicts the oldest poisoning first.
_POISONED: "collections.OrderedDict" = collections.OrderedDict()
_POISON_MAX = 1024

#: Chain signatures whose BUCKETED execution hit an OOM and recovered on the
#: exact-shape kernel (the ladder's debucket rung, ISSUE 9): future flushes
#: of the same signature skip aval bucketing outright — the padded
#: temporaries are what blew the memory plan, so re-trying them every flush
#: would be a retry tax. Capped like the poison set; cleared together.
_BUCKET_OOM: "collections.OrderedDict" = collections.OrderedDict()


def cache_info() -> dict:
    """Trace-cache statistics (entries/max/hits/misses/evictions), the number
    of poisoned signatures currently short-circuiting to eager replay, and the
    abstract-eval memo's occupancy/capacity (``eval_entries``/``eval_max`` —
    the two caches are sized and cleared together; see :func:`clear_cache`)."""
    ev = _eval_node_cached.cache_info()
    info = {
        "entries": len(_TRACE_CACHE),
        "max": _cache_max(),
        "poisoned": len(_POISONED),
        "bucket_oom": len(_BUCKET_OOM),
        "eval_entries": ev.currsize,
        "eval_max": ev.maxsize,
        **_cache_stats,
    }
    # per-tenant L1 partition occupancy (ISSUE 15) — attached only when
    # tenancy is armed so the off-mode dict is byte-identical to PR 14
    spec = os.environ.get("HEAT_TPU_TENANCY", "").strip()
    if spec and spec.lower() not in ("0", "false", "off"):
        from ..serving import tenancy as _tenancy

        info["l1_partitions"] = _tenancy.partition_info()
    return info


def clear_cache() -> None:
    """Drop every cached fused executable, every poisoned-signature record,
    AND the per-node abstract-eval memo (kept traces are re-built — and
    previously poisoned chains re-attempted — lazily). The eval memo is
    cleared coherently with the trace LRU: the two are independent caches
    with equal default capacity, and clearing one but not the other would
    let stale eval entries outlive every executable they described."""
    _TRACE_CACHE.clear()
    _POISONED.clear()
    _BUCKET_OOM.clear()
    _eval_node_cached.cache_clear()
    try:
        from ..serving import tenancy as _tenancy

        _tenancy.clear_partitions()
    except Exception:  # serving package mid-import: nothing partitioned yet
        pass
    try:
        from ..serving import symbolic as _symaot

        _symaot.clear()
    except Exception:  # same: the serving package may be mid-import
        pass


def _topo(root: _Node):
    """Post-order of the pending (value-less) subgraph under ``root``."""
    order, seen = [], set()
    stack = [(root, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for a in node.args:
            if isinstance(a, _Node) and a.value is None and id(a) not in seen:
                stack.append((a, False))
    return order


def _donatable(arr, owner_ref, out_avals, wrappers: int = 1) -> bool:
    """A leaf buffer may be donated to the fused call iff its owning DNDarray
    is dead, nothing else references the buffer (strict refcount bound), the
    backend actually implements donation, and the buffer aliases one of the
    kernel's outputs (same shape/dtype) so XLA can reuse it in place. The
    caller additionally verifies the flushed subgraph is *private* — no node
    in it is referenced by another live pending graph that could replay from
    the same leaves."""
    if owner_ref is not None and owner_ref() is not None:
        return False
    if not any(
        tuple(arr.shape) == tuple(av.shape) and arr.dtype == av.dtype
        for av in out_avals
    ):
        return False
    try:
        platform = next(iter(arr.devices())).platform
    except Exception:
        return False
    if platform not in ("tpu", "gpu", "cuda", "rocm") and not _donate_forced():
        return False
    # The flush plumbing itself pins a fixed number of references by the time
    # this check runs (the _Leaf.array slot, the leaf_arrays slot, the
    # caller's loop local, plus getrefcount's reported temporary — call
    # arguments are reference-borrowed under CPython's vectorcall, so frames
    # between here and the flush add nothing). Measured invariant at this
    # site: a cleanly dead single-graph buffer sits at exactly 6 across graph
    # shapes (calibrated by the ISSUE 19 decode steady-state, where the old
    # KV-cache buffer must donate every step) — with ONE in-graph holder.
    # A leaf consumed by several recorded nodes carries one live wrapper
    # (_Leaf.array or a concrete _Node.value) per holder, so the bound
    # widens by exactly the extra holders ``_build_flush`` counted (the
    # ISSUE 20 train step feeds theta to grad AND loss). One more than that
    # means a reference OUTSIDE this flush — a second graph's leaf, a
    # user-held .larray — and the buffer must survive this call.
    return sys.getrefcount(arr) <= 5 + max(1, int(wrappers))


def _replay_fn(program, out_idx):
    """The positional replay callable for a flush program (jitted for the
    fused kernel; also rebuilt donation-free by the recovery ladder)."""
    prog = tuple(program)

    def replay(*leaves):
        vals = []
        for fn, specs, kw, cast in prog:
            args = [
                vals[i] if tag == "n" else (leaves[i] if tag == "l" else i)
                for tag, i in specs
            ]
            vals.append(_apply(fn, args, kw, cast))
        return tuple(vals[i] for i in out_idx)

    return replay


def _eager_replay(program, leaf_arrays, out_idx):
    """Per-op eager replay of a flush program: every recorded op dispatches
    standalone on concrete arrays, exactly like ``HEAT_TPU_FUSION=0`` — the
    recovery ladder's always-works bottom rung (bit-identical to the eager
    path by construction: same ops, same order, no fused kernel for XLA to
    contract FMAs in)."""
    vals = []
    for fn, specs, kw, cast in program:
        args = [
            vals[i] if tag == "n" else (leaf_arrays[i] if tag == "l" else i)
            for tag, i in specs
        ]
        vals.append(_apply(fn, args, kw, cast))
    return tuple(vals[i] for i in out_idx)


def _classify_failure(e: BaseException, compiled: bool) -> str:
    """Failure class for ``fusion.flush_failures``: oom (RESOURCE_EXHAUSTED /
    out-of-memory signatures, whatever the phase), compile (a trace-cache miss
    whose build/compile raised), runtime (a cached executable raised)."""
    msg = str(e)
    if (
        isinstance(e, MemoryError)
        or "RESOURCE_EXHAUSTED" in msg
        or "out of memory" in msg.lower()
    ):
        return "oom"
    return "compile" if compiled else "runtime"


def _poison(key) -> None:
    if key is None or key in _POISONED:
        return
    _POISONED[key] = True
    while len(_POISONED) > _POISON_MAX:
        _POISONED.popitem(last=False)
    if _MON.enabled:
        _instr.fusion_poisoned()


def _audit_flush(
    values, program, leaf_arrays, out_idx, donate, key, stable_prog, digest=None
):
    """Shadow-replay audit of one sampled fused flush (ISSUE 12,
    ``HEAT_TPU_AUDIT_RATE``): re-run the retained per-op eager replay — the
    ladder's rung-3 program, bit-parity with ``HEAT_TPU_FUSION=0`` by
    construction — and compare every output under the documented carve-out
    tolerances (:mod:`heat_tpu.robustness.integrity`). A mismatch counts
    ``robustness.integrity{mismatch}``, drops the suspect executable from
    the trace LRU, POISONS the signature (identical future chains run
    permanently eager) and quarantines the L2 entry + corpus recipe; policy
    ``HEAT_TPU_AUDIT_ACTION=raise`` raises
    :class:`~heat_tpu.robustness.integrity.IntegrityError` at the
    materialization barrier, the default ``degrade`` returns the trusted
    eager values. Donating flushes are skipped (the fused kernel may have
    consumed the retained leaves on accelerator backends — counted
    ``skip-donated``); the replay runs the exact recorded callables
    (pallas-backed sinks included), so a clean flush compares bit-for-bit
    up to the fused kernel's own FMA/excess-precision carve-outs."""
    if donate:
        if _MON.enabled:
            _instr.integrity("skip-donated")
        return values
    if _MON.enabled:
        _instr.integrity("audit")
    ref = _eager_replay(program, leaf_arrays, out_idx)
    bad = _INTEG.compare_outputs(values, ref)
    if not bad:
        return values
    if _MON.enabled:
        _instr.integrity("mismatch")
    if key is not None:
        # same thread as the flush: the tenant context (and so the L1 slice
        # the broken executable was stored in) is still installed
        _l1_cache()[0].pop(key, None)
    _poison(key)
    cache_dir = os.environ.get("HEAT_TPU_CACHE_DIR", "").strip()
    if cache_dir and stable_prog is not None:
        try:
            from ..serving import cache as _disk

            if digest is None:
                digest = _disk.digest_for(stable_prog, leaf_arrays, donate, out_idx)
            if digest is not None:
                _disk.evict(cache_dir, digest)
        except Exception:
            pass  # eviction is best-effort; poisoning already isolates L1
    if digest is not None and digest.startswith("sym-"):
        # a symbolic family whose flush failed the audit must not serve again
        # from the in-process family cache either (the L2 entry + corpus
        # recipe are quarantined above)
        try:
            from ..serving import symbolic as _symaot

            _symaot.forget(digest[len("sym-"):])
        except Exception:
            pass
    if _INTEG.audit_action() == "raise":
        raise _INTEG.IntegrityError(
            f"shadow-replay audit mismatch at fused output(s) {bad}: the "
            "fused kernel's values diverge from the retained eager replay "
            "beyond the documented carve-out tolerances (signature "
            "poisoned, cache entries evicted — see doc/integrity_notes.md)"
        )
    # degrade: the eager replay IS the rung-3 trusted value; serve it, and
    # the poisoned signature routes every identical future chain eager
    return ref


def _flush_ladder(
    fused, program, leaf_arrays, out_idx, donate, compiled, key,
    has_coll=False, debucket=None, has_pallas=False, note=None, compile_t0=None,
):
    """Execute a fused flush with graceful degradation.

    Rungs: (1) the fused kernel as planned; (1b) when the failure classifies
    ``oom`` and the program was shape-bucketed (``debucket`` is the caller's
    exact-shape retry closure), drop the padded temporaries and run the
    unbucketed kernel once — counted ``fusion.flush_failures{oom-bucketed}``,
    and the signature skips bucketing from then on; (2) on failure, one retry
    with buffer donation disabled (skipped when nothing was donated — the
    rebuild would be byte-identical); (3) per-op eager replay of the retained
    program, which cannot fail for reasons the fused kernel introduced, plus
    poisoning of the signature so identical future chains skip straight to
    eager. Each failed rung counts ``fusion.flush_failures{class}``; any
    recovery counts ``fusion.flush_recovered``. The ``fusion.compile``/
    ``fusion.execute`` fault-injection sites are consulted per attempt, so
    every rung is deterministically testable, and rung-1 outcomes feed the
    ``fusion.compile``/``collective.dispatch`` circuit breakers (ISSUE 9) so
    a flapping site eventually routes flushes straight to eager replay.
    A pallas-bearing program (``has_pallas``) additionally consults the
    ``pallas.execute`` fault site on the fused attempt — and the recovery
    rungs run under :func:`heat_tpu.core.pallas.recovery_mode`, in which
    every pallas-backed sink callable re-emits its XLA reference formulation
    instead of the kernel, so a failing kernel degrades to the XLA path (the
    ``collective.dispatch`` precedent: recovery is proven, not prevented).
    Caveat (documented in robustness_notes): if a *donating* kernel fails
    after consuming its donated buffers — possible on TPU/GPU only — the
    retained leaves are gone and the rung-2/3 replays surface that error
    instead; donation requires owner-death, so no user-visible array is ever
    lost.

    Observability (ISSUE 13): ``note`` (a dict, only when the flight
    recorder is armed) receives ``rung`` — which rung produced the values —
    and ``failures`` — the failure classes of the rungs that did not;
    ``compile_t0`` (a ``perf_counter`` stamp, only when this flush built a
    fresh in-memory kernel whose first dispatch pays the XLA compile) feeds
    the ``fusion.compile_latency`` histogram on rung-1 success."""
    try:
        if compiled:
            _FI.check("fusion.compile")
        _FI.check("fusion.execute")
        if has_coll:
            # collective-bearing flush: the fused program IS the dispatch of
            # its recorded collectives, so the distributed layer's fault site
            # is consulted here (once per attempt); the ladder's eager replay
            # below is the recovery path and deliberately does not re-consult
            # it — a standing collective.dispatch plan proves recovery instead
            # of making recovery impossible
            _FI.check("collective.dispatch")
        if has_pallas:
            _FI.check("pallas.execute")
        values = fused(*leaf_arrays)
        # value-level fault site (ISSUE 12): the SDC adversary perturbs the
        # FUSED kernel's outputs — the one execution path nobody re-checks —
        # which the shadow-replay audit in materialize_for must catch. The
        # recovery rungs below replay the retained program per-op and are
        # deliberately never corrupted: they are the trusted reference.
        values = _FI.corrupt_value("fusion.execute", values)
        if compile_t0 is not None:
            # in-memory compile path: the first dispatch of the fresh jit
            # wrapper just paid trace + XLA compile (+ a negligible execute)
            dt = time.perf_counter() - compile_t0
            if _MON.enabled:
                _instr.fusion_compile_latency(dt)
            _trace.stage("compile", dt)
        if note is not None:
            note["rung"] = "fused"
        if compiled:
            _BRK.breaker("fusion.compile").record_success()
        if has_coll:
            _BRK.breaker("collective.dispatch").record_success()
        return values
    except (KeyboardInterrupt, SystemExit, _FI.FaultPlanError):
        raise  # a malformed fault PLAN is a config error, not a failure
    except Exception as e:
        cls = _classify_failure(e, compiled)
        if _MON.enabled:
            _instr.fusion_flush_failure(cls)
        if note is not None:
            note.setdefault("failures", []).append(cls)
        if compiled:
            _BRK.breaker("fusion.compile").record_failure()
        if has_coll:
            _BRK.breaker("collective.dispatch").record_failure()
        if key is not None:
            # never hand the broken executable to a future flush (the ladder
            # runs on the flush's own thread, so the tenant L1 slice matches)
            _l1_cache()[0].pop(key, None)
        values = None
        if cls == "oom" and debucket is not None:
            # the padded bucket temporaries are the likeliest extra memory in
            # the failed plan: retry once at the exact shapes before demoting
            # the whole signature to eager replay
            if _MON.enabled:
                _instr.fusion_flush_failure("oom-bucketed")
            try:
                _FI.check("fusion.compile")  # the exact-shape kernel is fresh
                _FI.check("fusion.execute")
                if has_coll:
                    _FI.check("collective.dispatch")
                with _PL.recovery_mode():
                    values = debucket()
                if note is not None:
                    note["rung"] = "oom-debucket"
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e1:
                cls1 = _classify_failure(e1, True)
                if _MON.enabled:
                    _instr.fusion_flush_failure(cls1)
                if note is not None:
                    note.setdefault("failures", []).append(cls1)
        if values is None and donate:
            try:
                _FI.check("fusion.compile")  # rung 2 always builds fresh
                _FI.check("fusion.execute")
                if has_coll:
                    _FI.check("collective.dispatch")
                with _PL.recovery_mode():
                    values = jax.jit(_replay_fn(program, out_idx))(*leaf_arrays)
                if note is not None:
                    note["rung"] = "donation-off"
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e2:
                cls2 = _classify_failure(e2, compiled)
                if _MON.enabled:
                    _instr.fusion_flush_failure(cls2)
                if note is not None:
                    note.setdefault("failures", []).append(cls2)
        if values is None:
            with _PL.recovery_mode():
                values = _eager_replay(program, leaf_arrays, out_idx)
            _poison(key)
            if note is not None:
                note["rung"] = "eager-replay"
        if _MON.enabled:
            _instr.fusion_flush_recovered()
        return values


def _build_flush(root: _Node):
    """Positional replay program of the pending subgraph under ``root``:
    ``(topo, index_of, program, key_prog, stable_prog, leaf_arrays,
    leaf_owners, internal_rc)`` — shared by :func:`materialize_for` and
    :func:`flush_through`.

    ``stable_prog`` is the cross-process twin of ``key_prog`` the serving
    layer keys its persistent disk cache and shape corpus on: per node
    ``(skey, specs, kwargs, cast_key)`` with baked constants carried as
    ``("c", type_name, value)`` instead of live type objects. It is ``None``
    whenever any node lacks a stable identity (collective nodes close over
    mesh/comm objects) — such programs stay in-memory-only.

    ``leaf_holders`` (parallel to ``leaf_arrays``) counts the DISTINCT live
    wrapper objects holding each deduplicated buffer inside this graph — one
    ``_Leaf`` per (node, operand) record site, or one concrete ``_Node`` —
    so :func:`_donatable`'s refcount bound can widen for multi-consumer
    leaves instead of silently refusing donation."""
    topo = _topo(root)
    index_of = {id(n): i for i, n in enumerate(topo)}

    leaf_ids: dict = {}
    leaf_arrays: list = []
    leaf_owners: list = []
    leaf_holder_ids: list = []

    def leaf_index(arr, owner, holder):
        key = id(arr)
        i = leaf_ids.get(key)
        if i is None:
            i = len(leaf_arrays)
            leaf_ids[key] = i
            leaf_arrays.append(arr)
            leaf_owners.append(owner)
            leaf_holder_ids.append(set())
        leaf_holder_ids[i].add(id(holder))
        return i

    program = []  # (fn, specs, kwargs, cast) per node, positional
    key_prog = []
    stable_prog = []
    stable_ok = True
    internal_rc: dict = {}
    for n in topo:
        specs = []
        key_specs = []
        stable_specs = []
        for a in n.args:
            if isinstance(a, _Node):
                if a.value is not None:
                    i = leaf_index(a.value, a.owner, a)
                    specs.append(("l", i))
                    key_specs.append(("l", i))
                    stable_specs.append(("l", i))
                else:
                    internal_rc[id(a)] = internal_rc.get(id(a), 0) + 1
                    specs.append(("n", index_of[id(a)]))
                    key_specs.append(("n", index_of[id(a)]))
                    stable_specs.append(("n", index_of[id(a)]))
            elif isinstance(a, _Leaf):
                i = leaf_index(a.array, a.owner, a)
                specs.append(("l", i))
                key_specs.append(("l", i))
                stable_specs.append(("l", i))
            else:
                specs.append(("c", a))
                key_specs.append(_const_key(a))
                stable_specs.append(("c", type(a).__name__, a))
        program.append((n.fn, tuple(specs), dict(n.kwargs), n.cast))
        cast_key = None if n.cast is None else (str(n.cast[0]), n.cast[1])
        key_prog.append((n.op_key, tuple(key_specs), n.kwargs, cast_key))
        if n.skey is None:
            stable_ok = False
        else:
            stable_prog.append((n.skey, tuple(stable_specs), n.kwargs, cast_key))
    return (
        topo, index_of, program, key_prog,
        tuple(stable_prog) if stable_ok else None,
        leaf_arrays, leaf_owners, internal_rc,
        tuple(len(h) for h in leaf_holder_ids),
    )


def _leaf_cache_key(leaf_arrays):
    return tuple(
        (
            tuple(a.shape),
            str(a.dtype),
            bool(getattr(a, "weak_type", False)),
            getattr(a, "sharding", None),
        )
        for a in leaf_arrays
    )


def materialize_for(d: DNDarray):
    """Flush the pending subgraph behind ``d`` through one fused, cached,
    jitted kernel and return the canonical (placed) physical array."""
    from .communication import MeshCommunication

    root = d._expr()
    if root is None:  # pragma: no cover — callers check
        raise RuntimeError("materialize_for() on a concrete DNDarray")
    if root.value is not None:
        return root.value

    (
        topo, index_of, program, key_prog, stable_prog,
        leaf_arrays, leaf_owners, internal_rc, leaf_holders,
    ) = _build_flush(root)

    # ---- observability: execution flight recorder (ISSUE 13). Armed by
    # HEAT_TPU_FLIGHT=1; off (the default) this is ONE env read per flush —
    # no note dict, no timing stamps, no ring allocation. Recording is a
    # pure observation: nothing below branches on flight_on except the
    # bookkeeping itself, so results are bit-identical either way.
    flight_on = _FL.flight_enabled()
    t_flush0 = time.perf_counter() if flight_on else 0.0
    note: Optional[dict] = {} if flight_on else None
    # distributed tracing (ISSUE 16): the scheduler installed the request's
    # trace context on this thread when sampled; unsampled = one thread-local
    # read, no stamps, no stage records — same pure-observation contract.
    req_trace = _trace.current()

    # Recorded collectives in the program (excluding the pure-slice halo
    # views): they gate the dispatch-site fault check, the comm.collective
    # accounting, and the widened multi-output rule below.
    coll_kinds = [
        n.op_key[1]
        for n in topo
        if n.op_key and n.op_key[0] == "collective" and n.op_key[1] != "haloslice"
    ]

    # Pallas-backed sink nodes in the program: they gate the pallas.execute
    # fault site in the ladder's fused attempt, and the recovery rungs run
    # under pallas.recovery_mode so the replay re-emits the XLA reference
    # formulation instead of the failed kernel.
    has_pallas = any(
        n.op_key and n.op_key[0] == "sink" and len(n.op_key) > 1
        and n.op_key[1] == "pallas"
        for n in topo
    )

    # Outputs: the root — and, when the root is a reduction SINK or the
    # program carries a COLLECTIVE, every pending interior node whose owning
    # DNDarray is still alive. A sink leaves its consumed chain pending; when
    # the chain will plausibly be read later (a live owner), materializing it
    # as a SECOND output of the same kernel costs only the write the pre-sink
    # path always paid, and saves a full recompute + recompile when the owner
    # is read. Dead-owner chains (the hot loss/norm pattern) keep the
    # single-read floor. Collective-bearing programs widen the same way so a
    # later read of the consumed chain (or of the halo exchange's stacked
    # block from one of its slice views) never re-dispatches the ICI
    # transfer.
    out_nodes = [root]
    if (root.op_key and root.op_key[0] == "sink") or coll_kinds:
        for n in topo:
            if n is not root and n.owner is not None and n.owner() is not None:
                out_nodes.append(n)
    out_ids = {id(n) for n in out_nodes}
    out_idx = tuple(index_of[id(n)] for n in out_nodes)

    out_avals = tuple(n.aval for n in out_nodes)
    donate = ()
    if _donate_enabled():
        # donation is only safe when this subgraph is private: every non-root
        # node's recorded parents all sit inside the subgraph AND it cannot be
        # replayed later — its owning DNDarray is dead, or it receives a value
        # as an output of this very flush. Otherwise a live pending graph (a
        # reduction sink leaves its operand chain pending) could replay these
        # nodes from the donated leaves.
        private = all(
            n is root
            or id(n) in out_ids
            or (
                n.rc == internal_rc.get(id(n), 0)
                and (n.owner is None or n.owner() is None)
            )
            for n in topo
        )
        if private:
            # L2-persistable flushes (cache dir armed + stable program: this
            # executable may be serialized and later DESERIALIZED by another
            # process) never donate a MULTI-consumer leaf. A deserialized
            # executable honors the baked-in input-output alias, but the
            # reloaded call contract loses the donated-argument bookkeeping
            # for a buffer the program also reads through a second node —
            # input and aliased output then both own the allocation and it
            # double-frees at teardown. Single-consumer aliases round-trip
            # cleanly (the ISSUE 19 decode caches); in-memory-only flushes
            # keep the widened multi-holder mask. The mask is part of the
            # L2 digest, so every process derives the same rule and no
            # entry with the unsafe alias ever lands on disk.
            persistable = stable_prog is not None and bool(
                os.environ.get("HEAT_TPU_CACHE_DIR", "").strip()
            )
            donate_idx = []
            for i in range(len(leaf_arrays)):
                if persistable and leaf_holders[i] > 1:
                    continue
                arr = leaf_arrays[i]
                if _donatable(arr, leaf_owners[i], out_avals, leaf_holders[i]):
                    donate_idx.append(i)
                del arr
            donate = tuple(donate_idx)

    # ---- serving: symbolic-family AOT (ISSUE 17). Under
    # HEAT_TPU_SYMBOLIC_AOT=1, a program passing the SAME eligibility rule
    # bucketing uses (pointwise, single-output, uniform single-device leaves)
    # is served by one jax.export shape-polymorphic executable per *family*
    # (shapes erased from the key) instead of one kernel per bucket: no pad,
    # no slice, kernel count below the bucketing floor. Supersedes bucketing
    # for eligible programs (the bucket block below is skipped, so
    # serving.bucket{pad_waste_bytes} stays 0 on symbolic-served flushes);
    # ineligible programs take the exact path untouched. Env-gated: the off
    # path costs one os.environ read.
    sym_family = None
    if stable_prog is not None and os.environ.get(
        "HEAT_TPU_SYMBOLIC_AOT", ""
    ).strip().lower() in ("1", "true", "on"):
        from ..serving import symbolic as _symaot

        sym_family = _symaot.family_digest(
            stable_prog, out_idx, tuple(root.aval.shape), leaf_arrays
        )
        if sym_family is not None:
            donate = ()  # family executables are exported donation-free

    # ---- serving: aval bucketing (ISSUE 8). Pointwise-only programs over
    # uniform single-device leaves may have their leaves zero-padded up to the
    # configured bucket edges BEFORE keying, so shape-diverse traffic shares
    # one kernel per bucket instead of one per distinct shape; the root output
    # is sliced back to the logical shape after the ladder below (bit-parity:
    # every surviving op is pointwise, so the pad region never influences a
    # logical element). Env-gated: the off path costs one os.environ read.
    bucket_slicer = None
    debucket = None
    bspec = os.environ.get("HEAT_TPU_SHAPE_BUCKETS", "").strip()
    if (
        sym_family is None
        and bspec
        and bspec.lower() not in ("0", "false", "off")
        and stable_prog is not None
    ):
        from ..serving import buckets as _buckets

        # a signature whose bucketed execution already hit OOM (and recovered
        # on the exact-shape kernel) skips bucketing outright — the padded
        # temporaries are what blew the memory plan (ISSUE 9 satellite)
        try:
            bkey = (tuple(key_prog), _leaf_cache_key(leaf_arrays), out_idx)
            skip_bucketing = bkey in _BUCKET_OOM
        except TypeError:  # unhashable sharding — no OOM memo either
            bkey, skip_bucketing = None, False
        bplan = (
            None
            if skip_bucketing
            else _buckets.plan(
                bspec, stable_prog, out_idx, tuple(root.aval.shape), leaf_arrays
            )
        )
        if bplan is not None:
            orig_leaves = leaf_arrays
            leaf_arrays, bucket_slicer = bplan
            donate = ()  # the padded copies are fresh private temporaries
            if note is not None:
                note["pad_waste"] = int(
                    sum(int(getattr(a, "nbytes", 0)) for a in leaf_arrays)
                    - sum(int(getattr(a, "nbytes", 0)) for a in orig_leaves)
                )

            def debucket(_orig=orig_leaves, _bkey=bkey):
                # the ladder's oom-bucketed rung: run the exact-shape kernel
                # (no padded temporaries) and remember the signature so
                # future flushes of this chain key on exact shapes directly
                values = jax.jit(_replay_fn(program, out_idx))(*_orig)
                if _bkey is not None:
                    _BUCKET_OOM[_bkey] = True
                    while len(_BUCKET_OOM) > _POISON_MAX:
                        _BUCKET_OOM.popitem(last=False)
                return values

    leaf_key = _leaf_cache_key(leaf_arrays)
    l1, l1_tenant = _l1_cache()
    try:
        # a symbolic-served signature keys under its own tag so flipping the
        # hatch mid-process never aliases a family executable with an exact
        # kernel (both are bit-identical; the tag keeps accounting honest)
        key = (tuple(key_prog), leaf_key, donate, out_idx) + (
            ("sym",) if sym_family is not None else ()
        )
        fused = l1.get(key)
    except TypeError:  # unhashable sharding — compile uncached
        key, fused = None, None

    if _MON.enabled and coll_kinds:
        # the flush dispatches the recorded collectives exactly once whichever
        # rung executes them; mirror the eager shims' accounting (resplit and
        # halo count placement/resharding at their record sites, like their
        # eager paths, and never went through a named shim)
        for k in coll_kinds:
            if k in ("ppermute", "alltoall"):
                _instr.collective(k)

    digest = None  # the flight record reads it whichever branch runs
    poisoned = key is not None and key in _POISONED
    breaker_eager = False
    if not poisoned:
        # site-level circuit breakers (ISSUE 9, robustness/breaker.py): an
        # open fusion.compile breaker routes L1-miss flushes straight to the
        # eager-replay rung (skipping a doomed compile attempt); an open
        # collective.dispatch breaker fails collective-bearing flushes fast
        # to the retained eager barrier path. Both are bit-identical to the
        # ladder's own recovery — the breaker only removes the retry tax.
        if fused is None and not _BRK.breaker("fusion.compile").allow():
            breaker_eager = True
        elif coll_kinds and not _BRK.breaker("collective.dispatch").allow():
            breaker_eager = True
    if poisoned or breaker_eager:
        # per-signature poisoning (the recovery ladder's own breaker) or an
        # open site breaker: skip straight to eager (no compile, no retry
        # tax); the result is bit-identical by construction
        if poisoned:
            try:
                _POISONED.move_to_end(key)
            except KeyError:  # concurrent clear_cache (scheduler threads)
                pass
        if _MON.enabled:
            _instr.fusion_flush(
                len(topo), cache_hit=False, compiled=False, reason=_reason_stack()[-1]
            )
        if note is not None:
            note["cache"] = "eager"
            note["rung"] = "eager-replay"
            note["poisoned"] = bool(poisoned)
        with _PL.recovery_mode():
            values = _eager_replay(program, leaf_arrays, out_idx)
    else:
        # ---- serving: persistent L2 on L1 miss (ISSUE 8). With
        # HEAT_TPU_CACHE_DIR set, a trace-LRU miss consults the on-disk
        # compilation cache keyed by the process-stable twin of the LRU key
        # plus the jaxlib/backend fingerprint; a hit deserializes the
        # compiled executable — no XLA compile, counted as a cache hit — and
        # a miss AOT-compiles via .lower().compile() so the executable can
        # be serialized back to disk for every future process.
        from_disk = False
        digest = None
        disk = None
        sym_state = None
        cache_dir = ""
        if fused is None:
            cache_dir = os.environ.get("HEAT_TPU_CACHE_DIR", "").strip()
        if fused is None and sym_family is not None:
            # symbolic-family resolution (ISSUE 17): in-process family cache,
            # then the L2 symbolic entry, then a fresh export (persisted +
            # corpus-recorded). A fresh export is the family's ONE compile
            # tick; family/L2 service is a cache hit. Failure falls through
            # to the exact path below, bit-identical by construction.
            from ..serving import symbolic as _symaot

            t_sym0 = time.perf_counter()
            fused, sym_state = _symaot.executable(
                cache_dir, sym_family, program, out_idx, leaf_arrays, stable_prog
            )
            if fused is not None:
                digest = _symaot.DIGEST_PREFIX + sym_family
                if sym_state != "export":
                    from_disk = True
        if fused is None and cache_dir:
            from ..serving import cache as disk

            if stable_prog is None:
                disk.incompatible("unstable-program")
            else:
                digest = disk.digest_for(stable_prog, leaf_arrays, donate, out_idx)
                if digest is None:
                    disk.incompatible("leaf-layout")
                else:
                    fused = disk.load(cache_dir, digest)
                    from_disk = fused is not None
        compiled = fused is None or sym_state == "export"
        if from_disk:
            # a disk-served executable satisfies the compile-class operation
            # (incl. a half-open probe) even though no XLA compile ran
            _BRK.breaker("fusion.compile").record_success()
            if flight_on and cache_dir and sym_state is None:
                # a zero-compile process keeps attribution: the compiling
                # process persisted a cost card beside the L2 entry
                _FL.load_cost_card(cache_dir, digest)
        compile_t0 = None
        if sym_state == "export":
            # the export paid trace + lowering; the first dispatch of
            # jit(exported.call) below pays the per-shape XLA refinement —
            # rung 1 attributes the whole span to the compile stage
            compile_t0 = t_sym0
        if fused is None:
            compile_t0 = time.perf_counter()
            fused = jax.jit(_replay_fn(program, out_idx), donate_argnums=donate)
            if digest is not None:
                # AOT-compile now so the executable is serializable; on
                # success the Compiled replaces the jit wrapper in L1 (same
                # call contract, no retrace) and lands on disk + in the
                # shape corpus for the warmup driver
                aot = disk.store(
                    cache_dir, digest, fused, leaf_arrays, stable_prog,
                    donate, out_idx,
                )
                if aot is not None:
                    fused = aot
                    # the AOT path paid the XLA compile inside store();
                    # the ladder's rung-1 dispatch is then execute-only
                    compile_dt = time.perf_counter() - compile_t0
                    if _MON.enabled:
                        _instr.fusion_compile_latency(compile_dt)
                    if req_trace is not None:
                        _trace.stage("compile", compile_dt, trace=req_trace)
                    compile_t0 = None
        if key is not None:
            if compiled or from_disk:
                l1[key] = fused
                _cache_stats["misses"] += 1
                if l1_tenant is None:
                    limit = _cache_max()
                else:
                    from ..serving import tenancy as _tenancy

                    limit = _tenancy.l1_capacity(l1_tenant, _cache_max())
                while len(l1) > limit:
                    l1.popitem(last=False)
                    _cache_stats["evictions"] += 1
                    if l1_tenant is not None:
                        from ..serving import tenancy as _tenancy

                        _tenancy.count_eviction(l1_tenant)
            else:
                try:
                    l1.move_to_end(key)
                except KeyError:  # concurrent eviction (scheduler threads)
                    pass
                _cache_stats["hits"] += 1

        if _MON.enabled:
            # NB: `compiled` counts the compile ATTEMPT — if it fails, the
            # ladder counters below carry the outcome and the broken entry is
            # dropped from the cache; a disk-cache hit is a cache hit (the
            # executable was deserialized, never compiled)
            _instr.fusion_flush(
                len(topo),
                cache_hit=not compiled,
                compiled=compiled,
                reason=_reason_stack()[-1],
            )
            if donate:
                # ISSUE 19: a steady_state tick is a donated buffer riding a
                # trace-cache HIT — the persistent KV-cache re-donation
                # proof (before this counter only the first, compiling,
                # donation was observable on the ledger)
                _instr.fusion_donated(len(donate), steady=not compiled)

        if note is not None:
            note["cache"] = "l2" if from_disk else ("compile" if compiled else "l1")
            if sym_state is not None:
                note["symbolic"] = sym_state

        # execute = ladder wall minus whatever compile time the ladder itself
        # attributed (the in-memory first dispatch records its compile stage
        # inside rung 1) — the two stages partition the dispatch exactly
        t_exec0 = time.perf_counter()
        c_before = req_trace.stage_s("compile") if req_trace is not None else 0.0
        values = _flush_ladder(
            fused, program, leaf_arrays, out_idx, donate, compiled, key,
            has_coll=bool(coll_kinds), debucket=debucket, has_pallas=has_pallas,
            note=note, compile_t0=compile_t0,
        )
        if req_trace is not None:
            ladder_wall = time.perf_counter() - t_exec0
            c_gain = req_trace.stage_s("compile") - c_before
            _trace.stage("execute", max(0.0, ladder_wall - c_gain), trace=req_trace)

        # ---- integrity: shadow-replay audit (ISSUE 12). Every Nth fused
        # flush also runs the retained eager replay and compares outputs;
        # off (the default) this is one os.environ read. The poisoned /
        # breaker-eager branch above IS the eager replay — nothing to audit.
        if _INTEG.audit_due():
            audited = _audit_flush(
                values, program, leaf_arrays, out_idx, donate, key, stable_prog,
                digest=digest,
            )
            if note is not None:
                note["audit"] = (
                    "skip-donated" if donate
                    else ("clean" if audited is values else "mismatch")
                )
            values = audited

    t_carve0 = time.perf_counter() if req_trace is not None else 0.0
    if bucket_slicer is not None:
        # restore the logical view from the bucket-padded root output (the
        # plan admits single-output pointwise programs only)
        values = (values[0][bucket_slicer],)

    # canonical placement — the step DNDarray.__init__ applies to every eager
    # intermediate, applied once per fused output here (the root places on
    # ``d``'s layout; extra sink-chain outputs on their live owner's)
    for n, value in zip(out_nodes, values):
        owner = d if n is root else n.owner()
        if owner is not None:
            split = owner.split
            comm = owner.comm
            if (
                split is not None
                and isinstance(comm, MeshCommunication)
                and comm.is_distributed()
            ):
                value = comm.placed(value, split, owner.shape)
        n.value = value
    if req_trace is not None:
        _trace.stage("carve", time.perf_counter() - t_carve0, trace=req_trace)

    if flight_on:
        # one structured record per flush. The signature is the L2 digest
        # when the flush computed one; otherwise it is derived here (same
        # canonical serialization, so in-memory and disk-served flushes of
        # one program share a signature); unstable programs (collective
        # nodes close over mesh objects) fall back to the in-process L1 key
        # hash, unhashable shardings to "unkeyed".
        sig = digest
        if sig is None and stable_prog is not None:
            from ..serving import cache as _svc

            sig = _svc.digest_for(stable_prog, leaf_arrays, donate, out_idx)
        if sig is None:
            sig = (
                "mem:%016x" % (hash(key) & 0xFFFFFFFFFFFFFFFF)
                if key is not None
                else "unkeyed"
            )
        kinds: dict = {}
        for n in topo:
            k = str(n.op_key[0]) if isinstance(n.op_key, tuple) and n.op_key else "other"
            kinds[k] = kinds.get(k, 0) + 1
        _FL.record_flush(
            sig,
            time.perf_counter() - t_flush0,
            reason=_reason_stack()[-1],
            chain=len(topo),
            kinds=kinds,
            outputs=len(out_idx),
            leaves=len(leaf_arrays),
            donate=list(donate),
            collectives=list(coll_kinds) or None,
            # trace linkage (ISSUE 16): the flush record parents under the
            # scheduler's serving.flush span id, so the merged Chrome trace
            # hangs the ladder under the request's own subtree
            **(
                {
                    "trace_id": req_trace.trace_id,
                    "parent_span": _trace.current_span_id(),
                }
                if req_trace is not None
                else {}
            ),
            **note,
        )
    return root.value


def flush_through(x: DNDarray, consumer, consumer_key, reason: str = "linalg"):
    """Materialize ``x``'s pending expression THROUGH ``consumer`` — a
    jax-traceable callable taking the chain's physical array — as ONE jitted,
    trace-LRU-cached program: the collective-aware path for library consumers
    whose own program is a shard_map pipeline (the TSQR merge in
    ``linalg/qr.py``). The operand chain, the consumer's collectives, and the
    chain's own materialization compile together, so XLA overlaps the ICI
    transfer with the producer compute; ``x``'s chain value rides the same
    kernel as an extra output (its owner is alive by construction), so a
    later read of ``x`` costs no recompute.

    ``consumer_key`` is the consumer's static identity in the trace-LRU key
    (mesh/axis/size/kernel-flavor — the caller owns it). Returns the tuple of
    consumer outputs, or None when ``x`` is not pending (caller falls back to
    its flushing path). Failures ride the recovery ladder: the fused attempt
    consults the ``fusion.compile``/``fusion.execute``/``collective.dispatch``
    fault sites and a failure is recovered by replaying the retained chain
    per-op and dispatching the consumer's (cached, jitted) program eagerly —
    the retained barrier path, bit-identical by construction."""
    root = x._expr()
    if root is None or root.value is not None:
        return None

    (
        topo, index_of, program, key_prog, _stable,
        leaf_arrays, _owners, _rc, _holders,
    ) = _build_flush(root)
    ridx = index_of[id(root)]
    chain_replay = _replay_fn(program, (ridx,))

    def fused(*leaves):
        (chain_val,) = chain_replay(*leaves)
        out = consumer(chain_val)
        if not isinstance(out, tuple):
            out = (out,)
        return (*out, chain_val)

    leaf_key = _leaf_cache_key(leaf_arrays)
    try:
        key = ("through", consumer_key, tuple(key_prog), leaf_key)
        cached = _TRACE_CACHE.get(key)
    except TypeError:  # unhashable sharding/consumer key — compile uncached
        key, cached = None, None

    has_pallas = any(
        n.op_key and n.op_key[0] == "sink" and len(n.op_key) > 1
        and n.op_key[1] == "pallas"
        for n in topo
    )

    def _eager():
        # recovery mode: pallas-backed sink nodes replay their XLA reference
        with _PL.recovery_mode():
            (chain_val,) = _eager_replay(program, leaf_arrays, (ridx,))
            out = consumer(chain_val)
        if not isinstance(out, tuple):
            out = (out,)
        return (*out, chain_val)

    if key is not None and key in _POISONED:
        _POISONED.move_to_end(key)
        if _MON.enabled:
            _instr.fusion_flush(
                len(topo), cache_hit=False, compiled=False, reason=reason
            )
        values = _eager()
    else:
        compiled = cached is None
        if cached is None:
            cached = jax.jit(fused)
            if key is not None:
                _TRACE_CACHE[key] = cached
                _cache_stats["misses"] += 1
                limit = _cache_max()
                while len(_TRACE_CACHE) > limit:
                    _TRACE_CACHE.popitem(last=False)
                    _cache_stats["evictions"] += 1
        else:
            _TRACE_CACHE.move_to_end(key)
            _cache_stats["hits"] += 1
        if _MON.enabled:
            _instr.fusion_flush(
                len(topo), cache_hit=not compiled, compiled=compiled, reason=reason
            )
        try:
            if compiled:
                _FI.check("fusion.compile")
            _FI.check("fusion.execute")
            _FI.check("collective.dispatch")
            if has_pallas:
                _FI.check("pallas.execute")
            values = cached(*leaf_arrays)
        except (KeyboardInterrupt, SystemExit, _FI.FaultPlanError):
            raise
        except Exception as e:
            if _MON.enabled:
                _instr.fusion_flush_failure(_classify_failure(e, compiled))
            if key is not None:
                _TRACE_CACHE.pop(key, None)
            values = _eager()
            _poison(key)
            if _MON.enabled:
                _instr.fusion_flush_recovered()

    *out, chain_val = values
    # the chain's own value: canonical placement on x's layout, then retained
    # on the node so a later read of x is a no-op
    from .communication import MeshCommunication

    comm = x.comm
    if (
        x.split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
    ):
        chain_val = comm.placed(chain_val, x.split, x.shape)
    root.value = chain_val
    return tuple(out)
