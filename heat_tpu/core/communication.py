"""
The communication substrate: a device-mesh layer replacing the reference's MPI backend.

The reference implements distribution as explicit MPI messages between Python processes
(``MPICommunication`` wrapping mpi4py, reference heat/core/communication.py:120-1888,
with ``MPI_WORLD`` at :1890 and ``get_comm``/``use_comm``/``sanitize_comm`` at
:1897-1940). The TPU-native redesign is single-controller SPMD: one logical program over
a :class:`jax.sharding.Mesh`; a *split* axis of a global array corresponds to a
``NamedSharding`` partitioning that axis over the mesh, and all communication is emitted
by XLA as ICI/DCN collectives (``psum``/``all_gather``/``all_to_all``/``ppermute``)
when ops consume sharded operands. Hence this module carries no message-passing code at
all — it owns the mesh, the split-axis chunk arithmetic (identical layout math to
reference communication.py:161-240 so user code and tests port unchanged), and the
placement helpers that map ``split`` metadata onto shardings.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "Communication",
    "MeshCommunication",
    "WORLD",
    "SELF",
    "MPI_WORLD",
    "MPI_SELF",
    "get_comm",
    "sanitize_comm",
    "use_comm",
    "distributed_init",
]

#: The mesh axis name every 1-D split sharding partitions over.
SPLIT_AXIS: str = "split"


class Communication:
    """
    Base class for communications. Reference parity: the abstract ``Communication``
    base "intended for other backends" (reference heat/core/communication.py:88-118).
    """

    @staticmethod
    def is_distributed() -> bool:
        """Whether this communicator spans more than one device."""
        raise NotImplementedError()

    def chunk(self, shape, split) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """
        Calculates the chunk of data that will be assigned to this compute node given a
        global data shape and a split axis. Returns ``(offset, local_shape, slices)``.
        """
        raise NotImplementedError()


class MeshCommunication(Communication):
    """
    Communicator backed by a JAX device mesh.

    The mesh is one-dimensional with axis name ``"split"``; ``size`` is the number of
    devices along it (the analog of the reference's MPI world size), and ``rank`` is
    this controller's process index (``0`` in single-controller mode — all devices are
    addressed from one program, unlike the reference where every rank owns one shard).

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices forming the mesh. Defaults to all devices of the default backend.
    mesh : jax.sharding.Mesh, optional
        A pre-built 1-D mesh to wrap; mutually exclusive with ``devices``.

    Reference parity: ``MPICommunication`` (heat/core/communication.py:120). The wrapped
    Send/Recv/Bcast/Allreduce/… surface (:521-1873) is intentionally absent: those
    crossings are compiled into the program by XLA.
    """

    def __init__(self, devices: Optional[Sequence["jax.Device"]] = None, mesh: Optional[Mesh] = None):
        if mesh is not None and devices is not None:
            raise ValueError("pass either devices or mesh, not both")
        self.__devices = list(devices) if devices is not None else None
        self.__mesh: Optional[Mesh] = mesh
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError(f"MeshCommunication requires a 1-D mesh, got axes {mesh.axis_names}")
            self.__axis_name = mesh.axis_names[0]
        else:
            self.__axis_name = SPLIT_AXIS

    # ------------------------------------------------------------------ mesh access
    @property
    def mesh(self) -> Mesh:
        """The underlying 1-D device mesh (built lazily on first access)."""
        if self.__mesh is None:
            devs = self.__devices if self.__devices is not None else jax.devices()
            self.__mesh = Mesh(np.asarray(devs), (self.__axis_name,))
        return self.__mesh

    @property
    def axis_name(self) -> str:
        """Name of the mesh axis split arrays are partitioned over."""
        return self.__axis_name

    @property
    def size(self) -> int:
        """Number of devices in the mesh (analog of MPI world size)."""
        return self.mesh.devices.size

    @property
    def nnodes(self) -> int:
        """Alias for :attr:`size` (number of 'compute nodes' = devices)."""
        return self.size

    @property
    def rank(self) -> int:
        """This controller's process index (0 in single-controller SPMD)."""
        return jax.process_index()

    def is_distributed(self) -> bool:
        """Whether the mesh spans more than one device."""
        return self.size > 1

    # ------------------------------------------------------------------ chunk math
    def chunk(
        self,
        shape: Sequence[int],
        split: Optional[int],
        rank: Optional[int] = None,
        w_size: Optional[int] = None,
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """
        Calculates the chunk of data assigned to device ``rank`` given a global
        ``shape`` and a ``split`` axis: returns ``(offset, local_shape, slices)``.

        Sizes differ by at most one; the remainder is spread over the lowest ranks,
        identical to the reference layout (heat/core/communication.py:161-210) so
        chunk-dependent user code ports unchanged.

        Parameters
        ----------
        shape : Tuple[int,...]
            The global shape of the data to be split.
        split : int or None
            The axis along which to chunk the data. ``None`` means no chunking.
        rank : int, optional
            Device slot to compute the chunk for; defaults to 0 (in the reference this
            defaults to the calling MPI rank — here there is one controller).
        w_size : int, optional
            Override for the number of chunks; defaults to :attr:`size`.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(None) for _ in shape)
        split = int(split) % len(shape) if len(shape) else 0
        rank = 0 if rank is None else int(rank)
        size = self.size if w_size is None else int(w_size)
        n = shape[split]
        base, rem = divmod(n, size)
        if rank < rem:
            lsize = base + 1
            offset = rank * (base + 1)
        else:
            lsize = base
            offset = rem * (base + 1) + (rank - rem) * base
        lshape = shape[:split] + (lsize,) + shape[split + 1 :]
        slices = tuple(
            slice(offset, offset + lsize) if d == split else slice(None) for d in range(len(shape))
        )
        return offset, lshape, slices

    def counts_displs(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """
        Per-device counts and displacements along the split axis — the layout the
        reference feeds its vector collectives (heat/core/communication.py:211-240).
        """
        counts, displs = [], []
        for r in range(self.size):
            offset, lshape, _ = self.chunk(shape, split, rank=r)
            counts.append(lshape[split])
            displs.append(offset)
        return tuple(counts), tuple(displs)

    def lshape_map(self, shape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """``(size, ndim)`` array of every device's local shape under :meth:`chunk`."""
        return np.array(
            [self.chunk(shape, split, rank=r)[1] for r in range(self.size)], dtype=np.int64
        )

    # ------------------------------------------------------------------ placement
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """The :class:`PartitionSpec` expressing ``split`` for an ``ndim``-d array."""
        if split is None:
            return PartitionSpec()
        split = int(split) % max(ndim, 1)
        return PartitionSpec(*([None] * split), self.__axis_name)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """The :class:`NamedSharding` expressing ``split`` for an ``ndim``-d array."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    def is_shardable(self, shape: Sequence[int], split: Optional[int]) -> bool:
        """
        Whether ``shape`` can be physically partitioned on ``split`` over this mesh.
        JAX requires the split axis to be divisible by the mesh size; ragged
        distributions (reference dndarray.py:1033 allows arbitrary lshape maps) fall
        back to replicated placement with logical ``split`` metadata retained.
        """
        if split is None:
            return True
        shape = tuple(shape)
        if not shape:
            return False
        split = int(split) % len(shape)
        return shape[split] % self.size == 0

    def shard(self, array: "jax.Array", split: Optional[int]) -> "jax.Array":
        """
        Places ``array`` according to ``split``: partitioned over the mesh when the
        axis is divisible by the mesh size, replicated otherwise. This is the whole of
        the reference's ``resplit_``/``redistribute_`` machinery
        (dndarray.py:1033-1362) — a single resharding ``device_put``; XLA emits the
        all-gather / slice-exchange collectives.
        """
        eff_split = split if self.is_shardable(array.shape, split) else None
        return jax.device_put(array, self.sharding(array.ndim, eff_split))

    def __repr__(self) -> str:
        return f"MeshCommunication(size={self.size if self.__mesh or self.__devices else '?'})"


class _LazyWorld(MeshCommunication):
    """World communicator whose mesh is built on first use (lets test harnesses force
    the platform before any backend initialisation)."""

    def __init__(self, self_only: bool = False):
        super().__init__()
        self.__self_only = self_only
        self.__built = False

    @property
    def mesh_built(self) -> bool:
        """Whether the lazy mesh has been resolved to concrete devices."""
        return self.__built

    @property
    def mesh(self) -> Mesh:
        if not self.__built:
            devs = jax.devices()
            if self.__self_only:
                devs = devs[:1]
            # rebuild parent lazily with the resolved devices
            MeshCommunication.__init__(self, devices=devs)
            self.__built = True
        return MeshCommunication.mesh.fget(self)


WORLD: MeshCommunication = _LazyWorld()
"""Communicator spanning every visible device (reference ``MPI_WORLD``,
communication.py:1890)."""

SELF: MeshCommunication = _LazyWorld(self_only=True)
"""Single-device communicator (reference ``MPI_SELF``, communication.py:1891)."""

# Drop-in aliases so reference user code (`ht.MPI_WORLD.size`) ports unchanged.
MPI_WORLD = WORLD
MPI_SELF = SELF

__default_comm: MeshCommunication = WORLD


def get_comm() -> Communication:
    """Retrieves the globally set default communicator (reference
    communication.py:1897-1903)."""
    return __default_comm


def sanitize_comm(comm: Optional[Communication]) -> Communication:
    """
    Verifies that the passed communicator is valid; ``None`` resolves to the global
    default. Reference parity: communication.py:1904-1926.
    """
    if comm is None:
        return get_comm()
    if isinstance(comm, Communication):
        return comm
    if isinstance(comm, Mesh):
        return MeshCommunication(mesh=comm)
    raise TypeError(f"Expected a Communication object or Mesh, but got {type(comm)}")


def use_comm(comm: Optional[Communication] = None) -> None:
    """Sets the globally used default communicator (reference
    communication.py:1927-1940)."""
    global __default_comm
    __default_comm = sanitize_comm(comm)


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> MeshCommunication:
    """
    Join a multi-host run and return the world communicator spanning the whole pod.

    The reference framework becomes multi-node by launching every rank under
    ``mpirun``; the TPU-native equivalent is one controller process per host with
    ``jax.distributed.initialize`` wiring the pod topology (on Cloud TPU the
    arguments are auto-detected from the metadata server — call with no args).
    Must be called before any other JAX/heat_tpu operation in the process.
    After it returns, ``WORLD``/``get_comm()`` cover all chips in the pod and every
    ``split`` array spans hosts, with XLA routing collectives over ICI within a
    slice and DCN across slices.
    """
    if getattr(WORLD, "mesh_built", False) or getattr(SELF, "mesh_built", False):
        raise RuntimeError(
            "distributed_init() must run before any heat_tpu/JAX operation: a "
            "communicator has already resolved to this host's devices, so "
            "joining the pod now would leave every split array single-host"
        )
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return get_comm()
