"""
The communication substrate: a device-mesh layer replacing the reference's MPI backend.

The reference implements distribution as explicit MPI messages between Python processes
(``MPICommunication`` wrapping mpi4py, reference heat/core/communication.py:120-1888,
with ``MPI_WORLD`` at :1890 and ``get_comm``/``use_comm``/``sanitize_comm`` at
:1897-1940). The TPU-native redesign is single-controller SPMD: one logical program over
a :class:`jax.sharding.Mesh`; a *split* axis of a global array corresponds to a
``NamedSharding`` partitioning that axis over the mesh, and all communication is emitted
by XLA as ICI/DCN collectives (``psum``/``all_gather``/``all_to_all``/``ppermute``)
when ops consume sharded operands. Hence this module carries no message-passing code at
all — it owns the mesh, the split-axis chunk arithmetic (identical layout math to
reference communication.py:161-240 so user code and tests port unchanged), and the
placement helpers that map ``split`` metadata onto shardings.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ._compat import shard_map as _shard_map

# observability: disabled-path cost is one truthiness check (see monitoring/)
from ..monitoring.registry import STATE as _MON
from ..monitoring import flight as _flight
from ..monitoring import instrument as _instr
from ..robustness import faultinject as _FI

__all__ = [
    "Communication",
    "MeshCommunication",
    "WORLD",
    "SELF",
    "MPI_WORLD",
    "MPI_SELF",
    "get_comm",
    "sanitize_comm",
    "shift",
    "use_comm",
    "distributed_init",
]

#: The mesh axis name every 1-D split sharding partitions over.
SPLIT_AXIS: str = "split"


class Communication:
    """
    Base class for communications. Reference parity: the abstract ``Communication``
    base "intended for other backends" (reference heat/core/communication.py:88-118).
    """

    @staticmethod
    def is_distributed() -> bool:
        """Whether this communicator spans more than one device."""
        raise NotImplementedError()

    def chunk(self, shape, split) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """
        Calculates the chunk of data that will be assigned to this compute node given a
        global data shape and a split axis. Returns ``(offset, local_shape, slices)``.
        """
        raise NotImplementedError()


class MeshCommunication(Communication):
    """
    Communicator backed by a JAX device mesh.

    The mesh is one-dimensional with axis name ``"split"``; ``size`` is the number of
    devices along it (the analog of the reference's MPI world size), and ``rank`` is
    this controller's process index (``0`` in single-controller mode — all devices are
    addressed from one program, unlike the reference where every rank owns one shard).

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices forming the mesh. Defaults to all devices of the default backend.
    mesh : jax.sharding.Mesh, optional
        A pre-built 1-D mesh to wrap; mutually exclusive with ``devices``.

    Reference parity: ``MPICommunication`` (heat/core/communication.py:120). Ordinary
    ops never call collectives explicitly — XLA compiles the crossings from shardings.
    The reference's wrapped surface (:521-1873) is still provided as named collective
    shims (``Allreduce``/``Allgather(v)``/``Alltoall(v)``/``Bcast``/``Scan``/
    ``Exscan``/``Scatter(v)``/``Gather(v)``/``Ppermute``/``Split``, see the
    collectives section) for user code and algorithms that want explicit chunk-level
    communication; two-sided ``Send``/``Recv`` has no SPMD analog — ``Ppermute`` is
    the primitive those patterns compile to.
    """

    def __init__(
        self,
        devices: Optional[Sequence["jax.Device"]] = None,
        mesh: Optional[Mesh] = None,
        *,
        tiers: Optional[Tuple[int, int]] = None,
    ):
        if mesh is not None and devices is not None:
            raise ValueError("pass either devices or mesh, not both")
        self.__devices = list(devices) if devices is not None else None
        self.__mesh: Optional[Mesh] = mesh
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError(f"MeshCommunication requires a 1-D mesh, got axes {mesh.axis_names}")
            self.__axis_name = mesh.axis_names[0]
        else:
            self.__axis_name = SPLIT_AXIS
        if tiers is not None:
            dcn, ici = (int(tiers[0]), int(tiers[1]))
            if dcn < 1 or ici < 1:
                raise ValueError(f"tier sizes must be positive, got (dcn={dcn}, ici={ici})")
            tiers = (dcn, ici)
        self.__tiers: Optional[Tuple[int, int]] = tiers
        self.__tier_mesh: Optional[Mesh] = None

    # ------------------------------------------------------------------ two-tier topology
    @classmethod
    def two_tier(
        cls,
        ici: Optional[int] = None,
        dcn: Optional[int] = None,
        devices: Optional[Sequence["jax.Device"]] = None,
    ) -> "MeshCommunication":
        """
        Build a communicator whose flat ``split`` axis carries a **two-tier
        topology annotation**: the device order is ``ici``-inner (devices
        sharing an ICI domain — a host/slice — are adjacent) and the flat axis
        factors as ``dcn x ici``. Ordinary ``split`` semantics are unchanged
        (the mesh stays 1-D); collectives that have a hierarchical lowering
        (``Allreduce``/``Bcast``) compile a two-level program over the
        ``(dcn, ici)`` tier mesh instead — reduce within the ICI tier first,
        cross the DCN tier exactly once with already-reduced data (the
        communication-avoiding discipline of Demmel et al., PAPERS.md
        CAQR/CALU, applied one level up; PAPER.md §7 ICI/DCN mapping).

        Defaults infer the split from the pod wiring: ``dcn`` = the process
        count (every ``jax.distributed`` host is one DCN endpoint, localhost
        CPU simulation included), ``ici`` = devices-per-process. Pass explicit
        sizes to simulate a multi-host topology on a single-process virtual
        mesh (the CI/dev-container mode). ``HEAT_TPU_TWO_TIER=0`` restores the
        flat single-level programs bit for bit without rebuilding the comm.
        """
        devs = list(devices) if devices is not None else list(jax.devices())
        n = len(devs)
        if dcn is None and ici is None:
            dcn = jax.process_count()
        if dcn is None:
            dcn = n // int(ici) if int(ici) else 0
        if ici is None:
            ici = n // int(dcn) if int(dcn) else 0
        dcn, ici = int(dcn), int(ici)
        if dcn < 1 or ici < 1 or dcn * ici != n:
            raise ValueError(
                f"two-tier factorization (dcn={dcn}) x (ici={ici}) does not "
                f"cover the {n}-device mesh"
            )
        return cls(devices=devs, tiers=(dcn, ici))

    @property
    def tiers(self) -> Optional[Tuple[int, int]]:
        """``(dcn, ici)`` tier sizes of a two-tier comm, or None for a flat
        one. Part of every collective cache key — a tiered and a flat comm
        over the same devices never share compiled programs."""
        return self.__tiers

    @property
    def tier_mesh(self) -> Mesh:
        """The 2-D ``("dcn", "ici")`` view of a two-tier comm's devices
        (ici-inner flat order), built lazily like :attr:`mesh`."""
        if self.__tiers is None:
            raise ValueError("tier_mesh requires a two-tier communicator (see two_tier())")
        if self.__tier_mesh is None:
            dcn, ici = self.__tiers
            devs = np.asarray(self.mesh.devices).reshape(dcn, ici)
            self.__tier_mesh = Mesh(devs, ("dcn", "ici"))
        return self.__tier_mesh

    # ------------------------------------------------------------------ mesh access
    @property
    def mesh(self) -> Mesh:
        """The underlying 1-D device mesh (built lazily on first access)."""
        if self.__mesh is None:
            devs = self.__devices if self.__devices is not None else jax.devices()
            self.__mesh = Mesh(np.asarray(devs), (self.__axis_name,))
        if self.__tiers is not None and self.__tiers[0] * self.__tiers[1] != self.__mesh.devices.size:
            raise ValueError(
                f"two-tier factorization {self.__tiers} does not cover the "
                f"{self.__mesh.devices.size}-device mesh"
            )
        return self.__mesh

    @property
    def axis_name(self) -> str:
        """Name of the mesh axis split arrays are partitioned over."""
        return self.__axis_name

    @property
    def size(self) -> int:
        """Number of devices in the mesh (analog of MPI world size)."""
        return self.mesh.devices.size

    @property
    def nnodes(self) -> int:
        """Alias for :attr:`size` (number of 'compute nodes' = devices)."""
        return self.size

    @property
    def rank(self) -> int:
        """This controller's process index (0 in single-controller SPMD)."""
        return jax.process_index()

    def is_distributed(self) -> bool:
        """Whether the mesh spans more than one device."""
        return self.size > 1

    # ------------------------------------------------------------------ chunk math
    def chunk(
        self,
        shape: Sequence[int],
        split: Optional[int],
        rank: Optional[int] = None,
        w_size: Optional[int] = None,
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """
        Calculates the chunk of data assigned to device ``rank`` given a global
        ``shape`` and a ``split`` axis: returns ``(offset, local_shape, slices)``.

        Sizes differ by at most one; the remainder is spread over the lowest ranks,
        identical to the reference layout (heat/core/communication.py:161-210) so
        chunk-dependent user code ports unchanged. This is the reference-parity
        *logical* decomposition; the padded physical placement puts ``ceil(n/p)``
        rows on every device instead — :meth:`lshape_map` / :meth:`counts_displs`
        report that physical geometry.

        Parameters
        ----------
        shape : Tuple[int,...]
            The global shape of the data to be split.
        split : int or None
            The axis along which to chunk the data. ``None`` means no chunking.
        rank : int, optional
            Device slot to compute the chunk for; defaults to 0 (in the reference this
            defaults to the calling MPI rank — here there is one controller).
        w_size : int, optional
            Override for the number of chunks; defaults to :attr:`size`.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(None) for _ in shape)
        split = int(split) % len(shape) if len(shape) else 0
        rank = 0 if rank is None else int(rank)
        size = self.size if w_size is None else int(w_size)
        n = shape[split]
        base, rem = divmod(n, size)
        if rank < rem:
            lsize = base + 1
            offset = rank * (base + 1)
        else:
            lsize = base
            offset = rem * (base + 1) + (rank - rem) * base
        lshape = shape[:split] + (lsize,) + shape[split + 1 :]
        slices = tuple(
            slice(offset, offset + lsize) if d == split else slice(None) for d in range(len(shape))
        )
        return offset, lshape, slices

    def counts_displs(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """
        Per-device counts and displacements along the split axis — the layout the
        reference feeds its vector collectives (heat/core/communication.py:211-240).
        Derived from the *padded physical* placement (``ceil(n/p)`` rows per device,
        clamped at the global extent) so it agrees with
        ``parray.addressable_shards``; the reference's remainder-spread logical
        decomposition remains available via :meth:`chunk`.
        """
        shape = tuple(int(s) for s in shape)
        split = int(split) % len(shape) if len(shape) else 0
        n = shape[split]
        c = -(-n // self.size)  # ceil
        counts = tuple(max(0, min(c, n - r * c)) for r in range(self.size))
        displs = tuple(min(r * c, n) for r in range(self.size))
        return counts, displs

    def counts_displs_shape(
        self, shape: Sequence[int], axis: int, rank: Optional[int] = None
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """
        Reference-name entry point (heat/core/communication.py:211-240,
        ``counts_displs_shape``): remainder-spread counts and displacements for
        a variable-sized all-to-all, plus the receive-buffer shape under the
        all-equal-inputs assumption (``size * counts[rank]`` along ``axis``).
        Unlike :meth:`counts_displs` (padded physical placement), this uses the
        reference's own remainder-spread decomposition, so ported user code
        sees identical numbers. ``rank`` defaults to this controller's rank.
        """
        shape = tuple(int(s) for s in shape)
        axis = int(axis) % len(shape) if len(shape) else 0
        n = shape[axis]
        base, rem = divmod(n, self.size)
        counts = tuple(base + (1 if r < rem else 0) for r in range(self.size))
        displs = tuple(sum(counts[:r]) for r in range(self.size))
        r = self.rank if rank is None else int(rank)
        output_shape = list(shape)
        output_shape[axis] = self.size * counts[r]
        return counts, displs, tuple(output_shape)

    def lshape_map(self, shape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """
        ``(size, ndim)`` array of every device's shape of *owned logical data* under
        the padded physical layout: device ``r`` holds logical rows
        ``[r*ceil(n/p), min((r+1)*ceil(n/p), n))`` of the split axis (tail devices
        may own zero rows — their physical shard is pure pad). Consistent with
        ``parray.addressable_shards`` extents minus the zero pad; the reference
        gathers the equivalent map with an Allreduce (dndarray.py:573-605).
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return np.array([shape] * self.size, dtype=np.int64)
        split = int(split) % len(shape) if len(shape) else 0
        counts, _ = self.counts_displs(shape, split)
        out = np.tile(np.array(shape, dtype=np.int64), (self.size, 1))
        out[:, split] = counts
        return out

    # ------------------------------------------------------------------ placement
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """The :class:`PartitionSpec` expressing ``split`` for an ``ndim``-d array."""
        if split is None:
            return PartitionSpec()
        split = int(split) % max(ndim, 1)
        return PartitionSpec(*([None] * split), self.__axis_name)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """The :class:`NamedSharding` expressing ``split`` for an ``ndim``-d array."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    def is_shardable(self, shape: Sequence[int], split: Optional[int]) -> bool:
        """
        Whether ``shape`` can be physically partitioned on ``split`` over this mesh
        *without padding*: JAX NamedShardings require the split axis to be divisible
        by the mesh size. Non-divisible ("ragged") axes — which the reference chunks
        with the remainder spread over low ranks, communication.py:161-210 — are
        still genuinely distributed here, via the padded physical layout (see
        :meth:`padded_dim`/:meth:`placed`); this predicate only reports whether the
        pad is empty.
        """
        if split is None:
            return True
        shape = tuple(shape)
        if not shape:
            return False
        split = int(split) % len(shape)
        return shape[split] % self.size == 0

    # ------------------------------------------------------------------ padded layout
    #
    # JAX shardings are equal-chunk; the reference allows ragged distributions
    # (arbitrary axis lengths chunked per communication.py:161-210). The TPU-native
    # answer (SURVEY §7(a)) is a *padded physical layout*: an array split on an axis
    # of logical length n is physically stored with that axis padded at the global
    # END to ceil(n/p)*p and sharded evenly; the logical gshape is metadata. Because
    # the pad sits at the end, any in-bounds index is identical in logical and
    # physical coordinates, so indexing and elementwise compute run directly on the
    # sharded physical array. Reductions/contractions across the split axis mask the
    # pad with the operation's neutral element first (`_operations.py`), and
    # consumers of the logical array slice the pad off (``DNDarray.larray``).

    def padded_dim(self, n: int) -> int:
        """Physical length of a split axis of logical length ``n``: the smallest
        multiple of the mesh size >= n (== n when already divisible)."""
        p = self.size
        return -(-int(n) // p) * p

    def padded_shape(self, shape: Sequence[int], split: Optional[int]) -> Tuple[int, ...]:
        """Physical shape of a logically ``shape``-d array split on ``split``."""
        shape = tuple(int(s) for s in shape)
        if split is None or not shape or not self.is_distributed():
            return shape
        split = int(split) % len(shape)
        return shape[:split] + (self.padded_dim(shape[split]),) + shape[split + 1 :]

    def pad_physical(self, data: "jax.Array", split: int, fill=0) -> "jax.Array":
        """Pad a *logical* array at the end of ``split`` up to the physical shape,
        filling with ``fill`` (the consuming op's neutral element; 0 by default)."""
        split = int(split) % data.ndim
        n = data.shape[split]
        pn = self.padded_dim(n)
        if pn == n:
            return data
        widths = [(0, 0)] * data.ndim
        widths[split] = (0, pn - n)
        return jax.numpy.pad(data, widths, constant_values=fill)

    def placed(
        self,
        data: "jax.Array",
        split: Optional[int],
        gshape: Optional[Sequence[int]] = None,
        fill=0,
    ) -> "jax.Array":
        """
        Put ``data`` into the canonical physical layout for ``split``: padded at the
        global end of the split axis to an even multiple of the mesh size, and
        sharded over the mesh (replicated when ``split`` is None). Accepts either
        the logical array (padding applied here) or an already-padded physical
        array (placement re-asserted only). This one placement subsumes the
        reference's ``resplit_``/``redistribute_`` Send/Recv choreography
        (reference dndarray.py:1033-1362) — XLA emits the slice-exchange
        collectives.
        """
        if _MON.enabled:
            _instr.placement()
        if split is None or data.ndim == 0:
            return jax.device_put(data, self.sharding(data.ndim, None))
        split = int(split) % data.ndim
        gshape = tuple(data.shape) if gshape is None else tuple(int(s) for s in gshape)
        pshape = self.padded_shape(gshape, split)
        if tuple(data.shape) == pshape:
            pass  # already physical
        elif data.shape[split] == gshape[split]:
            data = self.pad_physical(data, split, fill=fill)
        else:
            raise ValueError(
                f"array of shape {tuple(data.shape)} is neither the logical {gshape} "
                f"nor the physical {pshape} layout for split={split}"
            )
        return jax.device_put(data, self.sharding(data.ndim, split))

    def shard(self, array: "jax.Array", split: Optional[int]) -> "jax.Array":
        """
        Places ``array`` (a *logical* global array) according to ``split`` — padding
        the split axis into the physical layout when it is not divisible by the mesh
        size. NOTE: for ragged axes the returned array is the padded physical array;
        callers tracking logical shapes should use :meth:`placed` and keep the
        logical gshape as metadata (``DNDarray`` does).
        """
        return self.placed(array, split)

    # ------------------------------------------------------------------ collectives
    #
    # Named collective shims with the reference's per-rank semantics: the chunks of
    # the ``split`` axis play the role of the ranks' local buffers (reference
    # MPICommunication's wrapped surface, communication.py:521-1873). Each lowers to
    # the SURVEY §5 mapping — Allreduce→psum, Allgather(v)→all_gather,
    # Alltoall(v)→all_to_all, Bcast→one-hot psum, Scan/Exscan→all_gather+prefix,
    # Send/Recv ring→ppermute — executed as one ``shard_map`` program over the mesh
    # so the crossings ride ICI/DCN. v-variants degenerate to their regular forms
    # because mesh layouts are balanced by construction (``chunk`` spreads any
    # remainder before data ever reaches a collective); ``counts_displs`` still
    # publishes the per-device layout for code that wants it.

    def _collective_fn(self, kind: str, split: int, ndim: int, op: str = "", **kw):
        """The cached compiled collective program WITHOUT the dispatch-site
        fault check or counter (package-internal: ``core/fusion.py`` replays
        these inside fused traces, where the flush path owns the accounting
        and the ``collective.dispatch`` fault site — a recorded collective
        must fault at FLUSH, recoverably, not at record)."""
        # two-tier lowering applies to the reduction-shaped collectives only:
        # ppermute/alltoall/allgather are pure data movement whose ici-inner
        # ring order is already topology-optimal (a flat ring crosses DCN
        # exactly dcn times — once per tier boundary — whatever the program
        # says), and scan/cumop exchange O(1)-per-device block totals.
        tiers = self.__tiers if (kind in _HIERARCHICAL_KINDS and two_tier_enabled()) else None
        key = (kind, op, self.mesh, self.__axis_name, split, ndim, tiers, tuple(sorted(kw.items())))
        fn = _COLLECTIVE_CACHE.get(key)
        if fn is None:
            fn = _build_collective(self, kind, split, ndim, op, tiers=tiers, **kw)
            _COLLECTIVE_CACHE[key] = fn
            _COLLECTIVE_CACHE.move_to_end(key)
            while len(_COLLECTIVE_CACHE) > _COLLECTIVE_CACHE_MAX:
                _COLLECTIVE_CACHE.popitem(last=False)  # bound executable/mesh retention
        else:
            _COLLECTIVE_CACHE.move_to_end(key)
        return fn

    def __collective(self, kind: str, split: int, ndim: int, op: str = "", **kw):
        # deterministic fault site for the distributed layer: an injected
        # failure here surfaces exactly where a real ICI/DCN dispatch error
        # would (an EAGER dispatch has no retained graph to replay — only a
        # collective recorded in a fused flush rides the recovery ladder,
        # whose fused attempt consults this same site). Outcomes feed the
        # collective.dispatch circuit breaker: the eager shim has no degraded
        # path of its own (the error still raises here), but its evidence is
        # what lets collective-bearing FUSED flushes fail fast to their
        # retained eager barrier path while the fabric is flapping.
        from ..robustness import breaker as _BRK

        b = _BRK.breaker("collective.dispatch")
        try:
            _FI.check("collective.dispatch")
        except (KeyboardInterrupt, SystemExit, _FI.FaultPlanError):
            raise
        except BaseException:
            b.record_failure()
            raise
        b.record_success()
        if _MON.enabled:
            _instr.collective(kind)
        fn = self._collective_fn(kind, split, ndim, op, **kw)
        deadline_ms = _collective_timeout_ms()
        if deadline_ms is not None:
            fn = _watched(fn, kind, deadline_ms)
        if kind in _CHECKSUM_KINDS:
            # value-level fault site + checksum lane (ISSUE 12): the SDC
            # adversary perturbs the dispatched result, and with
            # HEAT_TPU_COLLECTIVE_CHECKSUM=1 the per-chunk CRC lane (or the
            # allreduce f64 local-sum invariant) verifies it on receipt —
            # a mismatch raises IntegrityError (eager shims raise by
            # design: there is no retained graph to degrade to). Off, the
            # wrapper costs one dict lookup + one env read per dispatch.
            fn = _integrity_wrapped(self, fn, kind, split, op, kw)
        if _flight.flight_enabled():
            # flight recorder (ISSUE 13): one record per EAGER collective
            # dispatch, timed around the whole wrapped call (watchdog +
            # checksum lane included) — collectives recorded inside fused
            # flushes are part of their flush record instead. Outermost by
            # design; off = the one env read above.
            fn = _flight_wrapped(fn, kind, op)
        return fn

    def __prep(self, x, split: int):
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        split = int(split) % x.ndim
        if not self.is_shardable(x.shape, split):
            raise ValueError(
                f"axis {split} of shape {x.shape} does not partition evenly over "
                f"{self.size} devices"
            )
        return self.shard(x, split), split

    def Allreduce(self, x, op: str = "sum", split: int = 0):
        """
        Element-wise reduction of the split-axis chunks; the (chunk-shaped) result is
        replicated (reference Allreduce, communication.py:749-1001). ``op``:
        ``'sum' | 'prod' | 'max' | 'min' | 'land' | 'lor'``.
        """
        x, split = self.__prep(x, split)
        return self.__collective("allreduce", split, x.ndim, op)(x)

    def Reduce(self, x, op: str = "sum", root: int = 0, split: int = 0):
        """Reduction delivered to one logical root (reference Reduce). In
        single-controller SPMD the replicated Allreduce result IS addressable at the
        root — the collective is identical; ``root`` is kept for API parity."""
        return self.Allreduce(x, op=op, split=split)

    def Allgather(self, x, split: int = 0):
        """Concatenate every device's chunk along the split axis on all devices —
        i.e. replicate the global array (reference Allgather(v),
        communication.py:1002-1198)."""
        x, split = self.__prep(x, split)
        return self.__collective("allgather", split, x.ndim)(x)

    def Allgatherv(self, x, split: int = 0):
        """
        Vector form of :meth:`Allgather`: accepts *ragged* layouts — a split axis of
        any length (the reference's counts/displs collectives,
        communication.py:211-240, 1002-1198). The result is the replicated logical
        array; ragged chunks ride the padded physical layout and the pad is sliced
        off here.
        """
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        split = int(split) % x.ndim
        if self.is_shardable(x.shape, split):
            return self.Allgather(x, split=split)
        placed = self.placed(x, split)
        gathered = jax.device_put(placed, self.sharding(x.ndim, None))
        idx = tuple(
            slice(0, x.shape[d]) if d == split else slice(None) for d in range(x.ndim)
        )
        return gathered[idx]

    def Gather(self, x, root: int = 0, split: int = 0):
        """Gather chunks to the root (reference Gather(v), communication.py:1476-1873);
        identical to :meth:`Allgather` under one controller."""
        return self.Allgather(x, split=split)

    def Gatherv(self, x, root: int = 0, split: int = 0):
        """Vector form of :meth:`Gather` — ragged-capable like :meth:`Allgatherv`."""
        return self.Allgatherv(x, split=split)

    def Scatter(self, x, root: int = 0, split: int = 0):
        """Partition the root's array across the mesh along ``split`` (reference
        Scatter(v)): a resharding placement. Raises like the other shims when the
        axis does not partition evenly."""
        return self.__prep(x, split)[0]

    def Scatterv(self, x, root: int = 0, split: int = 0):
        """Vector form of :meth:`Scatter`: accepts ragged axes via the padded
        physical layout (reference communication.py:1476-1873 with counts/displs)."""
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        return self.placed(x, int(split) % x.ndim)

    def Bcast(self, x, root: int = 0, split: int = 0):
        """
        Replace every device's chunk with the ``root`` device's chunk (reference
        Bcast, communication.py:689-747): a one-hot mask + psum over the mesh axis.
        """
        if not 0 <= int(root) < self.size:
            raise ValueError(f"root {root} out of range for {self.size} devices")
        x, split = self.__prep(x, split)
        return self.__collective("bcast", split, x.ndim, root=int(root))(x)

    def Scan(self, x, op: str = "sum", split: int = 0):
        """Inclusive prefix reduction over the chunk sequence (reference Scan)."""
        x, split = self.__prep(x, split)
        return self.__collective("scan", split, x.ndim, op, exclusive=False)(x)

    def Exscan(self, x, op: str = "sum", split: int = 0):
        """Exclusive prefix reduction over the chunk sequence (reference Exscan);
        device 0's chunk of the result is the op's neutral element."""
        x, split = self.__prep(x, split)
        return self.__collective("scan", split, x.ndim, op, exclusive=True)(x)

    def Barrier(self) -> None:
        """
        Block until every controller process reaches this point (the reference
        delegates to ``MPI.COMM_WORLD.Barrier``). Single-controller SPMD needs
        no device barrier — dispatch order already serializes — so this only
        synchronizes *processes*: a no-op with one controller, a
        ``sync_global_devices`` fence under multi-controller (e.g. between a
        process-0 file write and a cross-process read of it).
        """
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("heat_tpu.Barrier")

    def Cum(self, x, op: str = "sum", split: int = 0):
        """
        Element-wise cumulative (``'sum'`` or ``'prod'``) ALONG the split axis,
        keeping the result sharded: chunk-local cumulative + exclusive prefix of
        the per-chunk totals + combine — the reference's local-cum + ``Exscan`` +
        final-op pipeline (_operations.py:185-281) as one shard_map program.
        Only the (…, 1, …) block totals cross the mesh.
        """
        if op not in ("sum", "prod"):
            raise ValueError(f"Cum supports 'sum' or 'prod', got {op!r}")
        x, split = self.__prep(x, split)
        return self.__collective("cumop", split, x.ndim, op)(x)

    def Alltoall(self, x, split_axis: int, concat_axis: int):
        """
        Re-chunk: every device exchanges slices so the array goes from being split on
        ``concat_axis`` to split on ``split_axis`` (reference Alltoall(v) axis
        rotation, communication.py:1199-1475) — one ``lax.all_to_all`` over ICI.

        A :class:`~.dndarray.DNDarray` operand (which must be split on
        ``concat_axis``) returns a DNDarray split on ``split_axis``; over a
        pending fused chain the exchange records a collective node
        (``core/fusion.py``) instead of flushing, so chain + all_to_all +
        follow-on chain compile as one program
        (``HEAT_TPU_FUSION_COLLECTIVES=0`` restores the flush barrier).
        """
        from .dndarray import DNDarray as _D

        if isinstance(x, _D):
            return self.__alltoall_dnd(x, split_axis, concat_axis)
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        split_axis = int(split_axis) % x.ndim
        concat_axis = int(concat_axis) % x.ndim
        if split_axis == concat_axis:
            raise ValueError("split_axis and concat_axis must differ")
        x, cur = self.__prep(x, concat_axis)
        if not self.is_shardable(x.shape, split_axis):
            raise ValueError(
                f"axis {split_axis} of shape {x.shape} does not partition evenly over "
                f"{self.size} devices"
            )
        return self.__collective("alltoall", cur, x.ndim, sa=split_axis)(x)

    def __alltoall_dnd(self, x, split_axis: int, concat_axis: int):
        """DNDarray form of :meth:`Alltoall` (validation mirrors the raw-array
        path; the exchange defers over a pending chain)."""
        from .dndarray import DNDarray as _D

        ndim = x.ndim
        if ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        sa = int(split_axis) % ndim
        ca = int(concat_axis) % ndim
        if sa == ca:
            raise ValueError("split_axis and concat_axis must differ")
        if x.split is None or int(x.split) % ndim != ca:
            raise ValueError(
                f"DNDarray operand of Alltoall must be split on concat_axis "
                f"({ca}), got split={x.split}"
            )
        if not (self.is_shardable(x.shape, sa) and self.is_shardable(x.shape, ca)):
            raise ValueError(
                f"axes ({sa}, {ca}) of shape {tuple(x.shape)} do not partition "
                f"evenly over {self.size} devices"
            )
        from . import fusion as _fusion

        if _fusion.collective_ready(x):
            res = _fusion.defer_alltoall(x, sa, ca)
            if res is not None:
                return res
        x._flush("collective")
        data = self.__collective("alltoall", ca, ndim, sa=sa)(x.parray)
        return _D(data, tuple(x.shape), x.dtype, sa, x.device, self, True)

    def Alltoallv(self, x, split_axis: int, concat_axis: int):
        """
        Vector form of :meth:`Alltoall`: accepts ragged axes (the reference's
        Alltoallw axis rotation with per-rank counts, communication.py:1199-1475).
        The re-chunk is a single resharding placement from ``concat_axis`` to
        ``split_axis`` — XLA emits the all-to-all.
        """
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            raise ValueError("collectives operate on arrays with a split axis, got a scalar")
        split_axis = int(split_axis) % x.ndim
        concat_axis = int(concat_axis) % x.ndim
        if split_axis == concat_axis:
            raise ValueError("split_axis and concat_axis must differ")
        if self.is_shardable(x.shape, split_axis) and self.is_shardable(x.shape, concat_axis):
            return self.Alltoall(x, split_axis, concat_axis)
        return self.placed(x, split_axis)

    def Ppermute(self, x, shift: int = 1, split: int = 0):
        """
        Rotate chunks around the device ring by ``shift`` positions (the reference's
        neighbor Send/Recv choreography, e.g. dndarray.py:360-446 halos and the ring
        of distance.py:279-346 — SPMD has no two-sided Send/Recv; ``lax.ppermute``
        is the primitive those patterns compile to).
        """
        x, split = self.__prep(x, split)
        return self.__collective("ppermute", split, x.ndim, shift=int(shift) % self.size)(x)

    def Split(self, devices=None, *, color=None) -> "MeshCommunication":
        """
        Sub-communicator over a subset of devices (reference communicator ``Split``,
        communication.py:445-456; DASO's per-GPU groups, dp_optimizer.py:182-199).

        Pass either ``devices`` — an explicit device-index list — or ``color`` — a
        per-device color list of length ``size``, where the devices sharing device
        0's color form the group (the two are keyword-separated: a color list that
        happens to be a permutation of device indices is not guessable).
        """
        if (devices is None) == (color is None):
            raise ValueError("pass exactly one of devices= or color=")
        devs = list(self.mesh.devices.ravel())
        if color is not None:
            colors = list(color)
            if len(colors) != self.size:
                raise ValueError(f"color list must have length {self.size}, got {len(colors)}")
            members = [d for d, c in zip(devs, colors) if c == colors[0]]
        else:
            idx = [int(i) for i in devices]
            bad = [i for i in idx if not 0 <= i < self.size]
            if bad:
                raise ValueError(f"device indices {bad} out of range for {self.size} devices")
            if len(set(idx)) != len(idx):
                raise ValueError(f"duplicate device indices in {idx}")
            members = [devs[i] for i in idx]
        if not members:
            raise ValueError("communicator split produced an empty group")
        return MeshCommunication(devices=members)

    def __repr__(self) -> str:
        size = self.size if self.__mesh or self.__devices else "?"
        if self.__tiers is not None:
            return f"MeshCommunication(size={size}, tiers=(dcn={self.__tiers[0]}, ici={self.__tiers[1]}))"
        return f"MeshCommunication(size={size})"


import collections as _collections
import logging as _logging
import os as _os
import time as _time

_logger = _logging.getLogger("heat_tpu.distributed")

_COLLECTIVE_CACHE: "_collections.OrderedDict" = _collections.OrderedDict()
_COLLECTIVE_CACHE_MAX = 256

#: Collective kinds with a genuine two-level (reduce-in-ICI, cross-DCN-once)
#: lowering; everything else is data movement that a flat ici-inner device
#: order already routes optimally (see ``_collective_fn``).
_HIERARCHICAL_KINDS = frozenset({"allreduce", "bcast"})


def two_tier_enabled() -> bool:
    """Whether two-tier comms lower their hierarchical collectives two-level
    (default). ``HEAT_TPU_TWO_TIER=0`` restores the flat single-level programs
    — the bit-parity hatch for the reassociated f32 sum (read per dispatch,
    the ``HEAT_TPU_FUSION`` cost class)."""
    return _os.environ.get("HEAT_TPU_TWO_TIER", "").strip().lower() not in ("0", "false", "off")


def _collective_timeout_ms() -> Optional[float]:
    """The ``HEAT_TPU_COLLECTIVE_TIMEOUT_MS`` dispatch deadline (None = off,
    the default — zero behavior change). Read per dispatch."""
    raw = _os.environ.get("HEAT_TPU_COLLECTIVE_TIMEOUT_MS", "").strip()
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        return None
    return ms if ms > 0 else None


def _flight_wrapped(fn, kind: str, op: str):
    """Flight-record one eager collective dispatch (ISSUE 13): kind, op and
    dispatch wall time (the host-side call — jax dispatch is async, so the
    device transfer overlaps unless the watchdog's ``block_until_ready`` is
    armed). A pure observation — the dispatched value is returned as-is."""

    def recorded(*args):
        t0 = _time.perf_counter()
        out = fn(*args)
        _flight.record_collective(
            kind, _time.perf_counter() - t0, op=op or None
        )
        return out

    return recorded


def _watched(fn, kind: str, deadline_ms: float):
    """The collective-dispatch watchdog (the PR 9 dispatch-watchdog
    semantics): block on the result, count + log an overrun as
    ``comm.collective_timeout{kind}`` — and never interrupt the running
    program (a mid-kernel kill would leave the mesh in an undefined
    collective epoch; a counted overrun feeds the elastic supervisor's
    evidence instead)."""

    def watched(*args):
        t0 = _time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        took_ms = (_time.perf_counter() - t0) * 1e3
        if took_ms > deadline_ms:
            if _MON.enabled:
                # the measured blocking time also lands in the
                # comm.collective_timeout_latency histogram so telemetry()
                # exports the uniform {count, p50_us, p99_us} latency shape
                # (ISSUE 14 satellite) beside the per-kind counter
                _instr.collective_timeout(kind, seconds=took_ms / 1e3)
            _logger.warning(
                "collective %s exceeded dispatch deadline in flight: %.1fms > %.1fms",
                kind, took_ms, deadline_ms,
            )
        return out

    return watched

# ------------------------------------------------------------------ checksum lane
#
# Silent-data-corruption defense for the EAGER collective shims (ISSUE 12;
# collectives recorded in fused flushes are covered by the shadow-replay
# audit in core/fusion.py instead). The pure data-movement kinds —
# ppermute / alltoall / allgather (and shift, which rides the Ppermute shim;
# the halo exchange has its own hook in dndarray.get_halo) — are *bitwise*
# by contract (PR 7), so their lane is exact: a CRC32 per chunk of the input
# is matched against the received chunks under the collective's documented
# permutation. Allreduce is reassociation-bounded, so its lane is the
# reduced f64 local-sum invariant (op 'sum'; max/min/land/lor verify exactly
# elementwise; float 'prod' is unchecked — documented). Verification runs on
# the host against the single-controller's own global view; a mismatch
# raises IntegrityError, counted ``robustness.integrity{collective-mismatch}``.

#: Collective kinds the checksum lane covers ('shift' arrives as ppermute).
_CHECKSUM_KINDS = frozenset({"ppermute", "alltoall", "allgather", "allreduce"})


def collective_checksum_enabled() -> bool:
    """Whether eager collective dispatches verify their checksum lane
    (``HEAT_TPU_COLLECTIVE_CHECKSUM=1``; default off = bit-for-bit the
    pre-ISSUE-12 dispatch). Read per dispatch."""
    return _os.environ.get("HEAT_TPU_COLLECTIVE_CHECKSUM", "").strip().lower() in (
        "1", "true", "on",
    )


def _integrity_wrapped(comm, fn, kind: str, split: int, op: str, kw: dict):
    """The per-dispatch integrity wrapper: consult the value-fault adversary
    (:func:`faultinject.corrupt_value`) on the result, then — when the lane
    is enabled — verify it on receipt."""

    def dispatch(x):
        out = _FI.corrupt_value("collective.dispatch", fn(x))
        if collective_checksum_enabled():
            _verify_collective(comm, kind, split, op, kw, x, out)
        return out

    return dispatch


def _crc(a) -> int:
    import zlib

    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _checksum_fail(kind: str, detail: str):
    from ..robustness.integrity import IntegrityError

    if _MON.enabled:
        _instr.integrity("collective-mismatch")
    raise IntegrityError(
        f"collective checksum lane mismatch on {kind}: {detail} — the "
        "received payload does not match the dispatched chunks "
        "(HEAT_TPU_COLLECTIVE_CHECKSUM=1; see doc/integrity_notes.md)"
    )


def _verify_collective(comm, kind: str, split: int, op: str, kw: dict, x, out) -> None:
    """Host-side receipt verification of one eager collective dispatch
    against the controller's own pre-dispatch view of the chunks."""
    p = comm.size
    xa = np.asarray(x)
    oa = np.asarray(out)
    in_chunks = np.split(xa, p, axis=split)
    if kind == "ppermute":
        shift_ = int(kw["shift"]) % p
        out_chunks = np.split(oa, p, axis=split)
        for j in range(p):
            src = in_chunks[(j - shift_) % p]
            if _crc(out_chunks[j]) != _crc(src):
                _checksum_fail(kind, f"chunk {j} != dispatched chunk {(j - shift_) % p}")
    elif kind == "allgather":
        out_chunks = np.split(oa, p, axis=split)
        for j in range(p):
            if _crc(out_chunks[j]) != _crc(in_chunks[j]):
                _checksum_fail(kind, f"gathered chunk {j} differs from its source")
    elif kind == "alltoall":
        sa = int(kw["sa"])
        out_chunks = np.split(oa, p, axis=sa)
        for j in range(p):
            blocks = [np.split(c, p, axis=sa)[j] for c in in_chunks]
            expected = np.concatenate(blocks, axis=split)
            if _crc(out_chunks[j]) != _crc(expected):
                _checksum_fail(kind, f"re-chunked slab {j} differs from its source blocks")
    elif kind == "allreduce":
        _verify_allreduce(kind, op, in_chunks, oa, p)
    if _MON.enabled:
        _instr.integrity("collective-verified")


def _verify_allreduce(kind: str, op: str, in_chunks, oa, p: int) -> None:
    stacked = np.stack([np.asarray(c) for c in in_chunks])
    if op == "sum":
        dt = stacked.dtype
        if jax.numpy.issubdtype(dt, jax.numpy.floating):
            # reduced f64 local-sum invariant: the scalar totals of input
            # and output agree within the documented reassociation bound
            from ..robustness.integrity import allreduce_sum_bound

            tin = float(np.sum(stacked.astype(np.float64)))
            tout = float(np.sum(oa.astype(np.float64)))
            bound = allreduce_sum_bound(float(np.sum(np.abs(stacked.astype(np.float64)))), dt, p)
            if not (abs(tin - tout) <= bound or (np.isnan(tin) and np.isnan(tout))):
                _checksum_fail(kind, f"f64 sum invariant |{tin} - {tout}| > {bound}")
        else:
            # exact dtypes: elementwise re-reduction with matching wraparound
            expected = np.add.reduce(stacked, axis=0, dtype=oa.dtype)
            if _crc(expected.astype(oa.dtype)) != _crc(oa):
                _checksum_fail(kind, "integer sum differs from re-reduction")
    elif op in ("max", "min"):
        red = np.maximum.reduce if op == "max" else np.minimum.reduce
        if _crc(red(stacked).astype(oa.dtype)) != _crc(oa):
            _checksum_fail(kind, f"{op} differs from exact re-reduction")
    elif op in ("land", "lor"):
        red = np.logical_and.reduce if op == "land" else np.logical_or.reduce
        if _crc(red(stacked != 0).astype(oa.dtype)) != _crc(oa):
            _checksum_fail(kind, f"{op} differs from exact re-reduction")
    # float 'prod' has no bounded invariant cheaper than recomputation:
    # unchecked by design (documented in doc/integrity_notes.md)


def _verify_halo(comm, phys: "np.ndarray", split: int, halo_size: int, prev, nxt, stacked) -> None:
    """Receipt verification of the eager halo exchange (``DNDarray.get_halo``):
    every received slab must equal the neighbor's boundary slice of the
    controller's own pre-dispatch view (zeros at the outer boundaries)."""
    p = comm.size
    chunks = np.split(np.asarray(phys), p, axis=split)
    h = halo_size

    def edge(c, first: bool):
        sl = [slice(None)] * c.ndim
        sl[split] = slice(0, h) if first else slice(c.shape[split] - h, None)
        return c[tuple(sl)]

    prev_chunks = np.split(np.asarray(prev), p, axis=split)
    next_chunks = np.split(np.asarray(nxt), p, axis=split)
    stacked_np = np.asarray(stacked)
    for i in range(p):
        exp_prev = np.zeros_like(prev_chunks[i]) if i == 0 else edge(chunks[i - 1], False)
        exp_next = np.zeros_like(next_chunks[i]) if i == p - 1 else edge(chunks[i + 1], True)
        if _crc(prev_chunks[i]) != _crc(exp_prev):
            _checksum_fail("halo", f"prev slab of shard {i} differs from its neighbor's edge")
        if _crc(next_chunks[i]) != _crc(exp_next):
            _checksum_fail("halo", f"next slab of shard {i} differs from its neighbor's edge")
        expected_stack = np.concatenate(
            [np.moveaxis(a, split, 0) for a in (exp_prev, chunks[i], exp_next)], axis=0
        )
        if _crc(stacked_np[i]) != _crc(expected_stack):
            _checksum_fail("halo", f"stacked block of shard {i} differs from its sources")
    if _MON.enabled:
        _instr.integrity("collective-verified")


_REDUCERS = {
    "sum": (lambda b, ax: jax.lax.psum(b, ax), jax.numpy.sum, lambda g: jax.lax.cumsum(g, axis=0)),
    "max": (lambda b, ax: jax.lax.pmax(b, ax), jax.numpy.max, lambda g: jax.lax.cummax(g, axis=0)),
    "min": (lambda b, ax: jax.lax.pmin(b, ax), jax.numpy.min, lambda g: jax.lax.cummin(g, axis=0)),
    "prod": (None, jax.numpy.prod, lambda g: jax.lax.cumprod(g, axis=0)),
    "land": (None, None, None),  # via bool min
    "lor": (None, None, None),  # via bool max
}


def _build_collective(
    comm: "MeshCommunication", kind: str, split: int, ndim: int, op: str, tiers=None, **kw
):
    """Compile one collective as a jitted shard_map program (cached per mesh/shape
    family by the caller). With ``tiers`` set the reduction-shaped kinds lower
    two-level over the ``(dcn, ici)`` tier mesh: reduce within the ICI tier
    first, cross the DCN tier exactly once with already-reduced chunks."""
    from jax import lax

    mesh = comm.mesh
    ax = comm.axis_name
    p = comm.size
    if kind in ("allreduce", "scan") and op not in _REDUCERS:
        raise ValueError(f"unknown reduction op {op!r}; expected one of {sorted(_REDUCERS)}")
    spec_split = PartitionSpec(*([None] * split + [ax]))
    spec_repl = PartitionSpec()
    if tiers is not None:
        # the flat split axis re-expressed over the tier mesh: dcn-major,
        # ici-minor — identical device-to-chunk assignment because the flat
        # order is ici-inner by the two_tier() contract
        mesh = comm.tier_mesh
        spec_split = PartitionSpec(*([None] * split + [("dcn", "ici")]))

    if op in ("land", "lor") and kind in ("allreduce", "scan"):
        inner = "min" if op == "land" else "max"
        inner_fn = _build_collective(comm, kind, split, ndim, inner, tiers=tiers, **kw)

        def logical(x):
            # truthiness, not a lossy integer cast: 256 and 0.5 are logically true
            return inner_fn((x != 0).astype(jax.numpy.uint8)).astype(jax.numpy.bool_)

        return logical

    if kind == "allreduce":
        preduce, local_reduce, _ = _REDUCERS[op]

        if tiers is not None:

            def body(b):
                # hierarchical: combine the ICI tier in full, then cross DCN
                # once with the tier-reduced chunk. Reassociates the f32 sum
                # (HEAT_TPU_TWO_TIER=0 is the bit-parity hatch); max/min/
                # land/lor and exact dtypes are order-free.
                if preduce is not None:
                    return preduce(preduce(b, "ici"), "dcn")
                g = lax.all_gather(b, "ici", axis=0)  # (ici, ...chunk)
                r = local_reduce(g, axis=0)
                g2 = lax.all_gather(r, "dcn", axis=0)  # (dcn, ...chunk)
                return local_reduce(g2, axis=0)

        else:

            def body(b):
                if preduce is not None:
                    return preduce(b, ax)
                g = lax.all_gather(b, ax, axis=0)  # (p, ...chunk)
                return local_reduce(g, axis=0)

        out_spec = spec_repl
    elif kind == "allgather":

        def body(b):
            return lax.all_gather(b, ax, axis=split, tiled=True)

        out_spec = spec_repl
    elif kind == "bcast":
        root = kw["root"]

        if tiers is not None:
            ici_size = tiers[1]

            def body(b):
                # one-hot in flat coordinates, then the two-level psum: the
                # root chunk fans out over its ICI tier first and crosses DCN
                # once (zeros elsewhere — exact whatever the dtype)
                i = lax.axis_index("dcn") * ici_size + lax.axis_index("ici")
                masked = jax.numpy.where(i == root, b, jax.numpy.zeros_like(b))
                return lax.psum(lax.psum(masked, "ici"), "dcn").astype(b.dtype)

        else:

            def body(b):
                i = lax.axis_index(ax)
                masked = jax.numpy.where(i == root, b, jax.numpy.zeros_like(b))
                # psum promotes bool -> int; restore the input dtype
                return lax.psum(masked, ax).astype(b.dtype)

        out_spec = spec_split  # every device's slot now holds the root chunk
    elif kind == "scan":
        exclusive = kw["exclusive"]
        _, local_reduce, cum = _REDUCERS[op]

        def body(b):
            g = lax.all_gather(b, ax, axis=0)  # (p, ...chunk)
            is_bool = g.dtype == jax.numpy.bool_
            if is_bool:
                # cummax/cummin reject bool (MPI's MAX/MIN are defined on
                # C_BOOL — reference dtype table communication.py:130): ride
                # uint8 there; sum/prod promote to int32 so a cumsum of >=256
                # True chunks cannot wrap back through 0
                carrier = jax.numpy.uint8 if op in ("max", "min") else jax.numpy.int32
                g = g.astype(carrier)
            c = cum(g)
            if is_bool:
                c = c.astype(jax.numpy.bool_)
            i = lax.axis_index(ax)
            if exclusive:
                neutral = {"sum": 0, "prod": 1}.get(op)
                if neutral is None:  # max/min exclusive scan: use own-dtype extremes
                    if b.dtype == jax.numpy.bool_:
                        neutral = op == "min"
                    else:
                        info = (
                            jax.numpy.finfo if jax.numpy.issubdtype(b.dtype, jax.numpy.floating) else jax.numpy.iinfo
                        )(b.dtype)
                        neutral = info.min if op == "max" else info.max
                first = jax.numpy.full_like(b, neutral)
                shifted = jax.numpy.concatenate([first[None], c[:-1]], axis=0)
                return shifted[i]
            return c[i]

        out_spec = spec_split
    elif kind == "cumop":
        # distributed cumulative along the split axis: local cum + exclusive
        # prefix of per-block TOTALS + combine (the reference's local-cum +
        # Exscan + final-op pipeline, _operations.py:185-281). Only the
        # (..., 1, ...) block totals cross the mesh — never the operand.
        cumfn = jax.numpy.cumsum if op == "sum" else jax.numpy.cumprod
        neutral = 0 if op == "sum" else 1

        def body(b):
            c = cumfn(b, axis=split)
            n_loc = b.shape[split]
            if n_loc == 0:  # 0-size split axis: nothing to exchange
                return c
            tot = lax.slice_in_dim(c, n_loc - 1, n_loc, axis=split)
            g = lax.all_gather(tot, ax, axis=split, tiled=True)  # (..., p, ...)
            first = jax.numpy.full_like(lax.slice_in_dim(g, 0, 1, axis=split), neutral)
            ex = jax.numpy.concatenate(
                [first, lax.slice_in_dim(g, 0, p - 1, axis=split)], axis=split
            )
            ex = cumfn(ex, axis=split)  # ex[j] = combine of totals of blocks < j
            off = lax.dynamic_slice_in_dim(ex, lax.axis_index(ax), 1, axis=split)
            return c + off if op == "sum" else c * off

        out_spec = spec_split
    elif kind == "alltoall":
        sa = kw["sa"]

        def body(b):
            return lax.all_to_all(b, ax, split_axis=sa, concat_axis=split, tiled=True)

        out_spec = PartitionSpec(*([None] * kw["sa"] + [ax]))
    elif kind == "ppermute":
        shift = kw["shift"]
        perm = [(i, (i + shift) % p) for i in range(p)]

        def body(b):
            return lax.ppermute(b, ax, perm)

        out_spec = spec_split
    else:  # pragma: no cover
        raise ValueError(f"unknown collective {kind}")

    return jax.jit(
        _shard_map(body, mesh=mesh, in_specs=spec_split, out_specs=out_spec, check_vma=False)
    )


class _LazyWorld(MeshCommunication):
    """World communicator whose mesh is built on first use (lets test harnesses force
    the platform before any backend initialisation)."""

    def __init__(self, self_only: bool = False):
        super().__init__()
        self.__self_only = self_only
        self.__built = False

    @property
    def mesh_built(self) -> bool:
        """Whether the lazy mesh has been resolved to concrete devices."""
        return self.__built

    @property
    def mesh(self) -> Mesh:
        if not self.__built:
            devs = jax.devices()
            if self.__self_only:
                devs = devs[:1]
            # rebuild parent lazily with the resolved devices
            MeshCommunication.__init__(self, devices=devs)
            self.__built = True
        return MeshCommunication.mesh.fget(self)


WORLD: MeshCommunication = _LazyWorld()
"""Communicator spanning every visible device (reference ``MPI_WORLD``,
communication.py:1890)."""

SELF: MeshCommunication = _LazyWorld(self_only=True)
"""Single-device communicator (reference ``MPI_SELF``, communication.py:1891)."""

# Drop-in aliases so reference user code (`ht.MPI_WORLD.size`) ports unchanged.
MPI_WORLD = WORLD
MPI_SELF = SELF

__default_comm: MeshCommunication = WORLD


def ensure_placement(data, split, comm, gshape=None):
    """
    Reconcile an array's physical layout with its ``split`` metadata: shape-changing
    XLA outputs can come back replicated even when the split axis shards evenly.
    Applies the canonical (padded, sharded) placement via :meth:`MeshCommunication.placed`;
    a no-op for local/replicated cases.
    """
    if split is not None and isinstance(comm, MeshCommunication) and comm.is_distributed():
        return comm.placed(data, split, gshape)
    return data


def shift(x, steps: int = 1):
    """
    Ring-rotate the split-axis CHUNKS of a DNDarray by ``steps`` device
    positions (the DNDarray counterpart of :meth:`MeshCommunication.Ppermute`
    — the reference's neighbor Send/Recv choreography, e.g. the rotating-slab
    rings of ``spatial/distance.py``; SPMD has no two-sided Send/Recv, so
    ``lax.ppermute`` is the primitive those patterns compile to).

    This is a *chunk-level* collective, not a logical ``roll``: device ``i``'s
    chunk moves to device ``(i + steps) % p``. On a ragged split axis the
    zero-filled pad slabs rotate along with their chunks (eager and fused
    paths do the identical fill, so the hatch is bit-for-bit); positions the
    rotated pad lands on read zero. Replicated or non-distributed operands
    return an unshifted copy (a one-device ring is the identity).

    Over a pending fused chain the rotation records a collective node
    (``core/fusion.py``): chain + ppermute + follow-on chain compile as one
    shard_map program. ``HEAT_TPU_FUSION_COLLECTIVES=0`` restores the flush
    barrier bit for bit.
    """
    from .dndarray import DNDarray as _D

    if not isinstance(x, _D):
        raise TypeError(f"shift expects a DNDarray, got {type(x)}")
    comm = x.comm
    if (
        x.split is None
        or not isinstance(comm, MeshCommunication)
        or not comm.is_distributed()
    ):
        return _D(x.parray, tuple(x.shape), x.dtype, x.split, x.device, comm, True)
    s_ax = int(x.split) % x.ndim
    from . import fusion as _fusion

    if _fusion.collective_ready(x):
        res = _fusion.defer_shift(x, steps)
        if res is not None:
            return res
    x._flush("collective")
    phys = x.filled(0) if x.is_padded else x.parray
    data = comm.Ppermute(phys, shift=steps, split=s_ax)
    return _D(data, tuple(x.shape), x.dtype, x.split, x.device, comm, True)


def get_comm() -> Communication:
    """Retrieves the globally set default communicator (reference
    communication.py:1897-1903)."""
    return __default_comm


def sanitize_comm(comm: Optional[Communication]) -> Communication:
    """
    Verifies that the passed communicator is valid; ``None`` resolves to the global
    default. Reference parity: communication.py:1904-1926.
    """
    if comm is None:
        return get_comm()
    if isinstance(comm, Communication):
        return comm
    if isinstance(comm, Mesh):
        return MeshCommunication(mesh=comm)
    raise TypeError(f"Expected a Communication object or Mesh, but got {type(comm)}")


def use_comm(comm: Optional[Communication] = None) -> None:
    """Sets the globally used default communicator (reference
    communication.py:1927-1940)."""
    global __default_comm
    __default_comm = sanitize_comm(comm)


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_devices: Optional[int] = None,
) -> MeshCommunication:
    """
    Join a multi-host run and return the world communicator spanning the whole pod.

    The reference framework becomes multi-node by launching every rank under
    ``mpirun``; the TPU-native equivalent is one controller process per host with
    ``jax.distributed.initialize`` wiring the pod topology (on Cloud TPU the
    arguments are auto-detected from the metadata server — call with no args).
    Must be called before any other JAX/heat_tpu operation in the process.
    After it returns, ``WORLD``/``get_comm()`` cover all chips in the pod and every
    ``split`` array spans hosts, with XLA routing collectives over ICI within a
    slice and DCN across slices.

    Explicit wiring must be complete: passing some of ``coordinator_address``/
    ``num_processes``/``process_id`` but not all three is rejected with a
    ``ValueError`` *here* — handing partial wiring to
    ``jax.distributed.initialize`` turns the mistake into an opaque
    coordination-service hang instead of an error.
    """
    explicit = {
        "coordinator_address": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
    }
    given = {k for k, v in explicit.items() if v is not None}
    if given and given != set(explicit):
        missing = sorted(set(explicit) - given)
        raise ValueError(
            f"incomplete distributed wiring: got {sorted(given)} without "
            f"{missing} — pass all three (or none, for Cloud TPU "
            "metadata-server auto-detection); a partial spec would hang in "
            "jax.distributed.initialize waiting for peers that were never told "
            "where the coordinator is"
        )
    if num_processes is not None:
        num_processes = int(num_processes)
        process_id = int(process_id)
        if num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {num_processes}")
        if not 0 <= process_id < num_processes:
            raise ValueError(
                f"process_id {process_id} out of range for num_processes="
                f"{num_processes} (valid: 0..{num_processes - 1})"
            )
    if local_devices is not None and int(local_devices) < 1:
        raise ValueError(f"local_devices must be >= 1, got {local_devices}")
    if getattr(WORLD, "mesh_built", False) or getattr(SELF, "mesh_built", False):
        raise RuntimeError(
            "distributed_init() must run before any heat_tpu/JAX operation: a "
            "communicator has already resolved to this host's devices, so "
            "joining the pod now would leave every split array single-host"
        )
    # Multi-process CPU runs (the reference's `mpirun -n N` development mode) need
    # the gloo cross-process collective client. Set it unconditionally — it only
    # affects CPU backend creation, so it is harmless for TPU pods, and gating on
    # the platform string would miss auto-detected CPU-only machines. Probing the
    # platform here would initialize the backend, which must not happen before
    # jax.distributed.initialize.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        import warnings

        warnings.warn(
            "could not enable gloo CPU collectives (jax config option missing); "
            "multi-process CPU collectives may hang",
            RuntimeWarning,
        )
    if local_devices is not None:
        from ._compat import set_cpu_device_count

        set_cpu_device_count(int(local_devices))
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return get_comm()
