"""
Exponential and logarithmic operations (all element-local).

Parity with the reference's ``heat/core/exponential.py`` (``__all__`` at
exponential.py:11-23).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "logaddexp", "logaddexp2", "sqrt", "square"]


def exp(x, out=None) -> DNDarray:
    """Element-wise exponential (reference exponential.py exp)."""
    return _operations.__local_op(jnp.exp, x, out)


def expm1(x, out=None) -> DNDarray:
    """Element-wise exp(x) - 1 (reference exponential.py expm1)."""
    return _operations.__local_op(jnp.expm1, x, out)


def exp2(x, out=None) -> DNDarray:
    """Element-wise 2**x (reference exponential.py exp2)."""
    return _operations.__local_op(jnp.exp2, x, out)


def log(x, out=None) -> DNDarray:
    """Element-wise natural logarithm (reference exponential.py log)."""
    return _operations.__local_op(jnp.log, x, out)


def log2(x, out=None) -> DNDarray:
    """Element-wise base-2 logarithm (reference exponential.py log2)."""
    return _operations.__local_op(jnp.log2, x, out)


def log10(x, out=None) -> DNDarray:
    """Element-wise base-10 logarithm (reference exponential.py log10)."""
    return _operations.__local_op(jnp.log10, x, out)


def log1p(x, out=None) -> DNDarray:
    """Element-wise log(1 + x) (reference exponential.py log1p)."""
    return _operations.__local_op(jnp.log1p, x, out)


def logaddexp(x1, x2, out=None) -> DNDarray:
    """Element-wise log(exp(x1) + exp(x2)) (reference exponential.py logaddexp)."""
    return _operations.__binary_op(jnp.logaddexp, x1, x2, out)


def logaddexp2(x1, x2, out=None) -> DNDarray:
    """Element-wise log2(2**x1 + 2**x2) (reference exponential.py logaddexp2)."""
    return _operations.__binary_op(jnp.logaddexp2, x1, x2, out)


def sqrt(x, out=None) -> DNDarray:
    """Element-wise square root (reference exponential.py sqrt)."""
    return _operations.__local_op(jnp.sqrt, x, out)


def square(x, out=None) -> DNDarray:
    """Element-wise square (reference exponential.py square)."""
    return _operations.__local_op(jnp.square, x, out)


DNDarray.exp = exp
DNDarray.log = log
DNDarray.sqrt = sqrt
