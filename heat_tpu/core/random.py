"""
Counter-based parallel pseudo-random number generation.

Parity with the reference's ``heat/core/random.py``: the reference hand-implements the
Threefry-2x32/2x64 block cipher in tensorized torch (random.py:868-1041) and assigns
each rank the counter range of its chunk (:55-202) so results are identical regardless
of process count. JAX's native PRNG *is* Threefry-2x32 — the same cipher family — so
this module keeps a global ``(seed, counter)`` state (:764-818) and derives a fresh key
per call by folding the counter into the seed key. Being single-controller, results are
trivially device-count-invariant; the sharding of the output only affects layout.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import devices as _devices
from . import factories
from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "ranf",
    "randint",
    "random_integer",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
]

# global (seed, counter) state, reference random.py:764-818
__seed: int = 0
__counter: int = 0


def __next_key(nelem: int) -> jax.Array:
    """Derive the key for the next ``nelem`` draws and advance the counter."""
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter % (2**31))
    __counter += max(int(nelem), 1)
    return key


def __wrap(data: jax.Array, dtype, split, device, comm) -> DNDarray:
    device = _devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    arr = factories.array(data, dtype=dtype, split=split, device=device, comm=comm)
    return arr


def get_state() -> Tuple[str, int, int, int, float]:
    """The internal state of the generator as
    ``('Threefry', seed, counter, 0, 0.0)`` (reference random.py:203-219)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple[str, int, int, int, float]) -> None:
    """
    Sets the internal state of the generator; accepts the tuple layout of
    :func:`get_state` (reference random.py:782-818).

    Raises
    ------
    TypeError / ValueError
        If the state tuple is malformed.
    """
    global __seed, __counter
    if not isinstance(state, (tuple, list)) or len(state) not in (3, 5):
        raise TypeError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def seed(new_seed: Optional[int] = None) -> None:
    """Seed the generator; ``None`` draws entropy from the OS (reference
    random.py:764-781)."""
    global __seed, __counter
    if new_seed is None:
        new_seed = int.from_bytes(np.random.bytes(4), "little")
    __seed = int(new_seed)
    __counter = 0


def __shape_of(args) -> Tuple[int, ...]:
    if len(args) == 0:
        return ()
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(args[0])
    return tuple(int(a) for a in args)


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """
    Uniform random samples in [0, 1) of the given shape (reference random.py:268-330).
    """
    shape = __shape_of(d)
    nelem = int(np.prod(shape)) if shape else 1
    key = __next_key(nelem)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.uniform(key, shape, dtype=jnp.float32).astype(dtype.jnp_type())
    return __wrap(data, dtype, split, device, comm)


def randint(
    low: int,
    high: Optional[int] = None,
    size: Optional[Union[int, Tuple[int, ...]]] = None,
    dtype=types.int32,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """
    Random integers in [low, high) — or [0, low) when ``high`` is None — of the given
    ``size`` (reference random.py:331-420).
    """
    if high is None:
        low, high = 0, low
    if high <= low:
        raise ValueError("low >= high")
    if size is None:
        size = ()
    shape = sanitize_shape(size) if size != () else ()
    nelem = int(np.prod(shape)) if shape else 1
    key = __next_key(nelem)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.randint(key, shape, int(low), int(high)).astype(dtype.jnp_type())
    return __wrap(data, dtype, split, device, comm)


random_integer = randint


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """
    Standard-normal random samples of the given shape (reference random.py:584-640 via
    the Kundu transform; jax uses inverse-CDF/Box-Muller in native XLA).
    """
    shape = __shape_of(d)
    nelem = int(np.prod(shape)) if shape else 1
    key = __next_key(nelem)
    dtype = types.canonical_heat_type(dtype)
    data = jax.random.normal(key, shape, dtype=jnp.float32).astype(dtype.jnp_type())
    return __wrap(data, dtype, split, device, comm)


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal samples with the given mean and standard deviation (reference
    random.py:641-700)."""
    if np.any(np.asarray(std) < 0):
        raise ValueError("std must be non-negative")
    shape = () if shape is None else sanitize_shape(shape)
    base = randn(*shape, dtype=dtype, split=split, device=device, comm=comm)
    return base * std + mean


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference random.py:701-763)."""
    shape = () if shape is None else sanitize_shape(shape)
    return randn(*shape, dtype=dtype, split=split, device=device, comm=comm)


def random(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0,1) samples of the given shape (reference random.py random/
    random_sample)."""
    shape = () if shape is None else sanitize_shape(shape)
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


random_sample = random
ranf = random
sample = random


def randperm(n: int, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """A random permutation of ``range(n)`` (reference random.py randperm)."""
    if not isinstance(n, (int, np.integer)):
        raise TypeError(f"n must be an integer, got {type(n)}")
    if dtype is None:
        dtype = types.default_index_type()
    key = __next_key(int(n))
    data = jax.random.permutation(key, int(n))
    return __wrap(data, types.canonical_heat_type(dtype), split, device, comm)


def permutation(x) -> DNDarray:
    """
    Randomly permute a sequence: ints become permuted ranges, arrays are shuffled
    along the first axis (reference random.py permutation).
    """
    if isinstance(x, (int, np.integer)):
        return randperm(int(x))
    if isinstance(x, DNDarray):
        key = __next_key(x.shape[0] if x.ndim else 1)
        data = jax.random.permutation(key, x.larray, axis=0)
        return DNDarray.__new_like__(x, data)
    raise TypeError(f"x must be int or DNDarray, got {type(x)}")
