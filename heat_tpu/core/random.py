"""
Counter-based parallel pseudo-random number generation.

Parity with the reference's ``heat/core/random.py``: the reference hand-implements the
Threefry-2x32/2x64 block cipher in tensorized torch (random.py:868-1041) and assigns
each rank the counter range of its chunk (:55-202) so results are identical regardless
of process count. Here the generation IS counter-based Threefry-2x32 (via
``jax.extend.random.threefry_2x32`` — the same cipher): element ``i`` of a draw is a
pure function of ``(seed, call_counter, logical_flat_index_i)``. Because the counter
is the *logical* index, results are bit-identical at any device count and any padding
of the physical layout, and the generator runs as one jitted program with
``out_shardings`` set — each device fills only its own shard (sharded at birth, the
analog of the reference's per-rank counter ranges :55-202).
"""

from __future__ import annotations

import functools
import operator
from typing import Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.extend.random import threefry2x32_p

from . import devices as _devices
from . import factories
from . import types
from .communication import MeshCommunication, sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "ranf",
    "randint",
    "random_integer",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
]

# global (seed, counter) state, reference random.py:764-818
__seed: int = 0
__counter: int = 0


def __next_prng(nelem: int) -> jax.Array:
    """Typed PRNG key for the next draw; advances the counter."""
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter % (2**31))
    __counter += max(int(nelem), 1)
    return key


def __next_key(nelem: int) -> jax.Array:
    """Derive the uint32[2] cipher key for the next draw and advance the counter."""
    return jax.random.key_data(__next_prng(nelem)).astype(jnp.uint32)


def __wrap(data: jax.Array, dtype, split, device, comm) -> DNDarray:
    device = _devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    arr = factories.array(data, dtype=dtype, split=split, device=device, comm=comm)
    return arr


@functools.lru_cache(maxsize=512)
def __generator(kind: str, gshape: Tuple[int, ...], jdtype: str, sharding):
    """
    One jitted counter-based generator per (kind, logical shape, dtype, placement).
    Draw ``i`` is ``threefry_2x32(key, logical_index(i))`` — the physical (possibly
    padded) output shape only changes *where* each element is produced, never its
    value (reference device-count invariance, random.py:55-202).
    """
    dt = np.dtype(jdtype)
    if sharding is not None:
        comm, split = sharding
        pshape = comm.padded_shape(gshape, split)
        out_shardings = comm.sharding(len(gshape), split)
    else:
        pshape = gshape
        out_shardings = None

    def logical_pair():
        # 64-bit LOGICAL counter of every physical position as a (hi, lo) uint32
        # pair: lo is the flat index within the largest dim suffix whose extent
        # fits 32 bits, hi the flat index over the remaining prefix dims — unique
        # for any array below 2**64 elements (single axes are limited to 2**32).
        # Pad positions get out-of-range counters; their values are never observed.
        ndim = len(gshape)
        pivot = ndim
        prod = 1
        while pivot > 0 and prod * int(gshape[pivot - 1]) < (1 << 32):
            prod *= int(gshape[pivot - 1])
            pivot -= 1

        def flat(dims):
            idx = jnp.zeros(pshape, dtype=jnp.uint32)
            stride = 1
            for d in reversed(dims):
                c = jax.lax.broadcasted_iota(jnp.uint32, pshape, d)
                idx = idx + c * jnp.uint32(stride)
                stride *= int(gshape[d])
            return idx

        return flat(range(0, pivot)), flat(range(pivot, ndim))

    def bits_fn(key):
        # per-element block cipher: counter = (hi, lo) logical pair, so draw i is a
        # pure function of (key, i) — bit-identical at any device count/padding.
        # Both cipher output words are returned: one 2x32 invocation yields 64
        # random bits per element, enough for a full f64 mantissa or an
        # effectively unbiased bounded integer.
        if gshape:
            hi, lo = logical_pair()
        else:
            hi = lo = jnp.zeros((), dtype=jnp.uint32)
        k1 = jnp.broadcast_to(key[0], lo.shape)
        k2 = jnp.broadcast_to(key[1], lo.shape)
        return threefry2x32_p.bind(k1, k2, hi, lo)

    wide = dt.itemsize == 8 and jax.config.jax_enable_x64

    def uniform_fn(key, offset):
        # 24-bit mantissa for ≤32-bit floats; 53-bit (27+26 from the two cipher
        # words) for f64 under x64 — matches the reference's Threefry-2x64
        # draw quality for 64-bit dtypes (reference random.py:220-267).
        w0, w1 = bits_fn(key)
        if wide:
            m = (w0 >> 5).astype(jnp.float64) * jnp.float64(1 << 26) + (w1 >> 6).astype(
                jnp.float64
            )
            return (m + offset) * jnp.float64(1.0 / (1 << 53))
        return ((w0 >> 8).astype(jnp.float32) + offset) * jnp.float32(1.0 / (1 << 24))

    if kind == "uniform":

        def f(key):
            return uniform_fn(key, 0.0).astype(dt)

    elif kind == "normal":
        from jax.scipy.special import ndtri

        def f(key):
            # strictly inside (0,1) so the inverse CDF stays finite
            return ndtri(uniform_fn(key, 0.5)).astype(dt)

    elif kind == "randint":

        def f(key, low, rng):
            # 64 random bits reduced mod rng: residual bias ≤ rng/2^64 for any
            # 32-bit range (the reference's 2x64 cipher reduced the same way,
            # random.py:331-420). Under x64 the reduction is a native u64 modulo
            # (also covering ranges > 2^32); without x64 an overflow-safe
            # double-word shift-and-subtract modulo in pure uint32 arithmetic.
            w0, w1 = bits_fn(key)
            if jax.config.jax_enable_x64:
                v64 = (w0.astype(jnp.uint64) << jnp.uint64(32)) | w1.astype(jnp.uint64)
                m = v64 % rng.astype(jnp.uint64)
                return (m.astype(jnp.int64) + low).astype(dt)
            rng32 = rng.astype(jnp.uint32)
            r = w0 % rng32  # (w0·2^32 + w1) mod rng == ((w0 mod rng)·2^32 + w1) mod rng
            for b in range(32):
                bit = (w1 >> (31 - b)) & jnp.uint32(1)
                # r = (2r + bit) mod rng without overflow: r < rng ≤ 2^32-1
                dbl = jnp.where(r >= rng32 - r, r - (rng32 - r), r + r)
                r = jnp.where(dbl + bit >= rng32, dbl + bit - rng32, dbl + bit)
            return (r.astype(jnp.int32) + low).astype(dt)

    else:  # pragma: no cover
        raise ValueError(kind)

    if out_shardings is None:
        return jax.jit(f)
    return jax.jit(f, out_shardings=out_shardings)


def __draw(kind: str, shape, dtype, split, device, comm, *args) -> DNDarray:
    """Generate a counter-based draw of logical ``shape``, sharded at birth."""
    device = _devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    shape = tuple(int(s) for s in shape)
    nelem = int(np.prod(shape)) if shape else 1
    key = __next_key(nelem)
    heat_dtype = types.canonical_heat_type(dtype)
    from .stride_tricks import sanitize_axis

    split = sanitize_axis(shape, split)
    distributed = (
        split is not None
        and isinstance(comm, MeshCommunication)
        and comm.is_distributed()
        and len(shape) > 0
    )
    gen = __generator(
        kind,
        shape,
        np.dtype(heat_dtype.jnp_type()).name,
        (comm, split) if distributed else None,
    )
    data = gen(key, *args)
    return DNDarray(data, shape, heat_dtype, split, device, comm, True)


def get_state() -> Tuple[str, int, int, int, float]:
    """The internal state of the generator as
    ``('Threefry', seed, counter, 0, 0.0)`` (reference random.py:203-219)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple[str, int, int, int, float]) -> None:
    """
    Sets the internal state of the generator; accepts the tuple layout of
    :func:`get_state` (reference random.py:782-818).

    Raises
    ------
    TypeError / ValueError
        If the state tuple is malformed.
    """
    global __seed, __counter
    if not isinstance(state, (tuple, list)) or len(state) not in (3, 5):
        raise TypeError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def seed(new_seed: Optional[int] = None) -> None:
    """Seed the generator; ``None`` draws entropy from the OS (reference
    random.py:764-781)."""
    global __seed, __counter
    if new_seed is None:
        new_seed = int.from_bytes(np.random.bytes(4), "little")
    __seed = int(new_seed)
    __counter = 0


def __shape_of(args) -> Tuple[int, ...]:
    if len(args) == 0:
        return ()
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(args[0])
    return tuple(int(a) for a in args)


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """
    Uniform random samples in [0, 1) of the given shape (reference random.py:268-330:
    Threefry bits → mantissa-masked floats :220-247; same construction here).
    """
    return __draw("uniform", __shape_of(d), dtype, split, device, comm)


def randint(
    low: int,
    high: Optional[int] = None,
    size: Optional[Union[int, Tuple[int, ...]]] = None,
    dtype=types.int32,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """
    Random integers in [low, high) — or [0, low) when ``high`` is None — of the given
    ``size`` (reference random.py:331-420).
    """
    if high is None:
        low, high = 0, low
    if high <= low:
        raise ValueError("low >= high")
    if size is None:
        size = ()
    shape = sanitize_shape(size) if size != () else ()
    rng = int(high) - int(low)
    if jax.config.jax_enable_x64:
        low_a, rng_a = jnp.int64(int(low)), jnp.uint64(rng)
    else:
        if rng > (1 << 32) - 1:
            raise ValueError(f"range {rng} needs 64-bit integers; enable jax x64")
        low_a, rng_a = jnp.int32(int(low)), jnp.uint32(rng)
    return __draw("randint", shape, dtype, split, device, comm, low_a, rng_a)


random_integer = randint


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """
    Standard-normal random samples of the given shape (reference random.py:584-640 via
    the Kundu transform; jax uses inverse-CDF/Box-Muller in native XLA).
    """
    return __draw("normal", __shape_of(d), dtype, split, device, comm)


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal samples with the given mean and standard deviation (reference
    random.py:641-700)."""
    if np.any(np.asarray(std) < 0):
        raise ValueError("std must be non-negative")
    shape = () if shape is None else sanitize_shape(shape)
    base = randn(*shape, dtype=dtype, split=split, device=device, comm=comm)
    return base * std + mean


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference random.py:701-763)."""
    shape = () if shape is None else sanitize_shape(shape)
    return randn(*shape, dtype=dtype, split=split, device=device, comm=comm)


def random(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0,1) samples of the given shape (reference random.py random/
    random_sample)."""
    shape = () if shape is None else sanitize_shape(shape)
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


random_sample = random
ranf = random
sample = random


def randperm(n: int, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """A random permutation of ``range(n)`` (reference random.py randperm)."""
    if not isinstance(n, (int, np.integer)):
        raise TypeError(f"n must be an integer, got {type(n)}")
    if dtype is None:
        dtype = types.default_index_type()
    key = __next_prng(int(n))
    data = jax.random.permutation(key, int(n))
    return __wrap(data, types.canonical_heat_type(dtype), split, device, comm)


def permutation(x) -> DNDarray:
    """
    Randomly permute a sequence: ints become permuted ranges, arrays are shuffled
    along the first axis (reference random.py permutation).
    """
    if isinstance(x, (int, np.integer)):
        return randperm(int(x))
    if isinstance(x, DNDarray):
        key = __next_prng(x.shape[0] if x.ndim else 1)
        data = jax.random.permutation(key, x.larray, axis=0)
        return DNDarray.__new_like__(x, data)
    raise TypeError(f"x must be int or DNDarray, got {type(x)}")
