"""
Rounding operations (all element-local).

Parity with the reference's ``heat/core/rounding.py`` (``__all__`` at
rounding.py:15-27).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from . import _operations
from . import sanitation
from .dndarray import DNDarray

__all__ = ["abs", "absolute", "ceil", "clip", "fabs", "floor", "modf", "nan_to_num", "round", "sgn", "sign", "trunc"]


def abs(x, out=None, dtype=None) -> DNDarray:
    """Element-wise absolute value; optional output dtype (reference rounding.py abs)."""
    from .types import canonical_heat_type, datatype

    res = _operations.__local_op(jnp.abs, x, out)
    if dtype is not None:
        if not isinstance(dtype, type) or not issubclass(dtype, datatype):
            raise TypeError("dtype must be a heat data type")
        res = res.astype(canonical_heat_type(dtype), copy=False)
    return res


absolute = abs


def ceil(x, out=None) -> DNDarray:
    """Element-wise ceiling (reference rounding.py ceil)."""
    return _operations.__local_op(jnp.ceil, x, out)


def clip(x, min, max, out=None) -> DNDarray:
    """Clip values to the interval [min, max]; bounds may be scalars or
    (broadcastable) arrays, DNDarrays included (reference rounding.py clip).
    Scalar bounds keep the single fused local op (one dispatch — the common,
    hot form); array bounds ride the binary-op template so they broadcast and
    distribution-match exactly like any other operand."""
    import numbers

    sanitation.sanitize_in(x)
    if min is None and max is None:
        raise ValueError("either min or max must be set")
    if all(b is None or isinstance(b, numbers.Number) for b in (min, max)):
        return _operations.__local_op(jnp.clip, x, out, min=min, max=max)
    res = x
    if min is not None:
        res = _operations.__binary_op(jnp.maximum, res, min)
    if max is not None:
        res = _operations.__binary_op(jnp.minimum, res, max)
    if out is not None:
        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def fabs(x, out=None) -> DNDarray:
    """Element-wise absolute value, float result (reference rounding.py fabs)."""
    from . import types

    res = _operations.__local_op(jnp.abs, x, None)
    if not types.heat_type_is_inexact(res.dtype):
        res = res.astype(types.float32, copy=False)
    if out is not None:
        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def floor(x, out=None) -> DNDarray:
    """Element-wise floor (reference rounding.py floor)."""
    return _operations.__local_op(jnp.floor, x, out)


def modf(x, out=None) -> Tuple[DNDarray, DNDarray]:
    """Fractional and integral parts (reference rounding.py modf)."""
    sanitation.sanitize_in(x)
    frac, integ = jnp.modf(x.larray)
    f = DNDarray.__new_like__(x, frac)
    i = DNDarray.__new_like__(x, integ)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a tuple of two DNDarrays")
        out[0].larray, out[1].larray = frac, integ
        return out
    return f, i


def round(x, decimals: int = 0, out=None, dtype=None) -> DNDarray:
    """Round to the given number of decimals (reference rounding.py round)."""
    from .types import canonical_heat_type

    res = _operations.__local_op(jnp.round, x, out, decimals=decimals)
    if dtype is not None:
        res = res.astype(canonical_heat_type(dtype), copy=False)
    return res


def sgn(x, out=None) -> DNDarray:
    """Element-wise sign (complex: x/|x|) (reference rounding.py sgn)."""
    return _operations.__local_op(jnp.sign, x, out)


def sign(x, out=None) -> DNDarray:
    """Element-wise sign; complex input uses the sign of the real part (reference
    rounding.py sign)."""
    from . import types

    if issubclass(x.dtype, types.complexfloating):
        sanitation.sanitize_in(x)
        res = jnp.sign(jnp.real(x.larray)).astype(x.dtype.jnp_type())
        return DNDarray.__new_like__(x, res)
    return _operations.__local_op(jnp.sign, x, out)


def nan_to_num(x, nan: float = 0.0, posinf=None, neginf=None, out=None) -> DNDarray:
    """Replace NaN/±inf with finite numbers, numpy semantics (beyond the
    reference snapshot, which lacks this symbol; numpy-API completion)."""
    return _operations.__local_op(
        jnp.nan_to_num, x, out, nan=nan, posinf=posinf, neginf=neginf
    )


def trunc(x, out=None) -> DNDarray:
    """Element-wise truncation (reference rounding.py trunc)."""
    return _operations.__local_op(jnp.trunc, x, out)


DNDarray.__abs__ = lambda self: abs(self)
DNDarray.abs = abs
DNDarray.clip = clip
DNDarray.round = round
