"""
Tile decompositions.

Parity with the reference's ``heat/core/tiling.py`` (``SplitTiles`` :14-330,
``SquareDiagTiles`` :331-1257). In the reference these drive hand-written
communication schedules (``resplit_``'s Isend/Irecv mesh, tiled QR); on TPU XLA owns
physical tiling, so these classes are *metadata* views: they expose the same tile-grid
geometry (one tile per device per dimension, square tiles on the diagonal) computed
from the balanced chunk layout, and tile get/set operate on the global array.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """
    One tile per device per dimension (reference tiling.py:14-330): the tile grid is
    the Cartesian product of every dimension's balanced chunk boundaries.
    """

    def __init__(self, arr: DNDarray):
        self.__arr = arr
        comm = arr.comm
        size = comm.size if isinstance(comm, MeshCommunication) else 1
        ends = []
        for dim, g in enumerate(arr.shape):
            bounds = [comm.chunk(arr.shape, dim, rank=r)[1][dim] for r in range(size)] if isinstance(
                comm, MeshCommunication
            ) else [g]
            ends.append(np.cumsum(bounds))
        self.__tile_ends_per_dim = ends
        # tile_locations: which device owns each tile along the split axis
        shape = tuple(size for _ in arr.shape)
        locs = np.zeros(shape, dtype=np.int64)
        if arr.split is not None:
            idx = [np.newaxis] * arr.ndim
            idx[arr.split] = slice(None)
            locs = locs + np.arange(size)[tuple(idx)]
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        """The tiled array."""
        return self.__arr

    @property
    def tile_ends_per_dim(self):
        """Cumulative tile end indices for every dimension."""
        return self.__tile_ends_per_dim

    @property
    def tile_locations(self) -> np.ndarray:
        """Device owning each tile (reference tiling.py set_tile_locations :108)."""
        return self.__tile_locations

    def __tile_slices(self, key) -> Tuple[slice, ...]:
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            ends = self.__tile_ends_per_dim[dim]
            starts = np.concatenate([[0], ends[:-1]])
            slices.append(slice(int(starts[k]), int(ends[k])))
        while len(slices) < self.__arr.ndim:
            slices.append(slice(None))
        return tuple(slices)

    def __getitem__(self, key):
        """The data of the indexed tile."""
        return self.__arr.larray[self.__tile_slices(key)]

    def __setitem__(self, key, value):
        """Set the data of the indexed tile."""
        if isinstance(value, DNDarray):
            value = value.larray
        self.__arr.larray = self.__arr.larray.at[self.__tile_slices(key)].set(value)


class SquareDiagTiles:
    """
    Tile grid with square tiles on the diagonal for tiled QR (reference
    tiling.py:331-1257). Geometry only: per-device tile row/column maps with square
    diagonal blocks sized by the split-axis chunking.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 1):
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        comm = arr.comm
        size = comm.size if isinstance(comm, MeshCommunication) else 1
        split = arr.split if arr.split is not None else 0
        # split-axis chunk boundaries subdivided tiles_per_proc ways
        bounds = []
        for r in range(size):
            _, lshape, _ = (
                comm.chunk(arr.shape, split, rank=r)
                if isinstance(comm, MeshCommunication)
                else (0, arr.shape, None)
            )
            n = lshape[split]
            base, rem = divmod(n, tiles_per_proc)
            bounds.extend([base + 1] * rem + [base] * (tiles_per_proc - rem))
        row_sizes = np.asarray([b for b in bounds if b > 0], dtype=np.int64)
        # square diagonal: column boundaries mirror row boundaries up to the smaller dim
        m, n = arr.shape
        col_sizes = []
        acc = 0
        for b in row_sizes:
            if acc + b <= n:
                col_sizes.append(b)
                acc += b
        if acc < n:
            col_sizes.append(n - acc)
        self.__row_indices = np.concatenate([[0], np.cumsum(row_sizes)])[:-1]
        self.__col_indices = np.concatenate([[0], np.cumsum(col_sizes)])[:-1]
        self.__row_sizes = row_sizes
        self.__col_sizes = np.asarray(col_sizes, dtype=np.int64)
        self.__tiles_per_proc = tiles_per_proc

    @property
    def arr(self) -> DNDarray:
        """The tiled array."""
        return self.__arr

    @property
    def row_indices(self) -> np.ndarray:
        """Start row of each tile row."""
        return self.__row_indices

    @property
    def col_indices(self) -> np.ndarray:
        """Start column of each tile column."""
        return self.__col_indices

    @property
    def tile_rows(self) -> int:
        """Number of tile rows."""
        return len(self.__row_sizes)

    @property
    def tile_columns(self) -> int:
        """Number of tile columns."""
        return len(self.__col_sizes)

    def get_tile(self, row: int, col: int):
        """The data of tile (row, col) (reference local_get/local_to_global)."""
        r0 = int(self.__row_indices[row])
        c0 = int(self.__col_indices[col])
        r1 = r0 + int(self.__row_sizes[row])
        c1 = c0 + int(self.__col_sizes[col])
        return self.__arr.larray[r0:r1, c0:c1]

    def set_tile(self, row: int, col: int, value) -> None:
        """Overwrite tile (row, col)."""
        if isinstance(value, DNDarray):
            value = value.larray
        r0 = int(self.__row_indices[row])
        c0 = int(self.__col_indices[col])
        r1 = r0 + int(self.__row_sizes[row])
        c1 = c0 + int(self.__col_sizes[col])
        self.__arr.larray = self.__arr.larray.at[r0:r1, c0:c1].set(value)
