"""
Tile decompositions.

Parity with the reference's ``heat/core/tiling.py`` (``SplitTiles`` :14-330,
``SquareDiagTiles`` :331-1257). In the reference these drive hand-written
communication schedules (``resplit_``'s Isend/Irecv mesh, tiled QR); on TPU XLA
owns physical tiling, so these classes are *metadata* views over the padded
physical layout — but the full reference API surfaces: tile grids, per-process
tile maps, owner lookup, device-local tile addressing
(``local_get``/``local_set``/``local_to_global``), cross-tiling
``match_tiles``, and tile get/set on the global array. User code written
against the reference's tile API ports; only the implicit ``comm.rank`` of the
per-rank methods becomes an explicit ``rank`` argument (single controller).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """
    One tile per device per dimension (reference tiling.py:14-330): the tile grid is
    the Cartesian product of every dimension's balanced chunk boundaries.
    """

    def __init__(self, arr: DNDarray):
        self.__arr = arr
        comm = arr.comm
        size = comm.size if isinstance(comm, MeshCommunication) else 1
        ends = []
        for dim, g in enumerate(arr.shape):
            # padded physical geometry — consistent with the device shards and
            # lshape_map (tail tiles of a ragged axis may be empty)
            bounds = (
                list(comm.counts_displs(arr.shape, dim)[0])
                if isinstance(comm, MeshCommunication)
                else [g]
            )
            ends.append(np.cumsum(bounds))
        self.__tile_ends_per_dim = ends
        # tile_locations: which device owns each tile along the split axis
        shape = tuple(size for _ in arr.shape)
        locs = np.zeros(shape, dtype=np.int64)
        if arr.split is not None:
            idx = [np.newaxis] * arr.ndim
            idx[arr.split] = slice(None)
            locs = locs + np.arange(size)[tuple(idx)]
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        """The tiled array."""
        return self.__arr

    @property
    def tile_ends_per_dim(self):
        """Cumulative tile end indices for every dimension."""
        return self.__tile_ends_per_dim

    @property
    def tile_locations(self) -> np.ndarray:
        """Device owning each tile (reference tiling.py set_tile_locations :108)."""
        return self.__tile_locations

    def __tile_slices(self, key) -> Tuple[slice, ...]:
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            ends = self.__tile_ends_per_dim[dim]
            starts = np.concatenate([[0], ends[:-1]])
            slices.append(slice(int(starts[k]), int(ends[k])))
        while len(slices) < self.__arr.ndim:
            slices.append(slice(None))
        return tuple(slices)

    def __getitem__(self, key):
        """The data of the indexed tile."""
        return self.__arr.larray[self.__tile_slices(key)]

    def __setitem__(self, key, value):
        """Set the data of the indexed tile."""
        if isinstance(value, DNDarray):
            value = value.larray
        self.__arr.larray = self.__arr.larray.at[self.__tile_slices(key)].set(value)


class SquareDiagTiles:
    """
    Tile grid with square tiles on the diagonal for tiled QR (reference
    tiling.py:331-1257) — the full reference API (``tile_map``,
    ``tile_rows_per_process``, ``get_start_stop``, ``local_get``/``local_set``/
    ``local_to_global``, ``match_tiles``) on the padded physical layout.

    Single-controller notes: where the reference's per-rank methods implicitly
    use ``comm.rank``, the equivalents here take an explicit ``rank`` (device
    slot) parameter, defaulting to 0; ``__getitem__`` returns the tile data for
    its unique owning device (the reference returns ``None`` on other ranks —
    there is no "other rank" under one controller). Cross-process tile slices
    raise ``ValueError`` exactly like the reference.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"expected a DNDarray for arr, got {type(arr)}")
        if not isinstance(tiles_per_proc, int):
            raise TypeError(f"expected an int for tiles_per_proc, got {type(tiles_per_proc)}")
        if tiles_per_proc < 1:
            raise ValueError(f"tiles_per_proc needs at least 1 tile per device, got {tiles_per_proc}")
        if arr.ndim != 2:
            raise ValueError(f"SquareDiagTiles needs a 2-D matrix, got shape {arr.shape}")
        self.__arr = arr
        comm = arr.comm
        size = comm.size if isinstance(comm, MeshCommunication) else 1
        split = arr.split if arr.split is not None else 0
        m, n = arr.shape
        # split-axis chunk boundaries (padded physical layout — consistent with
        # the device shards) subdivided tiles_per_proc ways; owner per piece
        if isinstance(comm, MeshCommunication):
            counts, _ = comm.counts_displs(arr.shape, split)
        else:
            counts = [arr.shape[split]]
        split_sizes, owners = [], []
        for r, cnt in enumerate(counts):
            base, rem = divmod(int(cnt), tiles_per_proc)
            for b in [base + 1] * rem + [base] * (tiles_per_proc - rem):
                if b > 0:
                    split_sizes.append(b)
                    owners.append(r)
        split_sizes = np.asarray(split_sizes, dtype=np.int64)
        # square diagonal: the other dimension mirrors the split boundaries up
        # to its extent, a remainder tile absorbing what is left
        other = n if split == 0 else m
        mirror, acc = [], 0
        for b in split_sizes:
            if acc + b <= other:
                mirror.append(b)
                acc += b
        if acc < other:
            mirror.append(other - acc)
        mirror = np.asarray(mirror, dtype=np.int64)
        if split == 0:
            row_sizes, col_sizes = split_sizes, mirror
        else:
            row_sizes, col_sizes = mirror, split_sizes
        self.__split = split
        self.__size = size
        self.__row_sizes = row_sizes
        self.__col_sizes = col_sizes
        self.__row_indices = np.concatenate([[0], np.cumsum(row_sizes)])[:-1]
        self.__col_indices = np.concatenate([[0], np.cumsum(col_sizes)])[:-1]
        self.__tiles_per_proc = tiles_per_proc
        # per-process tile counts along the split axis; the mirrored axis is
        # whole on every process
        per_proc = [0] * size
        for o in owners:
            per_proc[o] += 1
        if split == 0:
            self.__row_per_proc_list = per_proc
            self.__col_per_proc_list = [len(col_sizes)] * size
        else:
            self.__row_per_proc_list = [len(row_sizes)] * size
            self.__col_per_proc_list = per_proc
        self.__owners = owners  # owner of each split-axis tile piece
        self.__build_tile_map()

    def __build_tile_map(self) -> None:
        rows, cols = len(self.__row_sizes), len(self.__col_sizes)
        tm = np.zeros((rows, cols, 3), dtype=np.int64)
        tm[..., 0] = self.__row_indices[:, None]
        tm[..., 1] = self.__col_indices[None, :]
        # owner: by tile row for split=0, by tile column for split=1 (mirrored
        # tiles beyond the split pieces belong to the last owner)
        owners = self.__owners
        own = lambda i: owners[i] if i < len(owners) else (owners[-1] if owners else 0)
        if self.__split == 0:
            for i in range(rows):
                tm[i, :, 2] = own(i)
        else:
            for j in range(cols):
                tm[:, j, 2] = own(j)
        self.__tile_map = tm

    # ------------------------------------------------------------------ properties
    @property
    def arr(self) -> DNDarray:
        """The tiled array."""
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        """``(size, 2)`` per-device local shapes (reference tiling.py:738)."""
        return self.__arr.lshape_map

    @property
    def last_diagonal_process(self) -> int:
        """Device owning the last diagonal tile (reference tiling.py:747)."""
        d = min(len(self.__row_sizes), len(self.__col_sizes)) - 1
        tm = self.__tile_map
        return int(tm[d, d, 2])

    @property
    def row_indices(self):
        """Start row of each tile row (list, reference tiling.py:754)."""
        return [int(r) for r in self.__row_indices]

    @property
    def col_indices(self):
        """Start column of each tile column (list, reference tiling.py:732)."""
        return [int(c) for c in self.__col_indices]

    @property
    def tile_rows(self) -> int:
        """Number of tile rows."""
        return len(self.__row_sizes)

    @property
    def tile_columns(self) -> int:
        """Number of tile columns."""
        return len(self.__col_sizes)

    @property
    def tile_rows_per_process(self):
        """Tile rows owned by each device (reference tiling.py:818)."""
        return list(self.__row_per_proc_list)

    @property
    def tile_columns_per_process(self):
        """Tile columns owned by each device (reference tiling.py:768)."""
        return list(self.__col_per_proc_list)

    @property
    def tile_map(self) -> np.ndarray:
        """``(tile_rows, tile_cols, 3)`` array of ``(row_start, col_start,
        owner_device)`` per tile (reference tiling.py:775)."""
        return self.__tile_map.copy()

    # ------------------------------------------------------------------ indexing
    def __key_bounds(self, key):
        """Resolve a tile key to global (r0, r1, c0, c1) and the owner set."""
        if not isinstance(key, (int, tuple, slice)):
            raise TypeError(f"tile keys may be int, tuple, or slice — got {type(key)}")
        if isinstance(key, (int, slice)):
            key = (key, slice(None))
        key = tuple(key)
        if len(key) == 1:
            key = (key[0], slice(None))
        row_ends = np.concatenate([self.__row_indices[1:], [self.__arr.shape[0]]])
        col_ends = np.concatenate([self.__col_indices[1:], [self.__arr.shape[1]]])

        def rng(k, starts, ends):
            if isinstance(k, (int, np.integer)):
                k = int(k)
                return int(starts[k]), int(ends[k]), slice(k, k + 1)
            start = k.start if k.start is not None else 0
            stop = k.stop if k.stop is not None else len(starts)
            stop = min(stop, len(starts))
            return int(starts[start]), int(ends[stop - 1]), slice(start, stop)

        r0, r1, rsel = rng(key[0], self.__row_indices, row_ends)
        c0, c1, csel = rng(key[1], self.__col_indices, col_ends)
        owners = np.unique(self.__tile_map[rsel, csel, 2])
        return r0, r1, c0, c1, owners

    def get_start_stop(self, key):
        """
        ``(dim0 start, dim0 stop, dim1 start, dim1 stop)`` of the tile(s) under
        ``key``, relative to the OWNING device's chunk (reference
        tiling.py:824-889). The key must resolve to tiles of one device.
        """
        r0, r1, c0, c1, owners = self.__key_bounds(key)
        if len(owners) > 1:
            raise ValueError(f"Tile/s must be located on one process. currently on: {owners}")
        comm = self.__arr.comm
        if isinstance(comm, MeshCommunication):
            _, displs = comm.counts_displs(self.__arr.shape, self.__split)
            off = displs[int(owners[0])]
        else:
            off = 0
        if self.__split == 0:
            return r0 - off, r1 - off, c0, c1
        return r0, r1, c0 - off, c1 - off

    def __getitem__(self, key):
        """
        The data of the tile(s) under ``key`` — a global-view slice of the
        owning device's region (reference tiling.py:890-938; returns data
        instead of rank-conditional ``None`` under one controller). Raises on
        cross-device slices like the reference.
        """
        r0, r1, c0, c1, owners = self.__key_bounds(key)
        if len(owners) > 1:
            raise ValueError("Slicing across splits is not allowed")
        return self.__arr.larray[r0:r1, c0:c1]

    def __setitem__(self, key, value) -> None:
        """Write ``value`` into the tile(s) under ``key`` (reference
        tiling.py:1212-1257)."""
        if isinstance(value, DNDarray):
            value = value.larray
        r0, r1, c0, c1, owners = self.__key_bounds(key)
        if len(owners) > 1:
            raise ValueError("setting across splits is not allowed")
        self.__arr.larray = self.__arr.larray.at[r0:r1, c0:c1].set(value)

    # ------------------------------------------------------------------ local API
    def local_to_global(self, key, rank: int):
        """
        Convert device-local tile indices to global tile indices (reference
        tiling.py:1022-1083): tile row/column ``k`` *of device* ``rank`` maps to
        global tile index ``k + tiles-before-rank`` along the split axis.
        """
        if isinstance(key, (int, slice)):
            key = [key, slice(0, None)]
        else:
            key = list(key)
        per = self.__row_per_proc_list if self.__split == 0 else self.__col_per_proc_list
        prev = sum(per[:rank])
        loc = per[rank]
        d = 0 if self.__split == 0 else 1
        k = key[d]
        if isinstance(k, (int, np.integer)):
            key[d] = int(k) + prev
        elif isinstance(k, slice):
            start = k.start + prev if k.start is not None else prev
            stop = k.stop + prev if k.stop is not None else prev + loc
            # clamp to the device's own tile range: the reference clamps the
            # WIDTH (stop = start + loc), which lets a mid-start over-long
            # slice spill into the next rank's tiles — clamping the END keeps
            # 'local' meaning local
            stop = min(stop, prev + loc)
            key[d] = slice(start, stop)
        return tuple(key)

    def local_get(self, key, rank: int = 0):
        """The tile(s) under device-local ``key`` of device ``rank`` (reference
        tiling.py:939-958)."""
        return self.__getitem__(self.local_to_global(key, rank))

    def local_set(self, key, value, rank: int = 0) -> None:
        """Write ``value`` to the tile(s) under device-local ``key`` of device
        ``rank`` (reference tiling.py:959-1021)."""
        self.__setitem__(self.local_to_global(key, rank), value)

    # ------------------------------------------------------------------ match
    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """
        Overwrite this tiling's geometry to match another's (reference
        tiling.py:1084-1211) — intended for a square Q matching A/R's tiling:
        row and column boundaries both follow the matched split boundaries of
        the shorter dimension. Under XLA the reference's accompanying
        ``redistribute_`` collapses into the canonical placement, so only the
        metadata moves.
        """
        if not isinstance(tiles_to_match, SquareDiagTiles):
            raise TypeError(
                f"tiles_to_match must be a SquareDiagTiles object, currently: {type(tiles_to_match)}"
            )
        base, match = self.__arr, tiles_to_match.__arr
        msplit = match.split if match.split is not None else 0
        m, n = base.shape
        if msplit == 0:
            src = (
                tiles_to_match.__row_sizes if match.shape[0] >= match.shape[1]
                else tiles_to_match.__col_sizes
            )
            src_owners = tiles_to_match.__owners
        else:
            src = (
                tiles_to_match.__row_sizes if match.shape[0] <= match.shape[1]
                else tiles_to_match.__col_sizes
            )
            src_owners = tiles_to_match.__owners
        # a square base (Q) takes the source boundaries on BOTH axes, clipped
        # to its own extents with a remainder tile
        def fit(sizes, extent):
            out, acc = [], 0
            for b in sizes:
                if acc + b <= extent:
                    out.append(int(b))
                    acc += b
            if acc < extent:
                out.append(extent - acc)
            return np.asarray(out, dtype=np.int64)

        self.__row_sizes = fit(src, m)
        self.__col_sizes = fit(src, n)
        self.__row_indices = np.concatenate([[0], np.cumsum(self.__row_sizes)])[:-1]
        self.__col_indices = np.concatenate([[0], np.cumsum(self.__col_sizes)])[:-1]
        owners = list(src_owners[: len(self.__row_sizes if self.__split == 0 else self.__col_sizes)])
        while owners and len(owners) < (
            len(self.__row_sizes) if self.__split == 0 else len(self.__col_sizes)
        ):
            owners.append(owners[-1])
        self.__owners = owners or [0]
        per = [0] * self.__size
        for o in self.__owners:
            per[o] += 1
        if self.__split == 0:
            self.__row_per_proc_list = per
            self.__col_per_proc_list = [len(self.__col_sizes)] * self.__size
        else:
            self.__row_per_proc_list = [len(self.__row_sizes)] * self.__size
            self.__col_per_proc_list = per
        self.__build_tile_map()

    # round-2 convenience API (kept)
    def get_tile(self, row: int, col: int):
        """The data of tile (row, col) — alias of ``self[row, col]``."""
        return self[row, col]

    def set_tile(self, row: int, col: int, value) -> None:
        """Overwrite tile (row, col) — alias of ``self[row, col] = value``."""
        self[row, col] = value
