"""
Logical operations.

Parity with the reference's ``heat/core/logical.py`` (``__all__`` at logical.py:20-34).
``all``/``any`` reduce with MPI.LAND/LOR in the reference (via __reduce_op); here they
are sharded jnp reductions.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import _operations
from . import sanitation
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x, axis=None, out=None, keepdim=None, keepdims=None, where=None) -> DNDarray:
    """Whether all elements evaluate to True over the given axis (reference
    logical.py all → MPI.LAND). A pending fused chain on ``x`` is consumed as
    a reduction sink (core/fusion.py); ``where`` restricts the test to the
    masked elements (numpy semantics)."""
    kwargs = {} if where is None else {"where": where}
    return _operations.__reduce_op(x, jnp.all, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims), **kwargs)


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Whether all elements of two arrays are pairwise within tolerance (reference
    logical.py allclose — scalar Allreduce there)."""
    a = x.larray if isinstance(x, DNDarray) else jnp.asarray(x)
    b = y.larray if isinstance(y, DNDarray) else jnp.asarray(y)
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan))


def any(x, axis=None, out=None, keepdim=None, keepdims=None, where=None) -> DNDarray:
    """Whether any element evaluates to True over the given axis (reference
    logical.py any → MPI.LOR). A pending fused chain on ``x`` is consumed as
    a reduction sink (core/fusion.py); ``where`` restricts the test to the
    masked elements (numpy semantics)."""
    kwargs = {} if where is None else {"where": where}
    return _operations.__reduce_op(x, jnp.any, axis=axis, out=out, keepdims=_operations.resolve_keepdims(keepdim, keepdims), **kwargs)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Element-wise closeness within tolerance (reference logical.py isclose)."""
    return _operations.__binary_op(
        jnp.isclose, x, y, fn_kwargs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan}
    )


def isfinite(x) -> DNDarray:
    """Element-wise finiteness test (reference logical.py isfinite)."""
    return _operations.__local_op(jnp.isfinite, x)


def isinf(x) -> DNDarray:
    """Element-wise infinity test (reference logical.py isinf)."""
    return _operations.__local_op(jnp.isinf, x)


def isnan(x) -> DNDarray:
    """Element-wise NaN test (reference logical.py isnan)."""
    return _operations.__local_op(jnp.isnan, x)


def isneginf(x, out=None) -> DNDarray:
    """Element-wise negative-infinity test (reference logical.py isneginf)."""
    return _operations.__local_op(jnp.isneginf, x, out)


def isposinf(x, out=None) -> DNDarray:
    """Element-wise positive-infinity test (reference logical.py isposinf)."""
    return _operations.__local_op(jnp.isposinf, x, out)


def logical_and(t1, t2) -> DNDarray:
    """Element-wise logical AND (reference logical.py logical_and)."""
    return _operations.__binary_op(jnp.logical_and, t1, t2)


def logical_not(t, out=None) -> DNDarray:
    """Element-wise logical NOT (reference logical.py logical_not)."""
    return _operations.__local_op(jnp.logical_not, t, out)


def logical_or(t1, t2) -> DNDarray:
    """Element-wise logical OR (reference logical.py logical_or)."""
    return _operations.__binary_op(jnp.logical_or, t1, t2)


def logical_xor(t1, t2) -> DNDarray:
    """Element-wise logical XOR (reference logical.py logical_xor)."""
    return _operations.__binary_op(jnp.logical_xor, t1, t2)


def signbit(x, out=None) -> DNDarray:
    """Element-wise signbit test (reference logical.py signbit)."""
    return _operations.__local_op(jnp.signbit, x, out)


DNDarray.all = all
DNDarray.any = any
