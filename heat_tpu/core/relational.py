"""
Relational (comparison) operations.

Parity with the reference's ``heat/core/relational.py`` (``__all__`` at
relational.py:19-32). ``equal``'s global AND (there an MPI scalar Allreduce) is a
sharded jnp.all here.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "eq",
    "equal",
    "ge",
    "greater",
    "greater_equal",
    "gt",
    "le",
    "less",
    "less_equal",
    "lt",
    "ne",
    "not_equal",
]


def eq(t1, t2) -> DNDarray:
    """Element-wise ``t1 == t2`` as uint8/bool array (reference relational.py eq)."""
    return _operations.__binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """``True`` if both operands have the same shape and all elements equal
    (reference relational.py equal — scalar AND Allreduce there)."""
    from . import factories

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        t1 = factories.array(t1)
    a = t1.larray if isinstance(t1, DNDarray) else jnp.asarray(t1)
    b = t2.larray if isinstance(t2, DNDarray) else jnp.asarray(t2)
    if tuple(jnp.shape(a)) != tuple(jnp.shape(b)):
        try:
            jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b))
        except ValueError:
            return False
    return bool(jnp.all(a == b))


def ge(t1, t2) -> DNDarray:
    """Element-wise ``t1 >= t2`` (reference relational.py ge)."""
    return _operations.__binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    """Element-wise ``t1 > t2`` (reference relational.py gt)."""
    return _operations.__binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    """Element-wise ``t1 <= t2`` (reference relational.py le)."""
    return _operations.__binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    """Element-wise ``t1 < t2`` (reference relational.py lt)."""
    return _operations.__binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    """Element-wise ``t1 != t2`` (reference relational.py ne)."""
    return _operations.__binary_op(jnp.not_equal, t1, t2)


not_equal = ne

DNDarray.__eq__ = lambda self, other: eq(self, other)
DNDarray.__ne__ = lambda self, other: ne(self, other)
DNDarray.__lt__ = lambda self, other: lt(self, other)
DNDarray.__le__ = lambda self, other: le(self, other)
DNDarray.__gt__ = lambda self, other: gt(self, other)
DNDarray.__ge__ = lambda self, other: ge(self, other)
DNDarray.__hash__ = None
