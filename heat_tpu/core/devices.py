"""
Device abstraction over JAX platforms.

Parity with the reference's ``heat/core/devices.py`` (Device class at devices.py:17,
module globals ``cpu``/``gpu`` at :97-118, ``use_device``/``get_device``/
``sanitize_device`` at :121-167) — redesigned for JAX: a :class:`Device` names a JAX
*platform* (``cpu``, ``tpu``, ``gpu``) instead of a torch device, and ``tpu`` is the
first-class accelerator. Which concrete ``jax.Device`` objects back a ``Device`` is
decided by the communication layer's mesh (see ``communication.py``); the ``Device``
object itself is placement intent, matching the reference's process-global default
device semantics.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """
    Implements a compute device backed by a JAX platform.

    Parameters
    ----------
    device_type : str
        JAX platform name: ``"cpu"``, ``"tpu"`` or ``"gpu"``.
    device_id : int
        The index of the first device of this platform used by this process.

    Reference parity: heat/core/devices.py:17-96 (there backed by a torch device
    string; here by a JAX platform).
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = device_type
        self.__device_id = device_id

    @property
    def device_type(self) -> str:
        """String representation of the platform."""
        return self.__device_type

    @property
    def device_id(self) -> int:
        """Index of the first JAX device of this platform used by this process."""
        return self.__device_id

    @property
    def jax_device(self) -> "jax.Device":
        """The concrete first :class:`jax.Device` of this platform."""
        return jax.devices(self.__device_type)[self.__device_id]

    @property
    def jax_devices(self):
        """All :class:`jax.Device` objects of this platform visible to this process."""
        return jax.devices(self.__device_type)

    def __repr__(self) -> str:
        return f"device({self.__str__()!r})"

    def __str__(self) -> str:
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        return NotImplemented

    def __hash__(self) -> int:
        return hash((self.device_type, self.device_id))


cpu: Device = Device("cpu")
"""The standard CPU device. Always available."""

# Accelerators are registered lazily on first use: probing jax.devices("tpu") at import
# time would initialise the backend before test harnesses can force the cpu platform
# (tests/conftest.py sets jax_platforms *after* import of this module is possible).
__registered: dict = {"cpu": cpu}
__default_device: Optional[Device] = None


def __probe_accelerators() -> None:
    for platform in ("tpu", "gpu"):
        if platform in __registered:
            continue
        try:
            if jax.devices(platform):
                dev = Device(platform)
                __registered[platform] = dev
                globals()[platform] = dev
                if platform not in __all__:
                    __all__.append(platform)
        except RuntimeError:
            pass


def get_device() -> Device:
    """
    Retrieves the currently globally set default :class:`Device`. Defaults to the best
    available platform: ``tpu`` > ``gpu`` > ``cpu``.

    Reference parity: heat/core/devices.py:121-135.
    """
    global __default_device
    if __default_device is None:
        __probe_accelerators()
        __default_device = __registered.get(
            "tpu", __registered.get("gpu", __registered["cpu"])
        )
    return __default_device


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """
    Sanitizes a device or device identifier, i.e. checks whether it is already an
    instance of :class:`Device` or a string with known device identifier and maps it to
    a proper :class:`Device`.

    Parameters
    ----------
    device : str or Device, optional
        The device to be sanitized. ``None`` resolves to the global default device.

    Raises
    ------
    ValueError
        If the given device id is not recognized.

    Reference parity: heat/core/devices.py:138-154.
    """
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        name = device.strip().lower()
        if ":" in name:
            name, _, idx = name.partition(":")
            idx = int(idx)
        else:
            idx = 0
        __probe_accelerators()
        if name in __registered:
            base = __registered[name]
            return base if idx == base.device_id else Device(name, idx)
        raise ValueError(f"Unknown device, must be one of {sorted(__registered)}, got '{device}'")
    raise ValueError(f"Unknown device, got '{device}'")


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """
    Sets the globally used default :class:`Device`.

    Reference parity: heat/core/devices.py:157-167.
    """
    global __default_device
    __default_device = sanitize_device(device)
