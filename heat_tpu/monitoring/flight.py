"""
Execution flight recorder: per-flush structured tracing with XLA cost
attribution, Chrome-trace/Perfetto export, and the one-shot ``statusz``
health surface.

PR 1's counters say *how many* flushes/compiles/recoveries happened; nine
subsystems later nobody can answer *which signature* burned the time, *why*
a given flush compiled instead of hitting L2, or *what fraction of peak* a
kernel achieved — the per-kernel attribution the XLA-fusion analysis
methodology relies on (PAPERS.md arXiv:2301.13062). The flight recorder is
that answer: a bounded in-memory ring of structured records, one per fused
flush (plus eager collective dispatches and elastic-supervisor transitions),
each carrying

* ``signature`` — the cross-process digest of the flush program (the L2
  disk-cache key when the program is stable; ``mem:<hash>`` for in-memory-
  only keys, ``unkeyed`` for unhashable shardings);
* ``reason`` — the flush-reason taxonomy label (why the chain broke);
* ``chain`` / ``kinds`` — recorded DAG depth and per-node-kind counts;
* ``cache`` — the outcome lane: ``l1`` (trace-LRU hit), ``l2`` (disk-served
  executable, zero XLA compile), ``compile`` (fresh build), ``eager``
  (poisoned signature or open breaker — straight to per-op replay);
* ``rung`` — which recovery-ladder rung produced the values (``fused`` /
  ``oom-debucket`` / ``donation-off`` / ``eager-replay``) plus the failure
  classes of the rungs that failed;
* ``audit`` — the shadow-replay outcome when the flush was sampled
  (``clean`` / ``mismatch`` / ``skip-donated``);
* ``pad_waste`` — bucket pad bytes appended across the leaves;
* ``donate`` — the donation mask;
* ``queue_s`` / ``wall_s`` / ``tid`` — scheduler queue time (when the flush
  was dispatched by ``serving/scheduler.py``), dispatch wall time, and the
  executing thread id.

Gating contract (the ``HEAT_TPU_FUSION`` cost class): the recorder is armed
by ``HEAT_TPU_FLIGHT=1`` and *off by default* — every hook guards with
:func:`flight_enabled`, so the disabled cost is **one env read per flush**
(per collective dispatch / per transition at the other hook sites), zero
records, and **zero ring allocation** (the ring list is created lazily on
the first record). The ring holds ``HEAT_TPU_FLIGHT_RECORDS`` records
(default 1024); overflow evicts the *oldest* record and counts it — a long
run's recorder is a bounded flight recorder, not a leak. Recording is a
pure observation: no hook influences a computed value, so every workload is
bit-identical with the gate on or off (the ``observability-smoke`` CI leg
pins exactly this).

**Cost cards.** On every real (AOT) compile the serving layer queries
``compiled.cost_analysis()`` into a *cost card* — ``flops``, ``bytes
accessed``, ``output bytes`` — persisted beside the L2 entry under the same
digest (``<cache_dir>/cost/<digest>.json``), so a disk-served zero-compile
process keeps full attribution: an L2 hit loads the card instead of
re-deriving it. When ``cost_analysis`` is unavailable (older jaxlib, an
in-memory-only program, a backend that refuses the query) the card is
``{"available": false}`` — attribution degrades to wall time, never to an
error. Running totals per signature feed ``report.telemetry()``'s modeled-
utilization gauge (flops/s against a small per-platform peak table) and the
top-K hottest-signatures table in ``report.render()``.

**Export.** :func:`export_chrome_trace` renders the monitoring ``events``
spans *and* the flight records as Chrome-trace/Perfetto JSON (an object
with a ``traceEvents`` array of ``ph: "X"`` complete events carrying
``ts``/``dur`` in microseconds and the real ``tid``), loadable in
``chrome://tracing`` or https://ui.perfetto.dev.

**CLI.** ``python -m heat_tpu.monitoring.flight dump|trace|statusz``:
``dump`` prints the ring as JSON lines, ``trace`` the Chrome-trace JSON,
``statusz`` the one-shot health payload (telemetry + breaker/elastic states
+ cache SLOs) the fleet layer's readiness endpoint will serve (ROADMAP
item 2). ``--selftest`` runs a small fused workload first so a fresh
process demonstrates a populated surface; ``--out FILE`` writes instead of
printing.

See ``doc/observability_notes.md`` for the record schema, the cost-card
contract, and the overhead numbers.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from . import events as _events
from .registry import REGISTRY, STATE as _MON

__all__ = [
    "flight_enabled",
    "capacity",
    "record",
    "record_flush",
    "record_collective",
    "record_elastic",
    "records",
    "evicted",
    "clear",
    "ring_allocated",
    "sched_context",
    "sched_queue_s",
    "cost_card_from",
    "note_cost_card",
    "load_cost_card",
    "cost_cards",
    "totals",
    "hottest",
    "peak_flops",
    "modeled_utilization",
    "export_chrome_trace",
    "statusz",
]

_DEFAULT_RECORDS = 1024

#: The ring. ``None`` until the first record lands (off-mode allocates
#: nothing); once allocated its capacity is fixed for the process (documented
#: — re-reading the env per record would let a mid-run change silently drop
#: history).
_RING: Optional[List[dict]] = None
_CAP = _DEFAULT_RECORDS
_NEXT = 0  # ring cursor once full
_SEQ = 0  # total records ever appended (evicted = _SEQ - len(ring))
_LOCK = threading.Lock()

#: Per-signature running totals: {"flushes", "wall_s", "queue_s"} plus the
#: cost-card dims when a card is known.
_TOTALS: Dict[str, Dict[str, float]] = {}

#: digest -> cost card (in-memory attribution; populated at compile time or
#: lazily from the on-disk card on an L2 hit).
_COST_CARDS: Dict[str, dict] = {}

#: Last-observed elastic-supervisor state (the statusz surface; None until a
#: supervisor transitions).
_LAST_ELASTIC: Optional[str] = None

#: Scheduler context handed across the async worker threads: the flush that
#: runs inside ``FlushScheduler`` reads its queue time from here.
_TLS = threading.local()


# ------------------------------------------------------------------ gates
def flight_enabled() -> bool:
    """Whether the flight recorder is armed (``HEAT_TPU_FLIGHT=1``; default
    off). Read per hook — one env read is the entire disabled cost."""
    val = os.environ.get("HEAT_TPU_FLIGHT", "")
    return val.strip().lower() not in ("", "0", "false", "off")


def capacity() -> int:
    """Configured ring capacity (``HEAT_TPU_FLIGHT_RECORDS``, default 1024,
    min 1). Fixed at first-record time for the life of the ring."""
    try:
        return max(1, int(os.environ.get("HEAT_TPU_FLIGHT_RECORDS", "")
                          or _DEFAULT_RECORDS))
    except ValueError:
        return _DEFAULT_RECORDS


def ring_allocated() -> bool:
    """Whether the ring list exists (off-mode inertness: it must not)."""
    return _RING is not None


# ------------------------------------------------------------------ recording
def record(kind: str, **fields) -> None:
    """Append one flight record (callers gate on :func:`flight_enabled`).

    Every record carries ``kind``, ``ts`` (epoch seconds at the *start* of
    the recorded interval when the caller passes one, else now), ``tid``
    (the executing thread), and the caller's fields. Overflow evicts the
    oldest record."""
    global _RING, _NEXT, _SEQ, _CAP
    rec = dict(fields)
    rec["kind"] = kind
    rec.setdefault("ts", time.time())
    rec.setdefault("tid", threading.get_ident())
    with _LOCK:
        if _RING is None:
            _RING = []
            _CAP = capacity()
        _SEQ += 1
        if len(_RING) < _CAP:
            _RING.append(rec)
        else:
            _RING[_NEXT] = rec
            _NEXT = (_NEXT + 1) % _CAP


def record_flush(signature: str, wall_s: float, **fields) -> None:
    """One fused-flush record (called from ``core/fusion.py``) — also folds
    the flush into the per-signature running totals."""
    queue_s = sched_queue_s()
    if queue_s is not None:
        fields["queue_s"] = round(queue_s, 6)
    record(
        "flush",
        signature=signature,
        wall_s=round(float(wall_s), 6),
        ts=time.time() - float(wall_s),
        **fields,
    )
    with _LOCK:
        t = _TOTALS.setdefault(
            signature, {"flushes": 0, "wall_s": 0.0, "queue_s": 0.0}
        )
        t["flushes"] += 1
        t["wall_s"] += float(wall_s)
        if queue_s is not None:
            t["queue_s"] += float(queue_s)


def record_collective(kind: str, wall_s: float, **fields) -> None:
    """One eager collective dispatch (called from ``core/communication.py``;
    collectives recorded in fused flushes are part of their flush record)."""
    record(
        "collective",
        collective=kind,
        wall_s=round(float(wall_s), 6),
        ts=time.time() - float(wall_s),
        **fields,
    )


def record_elastic(state: str, **fields) -> None:
    """One elastic-supervisor state transition / evidence event (called from
    ``robustness/elastic.py``); the latest state also backs the ``statusz``
    ``elastic`` field."""
    global _LAST_ELASTIC
    _LAST_ELASTIC = state
    record("elastic", state=state, **fields)


def records(kind: Optional[str] = None) -> List[dict]:
    """Chronological copy of the resident records, optionally filtered."""
    with _LOCK:
        if _RING is None:
            out = []
        elif len(_RING) < _CAP:
            out = list(_RING)
        else:
            out = _RING[_NEXT:] + _RING[:_NEXT]
    if kind is not None:
        out = [r for r in out if r.get("kind") == kind]
    return out


def evicted() -> int:
    """Records evicted from the ring so far (oldest-first overflow)."""
    with _LOCK:
        return _SEQ - (len(_RING) if _RING is not None else 0)


def clear() -> None:
    """Drop the ring, totals, cost cards, and elastic state (test
    isolation). The next record re-reads ``HEAT_TPU_FLIGHT_RECORDS``."""
    global _RING, _NEXT, _SEQ, _LAST_ELASTIC
    with _LOCK:
        _RING = None
        _NEXT = 0
        _SEQ = 0
        _TOTALS.clear()
        _COST_CARDS.clear()
        _LAST_ELASTIC = None


# ------------------------------------------------------------------ scheduler
class sched_context:
    """Thread-local scheduler context the async flush workers install around
    a dispatched flush, so the flush record (written deep inside
    ``materialize_for``, which knows nothing of the scheduler) can carry the
    queue time. Re-entrant is unnecessary — one worker runs one flush."""

    def __init__(self, queue_s: float):
        self.queue_s = float(queue_s)

    def __enter__(self):
        _TLS.queue_s = self.queue_s
        return self

    def __exit__(self, *exc) -> bool:
        _TLS.queue_s = None
        return False


def sched_queue_s() -> Optional[float]:
    """Queue time of the scheduler dispatch currently running on this
    thread, or None when the flush was not scheduler-dispatched."""
    return getattr(_TLS, "queue_s", None)


# ------------------------------------------------------------------ cost cards
def cost_card_from(compiled) -> dict:
    """Build a cost card from a ``Compiled``'s ``cost_analysis()``.

    Normalizes the version-variant key spellings (``bytes accessed output``
    vs ``bytes accessedout{}``) into ``{"available": True, "flops",
    "bytes_accessed", "output_bytes"}``; any failure — method missing,
    backend refusal, unexpected shape — degrades to
    ``{"available": False}`` (the documented fallback: attribution then
    rests on wall time alone)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return {"available": False}
        out_bytes = 0.0
        for k, v in ca.items():
            if k == "bytes accessed output" or k.startswith("bytes accessedout"):
                out_bytes += float(v)
        return {
            "available": True,
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
            "output_bytes": out_bytes,
        }
    except Exception:
        return {"available": False}


def note_cost_card(signature: str, card: dict) -> None:
    """Attach a cost card to a signature's running totals (compile-time, or
    lazily from disk on an L2 hit)."""
    with _LOCK:
        _COST_CARDS[signature] = dict(card)


def load_cost_card(cache_dir: str, signature: str) -> Optional[dict]:
    """Fetch the persisted cost card for a disk-served signature (memoized;
    best-effort — a missing/corrupt card returns None and attribution stays
    wall-time-only)."""
    with _LOCK:
        card = _COST_CARDS.get(signature)
    if card is not None:
        return card
    from ..serving import cache as _cache

    path = _cache.cost_card_path(cache_dir, signature)
    try:
        with open(path, "r") as f:
            card = json.load(f)
        if not isinstance(card, dict):
            return None
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return None
    note_cost_card(signature, card)
    return card


def cost_cards() -> Dict[str, dict]:
    """Copy of the in-memory signature -> cost-card map."""
    with _LOCK:
        return {k: dict(v) for k, v in _COST_CARDS.items()}


# ------------------------------------------------------------------ attribution
#: Modeled peak FLOP/s by accelerator generation (dense f32-class peak — the
#: MXU bf16 peak is 2x on v4/v5; CPU is a deliberately rough single-core
#: estimate). Matched by substring against the lowercased device_kind, first
#: hit wins; unmatched platforms report utilization None rather than a lie.
PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
    ("cpu", 1e11),
)


def peak_flops() -> Optional[float]:
    """Modeled peak FLOP/s of local device 0, or None when the platform is
    not in the table."""
    try:
        import jax

        dev = jax.devices()[0]
        kind = str(getattr(dev, "device_kind", dev.platform)).lower()
        plat = str(dev.platform).lower()
    except Exception:
        return None
    for sub, peak in PEAK_FLOPS:
        if sub in kind or sub == plat:
            return peak
    return None


def totals() -> Dict[str, dict]:
    """Per-signature running totals, cost-card dims folded in where known:
    ``{signature: {flushes, wall_s, queue_s, flops?, bytes_accessed?,
    output_bytes?, modeled_util?}}``. ``modeled_util`` is per-flush flops
    over mean flush wall time, as a fraction of the platform peak."""
    peak = peak_flops()
    out: Dict[str, dict] = {}
    with _LOCK:
        items = [(k, dict(v)) for k, v in _TOTALS.items()]
        cards = {k: v for k, v in _COST_CARDS.items()}
    for sig, t in items:
        card = cards.get(sig)
        if card and card.get("available"):
            t["flops"] = card["flops"] * t["flushes"]
            t["bytes_accessed"] = card["bytes_accessed"] * t["flushes"]
            t["output_bytes"] = card["output_bytes"] * t["flushes"]
            if peak and t["wall_s"] > 0:
                t["modeled_util"] = round(t["flops"] / t["wall_s"] / peak, 6)
        out[sig] = t
    return out


def modeled_utilization() -> Optional[float]:
    """Aggregate modeled utilization: total attributed flops over total
    flush wall time, as a fraction of the platform peak. None when no cost
    card is available or the platform peak is unknown — the honest answer,
    never a fabricated number."""
    peak = peak_flops()
    if not peak:
        return None
    t = totals()
    flops = sum(v.get("flops", 0.0) for v in t.values())
    wall = sum(v["wall_s"] for v in t.values() if v.get("flops"))
    if flops <= 0.0 or wall <= 0.0:
        return None
    return round(flops / wall / peak, 6)


def hottest(k: int = 5) -> List[dict]:
    """Top-``k`` signatures by total flush wall time (the render table)."""
    rows = [dict(v, signature=sig) for sig, v in totals().items()]
    rows.sort(key=lambda r: r["wall_s"], reverse=True)
    return rows[: max(0, int(k))]


# ------------------------------------------------------------------ export
def _flight_trace_events(pid: int) -> List[dict]:
    evs = []
    for r in records():
        kind = r.get("kind", "flight")
        if kind == "flush":
            name = "flush %s" % str(r.get("signature", ""))[:12]
        elif kind == "collective":
            name = "collective %s" % r.get("collective", "")
        else:
            name = "%s %s" % (kind, r.get("state", ""))
        args = {
            k: v
            for k, v in r.items()
            if k not in ("kind", "ts", "tid", "wall_s") and v is not None
        }
        evs.append(
            {
                "name": name,
                "cat": "flight." + kind,
                "ph": "X",
                "ts": r["ts"] * 1e6,
                "dur": float(r.get("wall_s", 0.0)) * 1e6,
                "pid": pid,
                "tid": r.get("tid", 0),
                "args": args,
            }
        )
    return evs


def export_chrome_trace() -> str:
    """The monitoring ``events`` spans/events plus the flight ring as
    Chrome-trace JSON (the Perfetto-loadable ``traceEvents`` schema).

    Every emitted event is a ``ph: "X"`` *complete* event — spans with their
    measured ``dur``, point events and flight records without a duration as
    ``dur: 0`` — carrying ``ts``/``dur`` in microseconds, the OS thread id,
    and the record's attributes under ``args``. Events are sorted by ``ts``
    (the viewer requires monotone timestamps per process).

    Multi-process merging (ISSUE 14 satellite): every event carries the
    real ``pid``, and the export leads with ``ph: "M"`` metadata events —
    one ``process_name`` plus a ``thread_name`` per distinct tid — so
    traces from several processes concatenated by
    :func:`heat_tpu.monitoring.aggregate.merge_chrome_traces` render as
    separate named tracks in Perfetto instead of interleaving anonymously
    (PR 13 emitted tids only)."""
    pid = os.getpid()
    evs: List[dict] = []
    for r in _events.records():
        args = dict(r.get("attrs") or {})
        if r.get("parent"):
            args["parent"] = r["parent"]
        evs.append(
            {
                "name": r["name"],
                "cat": "events." + r.get("type", "span"),
                "ph": "X",
                "ts": r["t_start"] * 1e6,
                "dur": float(r.get("wall_s", 0.0)) * 1e6,
                "pid": pid,
                "tid": r.get("tid", 0),
                "args": args,
            }
        )
    evs.extend(_flight_trace_events(pid))
    evs.sort(key=lambda e: e["ts"])
    meta: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"heat_tpu pid {pid}"},
        }
    ]
    main_tid = threading.main_thread().ident
    for tid in sorted({e["tid"] for e in evs}):
        label = "main" if tid == main_tid else f"thread {tid}"
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": label},
            }
        )
    return json.dumps(
        {"traceEvents": meta + evs, "displayTimeUnit": "ms"},
        sort_keys=True,
        default=str,
    )


# ------------------------------------------------------------------ statusz
def statusz() -> dict:
    """The one-shot health payload the fleet layer's readiness endpoint will
    serve (ROADMAP item 2 specifies it "fed by ``report.telemetry()``"):
    telemetry, per-site breaker states, the last elastic-supervisor state,
    the cache SLOs, and the flight summary. Pure read — flushes pending
    work (the telemetry barrier) but changes no state."""
    from ..robustness import breaker as _BRK
    from . import report as _report

    tel = _report.telemetry()
    return {
        "ok": True,
        "time": time.time(),
        "pid": os.getpid(),
        "telemetry": tel,
        "breakers": _BRK.states(),
        "elastic": _LAST_ELASTIC,
        "cache_slo": tel.get("serving_cache_slo"),
        "flight": {
            "enabled": flight_enabled(),
            "records": len(records()),
            "evicted": evicted(),
            "capacity": _CAP if _RING is not None else capacity(),
            "signatures": len(_TOTALS),
            "modeled_utilization": modeled_utilization(),
        },
    }


# ------------------------------------------------------------------ CLI
_USAGE = """usage: python -m heat_tpu.monitoring.flight <command> [--out FILE] [--selftest]

commands:
  dump     print the resident flight records as JSON lines
  trace    print the Chrome-trace/Perfetto JSON (events spans + flight ring)
  statusz  print the one-shot health payload (telemetry + breakers + elastic
           + cache SLOs + flight summary)

options:
  --out FILE   write to FILE instead of stdout
  --selftest   run a small fused workload first (HEAT_TPU_FLIGHT=1 +
               monitoring enabled), so a fresh process demonstrates a
               populated surface
"""


def _selftest() -> None:
    """A tiny chain+sink workload under the recorder, so the CLI has
    something to show in a fresh process."""
    os.environ.setdefault("HEAT_TPU_FLIGHT", "1")
    import numpy as np

    from . import registry as _registry

    _registry.enable()
    import heat_tpu as ht

    x = ht.array(np.linspace(0.0, 1.0, 4096, dtype=np.float32).reshape(64, 64))
    with _events.span("flight.selftest"):
        y = ((x * 2.0 + 1.0) / 3.0 - 0.25).sum()
        float(y.larray)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        try:
            out_path = argv[i + 1]
        except IndexError:
            sys.stderr.write(_USAGE)
            return 2
        del argv[i : i + 2]
    selftest = "--selftest" in argv
    if selftest:
        argv.remove("--selftest")
    if len(argv) != 1 or argv[0] not in ("dump", "trace", "statusz"):
        sys.stderr.write(_USAGE)
        return 2
    if selftest:
        _selftest()
    cmd = argv[0]
    if cmd == "dump":
        text = "\n".join(
            json.dumps(r, sort_keys=True, default=str) for r in records()
        )
    elif cmd == "trace":
        text = export_chrome_trace()
    else:
        text = json.dumps(statusz(), sort_keys=True, default=str, indent=2)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
    else:
        sys.stdout.write(text + "\n")
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess tests
    # `python -m` executes this file as `__main__` — a SECOND module object
    # with its own ring. Delegate to the canonical import so the CLI reads
    # the ring the runtime hooks actually record into.
    from heat_tpu.monitoring import flight as _canonical

    sys.exit(_canonical.main())
