"""
Distributed request tracing: wire-propagated context + per-stage latency
decomposition (ISSUE 16).

Every observability tier so far is *per-process*: the flight recorder
(PR 13) sees one runtime's flushes, the telemetry plane (PR 14) merges
per-process counters, the fleet ingress (PR 15) routes requests it cannot
attribute. This module is the connective tissue: ONE request's journey —
ingress routing, scheduler queueing, batch linger, compile, execute,
carve, respond — tagged with one ``trace_id`` across every process and
thread it touches, so a fleet p99 outlier decomposes into *which stage,
which worker* instead of a number.

**Context propagation.** The ingress mints a ``trace_id`` (plus a root
``span_id``) per sampled request and carries both in the JSON wire body
(``{"trace_id": ..., "parent_span_id": ...}`` riding beside the loadgen
wire fields — :func:`~heat_tpu.serving.loadgen.eval_request` ignores
unknown keys by construction). The worker re-installs the context as a
thread-local (:class:`trace_context` — the PR 15 ``tenant_context``
idiom); the scheduler captures it at ``schedule()`` and re-installs it on
the worker thread (the ``parent_span`` cross-thread precedent), so the
batching coalescer and the fusion flush ladder read
:func:`current` from plain thread-local state with zero plumbing through
call signatures.

**Stage taxonomy** (:data:`STAGES`): ``ingress_route`` (ingress-side
parse + worker pick + wire overhead), ``queue`` (scheduler
admission-to-dequeue), ``batch_linger`` (time parked in a continuous-
batching group), ``compile`` (XLA build, both AOT and first-dispatch
in-memory — the :func:`~heat_tpu.monitoring.instrument
.fusion_compile_latency` sites), ``execute`` (fused kernel dispatch,
ladder wall minus compile), ``carve`` (batched-row carve + canonical
placement), ``respond`` (everything left: digesting, serialization, wire
transfer — computed as the residual so the seven stages sum to the
ingress-measured wall time by construction). Each measured stage lands in
a per-stage registry histogram (``trace.stage.<stage>``, the 1-2-5
dispatch buckets) *and* accumulates on the request's :class:`Trace`, which
the worker echoes back as ``stages_ms`` in the wire response.

**Sampling + overhead contract.** ``HEAT_TPU_TRACE_SAMPLE`` unset (the
default) costs one env read at the ingress per request and a thread-local
read (no env read) at the inner hooks: no context is ever installed, no
stage is recorded, no histogram is touched, no span grows a trace id —
results are bit-for-bit the PR 15 behavior (differential-tested). Set
(``1``/``on``/``true``, or a rate ``0 < r < 1`` sampling that fraction of
requests), sampled requests pay a uuid mint, a dict of float
accumulators, and one histogram observe per stage.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, Optional

from . import instrument as _instr
from .registry import STATE as _MON

__all__ = [
    "STAGES",
    "Trace",
    "sample_rate",
    "should_sample",
    "mint_trace_id",
    "mint_span_id",
    "trace_context",
    "install",
    "current",
    "current_span_id",
    "stage",
]

#: The per-request latency decomposition, in journey order.
STAGES = (
    "ingress_route",
    "queue",
    "batch_linger",
    "compile",
    "execute",
    "carve",
    "respond",
)

_TLS = threading.local()


def sample_rate() -> float:
    """The sampling rate (``HEAT_TPU_TRACE_SAMPLE``): 0.0 = off (the
    default — one env read, nothing else), 1.0 = every request, a float in
    between = that fraction. Read per request so tests reconfigure live."""
    raw = os.environ.get("HEAT_TPU_TRACE_SAMPLE", "").strip().lower()
    if not raw or raw in ("0", "off", "false"):
        return 0.0
    if raw in ("1", "on", "true"):
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 0.0


def should_sample() -> bool:
    """Sampling decision for one request. Deterministic at the endpoints
    (0.0 → never, 1.0 → always); fractional rates hash a fresh uuid so no
    seeded RNG state is consumed (tracing must not perturb any seeded
    workload stream)."""
    r = sample_rate()
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    return (uuid.uuid4().int % 10_000) < r * 10_000


def mint_trace_id() -> str:
    """A fresh 32-hex trace id."""
    return uuid.uuid4().hex


def mint_span_id() -> str:
    """A fresh 16-hex span id."""
    return uuid.uuid4().hex[:16]


class Trace:
    """One sampled request's propagated context + stage accumulator.

    The same object travels ingress → worker HTTP thread → scheduler
    worker thread (→ batching leader thread), so stage accumulation locks.
    ``parent_span_id`` is the *innermost enclosing* span when the context
    was installed (the ingress root span on the worker side)."""

    __slots__ = ("trace_id", "parent_span_id", "stages", "_lock")

    def __init__(self, trace_id: Optional[str] = None, parent_span_id: Optional[str] = None):
        self.trace_id = trace_id or mint_trace_id()
        self.parent_span_id = parent_span_id
        self.stages: Dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, stage_name: str, seconds: float) -> None:
        with self._lock:
            self.stages[stage_name] = self.stages.get(stage_name, 0.0) + max(0.0, seconds)

    def stage_s(self, stage_name: str) -> float:
        with self._lock:
            return self.stages.get(stage_name, 0.0)

    def stages_ms(self) -> Dict[str, float]:
        """The accumulated decomposition in milliseconds (wire shape)."""
        with self._lock:
            return {k: round(v * 1e3, 3) for k, v in self.stages.items()}


class _NullContext:
    """Shared no-op context for the unsampled path (the ``events._NULL``
    idiom — zero allocation per request when tracing is off)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullContext()


class trace_context:
    """Install ``trace`` (and optionally the enclosing ``span_id``) as the
    calling thread's trace context; restores the previous context on exit
    (the ``tenancy.tenant_context`` save/restore discipline, so nested
    installs — scheduler re-install inside a worker HTTP handler — are
    safe)."""

    __slots__ = ("_trace", "_span_id", "_prev")

    def __init__(self, trace: Trace, span_id: Optional[str] = None):
        self._trace = trace
        self._span_id = span_id

    def __enter__(self) -> Trace:
        self._prev = (
            getattr(_TLS, "trace", None),
            getattr(_TLS, "span_id", None),
        )
        _TLS.trace = self._trace
        _TLS.span_id = self._span_id
        return self._trace

    def __exit__(self, *exc) -> bool:
        _TLS.trace, _TLS.span_id = self._prev
        return False


def install(trace: Optional[Trace], span_id: Optional[str] = None):
    """``trace_context(trace, span_id)``, or a shared no-op context when
    ``trace`` is None — call sites stay one ``with`` line on both the
    sampled and unsampled paths."""
    if trace is None:
        return _NULL
    return trace_context(trace, span_id)


def current() -> Optional[Trace]:
    """The calling thread's installed :class:`Trace`, or None (one
    thread-local read — the inner-hook fast path when tracing is off)."""
    return getattr(_TLS, "trace", None)


def current_span_id() -> Optional[str]:
    """The span id installed beside the current trace (the flush span the
    flight record should parent under), or None."""
    return getattr(_TLS, "span_id", None)


def stage(stage_name: str, seconds: float, trace: Optional[Trace] = None) -> None:
    """Record one measured stage: accumulate on the request's trace and
    observe the per-stage registry histogram. No trace (``trace`` None and
    none installed) = record nothing — sampled-out requests must leave
    zero records. ``trace`` overrides the thread-local lookup for call
    sites acting on behalf of another request (the batching leader
    recording its followers' stages)."""
    tr = trace if trace is not None else current()
    if tr is None:
        return
    tr.add(stage_name, seconds)
    if _MON.enabled:
        _instr.trace_stage(stage_name, seconds)
