"""
Reporting: human-readable tables and compact JSON telemetry.

* :func:`snapshot` — the full observability state as one plain dict: every
  metric, a per-name span summary, and freshly sampled device-memory gauges.
* :func:`render` — the same as an aligned text table for terminals.
* :func:`telemetry` — a compact single-level dict sized for embedding in a
  benchmark's one-line JSON output (``bench.py`` attaches it as the
  ``telemetry`` block).
"""

from __future__ import annotations

import json
from typing import Dict

from . import events as _events
from . import flight as _flight
from . import instrument as _instrument
from .registry import REGISTRY

__all__ = ["snapshot", "render", "telemetry", "export_json"]


def _span_summary() -> Dict[str, dict]:
    """Per-name aggregation of the recorded spans: count + total/max wall."""
    out: Dict[str, dict] = {}
    for rec in _events.records():
        if rec.get("type") != "span":
            continue
        s = out.setdefault(rec["name"], {"count": 0, "wall_s": 0.0, "max_wall_s": 0.0})
        s["count"] += 1
        s["wall_s"] += rec.get("wall_s", 0.0)
        s["max_wall_s"] = max(s["max_wall_s"], rec.get("wall_s", 0.0))
    return out


def snapshot(flush: bool = True) -> dict:
    """Full observability snapshot as a plain (JSON-serialisable) dict.

    Exporting is a materialization barrier for the deferred-execution engine:
    pending fused chains are flushed first, so the ``fusion.*`` (and
    ``jit.*``) counters account for every recorded op. ``flush=False``
    skips the barrier — the telemetry-spool writer and the Prometheus
    exporter (ISSUE 14) use it because a *published* snapshot must be a
    pure observation: flushing someone else's pending chain from a
    telemetry thread would alter the execution schedule it is reporting
    on."""
    if flush:
        try:
            from ..core import fusion as _fusion

            _fusion.flush_pending()
        except Exception:  # core not importable / partially initialized: export anyway
            pass
    _instrument.sample_memory()
    return {
        "metrics": REGISTRY.snapshot(),
        "spans": _span_summary(),
        "events_recorded": len(_events.records()),
        "events_dropped": _events.dropped(),
    }


def export_json(indent: int = None) -> str:
    """The :func:`snapshot` dict serialised to JSON."""
    return json.dumps(snapshot(), sort_keys=True, default=str, indent=indent)


def render() -> str:
    """Human-readable table of the current snapshot."""
    snap = snapshot()
    lines = ["== heat_tpu monitoring =="]
    counters = snap["metrics"]["counters"]
    if counters:
        lines.append("-- counters --")
        for name, val in counters.items():
            if isinstance(val, dict):
                lines.append(f"  {name:<28} {val['total']}")
                for lab, n in sorted(val["labels"].items()):
                    lines.append(f"    {lab:<26} {n}")
            else:
                lines.append(f"  {name:<28} {val}")
    gauges = snap["metrics"]["gauges"]
    if gauges:
        lines.append("-- gauges --")
        for name, val in gauges.items():
            lines.append(f"  {name:<28} {val}")
    hists = snap["metrics"]["histograms"]
    if hists:
        lines.append("-- histograms --")
        for name, h in hists.items():
            mean = h["sum"] / h["count"] if h["count"] else 0.0
            lines.append(f"  {name:<28} n={h['count']} mean={mean:.6g} sum={h['sum']:.6g}")
    if snap["spans"]:
        lines.append("-- spans --")
        for name, s in sorted(snap["spans"].items()):
            lines.append(
                f"  {name:<28} n={s['count']} total={s['wall_s']:.4f}s "
                f"max={s['max_wall_s']:.4f}s"
            )
    # top-K hottest signatures (ISSUE 13): which flush programs burned the
    # wall time, with cost-card attribution where a compile (or its
    # persisted card) provided one
    hot = _flight.hottest(5)
    if hot:
        lines.append("-- flight: hottest signatures --")
        for row in hot:
            extra = ""
            if row.get("flops"):
                extra = f" gflops={row['flops'] / 1e9:.3g}"
                if row.get("modeled_util") is not None:
                    extra += f" util={100.0 * row['modeled_util']:.2g}%"
            lines.append(
                f"  {row['signature'][:20]:<20} n={row['flushes']} "
                f"wall={row['wall_s']:.4f}s{extra}"
            )
    lines.append(
        f"-- events: {snap['events_recorded']} recorded, "
        f"{snap['events_dropped']} dropped --"
    )
    return "\n".join(lines)


def telemetry(flush: bool = True) -> dict:
    """Compact telemetry block for benchmark output lines: non-zero counters,
    span counts/totals, compile stats, and device memory (where reported).
    ``flush=False`` skips the materialization barrier (see
    :func:`snapshot`)."""
    snap = snapshot(flush=flush)
    counters = {}
    for name, val in snap["metrics"]["counters"].items():
        counters[name] = val["total"] if isinstance(val, dict) else val
    spans = {
        name: {"n": s["count"], "wall_s": round(s["wall_s"], 4)}
        for name, s in sorted(snap["spans"].items())
    }
    out = {
        "counters": {k: v for k, v in counters.items() if v},
        "spans": spans,
    }
    # why-did-the-chain-break breakdown (ISSUEs 4/5): the labelled
    # fusion.flush_reason / fusion.reduction_sinks / fusion.ops_deferred /
    # fusion.view_fallbacks counters keep their labels in the compact block —
    # a single total hides exactly the answer (which node kinds deferred, and
    # which structural ops had to give up)
    for name, key in (
        ("fusion.flush_reason", "fusion_flush_reasons"),
        ("fusion.reduction_sinks", "fusion_reduction_sinks"),
        ("fusion.ops_deferred", "fusion_ops_deferred"),
        ("fusion.view_fallbacks", "fusion_view_fallbacks"),
        ("fusion.collective_fallbacks", "fusion_collective_fallbacks"),
        # pallas kernel tier (ISSUE 10): which kernels took dispatches, which
        # sites refused them and why, and which reductions still had to take
        # the eager sink fallback the tier exists to shrink
        ("fusion.sink_fallbacks", "fusion_sink_fallbacks"),
        ("pallas.dispatch", "pallas_dispatch"),
        ("pallas.fallbacks", "pallas_fallbacks"),
        # serving-runtime breakdowns (ISSUE 8): disk-cache hit/miss/write
        # traffic, bucket hits + pad waste, corpus/warmup outcomes
        ("serving.disk_cache", "serving_disk_cache"),
        ("serving.bucket", "serving_bucket"),
        ("serving.corpus", "serving_corpus"),
        ("serving.warmup", "serving_warmup"),
        # production-hardening breakdowns (ISSUE 9): admission-control sheds,
        # watchdog deadline misses, janitor evictions/quarantines, breaker
        # state transitions, and chaos-schedule fires — the counters that
        # prove the degraded paths (not luck) carried an adverse-load run
        # elastic multi-host runtime breakdowns (ISSUE 11): supervisor state
        # transitions + peer-loss evidence, and collective dispatches that
        # overran the watchdog deadline in flight
        # NB (ISSUE 15 satellite): the labelled `comm_collective_timeout`
        # telemetry key — the documented ONE-release alias of the uniform
        # `comm_collective_timeout_latency` {count,p50_us,p99_us} block that
        # shipped in PR 14 — is retired; the per-kind breakdown stays
        # readable from the registry counter `comm.collective_timeout`
        ("robustness.elastic", "robustness_elastic"),
        ("serving.shed", "serving_shed"),
        ("serving.deadline_miss", "serving_deadline_miss"),
        ("serving.janitor", "serving_janitor"),
        # fleet serving tier (ISSUE 15): continuous-batching coalescing
        # wins, per-tenant fairness accounting, and the ingress's routing/
        # reroute/shed ledger
        ("serving.batch", "serving_batch"),
        ("serving.tenant", "serving_tenant"),
        ("serving.ingress", "serving_ingress"),
        ("robustness.breaker", "robustness_breakers"),
        ("robustness.chaos", "chaos_fires"),
        # silent-data-corruption defense (ISSUE 12): audit/mismatch/checksum
        # outcomes and the fired value-level faults they must account for —
        # the fires-vs-detections ledger of the integrity-smoke CI legs
        ("robustness.integrity", "robustness_integrity"),
        ("faults.corrupted", "faults_corrupted"),
        # graceful-degradation breakdowns (ISSUE 6): which failure classes the
        # flush ladder absorbed, which writer paths retried, what the
        # checkpoint subsystem did, and which fault sites actually fired
        ("fusion.flush_failures", "fusion_flush_failures"),
        ("io.retries", "io_retries"),
        ("checkpoint.ops", "checkpoint_ops"),
        ("preemption.requests", "preemption_requests"),
        ("faults.injected", "faults_injected"),
        # fleet telemetry plane (ISSUE 14): spool writer/merge outcomes and
        # the exporter's per-route request accounting — the counters the
        # exporter-smoke CI legs read back over HTTP
        ("telemetry_spool.snapshots", "telemetry_spool_snapshots"),
        ("telemetry_spool.merge", "telemetry_spool_merge"),
        ("exporter.requests", "exporter_requests"),
        # distributed request tracing (ISSUE 16): sampled traces that could
        # not complete their journey, by drop reason
        ("trace.dropped", "trace_dropped"),
    ):
        val = snap["metrics"]["counters"].get(name)
        if isinstance(val, dict) and val.get("labels"):
            out[key] = dict(val["labels"])
    # scalar recovery counters, exported under their telemetry names when set
    for name, key in (
        ("fusion.flush_recovered", "fusion_flush_recovered"),
        ("fusion.poisoned_signatures", "fusion_poisoned_signatures"),
        ("trace.sampled", "trace_sampled"),
    ):
        val = counters.get(name)
        if val:
            out[key] = val
    # trace-cache occupancy + hit/miss/eviction + poisoned count (ISSUE 8
    # satellite: cache_info() was not exported, so the serving SLO had no
    # denominator) and the cache-hit-rate SLO itself: L1 = in-process trace
    # LRU hits, L2 = persistent disk-cache hits, lookups = L1 hits + L1
    # misses (every flush that consulted the cache)
    try:
        from ..core import fusion as _fusion

        ci = _fusion.cache_info()
        out["fusion_trace_cache"] = dict(ci)
        disk = snap["metrics"]["counters"].get("serving.disk_cache")
        l2_hits = disk["labels"].get("hit", 0) if isinstance(disk, dict) else 0
        lookups = ci["hits"] + ci["misses"]
        out["serving_cache_slo"] = {
            "l1_hits": ci["hits"],
            "l2_hits": l2_hits,
            # registry.reset() clears the disk counter but not the fusion
            # stats, so clamp the true-cold-compile estimate at zero
            "misses": max(0, ci["misses"] - l2_hits),
            "evictions": ci["evictions"],
            "hit_rate": round((ci["hits"] + l2_hits) / lookups, 4) if lookups else None,
        }
    except Exception:  # core not importable / partially initialized
        pass
    qd = snap["metrics"]["gauges"].get("serving.queue_depth")
    if qd is not None:
        out["serving_queue_depth"] = qd
    # latency-histogram export uniformity (ISSUE 14 satellite): the three
    # latency surfaces — scheduler dispatch, L2-miss compile, and collective
    # watchdog overruns — all export through ONE shared {count, p50_us,
    # p99_us} shape via _latency_block (their per-PR shapes had started to
    # drift; the labelled comm_collective_timeout alias shipped one release
    # and is now retired, ISSUE 15 satellite)
    for hist_name, key in (
        ("serving.dispatch_latency", "serving_dispatch_latency"),
        # L2-miss compile latency (ISSUE 13 satellite): compile time used to
        # be invisible outside the aggregate jit.compile_seconds sum — the
        # histogram answers "what does a cold signature cost this process?"
        ("fusion.compile_latency", "fusion_compile_latency"),
        ("comm.collective_timeout_latency", "comm_collective_timeout_latency"),
    ):
        h = snap["metrics"]["histograms"].get(hist_name)
        if h and h["count"]:
            out[key] = _latency_block(h)
    # per-stage request decomposition (ISSUE 16): one _latency_block per
    # trace stage with samples — absent entirely when tracing never sampled,
    # so the off-mode telemetry block stays byte-identical
    stages = {}
    for stage in ("ingress_route", "queue", "batch_linger", "compile", "execute", "carve", "respond"):
        h = snap["metrics"]["histograms"].get(f"trace.stage.{stage}")
        if h and h["count"]:
            stages[stage] = _latency_block(h)
    if stages:
        out["trace_stage_latency"] = stages
    # execution flight recorder (ISSUE 13): per-signature attribution
    # totals, the modeled-utilization gauge (attributed flops/s over the
    # per-platform peak table), and the ring occupancy — present only when
    # the recorder has records, so the off-mode telemetry block is
    # byte-identical to pre-flight output
    if _flight.ring_allocated():
        out["flight"] = {
            "records": len(_flight.records()),
            "evicted": _flight.evicted(),
            "signatures": len(_flight.totals()),
            "modeled_utilization": _flight.modeled_utilization(),
        }
    # SLO surface (ISSUE 14): the current scale signal (queue depth ×
    # dispatch p99 µs) when the engine or exporter has computed one
    sig = snap["metrics"]["gauges"].get("slo.scale_signal")
    if sig:
        out["slo_scale_signal"] = sig
    mem = {k: v for k, v in snap["metrics"]["gauges"].items() if k.startswith("memory.")}
    if mem:
        out["memory"] = mem
    comp = snap["metrics"]["histograms"].get("jit.compile_seconds")
    if comp and comp["count"]:
        out["jit_compile_seconds_total"] = round(comp["sum"], 3)
    return out


def _latency_block(h: dict) -> dict:
    """The shared latency-histogram export shape: ``{count, p50_us,
    p99_us}`` (ISSUE 14 satellite — every latency surface exports through
    this one function so the shapes can never drift apart again;
    regression-pinned by ``test_latency_export_contract``)."""
    return {
        "count": h["count"],
        "p50_us": round(_hist_quantile(h, 0.50) * 1e6, 1),
        "p99_us": round(_hist_quantile(h, 0.99) * 1e6, 1),
    }


def _hist_quantile(h: dict, q: float) -> float:
    """Quantile estimate from a bucketed histogram snapshot: linear
    interpolation inside the bucket the target rank lands in (the overflow
    bucket reports its lower bound — an under-estimate, flagged by the bench
    anchors which compute exact sample percentiles instead)."""
    target = q * h["count"]
    bounds = h["buckets"]
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = h["counts"][i]
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return lo + frac * (b - lo)
        cum += c
        lo = b
    return float(bounds[-1]) if bounds else 0.0
