"""
Runtime observability: metrics registry, structured event spans, and the
instrumentation hooks wired into the framework's hot paths.

The reference framework has none of this (SURVEY §5: bare ``time.perf_counter``
benchmark loops); ``heat_tpu.monitoring`` is the telemetry layer a production
deployment operates on. Zero dependencies beyond the standard library (jax is
only touched lazily, for the compile listener and device-memory gauges), and
near-zero cost when disabled: instrumented hot paths pay a single truthiness
check per dispatch.

Quick start::

    import heat_tpu as ht
    from heat_tpu import monitoring

    with monitoring.capture():
        model = ht.cluster.KMeans(n_clusters=8).fit(x)
    print(monitoring.report.render())
    snap = monitoring.report.snapshot()   # plain dict: counters/gauges/spans

or set ``HEAT_TPU_MONITORING=1`` to collect for the whole process.

Modules:

* :mod:`~heat_tpu.monitoring.registry` — ``Counter``/``Gauge``/``Histogram``,
  the process-global ``REGISTRY``, and the ``enabled()``/``capture()`` gate;
* :mod:`~heat_tpu.monitoring.events` — ``span()``/``event()`` structured
  records with nesting, wall time, optional device-time marks
  (``jax.block_until_ready``), JSON-lines export;
* :mod:`~heat_tpu.monitoring.instrument` — the hook functions the hot paths
  call (op dispatches, dtype fallbacks, reshardings, collectives, jit
  compile-cache misses, device memory, IO volume, step throughput);
* :mod:`~heat_tpu.monitoring.report` — human-readable tables and the compact
  ``telemetry`` block ``bench.py`` embeds in its output line;
* :mod:`~heat_tpu.monitoring.flight` — the execution flight recorder
  (``HEAT_TPU_FLIGHT=1``): a bounded ring of per-flush records with XLA cost
  attribution, Chrome-trace/Perfetto export
  (:func:`~heat_tpu.monitoring.flight.export_chrome_trace`), and the
  ``python -m heat_tpu.monitoring.flight dump|trace|statusz`` CLI;
* :mod:`~heat_tpu.monitoring.exporter` — the served fleet plane
  (``HEAT_TPU_METRICS_PORT``): Prometheus text exposition plus
  ``/metrics`` ``/healthz`` ``/readyz`` ``/statusz`` ``/trace`` on a
  stdlib ``http.server`` background thread, and the standalone
  ``python -m heat_tpu.monitoring.exporter`` spool scraper;
* :mod:`~heat_tpu.monitoring.aggregate` — the cross-process telemetry
  spool (``HEAT_TPU_TELEMETRY_DIR``): atomic per-process snapshots on a
  flush-count cadence, merged into one fleet view with per-process labels;
* :mod:`~heat_tpu.monitoring.slo` — declarative objectives evaluated over
  windowed snapshots into multi-window burn rates and the
  ``scale_signal`` (queue depth × dispatch p99) the fleet ingress
  consumes.
"""

from __future__ import annotations

from . import registry
from . import events
from . import flight
from . import instrument
from . import report
from . import slo
from . import aggregate
from . import exporter

from .flight import export_chrome_trace, statusz
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    capture,
    disable,
    enable,
    enabled,
)
from .events import span, event, export_jsonl
from .report import render, telemetry

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "aggregate",
    "capture",
    "disable",
    "enable",
    "enabled",
    "event",
    "export_chrome_trace",
    "export_jsonl",
    "exporter",
    "flight",
    "render",
    "reset",
    "slo",
    "snapshot",
    "span",
    "statusz",
    "telemetry",
]

# env-var enablement must also run the one-time enable hooks (jax compile
# listener registration) that capture()/enable() would run
if registry.STATE.enabled:
    registry._run_enable_hooks()

# fleet telemetry plane (ISSUE 14): HEAT_TPU_METRICS_PORT arms the served
# /metrics /healthz /readyz /statusz /trace endpoints at import. Unset (the
# default) this is one env read — zero threads, zero sockets.
exporter.maybe_start()


def snapshot() -> dict:
    """Full observability snapshot (metrics + span summary + memory gauges);
    see :func:`heat_tpu.monitoring.report.snapshot`."""
    return report.snapshot()


def reset() -> None:
    """Clear all metrics, recorded events, flight records, the SLO window,
    and the spool cadence (test isolation / between benchmark phases)."""
    registry.reset()
    events.clear()
    flight.clear()
    slo.reset()
    aggregate.reset()
