"""
Prometheus exposition + served health/readiness endpoints.

The registry, ``report.telemetry()``, and the flight ring are all readable
only from inside the process; this module is the outward-facing door an
operator's scrape loop, load balancer, and autoscaler actually talk to:

* :func:`exposition` renders the **full registry** in the Prometheus text
  format (version 0.0.4): counters as ``heat_tpu_<name>_total`` (one
  series per label under the generic ``label`` key, plus an unattributed
  ``label=""`` residual so ``sum()`` over the series always equals the
  counter total), gauges as ``heat_tpu_<name>``, histograms as summaries —
  ``_count``/``_sum`` plus ``quantile="0.5"``/``"0.99"`` gauges
  interpolated by the existing ``report._hist_quantile``. Bracketed
  dynamic names become labels (``memory.bytes_in_use[0]`` →
  ``heat_tpu_memory_bytes_in_use{device="0"}``; ``slo.burn[obj:win]`` →
  ``heat_tpu_slo_burn{objective="obj",window="win"}``). Every metric in
  the static :data:`CATALOG` (the code-side twin of the doc ledger, sync
  enforced by test) is present even at zero, so a scrape of a fresh
  process already carries the complete schema. A point-in-time
  ``heat_tpu_scale_signal`` sample (queue depth × dispatch p99 µs — the
  ROADMAP item 2 autoscaling input, see :mod:`~heat_tpu.monitoring.slo`)
  rides along.

* :class:`MetricsServer` serves the plane over a stdlib ``http.server``
  background thread: ``/metrics`` (exposition), ``/healthz`` (process
  liveness — always 200 while the thread breathes), ``/readyz``
  (readiness: 200/503 from :func:`readiness` — open or forced-open
  circuit breakers, a non-healthy elastic-supervisor state, and the
  optional cache-SLO / burn-rate floors), ``/statusz`` (the PR 13
  one-shot deep payload), and ``/trace`` (Chrome-trace JSON for
  Perfetto). Gating contract: ``HEAT_TPU_METRICS_PORT`` **default off =
  zero threads, zero sockets** — :func:`maybe_start` (run once at
  ``heat_tpu.monitoring`` import) reads the env exactly once and returns
  without side effects when unset/0/invalid; a bind failure warns and
  degrades (a child process inheriting the env must never crash on the
  parent's port).

* **Standalone fleet scrape**: ``python -m heat_tpu.monitoring.exporter
  --spool DIR [--once | --port N]`` aggregates a telemetry spool
  directory (:mod:`~heat_tpu.monitoring.aggregate`) into one exposition
  with per-process ``pid``/``nonce`` labels, fleet skip accounting, and
  the fleet ``scale_signal`` — the sidecar an operator points Prometheus
  at when the workers themselves have no port armed.

Readiness inputs (the callers own the semantics):

==========================  ================================================
open / forced-open breaker  ``robustness.breaker.open_sites()`` — a site on
                            its degraded path is serving, but not a target
                            you want new traffic routed to
elastic state               ``robustness.elastic.last_state()`` — anything
                            but ``healthy`` (or None = never supervised)
                            means the process is degraded/draining/saving
cache SLO floor             ``HEAT_TPU_READY_MIN_HIT_RATE`` (optional): the
                            combined L1+L2 hit rate below the floor marks
                            the process cold — route warmup traffic, not
                            user traffic
burn-rate ceiling           ``HEAT_TPU_READY_MAX_BURN`` (optional): any
                            objective's *long*-window burn above the
                            ceiling flips readiness — the SLO engine as an
                            admission gate
==========================  ================================================

Every served request is counted ``exporter.requests{route}``. The
exposition itself is **barrier-free** (no ``flush_pending``) — scraping a
serving process must never alter its execution schedule; ``/statusz`` is
the one deliberate exception (it serves the PR 13 payload, which flushes
by contract — documented there).
"""

from __future__ import annotations

import json
import logging
import os
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from . import instrument as _instr
from . import registry as _registry
from .registry import STATE as _MON

__all__ = [
    "CATALOG",
    "MetricsServer",
    "exposition",
    "fleet_exposition",
    "metric_name",
    "validate_exposition",
    "readiness",
    "maybe_start",
    "start",
    "stop",
    "running",
    "port",
]

_LOG = logging.getLogger("heat_tpu.monitoring")

#: Every statically-named metric in ``heat_tpu/`` as ``(name, kind)`` — the
#: code-side twin of the doc ledger (``doc/observability_notes.md``), kept
#: in sync by ``tests/test_exporter.py::test_catalog_matches_source`` (the
#: same grep as the ledger drift guard). The exposition pre-renders every
#: row at zero so a fresh process's first scrape already carries the full
#: schema. Dynamic names (``memory.*[dev]``, ``io.bytes_*``,
#: ``slo.burn[...]``, per-step ``{name}.*`` templates) appear once their
#: first sample lands.
CATALOG: Tuple[Tuple[str, str], ...] = (
    ("checkpoint.ops", "counter"),
    ("comm.collective", "counter"),
    ("comm.collective_timeout", "counter"),
    ("comm.collective_timeout_latency", "histogram"),
    ("comm.placement", "counter"),
    ("comm.redistribution", "counter"),
    ("comm.resharding", "counter"),
    ("exporter.requests", "counter"),
    ("faults.corrupted", "counter"),
    ("faults.injected", "counter"),
    ("fusion.cache_hits", "counter"),
    ("fusion.chain_length", "histogram"),
    ("fusion.collective_fallbacks", "counter"),
    ("fusion.compile_latency", "histogram"),
    ("fusion.donated", "counter"),
    ("fusion.elided_writes", "counter"),
    ("fusion.flush_failures", "counter"),
    ("fusion.flush_reason", "counter"),
    ("fusion.flush_recovered", "counter"),
    ("fusion.flushes", "counter"),
    ("fusion.kernels_compiled", "counter"),
    ("fusion.ops_deferred", "counter"),
    ("fusion.poisoned_signatures", "counter"),
    ("fusion.reduction_sinks", "counter"),
    ("fusion.sink_fallbacks", "counter"),
    ("fusion.view_fallbacks", "counter"),
    ("io.calls", "counter"),
    ("io.retries", "counter"),
    ("io.seconds", "histogram"),
    ("jit.compile_seconds", "histogram"),
    ("jit.compiles", "counter"),
    ("nn.transformer", "counter"),
    ("ops.dispatch", "counter"),
    ("ops.dtype_fallback", "counter"),
    ("pallas.dispatch", "counter"),
    ("pallas.fallbacks", "counter"),
    ("preemption.requests", "counter"),
    ("robustness.breaker", "counter"),
    ("robustness.chaos", "counter"),
    ("robustness.elastic", "counter"),
    ("robustness.integrity", "counter"),
    ("serving.autoscale", "counter"),
    ("serving.batch", "counter"),
    ("serving.batch_occupancy", "gauge"),
    ("serving.bucket", "counter"),
    ("serving.corpus", "counter"),
    ("serving.deadline_miss", "counter"),
    ("serving.disk_cache", "counter"),
    ("serving.dispatch_latency", "histogram"),
    ("serving.generation", "counter"),
    ("serving.ingress", "counter"),
    ("serving.janitor", "counter"),
    ("serving.queue_depth", "gauge"),
    ("serving.shed", "counter"),
    ("serving.symbolic", "counter"),
    ("serving.tenant", "counter"),
    ("serving.warmup", "counter"),
    ("slo.evaluations", "counter"),
    ("slo.scale_signal", "gauge"),
    ("telemetry_spool.merge", "counter"),
    ("telemetry_spool.snapshots", "counter"),
    ("trace.dropped", "counter"),
    ("trace.sampled", "counter"),
    ("trace.stage.batch_linger", "histogram"),
    ("trace.stage.carve", "histogram"),
    ("trace.stage.compile", "histogram"),
    ("trace.stage.execute", "histogram"),
    ("trace.stage.ingress_route", "histogram"),
    ("trace.stage.queue", "histogram"),
    ("trace.stage.respond", "histogram"),
    ("tuning.lookup", "counter"),
)

_NAME_SAN = re.compile(r"[^a-zA-Z0-9_]")
_BRACKET = re.compile(r"^(.*?)\[(.*)\]$")


def metric_name(name: str, suffix: str = "") -> str:
    """``heat_tpu_``-prefixed Prometheus metric name for a registry name."""
    return "heat_tpu_" + _NAME_SAN.sub("_", name).strip("_") + suffix


def _esc(value) -> str:
    """Label-value escaping per the exposition format."""
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


def _num(value) -> str:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _gauge_series(name: str) -> Tuple[str, Dict[str, str]]:
    """Rendered metric name + labels for a gauge, folding the bracketed
    dynamic-name conventions into labels."""
    m = _BRACKET.match(name)
    if not m:
        return metric_name(name), {}
    base, arg = m.group(1), m.group(2)
    if base.startswith("memory."):
        return metric_name(base), {"device": arg}
    if base == "slo.burn" and ":" in arg:
        obj, win = arg.split(":", 1)
        return metric_name(base), {"objective": obj, "window": win}
    if base == "serving.tenant_depth":
        return metric_name(base), {"tenant": arg}
    return metric_name(base), {"key": arg}


def _scale_signal_from(snap: dict) -> float:
    """Point-in-time ``queue_depth × dispatch p99 (µs)`` straight from a
    registry snapshot (no flush, no telemetry build)."""
    from . import report as _report

    qd = float((snap.get("gauges") or {}).get("serving.queue_depth", 0) or 0)
    h = (snap.get("histograms") or {}).get("serving.dispatch_latency")
    if not qd or not h or not h.get("count"):
        return 0.0
    return round(qd * _report._hist_quantile(h, 0.99) * 1e6, 4)


def prometheus_text(
    sources: List[Tuple[Dict[str, str], dict]],
    include_catalog: bool = True,
    extra_samples: Optional[List[str]] = None,
) -> str:
    """Render one or more ``(extra_labels, registry_snapshot)`` sources as
    Prometheus text. One ``HELP``/``TYPE`` header per rendered metric name
    (required by the format even when several processes contribute
    series); ``extra_samples`` are appended verbatim (pre-rendered
    fleet-level lines)."""
    from . import report as _report

    counters: Dict[str, List[str]] = {}
    gauges: Dict[str, List[str]] = {}
    summaries: Dict[str, List[str]] = {}
    catalog = dict(CATALOG) if include_catalog else {}

    def counter_lines(name: str, val, extra: Dict[str, str]) -> None:
        mname = metric_name(name, "_total")
        rows = counters.setdefault(mname, [])
        total = val["total"] if isinstance(val, dict) else val
        labels = dict(val.get("labels") or {}) if isinstance(val, dict) else {}
        if labels:
            for lab in sorted(labels):
                rows.append(f"{mname}{_labels_str({'label': lab, **extra})} {_num(labels[lab])}")
            residual = total - sum(labels.values())
            if residual:
                rows.append(f"{mname}{_labels_str({'label': '', **extra})} {_num(residual)}")
        else:
            rows.append(f"{mname}{_labels_str(extra)} {_num(total)}")

    def gauge_lines(name: str, val, extra: Dict[str, str]) -> None:
        mname, labels = _gauge_series(name)
        gauges.setdefault(mname, []).append(
            f"{mname}{_labels_str({**labels, **extra})} {_num(val)}"
        )

    def hist_lines(name: str, h: dict, extra: Dict[str, str]) -> None:
        mname = metric_name(name)
        rows = summaries.setdefault(mname, [])
        count = int(h.get("count", 0) or 0)
        if count and h.get("buckets"):
            for q in (0.5, 0.99):
                rows.append(
                    f"{mname}{_labels_str({'quantile': str(q), **extra})} "
                    f"{_num(_report._hist_quantile(h, q))}"
                )
        rows.append(f"{mname}_sum{_labels_str(extra)} {_num(h.get('sum', 0.0))}")
        rows.append(f"{mname}_count{_labels_str(extra)} {_num(count)}")

    for extra, snap in sources:
        for name in sorted((snap.get("counters") or {})):
            counter_lines(name, snap["counters"][name], extra)
            catalog.pop(name, None)
        for name in sorted((snap.get("gauges") or {})):
            gauge_lines(name, snap["gauges"][name], extra)
            catalog.pop(name, None)
        for name in sorted((snap.get("histograms") or {})):
            hist_lines(name, snap["histograms"][name], extra)
            catalog.pop(name, None)
    for name, kind in catalog.items():  # absent catalog rows render at zero
        if kind == "counter":
            counter_lines(name, 0, {})
        elif kind == "gauge":
            gauge_lines(name, 0, {})
        else:
            hist_lines(name, {"count": 0, "sum": 0.0}, {})

    lines: List[str] = []
    for mname in sorted(counters):
        lines.append(f"# HELP {mname} heat_tpu counter")
        lines.append(f"# TYPE {mname} counter")
        lines.extend(counters[mname])
    for mname in sorted(gauges):
        lines.append(f"# HELP {mname} heat_tpu gauge")
        lines.append(f"# TYPE {mname} gauge")
        lines.extend(gauges[mname])
    for mname in sorted(summaries):
        lines.append(f"# HELP {mname} heat_tpu histogram (summary exposition)")
        lines.append(f"# TYPE {mname} summary")
        lines.extend(summaries[mname])
    lines.extend(extra_samples or [])
    return "\n".join(lines) + "\n"


def exposition() -> str:
    """This process's registry as Prometheus text (catalog rows included,
    SLO burn gauges refreshed, point-in-time ``heat_tpu_scale_signal``
    appended). Barrier-free by contract."""
    from . import slo as _slo

    try:
        _slo.engine().evaluate()  # refresh slo.burn[...] + slo.scale_signal
    except ValueError:
        pass  # malformed HEAT_TPU_SLO must not take /metrics down with it
    snap = _registry.snapshot()
    sig = _scale_signal_from(snap)
    if _MON.enabled:
        _instr.slo_scale_signal(sig)
        snap = _registry.snapshot()
    extra = [
        "# HELP heat_tpu_scale_signal queue depth x dispatch p99 (us)",
        "# TYPE heat_tpu_scale_signal gauge",
        f"heat_tpu_scale_signal {_num(sig)}",
    ]
    return prometheus_text([({}, snap)], include_catalog=True, extra_samples=extra)


def fleet_exposition(spool: str, max_age_s: Optional[float] = None) -> str:
    """A spool directory as one fleet exposition: per-process series
    labelled ``pid``/``nonce``, the spool skip accounting, process count,
    and the fleet ``scale_signal``."""
    from . import aggregate as _aggregate

    snaps, skips = _aggregate.read_snapshots(spool, max_age_s=max_age_s)
    sources = [
        ({"pid": str(s["pid"]), "nonce": str(s["nonce"])}, s.get("metrics") or {})
        for s in snaps
    ]
    view = _aggregate.fleet_view(spool, max_age_s=max_age_s)
    extra = [
        "# HELP heat_tpu_fleet_processes live processes in the telemetry spool",
        "# TYPE heat_tpu_fleet_processes gauge",
        f"heat_tpu_fleet_processes {_num(len(snaps))}",
        "# HELP heat_tpu_scale_signal fleet scale signal (sum queue depth x max p99 us)",
        "# TYPE heat_tpu_scale_signal gauge",
        f"heat_tpu_scale_signal {_num(view['scale_signal'])}",
        "# HELP heat_tpu_telemetry_spool_skips aggregator skip accounting",
        "# TYPE heat_tpu_telemetry_spool_skips gauge",
    ]
    for kind in sorted(skips):
        extra.append(
            f"heat_tpu_telemetry_spool_skips{_labels_str({'kind': kind})} {_num(skips[kind])}"
        )
    return prometheus_text(sources, include_catalog=False, extra_samples=extra)


# ------------------------------------------------------------- validation
_HELP_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* \S.*$")
_VALUE = r"[-+]?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?|\.?[0-9]+|NaN|Inf)"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{(?:%s)(?:,(?:%s))*\})? %s$" % (_LABEL, _LABEL, _VALUE)
)


def validate_exposition(text: str) -> List[str]:
    """Lines that do not parse as Prometheus text format (empty = clean).
    The CI smoke and the exporter tests assert this returns []."""
    bad = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            if not _HELP_RE.match(line):
                bad.append(line)
        elif not _SAMPLE_RE.match(line):
            bad.append(line)
    return bad


# ------------------------------------------------------------- readiness
def readiness() -> Tuple[bool, List[str]]:
    """``(ready, reasons)`` — the /readyz verdict. See the module docstring
    for the input table; an empty reason list is ready."""
    reasons: List[str] = []
    try:
        from ..robustness import breaker as _BRK

        for site in _BRK.open_sites():
            reasons.append(f"breaker:{site}")
    except Exception:
        pass
    try:
        from ..robustness import elastic as _EL

        st = _EL.last_state()
        if st is not None and st != "healthy":
            reasons.append(f"elastic:{st}")
    except Exception:
        pass
    min_hr = os.environ.get("HEAT_TPU_READY_MIN_HIT_RATE", "").strip()
    if min_hr:
        try:
            floor = float(min_hr)
        except ValueError:
            floor = None
        if floor is not None:
            from . import report as _report

            slo = _report.telemetry(flush=False).get("serving_cache_slo") or {}
            hr = slo.get("hit_rate")
            if hr is not None and hr < floor:
                reasons.append(f"cache-slo:hit_rate {hr} < {floor}")
    max_burn = os.environ.get("HEAT_TPU_READY_MAX_BURN", "").strip()
    if max_burn:
        try:
            ceiling = float(max_burn)
        except ValueError:
            ceiling = None
        if ceiling is not None:
            from . import slo as _slo

            try:
                ev = _slo.engine().evaluate()
            except ValueError:
                ev = {"objectives": {}}
            for name, row in ev["objectives"].items():
                burn = ((row.get("windows") or {}).get("long") or {}).get("burn", 0.0)
                if burn > ceiling:
                    reasons.append(f"slo-burn:{name} {burn} > {ceiling}")
    return (not reasons, reasons)


# ------------------------------------------------------------- HTTP plane
class _Handler(BaseHTTPRequestHandler):
    server_version = "heat-tpu-exporter"

    def log_message(self, *args):  # the operator scrapes every few seconds
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload, sort_keys=True, default=str), "application/json")

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        route = self.path.split("?", 1)[0].rstrip("/") or "/"
        spool = getattr(self.server, "heat_tpu_spool", None)
        max_age = getattr(self.server, "heat_tpu_max_age_s", None)
        try:
            if route == "/metrics":
                if _MON.enabled:
                    _instr.exporter_request("metrics")
                text = (
                    fleet_exposition(spool, max_age_s=max_age) if spool else exposition()
                )
                self._send(200, text, "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                if _MON.enabled:
                    _instr.exporter_request("healthz")
                self._send_json(200, {"ok": True, "pid": os.getpid(), "time": time.time()})
            elif route == "/readyz":
                if _MON.enabled:
                    _instr.exporter_request("readyz")
                if spool:
                    from . import aggregate as _aggregate

                    view = _aggregate.fleet_view(spool, max_age_s=max_age)
                    ready = bool(view["processes"])
                    payload = {
                        "ready": ready,
                        "reasons": [] if ready else ["no live spool snapshots"],
                        "scale_signal": view["scale_signal"],
                    }
                else:
                    ready, reasons = readiness()
                    payload = {
                        "ready": ready,
                        "reasons": reasons,
                        "scale_signal": _scale_signal_from(_registry.snapshot()),
                    }
                self._send_json(200 if payload["ready"] else 503, payload)
            elif route == "/statusz":
                if _MON.enabled:
                    _instr.exporter_request("statusz")
                if spool:
                    from . import aggregate as _aggregate

                    self._send_json(200, _aggregate.fleet_view(spool, max_age_s=max_age))
                else:
                    from . import flight as _flight

                    self._send_json(200, _flight.statusz())
            elif route == "/trace":
                if _MON.enabled:
                    _instr.exporter_request("trace")
                from . import flight as _flight

                self._send(200, _flight.export_chrome_trace(), "application/json")
            else:
                if _MON.enabled:
                    _instr.exporter_request("not-found")
                self._send_json(404, {"error": f"no route {route}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # a handler bug must not kill the server thread
            try:
                self._send_json(500, {"error": repr(e)[:400]})
            except Exception:
                pass


class MetricsServer:
    """The exporter's HTTP plane on a daemon background thread.

    ``port=0`` binds an ephemeral port (tests); ``spool`` switches the
    server into fleet mode (``/metrics``/``/readyz``/``/statusz`` answer
    from the aggregated spool instead of the local registry)."""

    def __init__(
        self,
        port: int = 0,
        host: Optional[str] = None,
        spool: Optional[str] = None,
        max_age_s: Optional[float] = None,
    ):
        if host is None:
            host = os.environ.get("HEAT_TPU_METRICS_HOST", "").strip() or "127.0.0.1"
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.heat_tpu_spool = spool
        self._httpd.heat_tpu_max_age_s = max_age_s
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.5},
            name="heat-tpu-exporter",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    def url(self, route: str = "/metrics") -> str:
        host = self.host if self.host not in ("0.0.0.0", "::") else "127.0.0.1"
        return f"http://{host}:{self.port}{route}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


_SERVER: Optional[MetricsServer] = None
_SERVER_LOCK = threading.Lock()


def start(
    port: int = 0,
    host: Optional[str] = None,
    spool: Optional[str] = None,
    max_age_s: Optional[float] = None,
) -> MetricsServer:
    """Start (or return) the process-default exporter server."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None:
            _SERVER = MetricsServer(port=port, host=host, spool=spool, max_age_s=max_age_s)
        return _SERVER


def stop() -> None:
    """Stop the process-default server (idempotent)."""
    global _SERVER
    with _SERVER_LOCK:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()


def running() -> bool:
    """Whether the process-default server is up (off-mode inertness: with
    ``HEAT_TPU_METRICS_PORT`` unset this must stay False — zero threads,
    zero sockets)."""
    return _SERVER is not None


def port() -> Optional[int]:
    """The bound port of the process-default server, or None."""
    return _SERVER.port if _SERVER is not None else None


def maybe_start() -> Optional[MetricsServer]:
    """Arm the exporter iff ``HEAT_TPU_METRICS_PORT`` is a positive int —
    run once at ``heat_tpu.monitoring`` import. Unset/0/invalid = no
    thread, no socket, no side effect; a bind failure (e.g. a child
    process inheriting the parent's port) warns and degrades, never
    raises."""
    raw = os.environ.get("HEAT_TPU_METRICS_PORT", "").strip()
    if not raw:
        return None
    try:
        p = int(raw)
    except ValueError:
        return None
    if p <= 0:
        return None
    try:
        return start(port=p)
    except OSError as e:
        _LOG.warning("metrics exporter could not bind port %s: %s", p, e)
        return None


# ------------------------------------------------------------------ CLI
_USAGE = """usage: python -m heat_tpu.monitoring.exporter [--spool DIR] [--max-age S]
                                              (--once [--out FILE] | --port N)

Standalone scrape surface for a telemetry spool directory (or, without
--spool, this process's own registry — mostly useful for --once debugging):

  --spool DIR   aggregate <DIR>/<pid>-<nonce>.json snapshots (fleet mode)
  --max-age S   treat snapshots older than S seconds as stale (skipped)
  --once        print the Prometheus exposition once and exit
  --out FILE    write --once output to FILE instead of stdout
  --port N      serve /metrics /healthz /readyz /statusz until interrupted
"""


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def take(flag):
        if flag in argv:
            i = argv.index(flag)
            try:
                val = argv[i + 1]
            except IndexError:
                return "", False
            del argv[i : i + 2]
            return val, True
        return None, True

    spool, ok1 = take("--spool")
    max_age_raw, ok2 = take("--max-age")
    out_path, ok3 = take("--out")
    port_raw, ok4 = take("--port")
    once = "--once" in argv
    if once:
        argv.remove("--once")
    if not (ok1 and ok2 and ok3 and ok4) or argv or (not once and port_raw is None):
        sys.stderr.write(_USAGE)
        return 2
    max_age = None
    if max_age_raw is not None:
        try:
            max_age = float(max_age_raw)
        except ValueError:
            sys.stderr.write(_USAGE)
            return 2
    if once:
        text = (
            fleet_exposition(spool, max_age_s=max_age) if spool else exposition()
        )
        if out_path:
            with open(out_path, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    try:
        p = int(port_raw)
    except ValueError:
        sys.stderr.write(_USAGE)
        return 2
    srv = MetricsServer(port=p, spool=spool, max_age_s=max_age)
    sys.stderr.write(f"serving on {srv.url('/')} (ctrl-c to stop)\n")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess tests
    # `python -m` executes this file as `__main__` — delegate to the
    # canonical import so CLI state (default server, counters) is shared
    # with the runtime hooks (the flight-CLI precedent).
    from heat_tpu.monitoring import exporter as _canonical

    sys.exit(_canonical.main())
