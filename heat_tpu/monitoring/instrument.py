"""
Instrumentation hooks the runtime's hot paths report through.

This module is the single funnel between the framework's hot paths and the
metrics registry / event recorder, so the instrumented call sites stay
one-liners (``if _MON.enabled: _instr.op_dispatch("binary")``) and the metric
naming stays consistent:

* ``ops.dispatch`` (labelled binary/reduce/local/cum) — every generic-template
  dispatch in ``core/_operations.py``;
* ``ops.dtype_fallback`` — results XLA returned in a dtype the heat promotion
  rules disagreed with (the cast-back fallback), plus the exact→float
  true-division promotion;
* ``comm.resharding`` (labelled ``old->new``) — genuine split changes that
  force XLA collectives (``DNDarray.resplit_``, recorded or eager);
* ``comm.redistribution`` — ``redistribute_`` placement re-asserts, which
  keep the split axis and therefore deliberately do NOT tick the resharding
  counter;
* ``comm.placement`` — canonical (padded, sharded) placements applied by
  ``MeshCommunication.placed``;
* ``comm.collective`` (labelled by kind) — explicit collective shim
  invocations (Allreduce/Allgather/…);
* ``jit.compiles`` + ``jit.compile_seconds`` — actual XLA backend compiles,
  i.e. jit cache *misses*, via a ``jax.monitoring`` duration listener
  (registered once, on first enablement; the listener itself is gated on
  ``STATE.enabled`` so a disabled process pays nothing);
* ``memory.bytes_in_use[...]`` gauges — sampled from
  ``device.memory_stats()`` where the backend provides it;
* ``io.bytes_read``/``io.bytes_written`` + ``io.seconds`` — parallel-IO
  load/save volume and latency;
* graceful-degradation counters (``heat_tpu.robustness`` + the fused-flush
  recovery ladder): ``fusion.flush_failures{compile,oom,runtime}`` /
  ``fusion.flush_recovered`` / ``fusion.poisoned_signatures``,
  ``io.retries{site}``, ``checkpoint.ops{write,restore,corrupt-skipped,
  orphan-cleaned,preemption-save}``, ``preemption.requests{signame}``, and
  ``faults.injected{site}`` for the deterministic injection framework;
* serving-runtime counters (``heat_tpu.serving``): ``serving.disk_cache``
  {hit,miss,write,incompatible,corrupt} for the persistent L2 compilation
  cache, ``serving.bucket`` {hit,pad_waste_bytes} for the aval-bucketing
  policy, ``serving.corpus`` {recorded,full,corrupt} and ``serving.warmup``
  {compiled,cached,skipped,error} for the shape corpus + AOT warmup driver,
  plus the ``serving.dispatch_latency`` histogram for the async flush
  scheduler;
* per-step spans for the algorithm/train loops (kmeans, lasso, data-parallel,
  DASO) via :func:`step_event` and ``events.span``.
"""

from __future__ import annotations

from typing import Optional

from . import events
from .registry import REGISTRY, STATE, _ON_ENABLE

__all__ = [
    "op_dispatch",
    "dtype_fallback",
    "resharding",
    "redistribution",
    "placement",
    "collective",
    "fusion_defer",
    "fusion_sink",
    "fusion_sink_fallback",
    "fusion_view_fallback",
    "pallas_dispatch",
    "pallas_fallback",
    "fusion_collective_fallback",
    "fusion_flush",
    "fusion_compile_latency",
    "fusion_flush_failure",
    "fusion_flush_recovered",
    "fusion_poisoned",
    "fusion_elided_write",
    "fusion_donated",
    "serving_disk_cache",
    "serving_bucket",
    "serving_symbolic",
    "serving_corpus",
    "serving_warmup",
    "serving_autoscale",
    "serving_dispatch",
    "serving_shed",
    "serving_deadline_miss",
    "serving_queue_depth",
    "serving_janitor",
    "serving_batch",
    "serving_generation",
    "serving_batch_occupancy",
    "serving_tenant",
    "serving_tenant_depth",
    "serving_ingress",
    "trace_stage",
    "trace_sampled",
    "trace_dropped",
    "telemetry_spool_snapshot",
    "tuning_event",
    "telemetry_spool_merge",
    "exporter_request",
    "slo_evaluation",
    "slo_scale_signal",
    "breaker_transition",
    "chaos_fire",
    "integrity",
    "fault_corrupted",
    "record_io",
    "io_retry",
    "checkpoint_op",
    "preemption_request",
    "fault_injected",
    "step_event",
    "sample_memory",
]

#: The jax.monitoring duration event emitted once per actual XLA compile —
#: each one is a jit compile-cache miss (hits re-use the executable and never
#: reach the backend).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_listener_registered = False


def _register_jax_listener() -> None:
    """Idempotently hook ``jax.monitoring`` compile-duration events. Run as an
    on-enable hook so a process that never enables monitoring never registers
    (and never imports jax from here)."""
    global _listener_registered
    if _listener_registered:
        return
    _listener_registered = True
    try:
        import jax.monitoring as _jm

        def _on_duration(name, duration, **kw):
            if STATE.enabled and name == _COMPILE_EVENT:
                REGISTRY.counter("jit.compiles").inc()
                REGISTRY.histogram("jit.compile_seconds").observe(duration)

        _jm.register_event_duration_secs_listener(_on_duration)
    except Exception:  # jax too old/new for the listener API: degrade silently
        pass


_ON_ENABLE.append(_register_jax_listener)


def op_dispatch(kind: str) -> None:
    """One generic-template dispatch (kind: binary/reduce/local/cum)."""
    REGISTRY.counter("ops.dispatch").inc(label=kind)


def dtype_fallback(kind: str) -> None:
    """One dtype-promotion fallback (result cast back to the heat-promoted
    type, or an exact→float division promotion)."""
    REGISTRY.counter("ops.dtype_fallback").inc(label=kind)


def resharding(old_split: Optional[int], new_split: Optional[int]) -> None:
    """One split change that forces XLA resharding collectives."""
    REGISTRY.counter("comm.resharding").inc(label=f"{old_split}->{new_split}")
    events.event("comm.resharding", old_split=old_split, new_split=new_split)


def redistribution() -> None:
    """One ``redistribute_`` call: a canonical-placement re-assert that keeps
    the split axis. Counted under its own name so ``comm.resharding`` answers
    "how many GENUINE split changes did this run pay?" without pollution
    (ISSUE 7 satellite: redistribution used to tick resharding{k->k})."""
    REGISTRY.counter("comm.redistribution").inc()


def placement() -> None:
    """One canonical (padded, sharded) placement applied by the mesh comm."""
    REGISTRY.counter("comm.placement").inc()


def collective(kind: str) -> None:
    """One explicit collective shim invocation (allreduce/allgather/…)."""
    REGISTRY.counter("comm.collective").inc(label=kind)


def collective_timeout(kind: str, seconds: Optional[float] = None) -> None:
    """One collective dispatch that exceeded the
    ``HEAT_TPU_COLLECTIVE_TIMEOUT_MS`` deadline in flight (counted + logged,
    never interrupted — the PR 9 dispatch-watchdog semantics applied to the
    distributed layer; evidence for the elastic supervisor). With
    ``seconds`` (the measured blocking dispatch time of the overrun — the
    watchdog already paid the ``block_until_ready``), the overrun also
    lands in the ``comm.collective_timeout_latency`` histogram so
    ``report.telemetry()`` can export the uniform ``{count, p50_us,
    p99_us}`` latency shape (ISSUE 14 satellite) beside the per-kind
    counter."""
    REGISTRY.counter("comm.collective_timeout").inc(label=kind)
    if seconds is not None:
        REGISTRY.histogram("comm.collective_timeout_latency", _DISPATCH_BOUNDS).observe(seconds)


def elastic_transition(state: str) -> None:
    """One elastic-supervisor state transition or detection event
    (``robustness.elastic{state}`` — healthy/degraded/draining/saving/saved/
    restart-pending, plus peer-lost/heartbeat-*/probe-* evidence labels; see
    :mod:`heat_tpu.robustness.elastic`)."""
    REGISTRY.counter("robustness.elastic").inc(label=state)


def fusion_defer(kind: str) -> None:
    """One op recorded in the deferred-execution DAG instead of dispatched
    eagerly (kind: binary/local/where/cast/view/gemm/collective)."""
    REGISTRY.counter("fusion.ops_deferred").inc(label=kind)


def fusion_sink(kind: str) -> None:
    """One reduction absorbed as a sink of a pending expression DAG instead
    of flushing it (kind: reduce/cum/moment/norm/vecdot)."""
    REGISTRY.counter("fusion.reduction_sinks").inc(label=kind)


def fusion_sink_fallback(kind: str) -> None:
    """One reduction over a pending chain that had to take the eager
    (flushing) fallback instead of sinking (kind: padded-operand — the eager
    path computes on the sliced logical view and no pallas ragged-reduce
    route applied; low-float — the sub-32-bit excess-precision carve-out)."""
    REGISTRY.counter("fusion.sink_fallbacks").inc(label=kind)


def pallas_dispatch(kernel: str) -> None:
    """One routing decision taken INTO a pallas-tier kernel
    (``heat_tpu/core/pallas/``; kernel: flash_ring / ragged_reduce /
    kmeans_step). Counts decisions, not launches — a cached fused program
    re-executes without re-recording its pallas sink."""
    REGISTRY.counter("pallas.dispatch").inc(label=kernel)


def pallas_fallback(kind: str) -> None:
    """One pallas-tier dispatch refused or degraded back to the XLA path
    (kind: hatch — ``HEAT_TPU_PALLAS[_<KERNEL>]=0``; platform — not a TPU
    backend and the interpreter not forced; dtype / shape — the kernel's
    availability predicate; execute — a kernel call point failed or was
    fault-injected at ``pallas.execute`` and the call site degraded)."""
    REGISTRY.counter("pallas.fallbacks").inc(label=kind)


def fusion_view_fallback(kind: str) -> None:
    """One structural op over a pending chain that had to take the eager
    (flushing) fallback because its pad motion has no in-trace form (kind:
    asymmetric-pad / stepped-split-slice)."""
    REGISTRY.counter("fusion.view_fallbacks").inc(label=kind)


def fusion_collective_fallback(kind: str) -> None:
    """One collective over a pending chain that had to take the eager
    (flushing) fallback because its layout motion has no in-trace form (kind:
    tracer-operand / abstract-eval / layout / padded-operand)."""
    REGISTRY.counter("fusion.collective_fallbacks").inc(label=kind)


def fusion_flush(chain_len: int, cache_hit: bool, compiled: bool, reason: str = "other") -> None:
    """One pending-expression flush through a fused jitted kernel: flush
    count, trace-cache hit/compile split, the chain-length histogram (how
    many ops each fused kernel absorbed), and the flush-reason breakdown
    (*why* the chain broke: reduction/cumulative/print/indexing/io/
    collective/out-alias/export/chain-bound/linalg/other)."""
    REGISTRY.counter("fusion.flushes").inc()
    REGISTRY.counter("fusion.flush_reason").inc(label=reason)
    if cache_hit:
        REGISTRY.counter("fusion.cache_hits").inc()
    if compiled:
        REGISTRY.counter("fusion.kernels_compiled").inc()
    REGISTRY.histogram("fusion.chain_length").observe(chain_len)


def fusion_compile_latency(seconds: float) -> None:
    """One L2-miss compile's latency (ISSUE 13 satellite — compile time used
    to be invisible outside the aggregate ``jit.compile_seconds`` sum). For
    the AOT/L2 path this times ``.lower().compile()`` (+ serialization)
    exactly; for the in-memory path it times the fused kernel's *first*
    dispatch (trace + compile + execute — compile-dominated). Same 1-2-5
    buckets as ``serving.dispatch_latency``, exported as ``p50_us``/
    ``p99_us`` by ``report.telemetry()``."""
    REGISTRY.histogram("fusion.compile_latency", _DISPATCH_BOUNDS).observe(seconds)


def fusion_flush_failure(kind: str) -> None:
    """One failed fused-flush attempt caught by the recovery ladder (kind:
    compile — the kernel build/compile raised on a trace-cache miss; oom — the
    failure carried a RESOURCE_EXHAUSTED/out-of-memory signature; runtime —
    a cached executable raised at dispatch). Each ladder rung that fails
    counts separately; ``fusion.flush_recovered`` tells whether the flush
    ultimately produced a result anyway."""
    REGISTRY.counter("fusion.flush_failures").inc(label=kind)


def fusion_flush_recovered() -> None:
    """One fused flush that failed at least one ladder rung but still returned
    correct values (donation-disabled retry or per-op eager replay)."""
    REGISTRY.counter("fusion.flush_recovered").inc()


def fusion_poisoned() -> None:
    """One graph signature poisoned in the trace LRU after eager-replay
    recovery: subsequent identical chains skip straight to eager (circuit
    breaker — no retry tax on a known-bad signature)."""
    REGISTRY.counter("fusion.poisoned_signatures").inc()


def fusion_elided_write() -> None:
    """One unflushed expression dropped by an overwrite (``out=`` aliasing):
    deferred work that never had to execute."""
    REGISTRY.counter("fusion.elided_writes").inc()


def fusion_donated(n: int, steady: bool = False) -> None:
    """Donated input buffers of one fused flush (``fusion.donated``, ISSUE
    19). Label ``buffers`` counts every leaf in the flush's donation mask;
    ``steady_state`` additionally counts the ones riding a trace-cache HIT —
    the persistent KV-cache re-donation proof (before this counter only the
    first, compiling, donation was observable on the ledger: every later
    steady-state step donated invisibly)."""
    c = REGISTRY.counter("fusion.donated")
    c.inc(int(n), label="buffers")
    if steady:
        c.inc(int(n), label="steady_state")


#: serving.dispatch_latency buckets: 1-2-5 log steps from 1 µs to 10 s —
#: dispatch latencies need finer resolution than the decade-wide defaults
#: for the p50/p99 interpolation in ``report.telemetry()`` to mean anything.
_DISPATCH_BOUNDS = tuple(m * 10.0**e for e in range(-6, 1) for m in (1, 2, 5)) + (10.0,)


def serving_disk_cache(kind: str) -> None:
    """One persistent-compilation-cache (L2) event (kind: hit — executable
    deserialized from disk, no compile; miss — no entry; write — freshly
    compiled executable serialized and stored; incompatible — program not
    cross-process keyable / fingerprint mismatch / serialization unsupported;
    corrupt — an entry existed but could not be read, recompiled)."""
    REGISTRY.counter("serving.disk_cache").inc(label=kind)


def serving_bucket(pad_waste_bytes: int) -> None:
    """One flush keyed through an aval-bucketed shape: label ``hit`` counts
    the flush, label ``pad_waste_bytes`` accumulates the pad bytes appended
    across its leaves (the cost side of the bounded-kernel-count tradeoff)."""
    c = REGISTRY.counter("serving.bucket")
    c.inc(label="hit")
    if pad_waste_bytes:
        c.inc(int(pad_waste_bytes), label="pad_waste_bytes")


def serving_symbolic(kind: str) -> None:
    """One symbolic-family AOT event (``serving.symbolic``, ISSUE 17; kind:
    served — a flush served through a shape-polymorphic family executable;
    export — a fresh family export (trace+lower, the one
    ``fusion.kernels_compiled`` tick the family ever pays); hit / miss — the
    L2 probe outcome for a family not in the in-process cache; write — a
    family artifact persisted; incompatible — foreign fingerprint/format,
    re-exported; corrupt / checksum — unreadable / footer-mismatched entry,
    quarantined and re-exported; fallback — an eligible flush that fell back
    to the exact path; breaker-open — the shared ``serving.cache_read``
    breaker refused the disk probe)."""
    REGISTRY.counter("serving.symbolic").inc(label=kind)


def serving_autoscale(kind: str) -> None:
    """One autoscaler decision applied by the ingress monitor thread
    (``serving.autoscale``, ISSUE 17; kind: grow — a worker added because the
    spooled scale signal held above the grow threshold; shrink — a worker
    retired below the shrink threshold; held — a decision suppressed by
    hysteresis, cooldown, or the ``--min-workers``/``--max-workers``
    bounds)."""
    REGISTRY.counter("serving.autoscale").inc(label=kind)


def serving_corpus(kind: str) -> None:
    """One shape-corpus event (kind: recorded / full — bound hit, entry not
    recorded / corrupt — unreadable entry skipped during iteration)."""
    REGISTRY.counter("serving.corpus").inc(label=kind)


def serving_warmup(kind: str) -> None:
    """One corpus entry processed by the AOT warmup driver (kind: compiled /
    cached — executable already in the warmed cache / skipped — foreign
    fingerprint or not rebuildable / error / predicted — an entry ranked by
    the predictive order (frequency × compile cost, ISSUE 17) / budget-cut —
    an entry left cold by the ``--budget-s`` / ``--top`` cutoff)."""
    REGISTRY.counter("serving.warmup").inc(label=kind)


def serving_dispatch(seconds: float) -> None:
    """One scheduled flush's submit-to-materialized latency."""
    REGISTRY.histogram("serving.dispatch_latency", _DISPATCH_BOUNDS).observe(seconds)


def serving_shed(kind: str) -> None:
    """One scheduled flush shed by admission control instead of dispatched
    (kind: queue-full — the bounded queue overflowed under the ``shed``
    policy; deadline — the flush was already past ``HEAT_TPU_FLUSH_DEADLINE_MS``
    at dequeue). Shedding drops only the *async* dispatch: the owner's
    ``flush()`` still materializes the correct value synchronously."""
    REGISTRY.counter("serving.shed").inc(label=kind)


def serving_deadline_miss(kind: str) -> None:
    """One flush the dispatch watchdog observed exceeding the configured
    deadline *while already in flight* (kind: in-flight) — work is never
    aborted mid-kernel, so these are counted and logged, not killed."""
    REGISTRY.counter("serving.deadline_miss").inc(label=kind)


def serving_queue_depth(depth: int) -> None:
    """Current number of scheduled-but-unfinished flushes (gauge)."""
    REGISTRY.gauge("serving.queue_depth").set(int(depth))


def serving_janitor(kind: str, n: int = 1) -> None:
    """One disk-cache janitor outcome (kind: runs / evicted / evicted_bytes /
    quarantined / orphans / cost-evicted — a cost card dropped beside its
    evicted L2 entry / cost-orphans — age-gated sweep of cards whose entry
    was quarantined or evicted elsewhere (ISSUE 15) — mixed units by design,
    the labels are the content)."""
    REGISTRY.counter("serving.janitor").inc(int(n), label=kind)


def serving_batch(kind: str, n: int = 1) -> None:
    """Continuous-batching accounting (``serving.batch``, ISSUE 15; kind:
    coalesced — requests that rode a batched dispatch; flushes_saved —
    dispatches avoided, Σ (group size − 1); pad_waste_bytes — bucket-pad
    bytes appended across batched leaves; fallback — members of a failed
    batched attempt recovered through individual flushes). Mixed units by
    design — the labels are the content."""
    REGISTRY.counter("serving.batch").inc(int(n), label=kind)


def serving_generation(kind: str, n: int = 1) -> None:
    """Iteration-level generation-scheduler accounting
    (``serving.generation``, ISSUE 19; kind: admitted — a sequence joined
    the running decode batch / retired-eos / retired-maxlen /
    retired-deadline — why it left / steps — decode iterations /
    tokens — generated tokens emitted across all slots / grown — the KV
    cache re-bucketed to the next capacity edge / shed-budget — admission
    deferred because the tenant's weighted slot budget was full). Mixed
    units by design — the labels are the content."""
    REGISTRY.counter("serving.generation").inc(int(n), label=kind)


def serving_batch_occupancy(pct: float) -> None:
    """Decode-batch slot occupancy of the last generation step (gauge,
    0–100: occupied slots / fixed batch slots — the utilization side of the
    recompile-free fixed-B contract, ISSUE 19)."""
    REGISTRY.gauge("serving.batch_occupancy").set(float(pct))


def transformer_event(kind: str, n: int = 1) -> None:
    """Fused-transformer step accounting (``nn.transformer``, ISSUE 20;
    kind: step-fused — a train step recorded as the one-executable chain /
    step-eager — the per-op reference ran instead (knob off or chain
    refused) / infer-fused / infer-eager — same split for the no-grad
    forward)."""
    REGISTRY.counter("nn.transformer").inc(int(n), label=kind)


def tuning_event(kind: str, n: int = 1) -> None:
    """One autotuning lookup outcome (``tuning.lookup``, ISSUE 18; kind:
    probed — a timed micro-probe or data miner ran; served — a measured
    value answered a lookup (memo, tune-dir, or fresh probe); fallback — the
    static default answered (tuning off never counts — the armed funnel
    could not measure); quarantined — a corrupt/truncated/foreign tune
    entry was moved to quarantine, never served)."""
    REGISTRY.counter("tuning.lookup").inc(int(n), label=kind)


def serving_tenant(tenant: str, event: str, n: int = 1) -> None:
    """Per-tenant fairness accounting (``serving.tenant{<tenant>:<event>}``,
    ISSUE 15; event: scheduled / shed-queue-full — the tenant's weighted
    admission share overflowed under the shed policy / shed-deadline /
    deadline-miss / l1-evict — an eviction inside the tenant's own L1
    partition, the proof evictions never cross tenants)."""
    REGISTRY.counter("serving.tenant").inc(int(n), label=f"{tenant}:{event}")


def serving_tenant_depth(tenant: str, depth: int) -> None:
    """One tenant's scheduled-but-unfinished flushes (gauge; the bracketed
    dynamic-name convention — the exporter folds it into a ``tenant``
    label)."""
    REGISTRY.gauge(f"serving.tenant_depth[{tenant}]").set(int(depth))


def serving_ingress(kind: str, n: int = 1) -> None:
    """One multi-process ingress event (``serving.ingress``, ISSUE 15; kind:
    routed — a request forwarded to a worker / rerouted — retried on another
    worker after a connection-level failure / shed — no live worker, 503 /
    worker-dead — a worker marked dead / respawned — a dead worker
    restarted)."""
    REGISTRY.counter("serving.ingress").inc(int(n), label=kind)


def trace_stage(stage: str, seconds: float) -> None:
    """One measured stage of a sampled request's latency decomposition
    (ISSUE 16 — ``trace.stage.<stage>``, one fixed-name histogram per stage
    in :data:`heat_tpu.monitoring.trace.STAGES`, the 1-2-5 dispatch buckets).
    Observed ONLY for sampled requests: an unsampled fleet keeps every one of
    these at count 0 (the off-inertness contract). The explicit if/elif chain
    keeps each metric name a grep-visible literal for the catalog and ledger
    drift guards."""
    if stage == "ingress_route":
        REGISTRY.histogram("trace.stage.ingress_route", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "queue":
        REGISTRY.histogram("trace.stage.queue", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "batch_linger":
        REGISTRY.histogram("trace.stage.batch_linger", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "compile":
        REGISTRY.histogram("trace.stage.compile", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "execute":
        REGISTRY.histogram("trace.stage.execute", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "carve":
        REGISTRY.histogram("trace.stage.carve", _DISPATCH_BOUNDS).observe(seconds)
    elif stage == "respond":
        REGISTRY.histogram("trace.stage.respond", _DISPATCH_BOUNDS).observe(seconds)


def trace_sampled() -> None:
    """One request the ingress sampled into a trace (``trace.sampled`` —
    denominator for /rpcz coverage; stays 0 with ``HEAT_TPU_TRACE_SAMPLE``
    unset)."""
    REGISTRY.counter("trace.sampled").inc()


def trace_dropped(reason: str) -> None:
    """One sampled trace that could not complete its journey
    (``trace.dropped{shed,deadline,worker-error}`` — the trace was minted but
    the request shed at the ingress, missed its queue deadline, or errored in
    the worker; its partial stage breakdown still reaches /rpcz)."""
    REGISTRY.counter("trace.dropped").inc(label=reason)


def telemetry_spool_snapshot(kind: str) -> None:
    """One cross-process telemetry-spool snapshot attempt
    (``telemetry_spool.snapshots{written,error}`` — the writer side of
    :mod:`heat_tpu.monitoring.aggregate`; errors are counted, never
    raised)."""
    REGISTRY.counter("telemetry_spool.snapshots").inc(label=kind)


def telemetry_spool_merge(kind: str, n: int = 1) -> None:
    """Aggregator-side spool accounting
    (``telemetry_spool.merge{merged,torn,stale,superseded}`` — the
    footer-discipline ledger: every skipped snapshot is counted, the merge
    never crashes on someone else's torn file)."""
    REGISTRY.counter("telemetry_spool.merge").inc(int(n), label=kind)


def exporter_request(route: str) -> None:
    """One request served by the metrics exporter's HTTP plane
    (``exporter.requests{metrics,healthz,readyz,statusz,trace,not-found}``)."""
    REGISTRY.counter("exporter.requests").inc(label=route)


def slo_evaluation() -> None:
    """One SLO-engine evaluation pass (``slo.evaluations``)."""
    REGISTRY.counter("slo.evaluations").inc()


def slo_scale_signal(value: float) -> None:
    """The current scale signal — queue depth × dispatch p99 µs
    (``slo.scale_signal`` gauge; the ROADMAP item 2 autoscaling input)."""
    REGISTRY.gauge("slo.scale_signal").set(float(value))


def breaker_transition(site: str, state: str) -> None:
    """One circuit-breaker state transition
    (``robustness.breaker{site:state}`` — closed / open / half-open)."""
    REGISTRY.counter("robustness.breaker").inc(label=f"{site}:{state}")


def chaos_fire(site: str) -> None:
    """One fault fired by a derandomized chaos schedule
    (:mod:`heat_tpu.robustness.chaos`) — counted on top of the generic
    ``faults.injected{site}`` (exception plans) or ``faults.corrupted{site}``
    (corrupt-mode value plans, ISSUE 12)."""
    REGISTRY.counter("robustness.chaos").inc(label=site)


def integrity(kind: str) -> None:
    """One value-integrity event (``robustness.integrity{kind}``, ISSUE 12):
    ``audit`` — a fused flush shadow-replayed; ``mismatch`` — the audit
    found the fused outputs diverging beyond the carve-out tolerances
    (signature poisoned, cache entries evicted); ``skip-donated`` — an
    audit-sampled flush skipped because donation consumed the retained
    leaves; ``collective-verified`` / ``collective-mismatch`` — a
    checksummed eager collective's lane verified / failed on receipt;
    ``checkpoint-crc`` — a checkpoint leaf checksum mismatch raised at
    load; ``scrub-scanned`` / ``scrub-corrupt`` / ``scrub-legacy`` — the
    offline scrubber's per-artifact outcomes."""
    REGISTRY.counter("robustness.integrity").inc(label=kind)


def fault_corrupted(site: str) -> None:
    """One value-level fault fired by an installed
    :class:`~heat_tpu.robustness.faultinject.ValueFaultPlan` — the site's
    return value was deterministically perturbed (the SDC adversary the
    integrity machinery must catch)."""
    REGISTRY.counter("faults.corrupted").inc(label=site)


def record_io(op: str, path: str, nbytes: int, seconds: float) -> None:
    """One IO load/save: volume counters + latency histogram + an event
    carrying path/bytes/duration."""
    direction = "io.bytes_read" if op.startswith("load") else "io.bytes_written"
    REGISTRY.counter(direction).inc(int(nbytes))
    REGISTRY.counter("io.calls").inc(label=op)
    REGISTRY.histogram("io.seconds").observe(seconds)
    events.record(f"io.{op}", seconds, path=path, bytes=int(nbytes))


def io_retry(site: str) -> None:
    """One transient-failure retry taken by the shared
    :class:`~heat_tpu.robustness.retry.RetryPolicy` (site: the wrapped writer/
    reader, e.g. save_hdf5 / load_csv / checkpoint.write)."""
    REGISTRY.counter("io.retries").inc(label=site)


def checkpoint_op(kind: str) -> None:
    """One checkpoint-subsystem operation (kind: write / restore /
    corrupt-skipped / orphan-cleaned / preemption-save)."""
    REGISTRY.counter("checkpoint.ops").inc(label=kind)


def preemption_request(signame: str) -> None:
    """One preemption signal intercepted by an active
    :class:`~heat_tpu.robustness.preemption.PreemptionGuard` (labelled by the
    signal name; the checkpoint itself lands at the next step boundary)."""
    REGISTRY.counter("preemption.requests").inc(label=signame)


def fault_injected(site: str) -> None:
    """One deterministic fault fired by an installed
    :mod:`~heat_tpu.robustness.faultinject` plan."""
    REGISTRY.counter("faults.injected").inc(label=site)


def step_event(name: str, seconds: float, rows: Optional[int] = None, **attrs) -> None:
    """One training/algorithm step measured by the caller: step counter,
    latency histogram, optional row throughput, and a span record."""
    REGISTRY.counter(f"{name}.steps").inc()
    REGISTRY.histogram(f"{name}.seconds").observe(seconds)
    if rows is not None:
        REGISTRY.counter(f"{name}.rows").inc(int(rows))
        if seconds > 0:
            attrs["rows_per_s"] = rows / seconds
        attrs["rows"] = rows
    events.record(name, seconds, **attrs)


def sample_memory() -> dict:
    """Sample ``device.memory_stats()`` into gauges for every local device
    that reports them (TPU/GPU backends; CPU returns nothing). Returns the
    sampled ``{gauge_name: bytes}`` dict."""
    out = {}
    try:
        import jax

        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if key in stats:
                    name = f"memory.{key}[{dev.id}]"
                    REGISTRY.gauge(name).set(int(stats[key]))
                    out[name] = int(stats[key])
    except Exception:
        pass
    return out
