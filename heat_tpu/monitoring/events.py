"""
Structured span/event recorder.

Complements the aggregate metrics of ``registry.py`` with per-occurrence
records: a :func:`span` context manager captures wall time (and, via
:meth:`_Span.mark`, optional device-time marks that ``jax.block_until_ready``
a value before stamping), nesting (parent/depth via a thread-local stack) and
arbitrary attributes. Records are held in memory and exportable as JSON lines
(:func:`export_jsonl`) — the shape every log shipper ingests — or as
Chrome-trace/Perfetto JSON via
:func:`heat_tpu.monitoring.flight.export_chrome_trace`.

Threading contract (ISSUE 13 satellite): the span stack is **per-thread**
(a ``threading.local``), so concurrent async flushes on
``FlushScheduler`` worker threads can never corrupt each other's nesting,
and every record is tagged with the OS thread id (``tid``) so export
consumers can reconstruct per-thread timelines. Cross-thread nesting is
explicit: a caller that hands work to another thread captures
:func:`current_span_name` on the submitting thread and passes it as
``span(..., parent=...)`` on the worker — the serving scheduler does
exactly this, so a flush's span nests under the request that scheduled it.

Disabled mode (``registry.STATE.enabled`` False) returns a shared no-op span
object and records nothing — callers need no branching of their own, though
per-dispatch hot paths still guard with ``if _MON.enabled:`` so the disabled
cost stays a single truthiness check.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from .registry import STATE

__all__ = [
    "span",
    "event",
    "record",
    "records",
    "current_span_name",
    "export_jsonl",
    "clear",
    "dropped",
]

#: Bound on resident records; overflow is counted, not stored (a long training
#: run with per-step spans must not grow memory without bound).
MAX_RECORDS = 65536

_RECORDS: List[dict] = []
_DROPPED = 0
_LOCK = threading.Lock()
_TLS = threading.local()


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _append(rec: dict) -> None:
    global _DROPPED
    with _LOCK:
        if len(_RECORDS) < MAX_RECORDS:
            _RECORDS.append(rec)
        else:
            _DROPPED += 1


class _NullSpan:
    """Shared do-nothing span handed out while collection is disabled."""

    __slots__ = ()
    wall_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def mark(self, name, block_on=None):
        return self


_NULL = _NullSpan()


class _Span:
    __slots__ = (
        "name", "attrs", "marks", "t0", "t0_wall", "depth", "parent",
        "wall_s", "_parent_override",
    )

    def __init__(self, name: str, attrs: Dict[str, Any], parent: Optional[str] = None):
        self.name = name
        self.attrs = attrs
        self.marks: List[dict] = []
        self.wall_s = 0.0
        self._parent_override = parent

    def __enter__(self):
        st = _stack()
        if self._parent_override is not None:
            # cross-thread nesting: the submitting thread's span, captured by
            # the caller via current_span_name() and handed across explicitly
            self.parent = self._parent_override
        else:
            self.parent = st[-1].name if st else None
        self.depth = len(st)
        st.append(self)
        self.t0_wall = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.wall_s = time.perf_counter() - self.t0
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        rec = {
            "type": "span",
            "name": self.name,
            "t_start": self.t0_wall,
            "wall_s": self.wall_s,
            "depth": self.depth,
            "parent": self.parent,
            "tid": threading.get_ident(),
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.marks:
            rec["marks"] = self.marks
        _append(rec)
        return False

    def set(self, **attrs) -> "_Span":
        """Attach attributes (e.g. a convergence delta) to the span record."""
        self.attrs.update(attrs)
        return self

    def mark(self, name: str, block_on=None) -> "_Span":
        """Stamp an intra-span mark; with ``block_on``, the stamp is a
        *device-time* mark — taken only after ``jax.block_until_ready`` drains
        the async dispatch queue up to that value."""
        if block_on is not None:
            import jax

            jax.block_until_ready(block_on)
        self.marks.append({"name": name, "at_s": time.perf_counter() - self.t0})
        return self


def span(name: str, parent: Optional[str] = None, **attrs):
    """Context manager recording a named span with wall time and attributes.

    ``parent`` overrides the nesting parent (normally the enclosing span on
    *this* thread) — the cross-thread propagation hook: capture
    :func:`current_span_name` on the submitting thread, pass it here on the
    worker, and the worker's span nests under the submitter's.

    >>> with span("kmeans.step", iteration=3) as sp:
    ...     shift = step(...)
    ...     sp.mark("device_done", block_on=shift).set(shift=float(shift))
    """
    if not STATE.enabled:
        return _NULL
    return _Span(name, attrs, parent=parent)


def current_span_name() -> Optional[str]:
    """Name of the innermost open span on this thread (None outside any
    span) — what a scheduler captures before handing work to a worker."""
    st = _stack()
    return st[-1].name if st else None


def event(name: str, **attrs) -> None:
    """Record a point-in-time event (no duration)."""
    if not STATE.enabled:
        return
    st = _stack()
    rec = {
        "type": "event",
        "name": name,
        "t_start": time.time(),
        "depth": len(st),
        "parent": st[-1].name if st else None,
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = attrs
    _append(rec)


def record(name: str, wall_s: float, **attrs) -> None:
    """Record a pre-timed span (for callers that measured the duration
    themselves, e.g. around a jitted train step)."""
    if not STATE.enabled:
        return
    st = _stack()
    rec = {
        "type": "span",
        "name": name,
        "t_start": time.time() - wall_s,
        "wall_s": wall_s,
        "depth": len(st),
        "parent": st[-1].name if st else None,
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = attrs
    _append(rec)


def records(name: Optional[str] = None) -> List[dict]:
    """Copy of the recorded spans/events, optionally filtered by name."""
    with _LOCK:
        recs = list(_RECORDS)
    if name is not None:
        recs = [r for r in recs if r["name"] == name]
    return recs


def dropped() -> int:
    """Number of records discarded after :data:`MAX_RECORDS` was reached."""
    return _DROPPED


def export_jsonl() -> str:
    """All records as JSON lines (one record per line)."""
    return "\n".join(json.dumps(r, sort_keys=True, default=str) for r in records())


def clear() -> None:
    """Drop all recorded spans/events (test isolation)."""
    global _DROPPED
    with _LOCK:
        _RECORDS.clear()
        _DROPPED = 0
