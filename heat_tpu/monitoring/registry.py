"""
Process-local metrics registry: counters, gauges and log-scale histograms.

The reference framework has no observability at all (SURVEY §5: its benchmarks
are bare ``time.perf_counter`` loops); this registry is the accumulation core of
the ``heat_tpu.monitoring`` subsystem. Zero dependencies, and near-zero cost
when disabled: every instrumented hot path guards with a single truthiness
check on :data:`STATE` (``if _MON.enabled:``) — no dict lookup, no string
formatting, no function call happens on the disabled path.

Enablement
----------
* env var ``HEAT_TPU_MONITORING`` (any value except ``""``/``0``/``false``/
  ``off``) turns collection on at import;
* :func:`capture` turns it on for a ``with`` block (re-entrant, restores the
  previous state);
* :func:`enable`/:func:`disable` flip it programmatically.

``snapshot()`` returns a plain (JSON-serialisable) dict; nothing here ever
touches a device.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "STATE",
    "capture",
    "disable",
    "enable",
    "enabled",
    "merge_snapshots",
    "reset",
    "snapshot",
]


class _State:
    """Mutable enablement flag read by every instrumented hot path.

    A dedicated slotted object (rather than a module global) so hot paths can
    bind it once at import (``from ...registry import STATE as _MON``) and pay
    exactly one attribute load + truthiness test per dispatch when disabled.
    """

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool = False):
        self.enabled = enabled


def _env_enabled() -> bool:
    val = os.environ.get("HEAT_TPU_MONITORING", "")
    return val.strip().lower() not in ("", "0", "false", "off")


STATE = _State(_env_enabled())

#: Hooks run exactly once, on first enablement (e.g. registering the
#: ``jax.monitoring`` compile listener — see ``instrument.py``). Appending is
#: done at import time by the instrument module; running is idempotent.
_ON_ENABLE = []
_hooks_ran = False
_lock = threading.Lock()


def _run_enable_hooks() -> None:
    global _hooks_ran
    with _lock:
        if _hooks_ran:
            return
        _hooks_ran = True
        hooks = list(_ON_ENABLE)
    for hook in hooks:
        hook()


def enable() -> None:
    """Turn metric/event collection on (process-wide)."""
    _run_enable_hooks()
    STATE.enabled = True


def disable() -> None:
    """Turn metric/event collection off. Accumulated data is retained."""
    STATE.enabled = False


def enabled() -> bool:
    """Whether collection is currently on (env var or :func:`capture`)."""
    return STATE.enabled


@contextlib.contextmanager
def capture():
    """Enable collection for the duration of the ``with`` block.

    Re-entrant; restores the previous enablement on exit (so nesting inside an
    env-var-enabled process is a no-op rather than a disable).
    """
    prev = STATE.enabled
    enable()
    try:
        yield REGISTRY
    finally:
        STATE.enabled = prev


class Counter:
    """Monotonically increasing count, optionally broken down by label.

    Increments are plain ``+=`` under the GIL — the registry trades perfect
    cross-thread atomicity for zero locking on the hot path (a lost increment
    under free-threading race is acceptable for telemetry).
    """

    __slots__ = ("name", "value", "labels")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.labels: Dict[str, int] = {}

    def inc(self, n: int = 1, label: Optional[str] = None) -> None:
        """Add ``n`` (and attribute it to ``label`` when given)."""
        self.value += n
        if label is not None:
            self.labels[label] = self.labels.get(label, 0) + n

    def get(self, label: Optional[str] = None) -> int:
        return self.value if label is None else self.labels.get(label, 0)

    def _snapshot(self):
        if self.labels:
            return {"total": self.value, "labels": dict(self.labels)}
        return self.value


class Gauge:
    """Last-written value (e.g. live HBM bytes)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value) -> None:
        self.value = value

    def get(self):
        return self.value

    def _snapshot(self):
        return self.value


#: Default histogram buckets: log-scale decades 1e-7..1e2 — sized for
#: durations in seconds, from microsecond kernels to minute-long fits.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(10.0**e for e in range(-7, 3))


class Histogram:
    """Fixed log-scale-bucket histogram (upper-bound buckets + overflow).

    ``counts[i]`` counts observations ``<= bounds[i]``; the final slot counts
    overflow. ``sum``/``count`` allow mean recovery without the buckets.
    """

    __slots__ = ("name", "bounds", "counts", "count", "sum")

    def __init__(self, name: str, bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.bounds = tuple(sorted(bounds)) if bounds is not None else DEFAULT_BUCKETS
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        for i, b in enumerate(self.bounds):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def _snapshot(self):
        return {
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.sum,
        }


class MetricsRegistry:
    """Name-keyed collection of counters/gauges/histograms.

    Metric creation takes a lock (rare); increments on already-created metrics
    are lock-free. Instrumented code should fetch the metric once per event:
    ``REGISTRY.counter("ops.dispatch").inc()``.
    """

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str, bounds: Optional[Sequence[float]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, bounds))
        return h

    def snapshot(self) -> dict:
        """Plain-dict view of every metric (JSON-serialisable)."""
        return {
            "counters": {k: v._snapshot() for k, v in sorted(self._counters.items())},
            "gauges": {k: v._snapshot() for k, v in sorted(self._gauges.items())},
            "histograms": {k: v._snapshot() for k, v in sorted(self._histograms.items())},
        }

    def reset(self) -> None:
        """Drop every metric (test isolation / between benchmark phases)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: The process-global registry all instrumentation records into.
REGISTRY = MetricsRegistry()


def snapshot() -> dict:
    """Module-level alias of ``REGISTRY.snapshot()``."""
    return REGISTRY.snapshot()


def reset() -> None:
    """Module-level alias of ``REGISTRY.reset()``."""
    REGISTRY.reset()


def merge_snapshots(snaps) -> dict:
    """Merge several :func:`snapshot`-shaped dicts into one (the fleet
    aggregation primitive — each input is one process's registry state).

    Counters sum, labels included. Gauges sum — the fleet semantics: queue
    depths, memory bytes and scale signals are additive across processes
    (a last-writer-wins merge would silently drop N-1 processes). Histograms
    sum ``count``/``sum`` always and the bucket counts element-wise when
    every contributor agrees on the bounds; disagreeing bounds drop the
    buckets (the count/sum totals stay exact) — quantiles over a fleet of
    mixed bucket layouts would be a fabricated number."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for name, val in (snap.get("counters") or {}).items():
            total = val["total"] if isinstance(val, dict) else val
            labels = dict(val.get("labels") or {}) if isinstance(val, dict) else {}
            cur = out["counters"].get(name)
            if cur is None:
                out["counters"][name] = {"total": total, "labels": labels} if labels else total
            else:
                cur_total = cur["total"] if isinstance(cur, dict) else cur
                cur_labels = dict(cur.get("labels") or {}) if isinstance(cur, dict) else {}
                for k, v in labels.items():
                    cur_labels[k] = cur_labels.get(k, 0) + v
                merged_total = cur_total + total
                out["counters"][name] = (
                    {"total": merged_total, "labels": cur_labels} if cur_labels else merged_total
                )
        for name, val in (snap.get("gauges") or {}).items():
            try:
                out["gauges"][name] = out["gauges"].get(name, 0.0) + float(val)
            except (TypeError, ValueError):
                out["gauges"].setdefault(name, val)
        for name, h in (snap.get("histograms") or {}).items():
            if not isinstance(h, dict):
                continue
            cur = out["histograms"].get(name)
            if cur is None:
                out["histograms"][name] = {
                    "buckets": list(h.get("buckets") or []),
                    "counts": list(h.get("counts") or []),
                    "count": h.get("count", 0),
                    "sum": h.get("sum", 0.0),
                }
            else:
                cur["count"] += h.get("count", 0)
                cur["sum"] += h.get("sum", 0.0)
                if cur.get("buckets") and cur["buckets"] == list(h.get("buckets") or []):
                    cur["counts"] = [
                        a + b for a, b in zip(cur["counts"], h.get("counts") or [])
                    ]
                else:
                    cur["buckets"], cur["counts"] = [], []
    return out
