"""
Declarative SLO engine: windowed objectives, multi-window burn rates, and
the ``scale_signal`` the fleet ingress consumes.

ROADMAP item 2 specifies the fleet layer's autoscaling input as "an
SLO-driven scale signal (queue depth × dispatch p99)". Raw counters cannot
answer "are we inside the objective *right now*" — a counter only ever grows
— so this module evaluates declarative objectives over a *window of
telemetry snapshots* (the cross-process spool's cadence is the window
clock; see :mod:`~heat_tpu.monitoring.aggregate`) into **burn rates**: the
fraction of recent snapshots violating the objective, divided by the
objective's error budget. A burn rate of 1.0 means the budget is being
consumed exactly as provisioned; >1.0 means faster (alert); ≈0 means
healthy. Two windows — a short one that reacts and a long one that
confirms — follow the standard multi-window burn-rate alerting shape, but
measured in **snapshots, not wall time**: like every robustness knob in
this repo (breaker cool-downs, fault schedules), the engine is
call-count-deterministic so a replayed run evaluates identically.

Default objectives (overridable via ``HEAT_TPU_SLO`` — a JSON list, or
``@/path/to/file.json``):

==================  ========================================================
``dispatch_p99_us`` scheduler submit-to-materialized p99 (µs, from the
                    ``serving.dispatch_latency`` histogram) ``<=`` target
``cache_hit_rate``  combined L1+L2 compilation-cache hit rate ``>=`` target
``shed_ratio``      admission-control sheds over flushes ``<=`` target
``queue_depth``     scheduled-but-unfinished flushes ``<=`` target
``deadline_misses`` new in-flight deadline overruns per snapshot ``<=``
                    target (a counter *delta*, not the lifetime total)
==================  ========================================================

Each objective carries a ``budget`` — the allowed violating-snapshot
fraction. ``evaluate()`` exports one gauge per objective × window
(``slo.burn[{name}:{window}]``, a dynamic name documented in the metric
ledger as a template) plus the single ``slo.scale_signal`` gauge:

    ``scale_signal = serving.queue_depth × dispatch p99 (µs)``

— dimensionally "queued work × how slow work currently is", monotone in
both overload directions, zero when idle. The fleet aggregator combines
per-process signals as ``(Σ queue_depth) × max(p99)`` (pessimistic on
latency, additive on backlog).

Everything here is a pure consumer of telemetry dicts: no device, no
threads, no flush barrier. With no snapshots observed, ``evaluate()``
reports every burn as 0.0 and ``ok`` — the engine never alarms on absence
of evidence.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import instrument as _instr
from .registry import REGISTRY, STATE as _MON

__all__ = [
    "DEFAULT_OBJECTIVES",
    "DEFAULT_WINDOWS",
    "Objective",
    "SloEngine",
    "engine",
    "objectives_from_env",
    "observe",
    "evaluate",
    "scale_signal",
    "reset",
]

#: (window-name, window-length-in-snapshots) — short reacts, long confirms.
DEFAULT_WINDOWS: Tuple[Tuple[str, int], ...] = (("short", 8), ("long", 64))


class Objective:
    """One declarative objective over a telemetry measurement.

    ``op`` is ``"<="`` (measurement must stay at or below ``target``) or
    ``">="`` (at or above). ``budget`` is the allowed fraction of violating
    snapshots per window (the error budget the burn rate is measured
    against). ``metric`` names the extractor (default: same as ``name``);
    snapshots where the measurement is unavailable (e.g. no dispatch has
    ever been observed) are skipped, never counted as violations."""

    __slots__ = ("name", "metric", "op", "target", "budget")

    def __init__(self, name, metric=None, op="<=", target=0.0, budget=0.05):
        if op not in ("<=", ">="):
            raise ValueError(f"objective op must be '<=' or '>=', got {op!r}")
        if not 0.0 < float(budget) <= 1.0:
            raise ValueError(f"objective budget must be in (0, 1], got {budget!r}")
        self.name = str(name)
        self.metric = str(metric or name)
        self.op = op
        self.target = float(target)
        self.budget = float(budget)

    def violated(self, value: float) -> bool:
        return value > self.target if self.op == "<=" else value < self.target

    def _asdict(self) -> dict:
        return {
            "name": self.name,
            "metric": self.metric,
            "op": self.op,
            "target": self.target,
            "budget": self.budget,
        }


#: The out-of-the-box objective set (generous targets — the point of the
#: defaults is a working burn-rate surface, not a tuned alert policy; a
#: deployment overrides them via ``HEAT_TPU_SLO``).
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective("dispatch_p99_us", op="<=", target=100_000.0, budget=0.05),
    Objective("cache_hit_rate", op=">=", target=0.5, budget=0.10),
    Objective("shed_ratio", op="<=", target=0.01, budget=0.10),
    Objective("queue_depth", op="<=", target=64.0, budget=0.05),
    Objective("deadline_misses", op="<=", target=0.0, budget=0.05),
)


def _counter_total(tel: dict, name: str):
    val = (tel.get("counters") or {}).get(name, 0)
    return float(val) if isinstance(val, (int, float)) else 0.0


def _measure(metric: str, tel: dict, prev: Optional[dict]) -> Optional[float]:
    """Extract one measurement from a compact telemetry dict (None =
    unavailable this snapshot). ``prev`` is the previous snapshot's
    telemetry — counter-delta metrics difference against it."""
    if metric == "dispatch_p99_us":
        lat = tel.get("serving_dispatch_latency")
        return float(lat["p99_us"]) if lat and lat.get("p99_us") is not None else None
    if metric == "cache_hit_rate":
        slo = tel.get("serving_cache_slo")
        if not slo or slo.get("hit_rate") is None:
            return None
        return float(slo["hit_rate"])
    if metric == "shed_ratio":
        flushes = _counter_total(tel, "fusion.flushes")
        if flushes <= 0:
            return None
        return _counter_total(tel, "serving.shed") / flushes
    if metric == "queue_depth":
        qd = tel.get("serving_queue_depth")
        return float(qd) if qd is not None else 0.0
    if metric == "deadline_misses":
        cur = _counter_total(tel, "serving.deadline_miss")
        if prev is None:
            return cur
        return max(0.0, cur - _counter_total(prev, "serving.deadline_miss"))
    # unknown metric: treat a bare counter name as its lifetime total so a
    # config can target any ledger counter without a code change
    if (tel.get("counters") or {}).get(metric) is not None:
        return _counter_total(tel, metric)
    return None


def scale_signal(tel: dict) -> float:
    """``queue_depth × dispatch p99 (µs)`` from one telemetry dict — the
    quantity the ingress autoscaler consumes. 0.0 when idle or when no
    dispatch latency has ever been observed. The formula itself lives in
    :func:`heat_tpu.monitoring.aggregate.process_scale_signal` (ISSUE 17:
    one definition shared by this gauge, the fleet view, and the
    autoscaler — they can never disagree)."""
    from . import aggregate as _agg

    lat = tel.get("serving_dispatch_latency") or {}
    return _agg.process_scale_signal(
        tel.get("serving_queue_depth"), lat.get("p99_us")
    )


def objectives_from_env() -> Tuple[Objective, ...]:
    """The objective set: ``HEAT_TPU_SLO`` (a JSON list of objective dicts,
    or ``@/path`` to a JSON file) when set and parseable, else the
    defaults. A malformed spec raises ``ValueError`` — a typo'd SLO config
    silently falling back to defaults would be an alerting hole."""
    spec = os.environ.get("HEAT_TPU_SLO", "").strip()
    if not spec:
        return DEFAULT_OBJECTIVES
    if spec.startswith("@"):
        with open(spec[1:], "r") as f:
            spec = f.read()
    try:
        rows = json.loads(spec)
        if not isinstance(rows, list):
            raise TypeError("HEAT_TPU_SLO must be a JSON list")
        return tuple(Objective(**row) for row in rows)
    except (ValueError, TypeError, KeyError) as e:
        raise ValueError(f"malformed HEAT_TPU_SLO spec: {e}") from e


class SloEngine:
    """Windowed burn-rate evaluator over a bounded snapshot history.

    ``observe(telemetry)`` appends one snapshot's measurements;
    ``evaluate()`` folds the resident window into per-objective,
    per-window burn rates and updates the ``slo.*`` gauges. History is
    bounded by the longest window — memory is O(windows), not O(run)."""

    def __init__(
        self,
        objectives: Optional[Sequence[Objective]] = None,
        windows: Optional[Sequence[Tuple[str, int]]] = None,
    ):
        self.objectives = tuple(objectives) if objectives is not None else None
        self.windows = tuple(windows or DEFAULT_WINDOWS)
        maxlen = max(n for _, n in self.windows)
        self._samples: deque = deque(maxlen=maxlen)
        self._prev_tel: Optional[dict] = None
        self._last_signal = 0.0

    def _objectives(self) -> Tuple[Objective, ...]:
        return self.objectives if self.objectives is not None else objectives_from_env()

    def observe(self, tel: dict) -> dict:
        """Fold one compact telemetry dict (``report.telemetry()`` shape)
        into the window. Returns the extracted measurements."""
        sample: Dict[str, Optional[float]] = {}
        for obj in self._objectives():
            sample[obj.name] = _measure(obj.metric, tel, self._prev_tel)
        self._samples.append(sample)
        self._prev_tel = {
            "counters": dict(tel.get("counters") or {}),
        }
        self._last_signal = scale_signal(tel)
        return sample

    def evaluate(self) -> dict:
        """Burn rates per objective × window plus the scale signal.

        ``burn = violating-snapshot fraction / budget`` over the window's
        resident samples (samples where the measurement was unavailable are
        excluded from the denominator). Updates the ``slo.burn[...]``
        template gauges and ``slo.scale_signal``; counted
        ``slo.evaluations``."""
        samples = list(self._samples)
        out: Dict[str, dict] = {}
        for obj in self._objectives():
            row: dict = {"target": obj.target, "op": obj.op, "budget": obj.budget, "windows": {}}
            vals = [s.get(obj.name) for s in samples]
            row["value"] = next((v for v in reversed(vals) if v is not None), None)
            ok = True
            for wname, wlen in self.windows:
                wvals = [v for v in vals[-wlen:] if v is not None]
                violations = sum(1 for v in wvals if obj.violated(v))
                frac = violations / len(wvals) if wvals else 0.0
                burn = frac / obj.budget
                row["windows"][wname] = {
                    "samples": len(wvals),
                    "violations": violations,
                    "burn": round(burn, 4),
                }
                ok = ok and burn < 1.0
                if _MON.enabled:
                    name, window = obj.name, wname
                    REGISTRY.gauge(f"slo.burn[{name}:{window}]").set(round(burn, 4))
            row["ok"] = ok
            out[obj.name] = row
        if _MON.enabled:
            _instr.slo_evaluation()
            _instr.slo_scale_signal(self._last_signal)
        return {"objectives": out, "scale_signal": round(self._last_signal, 4)}

    def reset(self) -> None:
        self._samples.clear()
        self._prev_tel = None
        self._last_signal = 0.0


_ENGINE: Optional[SloEngine] = None


def engine() -> SloEngine:
    """The process-default engine (fed by the telemetry spool's cadence and
    the exporter's scrape handler)."""
    global _ENGINE
    if _ENGINE is None:
        _ENGINE = SloEngine()
    return _ENGINE


def observe(tel: dict) -> dict:
    """Module-level alias of ``engine().observe``."""
    return engine().observe(tel)


def evaluate() -> dict:
    """Module-level alias of ``engine().evaluate``."""
    return engine().evaluate()


def reset() -> None:
    """Drop the default engine's window (test isolation)."""
    global _ENGINE
    _ENGINE = None
