"""
Cross-process telemetry spool + fleet aggregation.

Every observability surface through PR 13 — registry counters,
``report.telemetry()``, the flight ring, ``statusz`` — is in-process:
readable only by calling Python *inside* that process. A fleet (ROADMAP
item 2: many worker processes behind one ingress) needs the inverse: each
process publishes, an aggregator merges. This module is that plane's
transport:

* **Writer** — :func:`maybe_snapshot` is called from the runtime's flush
  paths (the serving scheduler after each dispatched flush, the L2 cache
  after each persist). With ``HEAT_TPU_TELEMETRY_DIR`` unset (the default)
  the entire cost is **one env read** — no file, no thread, no timer. Set,
  every ``HEAT_TPU_TELEMETRY_EVERY``-th trigger (default 32; the *first*
  trigger always writes so short-lived processes publish at least once)
  atomically snapshots this process's full registry state + compact
  telemetry + flight summary + SLO evaluation to
  ``<dir>/<pid>-<nonce>.json`` (same-directory tempfile + ``os.replace``,
  the L2-cache atomic-write idiom — a reader sees the old snapshot or the
  new one, never a torn file). The cadence is **per-flush-count, not a
  wall-clock thread**: an idle process writes nothing and spawns nothing.
  Snapshots are **barrier-free** (``report.telemetry(flush=False)``): a
  telemetry write must never flush pending fused chains — publishing is a
  pure observation and cannot alter the execution schedule.

* **Aggregator** — :func:`read_snapshots` / :func:`fleet_view` merge the
  live snapshots of a spool directory into one fleet view with per-process
  labels (``pid``/``nonce``/``host``). The reader applies the PR 12 footer
  discipline to other people's files: torn or partial JSON, unparseable
  payloads, snapshots older than ``max_age_s``, and superseded duplicates
  (a reused pid with a newer nonce) are **counted, never a crash**
  (``telemetry_spool.merge{torn,stale,superseded,merged}``). Counter totals
  sum across processes (labels included), gauges sum (queue depths and
  memory are additive fleet-wise), histograms sum bucket-wise when the
  bounds agree (see :func:`registry.merge_snapshots`), and the fleet
  ``scale_signal`` is ``(Σ queue_depth) × max(p99)`` — additive on backlog,
  pessimistic on latency.

* **Trace merge** — :func:`merge_chrome_traces` concatenates Chrome-trace
  exports from several processes into one Perfetto-loadable timeline;
  every event already carries its real ``pid`` and each process emits
  ``process_name``/``thread_name`` metadata events, so merged traces
  render as separate tracks per process.

The spool file name is ``<pid>-<nonce>.json``: one file per process,
overwritten in place each cadence. The nonce (minted once per process)
disambiguates pid reuse — the aggregator keeps the newest snapshot per pid
and counts the loser ``superseded``.
"""

from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from . import instrument as _instr
from . import registry as _registry
from .registry import STATE as _MON

__all__ = [
    "spool_dir",
    "snapshot_every",
    "maybe_snapshot",
    "write_snapshot",
    "build_snapshot",
    "read_snapshots",
    "fleet_view",
    "process_scale_signal",
    "fleet_scale_signal",
    "merge_chrome_traces",
    "reset",
]

_DEFAULT_EVERY = 32

#: Per-process spool identity: minted once, survives for the process life,
#: distinguishes two processes that reused one pid.
_NONCE = uuid.uuid4().hex[:8]

_LOCK = threading.Lock()
_TRIGGERS = 0
_SEQ = 0


def spool_dir() -> Optional[str]:
    """The spool directory (``HEAT_TPU_TELEMETRY_DIR``), or None = off (the
    default — zero files, zero threads). Read per trigger."""
    d = os.environ.get("HEAT_TPU_TELEMETRY_DIR", "").strip()
    return d or None


def snapshot_every() -> int:
    """Trigger count between snapshot writes (``HEAT_TPU_TELEMETRY_EVERY``,
    default 32, min 1)."""
    try:
        return max(1, int(os.environ.get("HEAT_TPU_TELEMETRY_EVERY", "") or _DEFAULT_EVERY))
    except ValueError:
        return _DEFAULT_EVERY


# ------------------------------------------------------------ scale signal
#
# THE scale-signal formula, defined exactly once (ISSUE 17 satellite): the
# single-process SLO gauge (monitoring/slo.py), the fleet /readyz view
# (fleet_view below) and the ingress autoscaler (serving/server.py) all call
# these two helpers, so the three consumers can never disagree about what
# "load" means. The formula is regression-pinned by tests/test_fleet.py.


def process_scale_signal(queue_depth, p99_us) -> float:
    """One process's scale signal: ``queue_depth × dispatch p99 (µs)`` —
    0.0 when idle or when no dispatch latency has ever been observed
    (``None`` inputs read as zero)."""
    return float(queue_depth or 0) * float(p99_us or 0.0)


def fleet_scale_signal(queue_depths, p99s_us) -> float:
    """The fleet aggregation: ``(Σ queue_depth) × max(p99 µs)`` — additive
    on backlog, pessimistic on latency. Empty inputs read as 0.0."""
    total = 0.0
    for q in queue_depths:
        total += float(q or 0)
    worst = 0.0
    for p in p99s_us:
        worst = max(worst, float(p or 0.0))
    return total * worst


def build_snapshot() -> dict:
    """This process's spool payload: identity labels, the full registry
    snapshot (labels preserved — the fleet exposition re-renders it
    per-process), the compact telemetry block (barrier-free), the flight
    summary, and the SLO evaluation over the freshly observed sample."""
    from . import flight as _flight
    from . import report as _report
    from . import slo as _slo

    tel = _report.telemetry(flush=False)
    eng = _slo.engine()
    eng.observe(tel)
    # per-signature traffic frequencies (ISSUE 17): the predictive warmup
    # driver mines these across the fleet's spool to rank corpus entries by
    # frequency × compile cost. Only published when the flight recorder is
    # armed (it owns the per-signature totals); bounded to the hottest 256
    # signatures so a long-lived process cannot bloat its snapshot.
    per_signature = None
    if _flight.flight_enabled():
        ranked = sorted(
            _flight.totals().items(),
            key=lambda kv: (-int(kv[1].get("flushes", 0) or 0), kv[0]),
        )[:256]
        per_signature = {
            sig: {
                "flushes": int(t.get("flushes", 0) or 0),
                "wall_s": round(float(t.get("wall_s", 0.0) or 0.0), 6),
            }
            for sig, t in ranked
        }
    # the measured-autotuning values this process is serving (ISSUE 18):
    # published so a bench/chip run is attributable to its knob settings.
    # Off (the default) this is one env read and no key at all.
    tuning_chosen = None
    try:
        from .. import tuning as _tuning

        if _tuning.enabled():
            tuning_chosen = _tuning.chosen()
    except Exception:  # pragma: no cover — publishing never crashes
        tuning_chosen = None
    return {
        "schema": 1,
        "pid": os.getpid(),
        "nonce": _NONCE,
        "host": socket.gethostname(),
        "time": time.time(),
        **({"tuning": tuning_chosen} if tuning_chosen else {}),
        "labels": {"pid": str(os.getpid()), "nonce": _NONCE, "host": socket.gethostname()},
        "metrics": _registry.snapshot(),
        "telemetry": tel,
        "flight": {
            "enabled": _flight.flight_enabled(),
            "records": len(_flight.records()),
            "evicted": _flight.evicted(),
            "signatures": len(_flight.totals()),
            "modeled_utilization": _flight.modeled_utilization(),
            **({"per_signature": per_signature} if per_signature is not None else {}),
        },
        "slo": eng.evaluate(),
    }


def _atomic_write_text(path: str, text: str) -> None:
    """Same-directory tempfile + ``os.replace`` (the L2-cache idiom): a
    concurrent aggregator sees the previous snapshot or this one whole."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=".tmp-", suffix=".json"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_snapshot(directory: Optional[str] = None, path: Optional[str] = None) -> Optional[dict]:
    """Write this process's snapshot now (ignoring the cadence): to
    ``<directory>/<pid>-<nonce>.json``, or to an explicit ``path`` (the
    bench sidecar uses this). Returns the payload, or None when the write
    failed (counted ``telemetry_spool.snapshots{error}`` — publishing can
    never crash the workload)."""
    global _SEQ
    try:
        payload = build_snapshot()
        with _LOCK:
            _SEQ += 1
            payload["seq"] = _SEQ
        if path is None:
            if directory is None:
                directory = spool_dir()
            if directory is None:
                return None
            path = os.path.join(directory, f"{payload['pid']}-{payload['nonce']}.json")
        _atomic_write_text(path, json.dumps(payload, sort_keys=True, default=str))
        if _MON.enabled:
            _instr.telemetry_spool_snapshot("written")
        return payload
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        if _MON.enabled:
            _instr.telemetry_spool_snapshot("error")
        return None


def maybe_snapshot() -> None:
    """The per-flush-count trigger the runtime's flush paths call. Off
    (``HEAT_TPU_TELEMETRY_DIR`` unset) = one env read, nothing else; armed,
    the first trigger and every ``snapshot_every()``-th thereafter writes a
    snapshot."""
    global _TRIGGERS
    d = spool_dir()
    if d is None:
        return
    with _LOCK:
        _TRIGGERS += 1
        due = _TRIGGERS == 1 or _TRIGGERS % snapshot_every() == 0
    if due:
        write_snapshot(d)


def write_trace(directory: Optional[str] = None) -> Optional[str]:
    """Publish this process's Chrome-trace export as a spool sidecar
    (``<directory>/<pid>-<nonce>.trace.json`` — the ``.trace.json`` suffix
    keeps :func:`read_snapshots` from counting it torn). The worker calls
    this after each traced request so the ingress's fleet-merged ``/trace``
    view (ISSUE 16 satellite) sees worker-side spans without a CLI round
    trip. Same discipline as :func:`write_snapshot`: atomic replace, never
    raises, returns the path or None."""
    try:
        if directory is None:
            directory = spool_dir()
        if directory is None:
            return None
        from . import flight as _flight

        path = os.path.join(directory, f"{os.getpid()}-{_NONCE}.trace.json")
        _atomic_write_text(path, _flight.export_chrome_trace())
        return path
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        if _MON.enabled:
            _instr.telemetry_spool_snapshot("error")
        return None


def read_traces(directory: str) -> List[str]:
    """The raw Chrome-trace sidecar strings of a spool directory (newest
    write wins per process by filename identity). Unreadable files are
    skipped — the merged view tolerates a sidecar mid-replace."""
    out: List[str] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".trace.json") or name.startswith(".tmp-"):
            continue
        try:
            with open(os.path.join(directory, name), "r") as f:
                out.append(f.read())
        except OSError:
            continue
    return out


# ------------------------------------------------------------------ aggregation
def read_snapshots(
    directory: str, max_age_s: Optional[float] = None
) -> Tuple[List[dict], Dict[str, int]]:
    """All live snapshots of a spool directory, plus the skip accounting.

    Tolerates the fleet's failure modes without ever raising: torn/partial
    JSON and payloads missing the identity fields count ``torn``; snapshots
    whose ``time`` is older than ``max_age_s`` (when given) count
    ``stale``; duplicate pids (reuse across nonces) keep the newest by
    write time and count the losers ``superseded``. Every accepted
    snapshot counts ``merged``."""
    skips = {"merged": 0, "torn": 0, "stale": 0, "superseded": 0}
    by_pid: Dict[int, dict] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return [], skips
    now = time.time()
    for name in names:
        if not name.endswith(".json") or name.startswith(".tmp-"):
            continue
        if name.endswith(".trace.json"):
            continue  # Chrome-trace sidecars (write_trace) are not snapshots
        path = os.path.join(directory, name)
        try:
            with open(path, "r") as f:
                snap = json.load(f)
            if not isinstance(snap, dict):
                raise ValueError("snapshot is not an object")
            pid = int(snap["pid"])
            snap["nonce"], snap["time"]
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:
            skips["torn"] += 1
            continue
        if max_age_s is not None and now - float(snap["time"]) > max_age_s:
            skips["stale"] += 1
            continue
        prev = by_pid.get(pid)
        if prev is not None:
            # pid reuse: one of the two processes is gone — keep the newest
            if float(snap["time"]) >= float(prev["time"]):
                by_pid[pid] = snap
            skips["superseded"] += 1
        else:
            by_pid[pid] = snap
    snaps = sorted(by_pid.values(), key=lambda s: (int(s["pid"]), str(s["nonce"])))
    skips["merged"] = len(snaps)
    if _MON.enabled:
        for kind, n in skips.items():
            if n:
                _instr.telemetry_spool_merge(kind, n)
    return snaps, skips


def fleet_view(directory: str, max_age_s: Optional[float] = None) -> dict:
    """One merged fleet view of a spool directory.

    Per-process summaries keyed ``<pid>-<nonce>`` ride beside the merged
    registry snapshot (:func:`registry.merge_snapshots`: counters and
    gauges sum, histograms sum bucket-wise where bounds agree) and the
    fleet ``scale_signal`` — ``(Σ queue_depth) × max(dispatch p99 µs)``
    across live processes."""
    snaps, skips = read_snapshots(directory, max_age_s=max_age_s)
    queue_depths = []
    p99s = []
    processes = {}
    for s in snaps:
        tel = s.get("telemetry") or {}
        qd = float(tel.get("serving_queue_depth") or 0)
        p99 = float((tel.get("serving_dispatch_latency") or {}).get("p99_us") or 0.0)
        queue_depths.append(qd)
        p99s.append(p99)
        processes[f"{s['pid']}-{s['nonce']}"] = {
            "pid": s["pid"],
            "nonce": s["nonce"],
            "host": s.get("host"),
            "time": s["time"],
            "seq": s.get("seq"),
            "queue_depth": qd,
            "dispatch_p99_us": p99 or None,
            "scale_signal": (s.get("slo") or {}).get("scale_signal"),
            "flight": s.get("flight"),
        }
    return {
        "processes": processes,
        "metrics": _registry.merge_snapshots([s.get("metrics") or {} for s in snaps]),
        "scale_signal": round(fleet_scale_signal(queue_depths, p99s), 4),
        "skips": skips,
    }


def merge_chrome_traces(traces) -> str:
    """Merge several per-process Chrome-trace exports (JSON strings or
    already-parsed dicts) into one Perfetto-loadable document. Metadata
    (``ph: "M"``) events lead; timed events are re-sorted by ``ts`` across
    processes. Unparseable inputs are skipped (counted ``torn``) — the
    merged timeline degrades, never crashes."""
    meta: List[dict] = []
    timed: List[dict] = []
    for t in traces:
        try:
            doc = json.loads(t) if isinstance(t, str) else t
            events = doc["traceEvents"]
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:
            if _MON.enabled:
                _instr.telemetry_spool_merge("torn")
            continue
        for ev in events:
            (meta if ev.get("ph") == "M" else timed).append(ev)
    timed.sort(key=lambda e: e.get("ts", 0.0))
    return json.dumps(
        {"traceEvents": meta + timed, "displayTimeUnit": "ms"},
        sort_keys=True,
        default=str,
    )


def reset() -> None:
    """Drop the trigger/sequence state (test isolation). The nonce is
    deliberately *not* re-minted — it is the process identity."""
    global _TRIGGERS, _SEQ
    with _LOCK:
        _TRIGGERS = 0
        _SEQ = 0
