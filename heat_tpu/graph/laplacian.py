"""
Graph Laplacian.

Parity with the reference's ``heat/graph/laplacian.py`` (``Laplacian`` :39-146:
similarity matrix → optional eNeighbour thresholding → ``L = D - A`` or the
symmetric-normalized variant).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

import heat_tpu as ht
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """
    Graph Laplacian from pairwise similarity.

    Parameters
    ----------
    similarity : Callable
        f(X) -> (n, n) similarity/adjacency DNDarray (e.g. ``ht.spatial.rbf``).
    weighted : bool
        Weighted (True) or binarized (False) adjacency.
    definition : str
        ``'simple'`` (L = D - A) or ``'norm_sym'`` (L = I - D^-1/2 A D^-1/2).
    mode : str
        ``'fully_connected'`` or ``'eNeighbour'`` (threshold the similarity).
    threshold_key : str
        ``'upper'`` or ``'lower'`` — which side of the threshold keeps an edge.
    threshold_value : float
        The threshold.
    neighbours : int
        Parity parameter for kNN graphs (reference laplacian.py:39-60).

    Reference parity: heat/graph/laplacian.py:39-146.
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Currently only simple and normalized symmetric graph laplacians are supported"
            )
        self.definition = definition
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighborhood and fully-connected graphs supported at the moment."
            )
        self.mode = mode
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L = I - D^-1/2 A D^-1/2 (reference laplacian.py:61-90)."""
        a = A.larray
        d = jnp.sum(a, axis=1)
        d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        L = jnp.eye(a.shape[0], dtype=a.dtype) - d_inv_sqrt[:, None] * a * d_inv_sqrt[None, :]
        return ht.array(L, split=A.split, device=A.device, comm=A.comm)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D - A (reference laplacian.py:91-110)."""
        a = A.larray
        L = jnp.diag(jnp.sum(a, axis=1)) - a
        return ht.array(L, split=A.split, device=A.device, comm=A.comm)

    def construct(self, X: DNDarray) -> DNDarray:
        """Builds the Laplacian of the similarity graph of X (reference
        laplacian.py:111-146)."""
        S = self.similarity_metric(X)
        s = S.larray
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            if key == "upper":
                keep = s < value
            else:
                keep = s > value
            s = jnp.where(keep, s if self.weighted else jnp.ones_like(s), jnp.zeros_like(s))
        # zero the diagonal (no self-loops)
        s = s - jnp.diag(jnp.diag(s))
        A = ht.array(s, split=S.split, device=S.device, comm=S.comm)
        if self.definition == "simple":
            return self._simple_L(A)
        return self._normalized_symmetric_L(A)
