"""Graph algorithms (parity: reference heat/graph/__init__.py)."""

from .laplacian import *
