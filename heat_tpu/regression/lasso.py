"""
Lasso regression.

Parity with the reference's ``heat/regression/lasso.py`` (:50-186): coordinate
descent with soft-thresholding; every step is a distributed matvec on the (possibly
row-split) design matrix.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np
import jax
import jax.numpy as jnp

import heat_tpu as ht
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray
from ..monitoring import events as _ev
from ..monitoring.registry import REGISTRY as _REG, STATE as _MON
from ..robustness import preemption as _preempt

__all__ = ["Lasso"]


class Lasso(BaseEstimator, RegressionMixin):
    """
    Least absolute shrinkage and selection operator (coordinate descent).

    Parameters
    ----------
    lam : float
        Regularization strength λ.
    max_iter : int
        Number of coordinate-descent sweeps.
    tol : float
        Convergence tolerance on the coefficient update.

    Attributes
    ----------
    coef_ : DNDarray
        Feature coefficients (intercept excluded).
    intercept_ : DNDarray
        The intercept.

    Reference parity: heat/regression/lasso.py:50-186.
    """

    def __init__(
        self,
        lam: float = 0.1,
        max_iter: int = 100,
        tol: float = 1e-6,
        sweep_engine: str = "jit",
    ):
        if sweep_engine not in ("jit", "fused"):
            raise ValueError(f"sweep_engine must be 'jit' or 'fused', got {sweep_engine!r}")
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.sweep_engine = sweep_engine
        self.__theta = None
        self.n_iter = None

    @property
    def coef_(self) -> Optional[DNDarray]:
        """Slope parameters (without intercept)."""
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        """The intercept."""
        return None if self.__theta is None else self.__theta[0]

    @property
    def lam(self) -> float:
        """Regularization strength λ."""
        return self.__lam

    @lam.setter
    def lam(self, arg: float):
        self.__lam = arg

    @property
    def theta(self):
        """All fitted parameters (intercept first)."""
        return self.__theta

    def soft_threshold(self, rho):
        """Soft-thresholding operator (reference lasso.py:90-110)."""
        if isinstance(rho, DNDarray):
            out = jnp.where(
                rho < -self.__lam,
                rho.larray + self.__lam,
                jnp.where(rho.larray > self.__lam, rho.larray - self.__lam, 0.0),
            )
            return ht.array(out, device=rho.device, comm=rho.comm)
        return jnp.where(
            rho < -self.__lam, rho + self.__lam, jnp.where(rho > self.__lam, rho - self.__lam, 0.0)
        )

    def rmse(self, gt: DNDarray, yest: DNDarray) -> float:
        """Root mean squared error (reference lasso.py:111-125)."""
        return float(jnp.sqrt(jnp.mean((gt.larray - yest.larray) ** 2)))

    def sweep_once(self, x: DNDarray, y: DNDarray, theta: DNDarray) -> DNDarray:
        """One coordinate-descent sweep on the DNDarray op surface (ROADMAP
        item 1 / ISSUE 7): returns the updated ``theta`` as a DEFERRED array.

        Every coordinate update — the column view, the residual matvec (a
        GEMM producer whose cross-device psum over the row-sharded design
        matrix XLA emits from the shardings), the ``rho``/``z`` dot-product
        sinks, and the soft-threshold chain — records into ONE pending DAG,
        so the whole sweep flushes as ONE cached XLA program at the first
        read and ``fusion.flush_reason{collective}`` stays 0. The recorded
        depth is ~9 ops per coordinate: sweeps deeper than
        ``HEAT_TPU_FUSION_MAX_CHAIN`` split at the (counted) chain bound —
        still correct, just more than one kernel. The ``lax.fori_loop`` sweep
        (``sweep_engine='jit'``) remains the default fit path for large
        feature counts.

        ``x`` is the design matrix WITH the bias column (``(n, f+1)``,
        row-split or replicated), ``y`` the flat targets, ``theta`` the
        current ``(f+1,)`` coefficients; coordinate 0 is the unthresholded
        intercept, exactly like the jitted sweep."""
        n, f1 = (int(s) for s in x.shape)
        lam = self.__lam
        # pending identity roots: the per-coordinate column reads then record
        # view nodes (a concrete operand's basic read would dispatch eagerly)
        X = ht.positive(x)
        th = ht.positive(theta)
        iota = ht.arange(f1)
        for j in range(f1):
            xj = X[:, j]  # view node (n,)
            resid = y - ht.dot(X, th) + xj * th[j : j + 1]
            rho = ht.dot(xj, resid) / n
            zj = ht.dot(xj, xj) / n
            if j == 0:  # intercept coordinate: never thresholded
                new = rho / zj
            else:
                new = ht.sign(rho) * ht.maximum(ht.abs(rho) - lam, 0.0) / zj
            th = ht.where(iota == j, new, th)
        return th

    def _fit_fused(self, x: DNDarray, y: DNDarray) -> int:
        """Coordinate-descent fit driven through :meth:`sweep_once` (the
        deferred-DAG sweep): one fused executable per sweep, preemption
        polled at sweep boundaries like the jitted path. Returns n_iter and
        leaves the final theta in ``self.__theta``."""
        xa = x.larray
        ya = y.larray.reshape(-1)
        n, f = xa.shape
        X = ht.array(
            jnp.concatenate([jnp.ones((n, 1), dtype=xa.dtype), xa], axis=1),
            split=x.split, device=x.device, comm=x.comm,
        )
        yv = ht.array(ya, split=None if y.split is None else 0, device=y.device, comm=y.comm)
        theta = ht.zeros((f + 1,), dtype=x.dtype, device=x.device, comm=x.comm)
        n_iter = 0
        with _ev.span("lasso.fit", n=int(n), features=int(f)) as fit_sp:
            for n_iter in range(1, self.max_iter + 1):
                with _ev.span("lasso.sweep", iteration=n_iter) as sp:
                    new_theta = self.sweep_once(X, yv, theta)
                    # the max-|Δ| sink consumes the sweep DAG: this readback
                    # is the ONE flush (and the device sync the loop needs)
                    diff = float(ht.max(ht.abs(new_theta - theta)).item())
                    sp.set(delta=diff)
                theta = new_theta
                if diff < self.tol:
                    break
                if _preempt.should_checkpoint():
                    _preempt.checkpoint_now(
                        {"theta": theta.larray, "sweep": n_iter}, step=n_iter
                    )
                    break
            fit_sp.set(n_iter=n_iter)
        self.__theta = ht.array(
            theta.larray.reshape(-1, 1), device=x.device, comm=x.comm
        )
        return n_iter

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """
        Coordinate descent fit (reference lasso.py:126-176). A bias column is
        prepended; the intercept coordinate is not thresholded.

        ``sweep_engine='jit'`` (default) runs the ``lax.fori_loop`` sweep;
        ``'fused'`` drives :meth:`sweep_once` through the deferred-execution
        engine — one fused XLA program per sweep recorded from the op
        surface.
        """
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise ValueError("x and y need to be ht.DNDarrays")
        if self.sweep_engine == "fused":
            n_iter = self._fit_fused(x, y)
            if _MON.enabled:
                _REG.counter("lasso.fits").inc()
                _REG.counter("lasso.sweeps").inc(n_iter)
            self.n_iter = n_iter
            return self
        xa = x.larray
        ya = y.larray.reshape(-1)
        n, f = xa.shape
        X = jnp.concatenate([jnp.ones((n, 1), dtype=xa.dtype), xa], axis=1)  # (n, f+1)
        theta = jnp.zeros((f + 1,), dtype=xa.dtype)
        lam = self.__lam

        def sweep(theta):
            def coord(j, th):
                xj = X[:, j]
                resid = ya - X @ th + xj * th[j]
                rho = jnp.dot(xj, resid) / n
                zj = jnp.dot(xj, xj) / n
                new = jnp.where(
                    j == 0,
                    rho / zj,
                    jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0) / zj,
                )
                return th.at[j].set(new)

            return jax.lax.fori_loop(0, f + 1, coord, theta)

        sweep_jit = jax.jit(sweep)
        n_iter = 0
        with _ev.span("lasso.fit", n=int(n), features=int(f)) as fit_sp:
            for n_iter in range(1, self.max_iter + 1):
                # per-sweep step span: the diff readback is the device sync the
                # loop performs anyway, so the span costs no extra blocking
                with _ev.span("lasso.sweep", iteration=n_iter) as sp:
                    new_theta = sweep_jit(theta)
                    diff = float(jnp.max(jnp.abs(new_theta - theta)))
                    sp.set(delta=diff)
                theta = new_theta
                if diff < self.tol:
                    break
                # preemption contract: a sweep boundary is a consistent
                # (theta, sweep) snapshot — poll the guard here, save through
                # its manager, and end the fit with the checkpointed state
                if _preempt.should_checkpoint():
                    _preempt.checkpoint_now(
                        {"theta": theta, "sweep": n_iter}, step=n_iter
                    )
                    break
            fit_sp.set(n_iter=n_iter)
        if _MON.enabled:
            _REG.counter("lasso.fits").inc()
            _REG.counter("lasso.sweeps").inc(n_iter)
        self.n_iter = n_iter
        self.__theta = ht.array(theta.reshape(-1, 1), device=x.device, comm=x.comm)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Predict targets (reference lasso.py:177-186)."""
        if self.__theta is None:
            raise RuntimeError("fit the estimator before predicting")
        xa = x.larray
        X = jnp.concatenate([jnp.ones((xa.shape[0], 1), dtype=xa.dtype), xa], axis=1)
        yest = X @ self.__theta.larray
        return ht.array(yest, split=x.split, device=x.device, comm=x.comm)
