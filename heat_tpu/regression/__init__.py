"""Regression (parity: reference heat/regression/__init__.py)."""

from .lasso import *
