"""Utilities subpackage (parity: reference heat/utils/__init__.py)."""

from . import data
from . import vision_transforms
from . import checkpoint
from . import profiling
from .checkpoint import CheckpointManager, load_checkpoint, save_checkpoint
