"""Utilities subpackage (parity: reference heat/utils/__init__.py)."""

from . import data
from . import vision_transforms
