"""
Tracing / profiling helpers.

The reference has no profiling support at all (SURVEY §5: bare ``time.perf_counter``
loops in its benchmarks). On TPU the platform profiler comes for free; this module
wraps it in a stable framework surface:

- :func:`trace` — context manager writing a Perfetto/TensorBoard-loadable trace of
  everything (XLA ops, collectives, host callbacks) under the block.
- :class:`Timer` — device-synchronizing wall-clock timer for benchmark loops; its
  ``block_on`` ensures async dispatch doesn't lie about step time.
- :func:`annotate` — named region in the trace timeline (``jax.profiler.TraceAnnotation``).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax

__all__ = ["trace", "annotate", "Timer"]


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device+host profile of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region on the profiler timeline (usable as context manager)."""
    return jax.profiler.TraceAnnotation(name)


class Timer:
    """Wall-clock timer that forces pending device work to finish at each mark.

    >>> t = Timer()
    >>> out = step(x)
    >>> dt = t.lap(out)       # seconds since last lap, after out is ready
    """

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()

    def lap(self, block_on: Optional[Any] = None) -> float:
        if block_on is not None:
            jax.block_until_ready(block_on)
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        return dt
