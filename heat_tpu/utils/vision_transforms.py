"""
Vision transforms.

Parity with the reference's ``heat/utils/vision_transforms.py`` (:12-33), a
``__getattr__`` fallthrough to ``torchvision.transforms``. torchvision is optional;
a small set of jnp-native transforms is provided first, then the fallthrough (when
torchvision is installed).
"""

from __future__ import annotations

import jax.numpy as jnp

try:
    import torchvision.transforms as _tvt
except ImportError:  # pragma: no cover - torchvision absent in TPU images
    _tvt = None


def normalize(mean, std):
    """Returns f(x) = (x - mean) / std (jnp-native Normalize)."""
    mean = jnp.asarray(mean)
    std = jnp.asarray(std)

    def _apply(x):
        return (jnp.asarray(x) - mean) / std

    return _apply


def to_tensor():
    """Returns f(x) = float32 array scaled to [0, 1] (jnp-native ToTensor)."""

    def _apply(x):
        x = jnp.asarray(x, dtype=jnp.float32)
        return x / 255.0 if x.max() > 1.0 else x

    return _apply


def __getattr__(name: str):
    """Fall through to torchvision.transforms when available (reference
    vision_transforms.py:12-33)."""
    if _tvt is not None and hasattr(_tvt, name):
        return getattr(_tvt, name)
    raise AttributeError(
        f"module 'heat_tpu.utils.vision_transforms' has no attribute {name!r}"
        + ("" if _tvt else " (torchvision not installed)")
    )
