"""
Vision transforms.

Parity with the reference's ``heat/utils/vision_transforms.py`` (:12-33), a
``__getattr__`` fallthrough to ``torchvision.transforms``. torchvision is optional;
a small set of jnp-native transforms is provided first, then the fallthrough (when
torchvision is installed).
"""

from __future__ import annotations

import jax.numpy as jnp

try:
    import torchvision.transforms as _tvt
except ImportError:  # pragma: no cover - torchvision absent in TPU images
    _tvt = None


def normalize(mean, std):
    """Returns f(x) = (x - mean) / std (functional form of :class:`Normalize`)."""
    return Normalize(mean, std)


def to_tensor():
    """Returns the HWC→CHW [0,1] conversion (functional form of :class:`ToTensor`)."""
    return ToTensor()


class Compose:
    """Chain transforms left to right (torchvision.transforms.Compose semantics)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    """(x - mean) / std, jnp-native (torchvision.transforms.Normalize semantics:
    per-channel stats broadcast over trailing image dims for CHW input)."""

    def __init__(self, mean, std):
        self.mean = jnp.asarray(mean)
        self.std = jnp.asarray(std)

    def __call__(self, x):
        x = jnp.asarray(x)
        mean, std = self.mean, self.std
        if mean.ndim == 1 and x.ndim >= 3:  # CHW layout: broadcast over H, W
            mean = mean[:, None, None]
            std = std[:, None, None]
        return (x - mean) / std


class ToTensor:
    """torchvision.transforms.ToTensor semantics on jnp arrays: an (H, W) or
    (H, W, C) image becomes float32 CHW, with integer dtypes scaled to [0, 1].
    Output is a jnp array (downstream transforms here are jnp-native too)."""

    def __call__(self, x):
        x = jnp.asarray(x)
        if x.ndim == 2:
            x = x[None, :, :]
        elif x.ndim == 3 and x.shape[-1] in (1, 3, 4):
            x = jnp.transpose(x, (2, 0, 1))  # HWC -> CHW
        if jnp.issubdtype(x.dtype, jnp.integer):
            return x.astype(jnp.float32) / 255.0
        return x.astype(jnp.float32)


class Lambda:
    """Wrap an arbitrary callable as a transform."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)


def __getattr__(name: str):
    """Fall through to torchvision.transforms when available (reference
    vision_transforms.py:12-33)."""
    if _tvt is not None and hasattr(_tvt, name):
        return getattr(_tvt, name)
    raise AttributeError(
        f"module 'heat_tpu.utils.vision_transforms' has no attribute {name!r}"
        + ("" if _tvt else " (torchvision not installed)")
    )
