"""
Vision transforms.

Parity with the reference's ``heat/utils/vision_transforms.py`` (:12-33), a
``__getattr__`` fallthrough to ``torchvision.transforms``. torchvision is optional;
a small set of jnp-native transforms is provided first, then the fallthrough (when
torchvision is installed).
"""

from __future__ import annotations

import jax.numpy as jnp

try:
    import torchvision.transforms as _tvt
except ImportError:  # pragma: no cover - torchvision absent in TPU images
    _tvt = None


def normalize(mean, std):
    """Returns the jnp-native f(x) = (x - mean) / std transform. Unlike the bare
    ``Normalize`` name (which resolves to torchvision when installed, reference
    parity), this helper is jnp-in/jnp-out regardless of the environment — a
    torchvision Normalize would reject jnp/numpy inputs."""
    return JnpNormalize(mean, std)


def to_tensor():
    """Returns the jnp-native HWC→CHW [0,1] conversion. Unlike the bare
    ``ToTensor`` name, always accepts numpy/jnp arrays (torchvision's rejects
    them)."""
    return JnpToTensor()


class JnpCompose:
    """Chain transforms left to right (torchvision.transforms.Compose semantics)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class JnpNormalize:
    """(x - mean) / std, jnp-native. Per-channel stats align against whichever
    axis matches their length — leading (CHW, torchvision layout) wins when
    ambiguous, trailing (HWC) otherwise."""

    def __init__(self, mean, std):
        self.mean = jnp.asarray(mean)
        self.std = jnp.asarray(std)

    def __call__(self, x):
        x = jnp.asarray(x)
        mean, std = self.mean, self.std
        if mean.ndim == 1 and x.ndim >= 3 and x.shape[-3] == mean.shape[0]:
            mean = mean[:, None, None]  # CHW: broadcast over H, W
            std = std[:, None, None]
        return (x - mean) / std


class JnpToTensor:
    """torchvision.transforms.ToTensor semantics on jnp arrays: an (H, W) or
    (H, W, C) image becomes float32 CHW, with integer dtypes scaled to [0, 1].
    Output is a jnp array (downstream transforms here are jnp-native too)."""

    def __call__(self, x):
        x = jnp.asarray(x)
        if x.ndim == 2:
            x = x[None, :, :]
        elif x.ndim == 3:
            x = jnp.transpose(x, (2, 0, 1))  # HWC -> CHW, any channel count
        if jnp.issubdtype(x.dtype, jnp.integer):
            return x.astype(jnp.float32) / 255.0
        return x.astype(jnp.float32)


class JnpLambda:
    """Wrap an arbitrary callable as a transform."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)


# With torchvision absent the common names resolve to the jnp-native versions.
_JNP_FALLBACK = {
    "Compose": JnpCompose,
    "Normalize": JnpNormalize,
    "ToTensor": JnpToTensor,
    "Lambda": JnpLambda,
}


def __getattr__(name: str):
    """Fall through to torchvision.transforms when available — torchvision wins,
    matching the reference's pure-passthrough module (vision_transforms.py:12-33) —
    else serve the jnp-native equivalents for the common transform names."""
    if _tvt is not None and hasattr(_tvt, name):
        return getattr(_tvt, name)
    if name in _JNP_FALLBACK:
        return _JNP_FALLBACK[name]
    raise AttributeError(
        f"module 'heat_tpu.utils.vision_transforms' has no attribute {name!r}"
        + ("" if _tvt else " (torchvision not installed)")
    )
